"""3x3 Chomp as a reference-style scalar module (SURVEY.md §2.1.1 API).

Same packing as gamesmanmpi_tpu.games.chomp.Chomp(3, 3): column heights at
2 bits each, little-endian — so oracle tables compare position-for-position
with the tensorized game. Declares level_of, so the compat shim can drive
the jitted engine too (max_moves is auto-derived).
"""

W, H = 3, 3
BITS = 2  # heights 0..3


def _heights(pos):
    return [(pos >> (c * BITS)) & ((1 << BITS) - 1) for c in range(W)]


def _pack(heights):
    out = 0
    for c, h in enumerate(heights):
        out |= h << (c * BITS)
    return out


initial_position = _pack([H] * W)


def gen_moves(pos):
    hs = _heights(pos)
    return [
        (c, r)
        for c in range(W)
        for r in range(H)
        if (c, r) != (0, 0) and hs[c] > r
    ]


def do_move(pos, move):
    c, r = move
    hs = _heights(pos)
    return _pack([min(h, r) if i >= c else h for i, h in enumerate(hs)])


def primitive(pos):
    return "LOSE" if pos == 1 else "UNDECIDED"


def level_of(pos):
    return W * H - sum(_heights(pos))


max_level_jump = W * H - 1
num_levels = W * H
