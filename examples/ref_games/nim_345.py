"""Nim with heaps (3, 4, 5) as a reference-style scalar module.

Positions use the same packed encoding as gamesmanmpi_tpu.games.nim (3 bits
per heap here) so tables can be compared entry-for-entry.
"""

HEAPS = (3, 4, 5)
BITS = 3
_MASK = (1 << BITS) - 1

initial_position = sum(h << (i * BITS) for i, h in enumerate(HEAPS))


def _heaps(pos):
    return [(pos >> (i * BITS)) & _MASK for i in range(len(HEAPS))]


def gen_moves(pos):
    moves = []
    for i, h in enumerate(_heaps(pos)):
        for take in range(1, h + 1):
            moves.append((i, take))
    return moves


def do_move(pos, move):
    i, take = move
    return pos - (take << (i * BITS))


def primitive(pos):
    return "LOSE" if pos == 0 else "UNDECIDED"
