"""Connect-4 on a 4x4 board as a reference-style scalar module.

Same guard-bit column encoding as gamesmanmpi_tpu.games.connect4 (5 bits per
column: stones of the player to move below a guard bit at the column height),
so tables can be compared entry-for-entry with the tensor engine.
"""

W, H, K = 4, 4, 4
H1 = H + 1
_COL = (1 << H1) - 1

initial_position = sum(1 << (c * H1) for c in range(W))


def _decompose(pos):
    guards = filled = 0
    for c in range(W):
        colv = (pos >> (c * H1)) & _COL
        g = 1 << (colv.bit_length() - 1)
        guards |= g << (c * H1)
        filled |= ((g - 1) & _COL) << (c * H1)
    current = pos ^ guards
    return guards, filled, current, filled ^ current


def gen_moves(pos):
    guards, _, _, _ = _decompose(pos)
    return [c for c in range(W) if not (guards >> (c * H1 + H)) & 1]


def do_move(pos, move):
    guards, _, _, opponent = _decompose(pos)
    g = guards & (_COL << (move * H1))
    return opponent | (guards + g)


def _connected(stones):
    for d in (1, H, H1, H + 2):
        x = stones
        for i in range(1, K):
            x &= stones >> (d * i)
        if x:
            return True
    return False


_FULL = sum(((1 << H) - 1) << (c * H1) for c in range(W))


def primitive(pos):
    _, filled, _, opponent = _decompose(pos)
    if _connected(opponent):
        return "LOSE"
    if filled == _FULL:
        return "TIE"
    return "UNDECIDED"
