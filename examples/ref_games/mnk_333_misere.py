"""Misere 3,3,3 tic-tac-toe as a reference-style scalar game module.

Same plugin shape and bit layout as tictactoe.py (X plane bits 0-8,
O plane bits 9-17, cell = row * 3 + col) with the misere convention:
completing three-in-a-row LOSES for its maker, so the player to move
facing a completed line has WON. The compiled counterpart is
examples/specs/mnk_3x3x3_misere.json — the variant exists purely as a
GameSpec (win.misere), no tensorized Python module.
"""

M, N, K = 3, 3, 3
CELLS = M * N

initial_position = 0


def _planes(pos):
    mask = (1 << CELLS) - 1
    return pos & mask, (pos >> CELLS) & mask


def _x_to_move(pos):
    x, o = _planes(pos)
    return bin(x).count("1") == bin(o).count("1")


def gen_moves(pos):
    x, o = _planes(pos)
    occupied = x | o
    return [i for i in range(CELLS) if not (occupied >> i) & 1]


def do_move(pos, move):
    if _x_to_move(pos):
        return pos | (1 << move)
    return pos | (1 << (CELLS + move))


_LINES = []
for r in range(M):
    for c in range(N):
        for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
            rr, cc = r + dr * (K - 1), c + dc * (K - 1)
            if 0 <= rr < M and 0 <= cc < N:
                mask = 0
                for i in range(K):
                    mask |= 1 << ((r + dr * i) * N + (c + dc * i))
                _LINES.append(mask)


def primitive(pos):
    x, o = _planes(pos)
    last = o if _x_to_move(pos) else x
    for line in _LINES:
        if last & line == line:
            return "WIN"  # misere: the line's maker has lost
    if x | o == (1 << CELLS) - 1:
        return "TIE"
    return "UNDECIDED"
