"""The 1-2-...-10 subtraction game ("ten to zero") as a reference-style module.

Shape of a swerwath/GamesmanMPI game plugin (SURVEY.md §2.2: games/1210.py).
Subtract 1 or 2 from the count; whoever faces 0 has lost (normal play).
"""

initial_position = 10
MOVES = (1, 2)


def gen_moves(pos):
    return [m for m in MOVES if pos >= m]


def do_move(pos, move):
    return pos - move


def primitive(pos):
    return "LOSE" if pos == 0 else "UNDECIDED"
