"""Gomoku (exact-3, overline forbidden) on 4x3 as a scalar game module.

Reference-style plugin shape (SURVEY.md §2.1.1): plain-int positions,
`initial_position` / `gen_moves` / `do_move` / `primitive`. The bit
layout matches the compiled examples/specs/gomoku_4x3x3.json game
(X plane bits 0-11, O plane bits 12-23, cell = row * 4 + col) so the
oracle's full table can be compared against the engine DB.

The win predicate is gomoku's exact-k rule: a 3-window only wins when
neither on-board extension cell belongs to the mover — a run of four
(an overline) does NOT win. On a width-4 board horizontal overlines
exist, so this differs from plain 3-in-a-row; the rule is inexpressible
in the hand-written m,n,k module and exists here purely as a GameSpec.
"""

M, N, K = 3, 4, 3
CELLS = M * N

initial_position = 0


def _planes(pos):
    mask = (1 << CELLS) - 1
    return pos & mask, (pos >> CELLS) & mask


def _x_to_move(pos):
    x, o = _planes(pos)
    return bin(x).count("1") == bin(o).count("1")


def gen_moves(pos):
    x, o = _planes(pos)
    occupied = x | o
    return [i for i in range(CELLS) if not (occupied >> i) & 1]


def do_move(pos, move):
    if _x_to_move(pos):
        return pos | (1 << move)
    return pos | (1 << (CELLS + move))


# (win_mask, forbid_mask) per 3-window: forbid holds the on-board cells
# immediately before and after the window along its direction.
_LINES = []
for r in range(M):
    for c in range(N):
        for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
            rr, cc = r + dr * (K - 1), c + dc * (K - 1)
            if not (0 <= rr < M and 0 <= cc < N):
                continue
            win = 0
            for i in range(K):
                win |= 1 << ((r + dr * i) * N + (c + dc * i))
            forbid = 0
            for fr, fc in ((r - dr, c - dc), (r + dr * K, c + dc * K)):
                if 0 <= fr < M and 0 <= fc < N:
                    forbid |= 1 << (fr * N + fc)
            _LINES.append((win, forbid))


def primitive(pos):
    x, o = _planes(pos)
    last = o if _x_to_move(pos) else x
    for win, forbid in _LINES:
        if last & win == win and last & forbid == 0:
            return "LOSE"
    if x | o == (1 << CELLS) - 1:
        return "TIE"
    return "UNDECIDED"
