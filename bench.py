#!/usr/bin/env python
"""Benchmark: positions-solved/sec/chip (BASELINE.json tracked metric).

Runs a full strong solve of a Connect-4 board on the available accelerator
and reports throughput over the complete solve (forward discovery + backward
value/remoteness propagation, all reachable positions).

Board selection: BASELINE.json's primary-metric config is Connect-4 6x6 on a
v4-16; on a single chip we default to the largest board that solves in a
benchmark-friendly time and raise it as kernels speed up (override with
BENCH_GAME). The metric (positions/sec/chip) is comparable across boards.

`vs_baseline`: the reference publishes no numbers (BASELINE.md), so the ratio
is computed against the north-star-implied per-chip rate: 4.5e12 states in
1 hour on 32 chips = 39.06M positions/sec/chip. vs_baseline = value / 39.06e6.

Failure isolation: this container's TPU is reached through an "axon" PJRT
plugin over a localhost relay, which has two observed failure modes —
(a) wedging at first backend touch (hangs, no error) and (b) its compile
service dying MID-RUN (every subsequent RPC raises Connection refused;
observed round 3 after ~35 min of a run). A benchmark that crashes or hangs
leaves the driver with no BENCH record at all, so the measurement itself
runs in a CHILD process with a wall-clock deadline: the parent probes the
backend first (with faulthandler stack dumps on hang), runs the child, and
on any child failure/timeout re-runs it pinned to CPU. The JSON line always
appears, and `device`/`fallback_cpu` record which platform actually ran.

Prints exactly ONE JSON line on stdout; everything else goes to stderr.
"""

import json
import os
import statistics
import subprocess
import sys
import threading
import time

# BASELINE.md's north star: 4.5e12 positions in 1h on 32 chips.
NORTH_STAR_PPS = 4.5e12 / 3600.0 / 32.0  # 39.06M pos/s/chip


def _is_feature_spam(line: str) -> bool:
    """XLA's host-feature-mismatch warning: a single multi-hundred-char
    line enumerating every CPU feature flag, emitted at backend init. It
    dwarfed the actual run lines in BENCH_r05.json's driver-captured
    stderr tail (ISSUE 14), carrying zero signal for this workload —
    filter it out of everything this script forwards."""
    return (
        "host machine features" in line
        or "could lead to execution errors" in line
        or ("+sse" in line and "-amx" in line)
    )


def _filter_spam(text: str) -> str:
    """Drop feature-mismatch spam lines from a captured stderr blob."""
    return "".join(
        line for line in text.splitlines(keepends=True)
        if not _is_feature_spam(line)
    )


def _pump_filtered(src, dst) -> None:
    """Forward a child's stderr line by line, minus the feature spam —
    live progress for the operator, a readable tail for the driver."""
    try:
        for line in src:
            if not _is_feature_spam(line):
                dst.write(line)
                dst.flush()
    except ValueError:  # dst closed during interpreter teardown
        pass

# DELIBERATE TWIN of gamesmanmpi_tpu/utils/platform.py's _PROBE_SRC (the
# CLI's fail-fast probe): this parent must never import jax, and the
# package __init__ imports jax at module level, so the source cannot be
# shared by import — a fix to either copy must be mirrored in the other.
_PROBE_SRC = r"""
import faulthandler, sys, time
# If init wedges, print every thread's stack to stderr before the parent's
# deadline so the parent can capture *where* it hung (relay dial, compile
# RPC, device enumeration, ...).
faulthandler.dump_traceback_later({dump_after}, exit=False, file=sys.stderr)
t0 = time.time()
import jax
print(f"probe: jax imported in {{time.time()-t0:.1f}}s", file=sys.stderr)
t0 = time.time()
devs = jax.devices()
print(f"probe: jax.devices() -> {{devs}} in {{time.time()-t0:.1f}}s",
      file=sys.stderr)
import jax.numpy as jnp
t0 = time.time()
x = jnp.arange(1024, dtype=jnp.uint32)
y = jnp.sort(x).block_until_ready()
print(f"probe: first kernel in {{time.time()-t0:.1f}}s", file=sys.stderr)
faulthandler.cancel_dump_traceback_later()
print("PROBE_OK", devs[0].platform)
"""


def _probe_accelerator(timeout: float) -> str | None:
    """Probe backend init in a throwaway subprocess; return its platform.

    Returns the platform string ("tpu"/"axon"/...) on success, None on
    failure/hang. On a hang the child's faulthandler stack dump (written
    shortly before the deadline) is forwarded to stderr — the evidence
    VERDICT.md round 1 asked for.
    """
    src = _PROBE_SRC.format(dump_after=max(timeout - 15.0, 5.0))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", src],
            timeout=timeout, capture_output=True, text=True,
        )
        if proc.stderr:
            sys.stderr.write(_filter_spam(proc.stderr))
        if proc.returncode == 0:
            for line in proc.stdout.splitlines():
                if line.startswith("PROBE_OK"):
                    return line.split()[1]
        print(f"probe: child exited rc={proc.returncode}", file=sys.stderr)
        return None
    except subprocess.TimeoutExpired as e:
        # The faulthandler dump fires before this deadline; forward it.
        for stream in (e.stderr, e.stdout):
            if stream:
                sys.stderr.write(_filter_spam(
                    stream if isinstance(stream, str) else stream.decode()
                ))
        print(f"probe: timed out after {timeout:.0f}s (stacks above)",
              file=sys.stderr)
        return None


def _last_json(text: str | bytes | None) -> dict | None:
    """Parse the LAST JSON object line out of a child's stdout."""
    if not text:
        return None
    if isinstance(text, bytes):
        text = text.decode(errors="replace")
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_inner(deadline: float, cpu: bool) -> dict | None:
    """Run the measurement child; return its parsed JSON record or None.

    The child inherits stderr (live progress); stdout is captured and the
    last JSON object line wins. The child prints its PRIMARY record as soon
    as the primary solves finish and an enriched record at the end, so a
    relay that dies or wedges during the optional sym/ladder extras (the
    longest solves) costs the extras, not the primary measurement: both the
    nonzero-exit and the timeout path salvage the last JSON line written.
    """
    env = dict(os.environ)
    if cpu:
        env["GAMESMAN_PLATFORM"] = "cpu"
    # stderr is PIPED through a filter thread (live forwarding minus the
    # XLA host-feature spam — see _is_feature_spam) instead of inherited;
    # stdout is collected on a second thread so the deadline kill can
    # still salvage everything written before it.
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--inner"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    collected: list = []
    t_err = threading.Thread(
        target=_pump_filtered, args=(proc.stderr, sys.stderr), daemon=True
    )
    t_out = threading.Thread(
        target=lambda: collected.append(proc.stdout.read()), daemon=True
    )
    t_err.start()
    t_out.start()
    try:
        rc = proc.wait(timeout=deadline)
    except subprocess.TimeoutExpired:
        print(f"bench child: exceeded {deadline:.0f}s deadline, killed",
              file=sys.stderr)
        proc.kill()
        proc.wait()
        rc = -1
    t_out.join(timeout=30.0)
    t_err.join(timeout=30.0)
    out = collected[0] if collected else ""
    record = _last_json(out)
    if rc != 0:
        print(f"bench child: exited rc={rc}"
              + (" (salvaged partial record)" if record else ""),
              file=sys.stderr)
    if record is None and rc == 0:
        print("bench child: produced no JSON record", file=sys.stderr)
    return record


def _env_float(name: str, default: float) -> float:
    """Parse a float env knob; a malformed value must not kill the parent
    (the whole point of the parent is that a JSON line always appears)."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        print(f"{name} is not a number; using {default}", file=sys.stderr)
        return default


def _multiprocess_smoke() -> dict | None:
    """BENCH_PROCESSES=2: a real N-process sharded solve through
    tools/launch_multihost.py, folded into a MULTICHIP_r06.json-style
    artifact (BENCH_PROCESSES_OUT) with per-rank level times — the
    distributed path's perf trajectory before the big multi-host runs.

    Runs in the PARENT (the harness is subprocess-only, so this side
    never touches jax) and must never kill the bench: any failure is
    recorded in the artifact and the summary, not raised.
    """
    try:
        procs = int(os.environ.get("BENCH_PROCESSES", "0"))
    except ValueError:
        print("BENCH_PROCESSES is not a number; skipping", file=sys.stderr)
        return None
    if procs <= 1:
        return None
    import tempfile

    from tools.launch_multihost import DEFAULT_LOCAL_DEVICES, launch

    spec = os.environ.get("BENCH_MP_GAME", "connect4:w=4,h=4")
    out_path = os.environ.get("BENCH_PROCESSES_OUT", "MULTICHIP_mp.json")
    shards = procs * DEFAULT_LOCAL_DEVICES
    artifact = {
        "processes": procs, "shards": shards, "game": spec, "ok": False,
    }
    t0 = time.perf_counter()
    try:
        with tempfile.TemporaryDirectory(prefix="bench_mp_") as td:
            jsonl = os.path.join(td, "m.jsonl")
            ranks = launch(
                [spec, "--devices", str(shards), "--no-tables",
                 "--jsonl", jsonl],
                processes=procs, timeout=_env_float(
                    "GAMESMAN_BENCH_DEADLINE", 3000.0),
                log_dir=td,
            )
            artifact["rc_by_rank"] = [r.returncode for r in ranks]
            artifact["secs_wall"] = round(time.perf_counter() - t0, 3)
            for r in ranks:
                if r.returncode != 0:
                    artifact["error"] = (
                        f"rank {r.rank} rc={r.returncode}: "
                        + r.stderr[-1500:]
                    )
                    return artifact
            # Per-rank level times from the rank-stamped JSONL streams
            # (the rank label is why they merge unambiguously).
            levels: dict = {}
            done: dict = {}
            for rank in range(procs):
                path = os.path.join(td, f"m.rank{rank}.jsonl")
                with open(path) as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        phase = rec.get("phase")
                        if phase in ("forward", "backward",
                                     "backward_edges") and "level" in rec:
                            row = levels.setdefault(
                                int(rec["level"]),
                                {"fwd_secs": {}, "bwd_secs": {}},
                            )
                            col = ("fwd_secs" if phase == "forward"
                                   else "bwd_secs")
                            row[col][str(rank)] = round(
                                row[col].get(str(rank), 0.0)
                                + float(rec.get("secs", 0.0)), 4)
                        elif phase == "done":
                            done[str(rank)] = {
                                "positions": rec.get("positions"),
                                "secs_total": round(
                                    rec.get("secs_total", 0.0), 3),
                            }
            artifact["levels"] = [
                {"level": k, **levels[k]} for k in sorted(levels)
            ]
            artifact["done_by_rank"] = done
            positions = max(
                (d.get("positions") or 0 for d in done.values()),
                default=0,
            )
            artifact["positions"] = positions
            artifact["positions_per_sec"] = round(
                positions / max(artifact["secs_wall"], 1e-9), 1)
            artifact["ok"] = True
    except Exception as e:  # noqa: BLE001 - the bench must survive this
        artifact["error"] = f"{type(e).__name__}: {e}"
    finally:
        artifact.setdefault("secs_wall",
                            round(time.perf_counter() - t0, 3))
        try:
            with open(out_path, "w") as fh:
                json.dump(artifact, fh, indent=1)
            print(f"multiprocess smoke: wrote {out_path} "
                  f"(ok={artifact['ok']})", file=sys.stderr)
        except OSError as e:
            print(f"multiprocess smoke: cannot write {out_path}: {e}",
                  file=sys.stderr)
    return artifact


def _launch_fleet(db: str, workers: int, env: dict | None = None,
                  extra_args: list | None = None):
    """Launch `cli serve --workers N` on ephemeral ports and wait until
    the fleet reports ready — the subprocess choreography _serve_bench
    and _db_compress_bench share (bounded banner read: a supervisor that
    wedges before its banner must fail the bench into the artifact, not
    hang it; every other wait is deadline-bounded too).

    -> {"proc", "port", "cport", "status"} on success (caller owns
    SIGTERM/kill teardown of proc), or {"error": ..., "proc": ...} —
    proc may be live on the error path and must still be torn down.
    """
    import json as _json
    import threading
    import urllib.request

    proc = subprocess.Popen(
        [sys.executable, "-m", "gamesmanmpi_tpu.cli", "serve", db,
         "--port", "0", "--workers", str(workers),
         "--control-port", "0", *(extra_args or [])],
        stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, **env) if env else None,
    )
    try:
        got: list = []
        t = threading.Thread(
            target=lambda: got.append(proc.stdout.readline()), daemon=True
        )
        t.start()
        t.join(120.0)
        if not got or not got[0]:
            return {"error": "fleet supervisor printed no banner",
                    "proc": proc}
        banner = got[0]
        port = int(banner.split("http://127.0.0.1:")[1].split(" ")[0])
        cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
        ready_deadline = time.monotonic() + 180.0
        status = {}
        while time.monotonic() < ready_deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{cport}/healthz", timeout=10
                ) as resp:
                    status = _json.loads(resp.read())
            except OSError:
                status = {}  # control port not accepting yet — keep polling
            if status.get("status") == "ok":
                break
            time.sleep(0.25)
        if status.get("status") != "ok":
            return {"error": f"fleet never became ready: {status}",
                    "proc": proc}
        return {"proc": proc, "port": port, "cport": cport,
                "status": status}
    except BaseException:
        # Unanticipated failure (malformed banner, poll crash): the
        # caller never sees `proc`, so nothing downstream could tear the
        # fleet down — kill it HERE or it outlives the bench.
        proc.kill()
        proc.wait()
        raise


def _serve_bench() -> dict | None:
    """BENCH_SERVE=1: the serving-fleet SLO benchmark (ROADMAP item 3).

    Exports a DB (child process), launches the supervised fleet
    (`cli serve --workers N`), drives concurrent query load through
    tools/load_gen for BENCH_SERVE_SECS, SIGKILLs one worker mid-load
    (BENCH_SERVE_CHAOS=0 disables), and gates on the latency SLO:
    p99-under-load <= BENCH_SERVE_SLO_P99_MS with zero dropped requests
    beyond the killed worker's in-flight budget and zero answer
    mismatches. The full record lands in BENCH_SERVE_OUT
    (BENCH_serve.json) — the p99-under-load trajectory next to the
    solve-throughput BENCH_*.json one.

    Runs in the PARENT (jax-free: load_gen is stdlib-only and the DB
    positions are read with plain numpy) and must never kill the bench:
    failures are recorded in the artifact, not raised.
    """
    if os.environ.get("BENCH_SERVE", "0") in ("0", "", "off"):
        return None
    import signal
    import tempfile
    import threading
    import urllib.request

    from tools.load_gen import run_load

    spec = os.environ.get("BENCH_SERVE_GAME", "connect4:w=4,h=4")
    workers = int(_env_float("BENCH_SERVE_WORKERS", 2))
    duration = _env_float("BENCH_SERVE_SECS", 10.0)
    conc = int(_env_float("BENCH_SERVE_CONC", 8))
    slo_ms = _env_float("BENCH_SERVE_SLO_P99_MS", 250.0)
    chaos = os.environ.get("BENCH_SERVE_CHAOS", "1") not in ("0", "off")
    out_path = os.environ.get("BENCH_SERVE_OUT", "BENCH_serve.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    artifact = {
        "game": spec, "workers": workers, "concurrency": conc,
        "slo_p99_ms": slo_ms, "chaos": chaos, "ok": False,
    }

    def _get_json(url):
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read())

    proc = None
    t0 = time.perf_counter()
    try:
        with tempfile.TemporaryDirectory(prefix="bench_serve_") as td:
            db = os.path.join(td, "db")
            export = subprocess.run(
                [sys.executable, "-m", "gamesmanmpi_tpu.cli", "export-db",
                 spec, "--out", db],
                timeout=deadline, capture_output=True, text=True,
            )
            if export.returncode != 0:
                artifact["error"] = "export-db failed: " \
                    + export.stderr[-1000:]
                return artifact
            fleet = _launch_fleet(db, workers)
            proc = fleet.get("proc")
            if "error" in fleet:
                artifact["error"] = fleet["error"]
                return artifact
            port, cport = fleet["port"], fleet["cport"]
            control = f"http://127.0.0.1:{cport}"
            artifact["spawn_mode"] = fleet["status"].get("spawn_mode")
            positions = _db_sample_positions(db)
            killed = {}

            def _chaos():
                try:
                    time.sleep(max(0.5, min(duration / 2, duration - 1)))
                    st = _get_json(control + "/healthz")
                    for idx, w in st.get("workers", {}).items():
                        if w.get("state") == "ready" and w.get("pid"):
                            killed["worker"] = idx
                            killed["pid"] = w["pid"]
                            killed["at"] = time.monotonic()
                            os.kill(w["pid"], signal.SIGKILL)
                            return
                    killed["error"] = "no ready worker to kill"
                except Exception as e:  # noqa: BLE001 - recorded below
                    killed["error"] = f"{type(e).__name__}: {e}"

            if chaos:
                threading.Thread(target=_chaos, daemon=True).start()
            load = run_load(
                f"http://127.0.0.1:{port}", positions,
                duration=duration, concurrency=conc,
            )
            load.pop("answers", None)
            artifact.update(load)
            if chaos and "pid" not in killed:
                # The kill never fired: say WHY the chaos gate fails
                # instead of an unexplained ok=False.
                artifact["error"] = "chaos kill did not fire: " + \
                    killed.get("error", "kill thread never ran")
            if chaos and killed.get("pid"):
                recover_deadline = time.monotonic() + 60.0
                recovered = None
                while time.monotonic() < recover_deadline:
                    st = _get_json(control + "/healthz")
                    w = st["workers"].get(killed["worker"], {})
                    if w.get("state") == "ready" \
                            and w.get("pid") != killed["pid"]:
                        recovered = time.monotonic() - killed["at"]
                        break
                    time.sleep(0.2)
                st = _get_json(control + "/healthz")
                artifact["worker_restarts"] = sum(
                    w.get("restarts", 0)
                    for w in st.get("workers", {}).values()
                )
                artifact["killed_worker"] = killed["worker"]
                artifact["recovered_secs"] = (
                    None if recovered is None else round(recovered, 2)
                )
            artifact["slo_ok"] = artifact.get("p99_ms", 1e9) <= slo_ms
            # The shed budget: a SIGKILLed worker may drop its in-flight
            # requests (at most the client concurrency) — more dropped
            # than that means requests failed that chaos cannot excuse.
            artifact["drop_budget"] = conc if chaos else 0
            artifact["ok"] = bool(
                artifact["slo_ok"]
                and artifact.get("mismatches", 1) == 0
                and artifact.get("errors", 1) == 0
                and artifact.get("dropped", 0) <= artifact["drop_budget"]
                and (not chaos or artifact.get("recovered_secs") is not None)
            )
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
            proc = None
            ab = _serve_trace_ab(db, workers, conc, positions)
            if ab is not None:
                artifact["trace_ab"] = ab
    except Exception as e:  # noqa: BLE001 - the bench must survive this
        artifact["error"] = f"{type(e).__name__}: {e}"
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        artifact.setdefault("secs_wall", round(time.perf_counter() - t0, 3))
        try:
            with open(out_path, "w") as fh:
                json.dump(artifact, fh, indent=1)
            print(f"serve bench: wrote {out_path} (ok={artifact['ok']})",
                  file=sys.stderr)
        except OSError as e:
            print(f"serve bench: cannot write {out_path}: {e}",
                  file=sys.stderr)
    return artifact


def _serve_trace_ab(db: str, workers: int, conc: int,
                    positions) -> dict | None:
    """BENCH_SERVE_TRACE_AB=1 (default on under BENCH_SERVE): the
    tracing-overhead A/B arm (ISSUE 17).

    Two fresh chaos-free fleets over the already-exported DB — one with
    query tracing on (the default), one with GAMESMAN_TRACE=0 in the
    fleet's environment — each driven by the same load shape. The gate
    (checked by tools/bench_compare.py): tracing-on p99 must stay within
    BENCH_SERVE_TRACE_MAX_PCT (5%) of tracing-off, with
    BENCH_SERVE_TRACE_SLACK_MS (2 ms) of absolute slack so a
    sub-millisecond p99 doesn't fail the ratio on scheduler noise.
    Sampling is tail-based, so the on-arm cost is span bookkeeping on
    every request — exactly what this arm bounds.
    """
    if os.environ.get("BENCH_SERVE_TRACE_AB", "1") in ("0", "", "off"):
        return None
    import signal

    from tools.load_gen import run_load

    secs = _env_float("BENCH_SERVE_AB_SECS", 5.0)
    max_pct = _env_float("BENCH_SERVE_TRACE_MAX_PCT", 5.0)
    slack_ms = _env_float("BENCH_SERVE_TRACE_SLACK_MS", 2.0)
    ab: dict = {"max_delta_pct": max_pct, "slack_ms": slack_ms,
                "secs": secs, "ok": False}
    arms: dict = {}
    for arm, env in (("on", {"GAMESMAN_TRACE": "1"}),
                     ("off", {"GAMESMAN_TRACE": "0"})):
        fleet = _launch_fleet(db, workers, env=env)
        proc = fleet.get("proc")
        try:
            if "error" in fleet:
                ab["error"] = f"{arm} arm: {fleet['error']}"
                return ab
            load = run_load(
                f"http://127.0.0.1:{fleet['port']}", positions,
                duration=secs, concurrency=conc,
            )
            arms[arm] = {
                "p50_ms": load["p50_ms"], "p99_ms": load["p99_ms"],
                "qps": load["qps"], "requests": load["requests"],
                "errors": load["errors"], "dropped": load["dropped"],
            }
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
            proc = None
        finally:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
    ab.update(arms)
    on, off = arms["on"]["p99_ms"], arms["off"]["p99_ms"]
    ab["delta_pct"] = round((on - off) / max(off, 1e-9) * 100.0, 2)
    ab["ok"] = bool(on <= off * (1.0 + max_pct / 100.0) + slack_ms)
    return ab


def _serve_hot_bench() -> dict | None:
    """BENCH_SERVE_HOT=1: the serving hot-path A/B (ISSUE 18).

    One compressed DB with a sealed opening book, two fresh fork-mode
    fleets over it, the same deterministic zipf request stream on each:

    * **baseline** — ``GAMESMAN_SHM_CACHE_MB=0`` +
      ``GAMESMAN_SERVE_BOOK=0``: every query runs the full path
      (canonicalize, private-cache block decode);
    * **hot** — the defaults: book hits answered from resident arrays,
      block decodes published to the cross-worker shared-memory cache,
      batcher dedup collapsing the zipf head.

    Both arms squeeze the PRIVATE decoded-block cache
    (``GAMESMAN_DB_CACHE_MB``) so the DB does not fit one worker's
    RAM — the deployment the shared tier exists for. Gates: zero
    errors/dropped/mismatches on both arms, hot qps >= baseline AND
    hot p99 <= baseline, book hits > 0, shm hits > 0, and a
    conditional-GET pass on the hot arm revalidating (304) with zero
    errors. Artifact -> BENCH_SERVE_HOT_OUT (BENCH_serve_hot.json),
    gated by tools/bench_compare.py's check_serve_hot. Runs in the
    PARENT (subprocess + stdlib load_gen only; never touches jax).
    """
    if os.environ.get("BENCH_SERVE_HOT", "0") in ("0", "", "off"):
        return None
    import signal
    import tempfile
    import urllib.request

    from tools.load_gen import run_load

    # The board must produce a DB that does NOT fit the squeezed
    # private cache — a toy DB is resident everywhere, probes cost
    # nothing, and the hot tiers read as pure overhead.
    spec = os.environ.get("BENCH_SERVE_HOT_GAME", "connect4:w=5,h=4")
    workers = int(_env_float("BENCH_SERVE_HOT_WORKERS", 2))
    secs = _env_float("BENCH_SERVE_HOT_SECS", 8.0)
    conc = int(_env_float("BENCH_SERVE_HOT_CONC", 8))
    zipf_s = _env_float("BENCH_SERVE_HOT_ZIPF_S", 1.1)
    plies = int(_env_float("BENCH_SERVE_HOT_BOOK_PLIES", 4))
    cache_mb = os.environ.get("BENCH_SERVE_HOT_DB_CACHE_MB", "1")
    # Both arms get a bounded answer LRU: on a toy bench DB the default
    # 65536-entry cache would swallow the whole sampled position set,
    # hiding the probe path the hot tiers exist to accelerate (a real
    # DB's query space dwarfs any per-worker answer cache).
    lru = os.environ.get("BENCH_SERVE_HOT_CACHE_SIZE", "256")
    # Single-position requests: the interactive regime the hot path
    # targets. A request is only exempt from the batcher window when
    # EVERY position in it is book-answered, so multi-position chunks
    # would re-impose the window wait on the whole zipf head.
    chunk = int(_env_float("BENCH_SERVE_HOT_CHUNK", 1))
    # Tight coalescing window, both arms: at interactive chunk=1 depth a
    # wide window makes every request's latency mostly *waiting for
    # strangers*, drowning the probe costs the A/B exists to compare.
    window_ms = _env_float("BENCH_SERVE_HOT_WINDOW_MS", 0.5)
    out_path = os.environ.get("BENCH_SERVE_HOT_OUT", "BENCH_serve_hot.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    dist = f"zipf:{zipf_s:g}"
    hot: dict = {
        "bench": "serve_hot_ab", "game": spec, "workers": workers,
        "concurrency": conc, "dist": dist, "book_plies": plies,
        "db_cache_mb": cache_mb, "cache_size": lru, "chunk": chunk,
        "window_ms": window_ms, "secs": secs, "ok": False,
    }
    artifact = {
        "metric": "serve_hot_qps", "value": 0.0,
        "device": os.environ.get("GAMESMAN_PLATFORM", "cpu"),
        "serve_hot": hot,
    }
    counters_wanted = (
        "gamesman_book_hits_total", "gamesman_shm_hits_total",
        "gamesman_shm_misses_total", "gamesman_shm_stores_total",
        "gamesman_shm_evictions_total", "gamesman_batch_dup_hits_total",
    )

    def _scrape_counters(url: str) -> dict:
        """Max-over-scrapes of the hot-path counters: each /metrics GET
        lands on whichever worker accepts it (registries are
        per-process), so repeated one-shot connections sample the fleet
        and the max proves at least one worker crossed zero."""
        best = {n: 0.0 for n in counters_wanted}
        for _ in range(max(4, workers * 4)):
            req = urllib.request.Request(
                url + "/metrics", headers={"Connection": "close"}
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    text = resp.read().decode()
            except OSError:
                continue
            cur = {n: 0.0 for n in counters_wanted}
            for line in text.splitlines():
                if line.startswith("#"):
                    continue
                for n in counters_wanted:
                    if line.startswith(n + "{") or line.startswith(n + " "):
                        try:
                            cur[n] += float(line.rsplit(" ", 1)[1])
                        except ValueError:
                            pass
            for n in counters_wanted:
                best[n] = max(best[n], cur[n])
        return best

    t0 = time.perf_counter()
    try:
        with tempfile.TemporaryDirectory(prefix="bench_serve_hot_") as td:
            db = os.path.join(td, "db")
            export = subprocess.run(
                [sys.executable, "-m", "gamesmanmpi_tpu.cli", "export-db",
                 spec, "--out", db, "--compress",
                 "--book-plies", str(plies)],
                timeout=deadline, capture_output=True, text=True,
            )
            if export.returncode != 0:
                hot["error"] = "export-db failed: " + export.stderr[-1000:]
                return artifact
            # A wide sample (all blocks, thousands of positions): the
            # zipf head must overflow the bounded answer LRU and the
            # tail must overflow the squeezed private block cache, or
            # neither arm ever probes after warmup and the A/B measures
            # nothing but fixed HTTP overhead.
            positions = _db_sample_positions(db, per_level=256, cap=4096)
            if not positions:
                hot["error"] = "no positions sampled from the DB"
                return artifact
            shared_env = {"GAMESMAN_DB_CACHE_MB": cache_mb}
            arm_envs = {
                "baseline": dict(shared_env, GAMESMAN_SHM_CACHE_MB="0",
                                 GAMESMAN_SERVE_BOOK="0"),
                "hot": shared_env,
            }
            for arm, env in arm_envs.items():
                fleet = _launch_fleet(
                    db, workers, env=env,
                    extra_args=["--cache-size", lru,
                                "--batch-window-ms", f"{window_ms:g}"],
                )
                proc = fleet.get("proc")
                try:
                    if "error" in fleet:
                        hot["error"] = f"{arm} arm: {fleet['error']}"
                        return artifact
                    hot.setdefault(
                        "spawn_mode", fleet["status"].get("spawn_mode")
                    )
                    url = f"http://127.0.0.1:{fleet['port']}"
                    load = run_load(
                        url, positions, duration=secs, concurrency=conc,
                        chunk_size=chunk, dist=dist, seed=18,
                    )
                    hot[arm] = {
                        k: load[k] for k in
                        ("qps", "p50_ms", "p95_ms", "p99_ms", "requests",
                         "ok", "shed", "errors", "dropped", "mismatches")
                    }
                    if arm == "hot":
                        # Same fleet, same zipf stream, conditional GETs:
                        # the edge-cacheable form must revalidate (304)
                        # without a single wrong or failed answer.
                        get = run_load(
                            url, positions, concurrency=conc,
                            duration=max(2.0, secs / 2), dist=dist,
                            mode="get", seed=18,
                        )
                        hot["get"] = {
                            k: get[k] for k in
                            ("qps", "p99_ms", "requests", "ok",
                             "not_modified", "errors", "dropped",
                             "mismatches")
                        }
                        hot["counters"] = _scrape_counters(url)
                    proc.send_signal(signal.SIGTERM)
                    proc.wait(timeout=60)
                    proc = None
                finally:
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                        proc.wait()
            base, hotarm = hot["baseline"], hot["hot"]
            ctr, get = hot["counters"], hot["get"]
            hot["clean"] = all(
                a["errors"] == 0 and a["dropped"] == 0
                and a["mismatches"] == 0 for a in (base, hotarm)
            )
            hot["perf_ok"] = bool(
                hotarm["qps"] >= base["qps"]
                and hotarm["p99_ms"] <= base["p99_ms"]
            )
            hot["book_hits"] = ctr["gamesman_book_hits_total"]
            hot["shm_hits"] = ctr["gamesman_shm_hits_total"]
            hot["hits_ok"] = bool(
                hot["book_hits"] > 0 and hot["shm_hits"] > 0
            )
            hot["get_ok"] = bool(
                get["errors"] == 0 and get["dropped"] == 0
                and get["mismatches"] == 0 and get["not_modified"] > 0
            )
            hot["ok"] = bool(
                hot["clean"] and hot["perf_ok"] and hot["hits_ok"]
                and hot["get_ok"]
            )
            artifact["value"] = hotarm["qps"]
    except Exception as e:  # noqa: BLE001 - the bench must survive this
        hot["error"] = f"{type(e).__name__}: {e}"
    finally:
        hot.setdefault("secs_wall", round(time.perf_counter() - t0, 3))
        try:
            with open(out_path, "w") as fh:
                json.dump(artifact, fh, indent=1)
            print(f"serve hot bench: wrote {out_path} (ok={hot['ok']})",
                  file=sys.stderr)
        except OSError as e:
            print(f"serve hot bench: cannot write {out_path}: {e}",
                  file=sys.stderr)
    return artifact


def _registry_bench() -> dict | None:
    """BENCH_REGISTRY=1: the DB-distribution robustness proof (ISSUE 19).

    Solve-on-demand end to end, through a crash: a registry with an
    empty catalog takes a POST /solve for a missing DB, the job runner
    is SIGKILLed right after its claim record is durable
    (`jobs.claim:kill:1` -> exit 77), a second runner reclaims the dead
    claim and drives campaign -> export-db -> publish; a replica then
    pulls the published epoch (checksums verified before the atomic
    install), a fork-mode fleet serves it, a re-exported epoch B is
    published and synced in under the fleet's rolling reload, and the
    SAME query must answer identically from both epochs with the ETag
    flipping exactly once. Gates: runner kill rc 77, job state
    `running` after the kill and `done` after the resume, the epoch in
    the sealed catalog, a verified install, `reloads_done == 1`, and
    matching answers across the flip. Record lands in
    BENCH_REGISTRY_OUT (BENCH_registry.json).

    Runs in the PARENT (registry/pull/jobs are stdlib+numpy; the
    solves happen in child processes) and must never kill the bench:
    failures are recorded in the artifact, not raised.
    """
    if os.environ.get("BENCH_REGISTRY", "0") in ("0", "", "off"):
        return None
    import signal
    import tempfile
    import threading
    import urllib.request

    spec = os.environ.get("BENCH_REGISTRY_GAME", "subtract:total=10")
    name = os.environ.get("BENCH_REGISTRY_NAME", "sub")
    out_path = os.environ.get("BENCH_REGISTRY_OUT", "BENCH_registry.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    artifact = {"game": spec, "name": name, "ok": False}
    cli = [sys.executable, "-m", "gamesmanmpi_tpu.cli"]
    env = dict(os.environ, GAMESMAN_PLATFORM="cpu")
    env.pop("GAMESMAN_FAULTS", None)

    def _get_json(url):
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read())

    proc = None
    srv = None
    t0 = time.perf_counter()
    try:
        from gamesmanmpi_tpu.registry import (
            JobQueue,
            RegistryServer,
            catalog_seal,
            load_catalog,
            publish_db,
            pull_db,
            sync_fleet,
        )
        from gamesmanmpi_tpu.registry.pull import ensure_db
        from gamesmanmpi_tpu.resilience.faults import KILL_EXIT_CODE

        with tempfile.TemporaryDirectory(prefix="bench_registry_") as td:
            root = os.path.join(td, "registry")
            queue = JobQueue(os.path.join(root, "jobs.jsonl"))
            srv = RegistryServer(root, queue=queue)
            srv.start()

            # 1. Solve-on-demand: the DB does not exist -> queued job.
            job = ensure_db(srv.url, name, spec)
            artifact["enqueued"] = {
                "status": job.get("status"), "job": job.get("id"),
            }

            # 2. Runner SIGKILLed right after its claim is durable.
            kill = subprocess.run(
                cli + ["registry", "run-jobs", "--root", root, "--once"],
                env=dict(env, GAMESMAN_FAULTS="jobs.claim:kill:1"),
                timeout=deadline, capture_output=True, text=True,
            )
            after_kill = list(
                JobQueue(os.path.join(root, "jobs.jsonl")).jobs().values()
            )
            artifact["runner_kill"] = {
                "rc": kill.returncode,
                "job_state": after_kill[0]["state"] if after_kill else None,
            }

            # 3. The next runner reclaims the dead claim and finishes.
            resume = subprocess.run(
                cli + ["registry", "run-jobs", "--root", root, "--once"],
                env=env, timeout=deadline, capture_output=True, text=True,
            )
            after = list(
                JobQueue(os.path.join(root, "jobs.jsonl")).jobs().values()
            )
            cat = load_catalog(root)
            artifact["runner_resume"] = {
                "rc": resume.returncode,
                "job_state": after[0]["state"] if after else None,
                "published": name in cat["dbs"],
                "catalog_sealed": cat["seal"] == catalog_seal(cat["dbs"]),
            }
            if resume.returncode != 0:
                artifact["error"] = "resume runner failed: " \
                    + resume.stderr[-1000:]
                return artifact

            # 4. Replica pull + fleet serve on the pulled epoch.
            dest = os.path.join(td, "dbs")
            pulled = pull_db(srv.url, name, dest)
            artifact["pull"] = {
                k: pulled[k] for k in
                ("epoch", "installed", "resumed_files", "refetched_files")
            }
            manifest = os.path.join(td, "fleet.json")
            with open(manifest, "w") as fh:
                json.dump({"version": 1, "games": [
                    {"name": name, "db": pulled["db"]}]}, fh)
            proc = subprocess.Popen(
                cli + ["serve", "--fleet-manifest", manifest, "--port",
                       "0", "--workers", "2", "--control-port", "0"],
                stdout=subprocess.PIPE, text=True, env=env,
            )
            got: list = []
            t = threading.Thread(
                target=lambda: got.append(proc.stdout.readline()),
                daemon=True,
            )
            t.start()
            t.join(120.0)
            if not got or not got[0]:
                artifact["error"] = "fleet printed no banner"
                return artifact
            banner = got[0]
            port = int(banner.split("http://127.0.0.1:")[1].split(" ")[0])
            cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
            base = f"http://127.0.0.1:{port}"
            control = f"http://127.0.0.1:{cport}"
            ready = time.monotonic() + 180.0
            while time.monotonic() < ready:
                try:
                    if _get_json(control + "/healthz")["status"] == "ok":
                        break
                except (OSError, ValueError, KeyError):
                    pass
                time.sleep(0.25)

            def _query():
                with urllib.request.urlopen(
                        f"{base}/query?p=0x2", timeout=10) as resp:
                    return resp.headers.get("ETag"), json.loads(resp.read())

            etag_a, answer_a = _query()
            artifact["serve"] = {"etag_a": etag_a, "answer_a": answer_a}

            # 5. Epoch B (same content, compressed) rolls in under sync.
            db_b = os.path.join(td, "db_b")
            export = subprocess.run(
                cli + ["export-db", spec, "--out", db_b, "--compress"],
                env=env, timeout=deadline, capture_output=True, text=True,
            )
            if export.returncode != 0:
                artifact["error"] = "epoch B export failed: " \
                    + export.stderr[-1000:]
                return artifact
            publish_db(root, name, db_b)
            sync = sync_fleet(srv.url, [name], manifest, dest,
                              control_url=control)
            artifact["sync"] = {
                "status": sync["status"], "rolled": sync["rolled"],
                "failed": sync["failed"],
            }
            flip = time.monotonic() + 60.0
            status = {}
            while time.monotonic() < flip:
                status = _get_json(control + "/healthz")
                if status.get("reloads_done") == 1 \
                        and status.get("status") == "ok":
                    break
                time.sleep(0.25)
            etag_b, answer_b = _query()
            artifact["serve"].update(etag_b=etag_b, answer_b=answer_b)
            artifact["reloads_done"] = status.get("reloads_done")
            artifact["registry_sync"] = status.get("registry_sync")
            artifact["ok"] = bool(
                artifact["runner_kill"]["rc"] == KILL_EXIT_CODE
                and artifact["runner_kill"]["job_state"] == "running"
                and artifact["runner_resume"]["job_state"] == "done"
                and artifact["runner_resume"]["published"]
                and artifact["runner_resume"]["catalog_sealed"]
                and pulled["installed"]
                and sync["status"] == "rolled"
                and status.get("reloads_done") == 1
                and etag_a and etag_b and etag_a != etag_b
                and answer_a == answer_b
            )
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
            proc = None
    except Exception as e:  # noqa: BLE001 - the bench must survive this
        artifact["error"] = f"{type(e).__name__}: {e}"
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        if srv is not None:
            srv.stop()
        artifact["secs_wall"] = round(time.perf_counter() - t0, 3)
        try:
            with open(out_path, "w") as fh:
                json.dump(artifact, fh, indent=1)
            print(f"registry bench: wrote {out_path} "
                  f"(ok={artifact['ok']})", file=sys.stderr)
        except OSError as e:
            print(f"registry bench: cannot write {out_path}: {e}",
                  file=sys.stderr)
    return artifact


def _store_bench() -> dict | None:
    """BENCH_STORE=1: the block-store I/O-overlap A/B (ISSUE 11).

    One spill-forcing sharded solve — device-store budget 0 so every
    discovered level and edge array leaves HBM, host tier squeezed to a
    few MB so edge arrays drop to the DISK tier (their sealed
    per-(level, shard) files become the only copy) — run twice from a
    cold checkpoint directory:

    * **sync** — `GAMESMAN_STORE_PREFETCH_THREADS=0`,
      `GAMESMAN_STORE_WRITEBEHIND=0`: every sealed read and every
      DEFLATE+fsync blocks the solve thread, exactly the pre-store
      code's behavior; `io_wait_secs` is the full I/O bill.
    * **prefetch** — the store's defaults: the backward schedule's
      readahead hints decode the next level's edge/checkpoint shards
      while the current level computes, and payload writes ride the
      write-behind worker.

    Gates: the prefetch arm's `io_wait_secs` strictly below the sync
    arm's, and the two `--table-out` tables byte-identical (the overlap
    must change WHEN bytes move, never WHICH bytes). Runs in the PARENT
    (subprocess-only, never touches jax); any failure is recorded, not
    raised. Full record → BENCH_STORE_OUT; summary joins the bench
    record under `store`.
    """
    if os.environ.get("BENCH_STORE", "0") in ("0", "", "off"):
        return None
    import tempfile

    import numpy as np

    spec = os.environ.get("BENCH_STORE_GAME", "connect4:w=4,h=4")
    shards = int(_env_float("BENCH_STORE_SHARDS", 2))
    out_path = os.environ.get("BENCH_STORE_OUT", "BENCH_store.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    record: dict = {
        "bench": "store_prefetch_ab",
        "spec": spec,
        "shards": shards,
        "config": {
            "GAMESMAN_DEVICE_STORE_MB": "0",
            "GAMESMAN_STORE_CACHE_MB": "4",
        },
    }

    def _arm(name: str, workdir: str, env: dict) -> dict:
        table = os.path.join(workdir, f"{name}.npz")
        metrics = os.path.join(workdir, f"{name}.jsonl")
        base = {
            "GAMESMAN_PLATFORM": "cpu",
            "GAMESMAN_FAKE_DEVICES": str(shards),
            # Spill-forcing: nothing stays in HBM between phases, and
            # the host tier is too small for the edge arrays — the
            # backward's edge loads come from sealed files on disk.
            "GAMESMAN_DEVICE_STORE_MB": "0",
            "GAMESMAN_STORE_CACHE_MB": "4",
        }
        base.update(env)
        child_env = dict(os.environ)
        child_env.pop("GAMESMAN_FAULTS", None)
        child_env.update(base)
        proc = subprocess.run(
            [sys.executable, "-m", "gamesmanmpi_tpu.cli", spec,
             "--devices", str(shards),
             "--checkpoint-dir", os.path.join(workdir, f"{name}_ck"),
             "--table-out", table, "--jsonl", metrics],
            capture_output=True, text=True, timeout=deadline,
            env=child_env, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        arm: dict = {"rc": proc.returncode, "table": table}
        if proc.returncode != 0:
            arm["error"] = proc.stderr[-1000:]
            return arm
        done = None
        try:
            with open(metrics) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("phase") == "done":
                        done = rec
        except OSError as e:
            arm["error"] = f"metrics unreadable: {e}"
            return arm
        if done is None:
            arm["error"] = "no done record in metrics stream"
            return arm
        for key in ("io_wait_secs", "prefetch_hits", "prefetch_misses",
                    "prefetch_hit_rate", "writebehind_writes",
                    "writebehind_queue_depth", "edges_bytes_disk",
                    "edges_bytes_spilled", "positions", "secs_total",
                    "positions_per_sec"):
            if key in done:
                arm[key] = done[key]
        return arm

    try:
        with tempfile.TemporaryDirectory(prefix="bench_store_") as wd:
            record["sync"] = _arm("sync", wd, {
                "GAMESMAN_STORE_PREFETCH_THREADS": "0",
                "GAMESMAN_STORE_WRITEBEHIND": "0",
            })
            record["prefetch"] = _arm("prefetch", wd, {
                "GAMESMAN_STORE_PREFETCH_THREADS": "2",
                "GAMESMAN_STORE_WRITEBEHIND": "1",
            })
            sync, pref = record["sync"], record["prefetch"]
            if "error" not in sync and "error" not in pref:
                record["io_wait_ok"] = bool(
                    pref["io_wait_secs"] < sync["io_wait_secs"]
                )
                # Byte parity: --table-out is always PLAIN npz (the
                # user-facing format), so member-wise equality IS the
                # solved-table equality proof.
                parity = True
                with np.load(sync["table"]) as za, \
                        np.load(pref["table"]) as zb:
                    parity = sorted(za.files) == sorted(zb.files) and all(
                        np.array_equal(za[f], zb[f]) for f in za.files
                    )
                record["parity_ok"] = bool(parity)
                record["io_wait_ratio"] = round(
                    pref["io_wait_secs"]
                    / max(sync["io_wait_secs"], 1e-9), 4
                )
                record["ok"] = bool(
                    record["io_wait_ok"] and record["parity_ok"]
                )
            else:
                record["ok"] = False
                record["error"] = (
                    sync.get("error") or pref.get("error") or "arm failed"
                )
            # The table paths die with the tempdir — drop them from the
            # committed artifact.
            sync.pop("table", None)
            pref.pop("table", None)
    except Exception as e:  # noqa: BLE001 - must never kill the bench
        record["ok"] = False
        record["error"] = f"{type(e).__name__}: {e}"
    try:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
            fh.write("\n")
        print(f"store bench: wrote {out_path} "
              f"(ok={record.get('ok')})", file=sys.stderr)
    except OSError as e:
        print(f"store bench: cannot write {out_path}: {e}",
              file=sys.stderr)
    return record


def _gamedsl_bench() -> dict | None:
    """BENCH_GAMEDSL=1: hand-written vs compiled-spec connect4 A/B.

    The game compiler's performance contract (ISSUE 16) is that a
    compiled GameSpec solves within 10% of the hand-written module it
    replicates — the lowering emits the same masks and smear shifts, so
    any gap is compiler overhead. Two CLI children on the same config
    (CPU-pinned for comparability): the registry spec
    ``connect4:w=W,h=H`` and a generated GameSpec .json for the same
    board. Best positions/sec of BENCH_GAMEDSL_RUNS (default 2) per arm;
    gates: compiled/hand >= BENCH_GAMEDSL_MIN_RATIO (default 0.9) and
    byte-identical --table-out tables. Runs in the PARENT
    (subprocess-only, never touches jax); any failure is recorded, not
    raised. Full record → BENCH_GAMEDSL_OUT; summary joins the bench
    record under `gamedsl`. The artifact doubles as a
    tools/bench_compare.py record (metric
    ``gamedsl_compiled_connect4_pps_ratio``).
    """
    if os.environ.get("BENCH_GAMEDSL", "0") in ("0", "", "off"):
        return None
    import tempfile

    import numpy as np

    width = int(_env_float("BENCH_GAMEDSL_W", 5))
    height = int(_env_float("BENCH_GAMEDSL_H", 4))
    runs = max(1, int(_env_float("BENCH_GAMEDSL_RUNS", 2)))
    min_ratio = _env_float("BENCH_GAMEDSL_MIN_RATIO", 0.9)
    out_path = os.environ.get("BENCH_GAMEDSL_OUT", "BENCH_gamedsl.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    hand_spec = f"connect4:w={width},h={height}"
    spec_doc = {
        "gamedsl": 1,
        "name": f"connect4_{width}x{height}",
        "board": {"width": width, "height": height},
        "moves": {"family": "drop"},
        "win": {"kind": "k_in_line", "k": 4},
    }
    record: dict = {
        "bench": "gamedsl_compiled_ab",
        "metric": "gamedsl_compiled_connect4_pps_ratio",
        "unit": "ratio",
        "device": "cpu",
        "game": hand_spec,
        "spec_doc": spec_doc,
        "runs": runs,
        "min_ratio": min_ratio,
    }

    def _arm(name: str, game_arg: str, workdir: str) -> dict:
        table = os.path.join(workdir, f"{name}.npz")
        child_env = dict(os.environ)
        child_env.pop("GAMESMAN_FAULTS", None)
        child_env["GAMESMAN_PLATFORM"] = "cpu"
        arm: dict = {"game": game_arg}
        best = 0.0
        for i in range(runs):
            cmd = [sys.executable, "-m", "gamesmanmpi_tpu.cli", game_arg]
            if i == 0:
                cmd += ["--table-out", table]
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=deadline,
                env=child_env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if proc.returncode != 0:
                arm["error"] = proc.stderr[-1000:]
                return arm
            pps = None
            for line in proc.stdout.splitlines():
                if line.startswith("throughput:"):
                    try:
                        pps = float(line.split()[1])
                    except (IndexError, ValueError):
                        pass
            if pps is None:
                arm["error"] = "no throughput line in solve output"
                return arm
            best = max(best, pps)
        arm["positions_per_sec"] = best
        arm["table"] = table
        return arm

    try:
        with tempfile.TemporaryDirectory(prefix="bench_gamedsl_") as wd:
            spec_path = os.path.join(wd, "spec.json")
            with open(spec_path, "w") as fh:
                json.dump(spec_doc, fh)
            hand = _arm("hand", hand_spec, wd)
            compiled = _arm("compiled", spec_path, wd)
            record["hand"] = hand
            record["compiled"] = compiled
            if "error" not in hand and "error" not in compiled:
                ratio = (compiled["positions_per_sec"]
                         / max(hand["positions_per_sec"], 1e-9))
                record["value"] = round(ratio, 4)
                record["hand_pps"] = hand["positions_per_sec"]
                record["compiled_pps"] = compiled["positions_per_sec"]
                # --table-out is plain npz: member-wise equality IS the
                # solved-table equality proof (same convention as the
                # store bench).
                with np.load(hand["table"]) as za, \
                        np.load(compiled["table"]) as zb:
                    parity = sorted(za.files) == sorted(zb.files) and all(
                        np.array_equal(za[f], zb[f]) for f in za.files
                    )
                record["parity_ok"] = bool(parity)
                record["ratio_ok"] = bool(ratio >= min_ratio)
                record["ok"] = bool(parity and record["ratio_ok"])
            else:
                record["ok"] = False
                record["error"] = (
                    hand.get("error") or compiled.get("error")
                    or "arm failed"
                )
            # The table paths die with the tempdir — drop them from the
            # committed artifact.
            hand.pop("table", None)
            compiled.pop("table", None)
    except Exception as e:  # noqa: BLE001 - must never kill the bench
        record["ok"] = False
        record["error"] = f"{type(e).__name__}: {e}"
    record.setdefault("value", 0.0)
    try:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
            fh.write("\n")
        print(f"gamedsl bench: wrote {out_path} "
              f"(ok={record.get('ok')})", file=sys.stderr)
    except OSError as e:
        print(f"gamedsl bench: cannot write {out_path}: {e}",
              file=sys.stderr)
    return record


def _campaign_bench() -> dict | None:
    """BENCH_CAMPAIGN=1: the self-healing campaign proof (ISSUE 12).

    One `tools/run_campaign.py` invocation drives a sharded solve —
    BENCH_CAMPAIGN_PROCESSES > 1 makes each attempt a real
    launch_multihost world — through THREE injected SIGKILLs at
    distinct points (forward, backward, mid-write-behind; rank 0 in a
    world, its peers exiting through the coordinated abort) to
    completion with zero operator input. Gates: campaign rc 0, the
    ledger records every attempt with the injected causes, and the
    final `--table-out` table is byte-identical to an uninterrupted
    solve of the same config. Full record (ledger included) →
    BENCH_CAMPAIGN_OUT; summary joins the bench record under
    `campaign`. Runs in the PARENT (subprocess-only); failures are
    recorded, never raised.
    """
    if os.environ.get("BENCH_CAMPAIGN", "0") in ("0", "", "off"):
        return None
    import tempfile

    import numpy as np

    spec = os.environ.get("BENCH_CAMPAIGN_GAME", "connect4:w=5,h=4")
    processes = int(_env_float("BENCH_CAMPAIGN_PROCESSES", 2))
    shards = int(_env_float("BENCH_CAMPAIGN_SHARDS", 4))
    out_path = os.environ.get("BENCH_CAMPAIGN_OUT", "BENCH_campaign.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    chaos = [
        "sharded.forward:kill:3",       # mid-forward
        "sharded.backward:kill:2",      # mid-backward
        "store.writebehind:kill:1",     # mid-write-behind payload
    ]
    record: dict = {
        "bench": "self_healing_campaign",
        "spec": spec,
        "processes": processes,
        "shards": shards,
        "chaos": chaos,
    }
    repo = os.path.dirname(os.path.abspath(__file__))

    def _resumed_table(workdir: str) -> str:
        # An N-process attempt rank-qualifies --table-out; the table is
        # the GLOBAL solved table either way, so rank0's file is
        # canonical. The golden solve is always single-process.
        if processes > 1:
            return os.path.join(workdir, "resumed.rank0.npz")
        return os.path.join(workdir, "resumed.npz")

    try:
        with tempfile.TemporaryDirectory(prefix="bench_campaign_") as wd:
            child_env = dict(os.environ)
            child_env.pop("GAMESMAN_FAULTS", None)
            child_env.update({
                "GAMESMAN_PLATFORM": "cpu",
                "GAMESMAN_CAMPAIGN_BACKOFF_BASE_SECS": "0.2",
                # A dead rank must resolve into a coordinated abort,
                # not a wedged world the attempt timeout reaps.
                "GAMESMAN_BARRIER_SECS": "30",
                "GAMESMAN_COLLECTIVE_TIMEOUT": "120",
            })
            t0 = time.time()
            golden_cmd = [
                sys.executable, "-m", "gamesmanmpi_tpu.cli", spec,
                "--devices", str(shards),
                "--table-out", os.path.join(wd, "golden.npz"),
            ]
            golden_env = dict(child_env)
            golden_env["GAMESMAN_FAKE_DEVICES"] = str(shards)
            golden = subprocess.run(
                golden_cmd, capture_output=True, text=True,
                timeout=deadline, env=golden_env, cwd=repo,
            )
            record["golden_secs"] = round(time.time() - t0, 3)
            if golden.returncode != 0:
                record["ok"] = False
                record["error"] = "golden: " + golden.stderr[-1000:]
                raise StopIteration
            ck = os.path.join(wd, "ck")
            cmd = [
                sys.executable, os.path.join(repo, "tools",
                                             "run_campaign.py"),
                spec, "--checkpoint-dir", ck,
                "--processes", str(processes),
            ]
            for c in chaos:
                cmd += ["--chaos", c]
            cmd += ["--", "--devices", str(shards),
                    "--table-out", os.path.join(wd, "resumed.npz")]
            t0 = time.time()
            camp = subprocess.run(
                cmd, capture_output=True, text=True, timeout=deadline,
                env=child_env, cwd=repo,
            )
            record["campaign_rc"] = camp.returncode
            record["campaign_secs"] = round(time.time() - t0, 3)
            ledger = []
            try:
                with open(os.path.join(ck, "campaign.jsonl")) as fh:
                    for line in fh:
                        try:
                            ledger.append(json.loads(line))
                        except json.JSONDecodeError:
                            continue
            except OSError:
                pass
            record["ledger"] = ledger
            attempts = [r for r in ledger
                        if r.get("phase") == "campaign_attempt"]
            record["attempts"] = len(attempts)
            record["causes"] = [a.get("cause") for a in attempts]
            record["resume_levels"] = [a.get("resume_level")
                                       for a in attempts]
            if camp.returncode != 0:
                record["ok"] = False
                record["error"] = camp.stderr[-2000:]
                raise StopIteration
            # The three injected deaths really happened, then it healed.
            record["chaos_ok"] = bool(
                len(attempts) == len(chaos) + 1
                and all(a.get("cause") == "killed"
                        for a in attempts[:len(chaos)])
                and attempts[-1].get("cause") == "complete"
            )
            parity = True
            with np.load(os.path.join(wd, "golden.npz")) as za, \
                    np.load(_resumed_table(wd)) as zb:
                parity = sorted(za.files) == sorted(zb.files) and all(
                    np.array_equal(za[f], zb[f]) for f in za.files
                )
            record["parity_ok"] = bool(parity)
            record["ok"] = bool(record["chaos_ok"] and parity)
    except StopIteration:
        pass
    except Exception as e:  # noqa: BLE001 - must never kill the bench
        record["ok"] = False
        record["error"] = f"{type(e).__name__}: {e}"
    try:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
            fh.write("\n")
        print(f"campaign bench: wrote {out_path} "
              f"(ok={record.get('ok')})", file=sys.stderr)
    except OSError as e:
        print(f"campaign bench: cannot write {out_path}: {e}",
              file=sys.stderr)
    return record


def _campaign_elastic_bench() -> dict | None:
    """BENCH_CAMPAIGN_ELASTIC=1: the elastic-resume proof (ISSUE 13).

    Two scenarios against one golden uninterrupted solve:

    * **reshard** — a sharded solve at BENCH_CAMPAIGN_ELASTIC_SEAL_SHARDS
      is SIGKILLed mid-backward, then a campaign resumes it at
      BENCH_CAMPAIGN_ELASTIC_SHARDS (a different shard count): the tree
      is adopted by reshard-on-resume (the ledger's first attempt shows
      sealed_shards != shards) and driven to completion;
    * **oom** — a campaign started at BENCH_CAMPAIGN_ELASTIC_OOM_SHARDS
      takes an injected `oom` death, auto-escalates geometry (shards
      doubled, store cache halved — the campaign_reshard ledger record)
      and completes at the escalated count.

    Gates: both campaigns rc 0 with zero operator input, every
    geometry change on the ledger, and BOTH `--table-out` tables
    byte-identical to the golden solve (shard-count invariance across
    resume). Runs in the PARENT (subprocess-only); failures land in
    the artifact, never raise. Full record → BENCH_CAMPAIGN_ELASTIC_OUT.
    """
    if os.environ.get("BENCH_CAMPAIGN_ELASTIC", "0") in ("0", "", "off"):
        return None
    import tempfile

    import numpy as np

    spec = os.environ.get("BENCH_CAMPAIGN_ELASTIC_GAME",
                          "connect4:w=5,h=4")
    shards = int(_env_float("BENCH_CAMPAIGN_ELASTIC_SHARDS", 4))
    seal_shards = int(_env_float("BENCH_CAMPAIGN_ELASTIC_SEAL_SHARDS", 8))
    oom_shards = int(_env_float("BENCH_CAMPAIGN_ELASTIC_OOM_SHARDS", 2))
    out_path = os.environ.get("BENCH_CAMPAIGN_ELASTIC_OUT",
                              "BENCH_campaign_elastic.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    record: dict = {
        "bench": "elastic_campaign",
        "spec": spec,
        "shards": shards,
        "seal_shards": seal_shards,
        "oom_shards": oom_shards,
    }
    repo = os.path.dirname(os.path.abspath(__file__))

    def _ledger_of(ck: str) -> list:
        out = []
        try:
            with open(os.path.join(ck, "campaign.jsonl")) as fh:
                for line in fh:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            pass
        return out

    def _parity(a: str, b: str) -> bool:
        with np.load(a) as za, np.load(b) as zb:
            return sorted(za.files) == sorted(zb.files) and all(
                np.array_equal(za[f], zb[f]) for f in za.files
            )

    try:
        with tempfile.TemporaryDirectory(prefix="bench_elastic_") as wd:
            base_env = dict(os.environ)
            base_env.pop("GAMESMAN_FAULTS", None)
            base_env.pop("XLA_FLAGS", None)
            base_env.update({
                "GAMESMAN_PLATFORM": "cpu",
                "GAMESMAN_CAMPAIGN_BACKOFF_BASE_SECS": "0.2",
            })
            t0 = time.time()
            golden = os.path.join(wd, "golden.npz")
            golden_env = dict(base_env)
            golden_env["GAMESMAN_FAKE_DEVICES"] = str(shards)
            g = subprocess.run(
                [sys.executable, "-m", "gamesmanmpi_tpu.cli", spec,
                 "--devices", str(shards), "--table-out", golden],
                capture_output=True, text=True, timeout=deadline,
                env=golden_env, cwd=repo,
            )
            record["golden_secs"] = round(time.time() - t0, 3)
            if g.returncode != 0:
                record["ok"] = False
                record["error"] = "golden: " + g.stderr[-1000:]
                raise StopIteration

            # --- scenario 1: SIGKILL at S=seal, campaign resumes at S
            ck = os.path.join(wd, "ck_reshard")
            kill_env = dict(base_env)
            kill_env["GAMESMAN_FAKE_DEVICES"] = str(seal_shards)
            kill_env["GAMESMAN_FAULTS"] = "sharded.backward:kill:2"
            t0 = time.time()
            killed = subprocess.run(
                [sys.executable, "-m", "gamesmanmpi_tpu.cli", spec,
                 "--devices", str(seal_shards),
                 "--checkpoint-dir", ck],
                capture_output=True, text=True, timeout=deadline,
                env=kill_env, cwd=repo,
            )
            resumed = os.path.join(wd, "resumed.npz")
            camp_env = dict(base_env)
            camp_env["GAMESMAN_FAKE_DEVICES"] = str(shards)
            camp = subprocess.run(
                [sys.executable,
                 os.path.join(repo, "tools", "run_campaign.py"), spec,
                 "--checkpoint-dir", ck, "--",
                 "--devices", str(shards), "--table-out", resumed],
                capture_output=True, text=True, timeout=deadline,
                env=camp_env, cwd=repo,
            )
            ledger = _ledger_of(ck)
            attempts = [r for r in ledger
                        if r.get("phase") == "campaign_attempt"]
            record["reshard"] = {
                "kill_rc": killed.returncode,
                "campaign_rc": camp.returncode,
                "secs": round(time.time() - t0, 3),
                "attempts": len(attempts),
                "causes": [a.get("cause") for a in attempts],
                "sealed_shards": (attempts[0].get("sealed_shards")
                                  if attempts else None),
                "attempt_shards": (attempts[0].get("shards")
                                   if attempts else None),
                "parity_ok": (camp.returncode == 0
                              and _parity(golden, resumed)),
                "ledger": ledger,
            }
            record["reshard"]["ok"] = bool(
                killed.returncode != 0
                and camp.returncode == 0
                and record["reshard"]["sealed_shards"] == seal_shards
                and record["reshard"]["attempt_shards"] == shards
                and record["reshard"]["parity_ok"]
            )
            if camp.returncode != 0:
                record["reshard"]["error"] = camp.stderr[-2000:]

            # --- scenario 2: injected oom, campaign auto-escalates
            ck2 = os.path.join(wd, "ck_oom")
            resumed2 = os.path.join(wd, "resumed_oom.npz")
            oom_env = dict(base_env)
            oom_env["GAMESMAN_FAKE_DEVICES"] = str(oom_shards)
            t0 = time.time()
            camp2 = subprocess.run(
                [sys.executable,
                 os.path.join(repo, "tools", "run_campaign.py"), spec,
                 "--checkpoint-dir", ck2,
                 "--chaos", "sharded.backward:oom:2", "--",
                 "--devices", str(oom_shards),
                 "--table-out", resumed2],
                capture_output=True, text=True, timeout=deadline,
                env=oom_env, cwd=repo,
            )
            ledger2 = _ledger_of(ck2)
            attempts2 = [r for r in ledger2
                         if r.get("phase") == "campaign_attempt"]
            reshards2 = [r for r in ledger2
                         if r.get("phase") == "campaign_reshard"]
            record["oom"] = {
                "campaign_rc": camp2.returncode,
                "secs": round(time.time() - t0, 3),
                "attempts": len(attempts2),
                "causes": [a.get("cause") for a in attempts2],
                "escalations": [
                    {k: r.get(k) for k in
                     ("from_shards", "to_shards", "from_cache_mb",
                      "to_cache_mb")}
                    for r in reshards2
                ],
                "parity_ok": (camp2.returncode == 0
                              and _parity(golden, resumed2)),
                "ledger": ledger2,
            }
            record["oom"]["ok"] = bool(
                camp2.returncode == 0
                and record["oom"]["causes"][:1] == ["oom"]
                and record["oom"]["causes"][-1:] == ["complete"]
                and reshards2
                and reshards2[0].get("from_shards") == oom_shards
                and reshards2[0].get("to_shards") == oom_shards * 2
                and record["oom"]["parity_ok"]
            )
            if camp2.returncode != 0:
                record["oom"]["error"] = camp2.stderr[-2000:]
            record["ok"] = bool(
                record["reshard"]["ok"] and record["oom"]["ok"]
            )
    except StopIteration:
        pass
    except Exception as e:  # noqa: BLE001 - must never kill the bench
        record["ok"] = False
        record["error"] = f"{type(e).__name__}: {e}"
    try:
        with open(out_path, "w") as fh:
            json.dump(record, fh, indent=1)
            fh.write("\n")
        print(f"elastic campaign bench: wrote {out_path} "
              f"(ok={record.get('ok')})", file=sys.stderr)
    except OSError as e:
        print(f"elastic campaign bench: cannot write {out_path}: {e}",
              file=sys.stderr)
    return record


def _db_compress_bench() -> dict | None:
    """BENCH_DB_COMPRESS=1: the compressed-DB ratio + latency benchmark
    (ROADMAP item 2 / ISSUE 9).

    One solve (child process, checkpointed), exported twice — format v1
    and block-compressed v2 — then:

    * integrity + **full logical equality**: tools/check_db.py checks
      the v2 directory and proves it answers every position identically
      to the v1 export (--same-as: levels, keys, cells — not a sample);
    * **ratio gate**: whole-DB stored bytes v1/v2 from the real files,
      gated on BENCH_DB_MIN_RATIO (default 3x, the ROADMAP claim);
    * **probe latency under load**: each directory serves through a
      real `serve --workers N` fleet driven by tools/load_gen; the v2
      p99 is gated on BENCH_DB_SLO_P99_MS (default 250 ms — the
      BENCH_serve_r07.json SLO must survive decompress-on-probe).

    Runs in the PARENT (jax-free: exports/serving are subprocesses,
    sampling reads the v1 .npy keys with plain numpy) and must never
    kill the bench: failures land in the artifact, not as exceptions.
    The full record writes to BENCH_DB_COMPRESS_OUT
    (BENCH_db_compress.json); a summary joins the bench record.
    """
    if os.environ.get("BENCH_DB_COMPRESS", "0") in ("0", "", "off"):
        return None
    import signal
    import tempfile

    from tools.load_gen import run_load

    spec = os.environ.get("BENCH_DB_GAME", "connect4:w=5,h=4")
    workers = int(_env_float("BENCH_DB_WORKERS", 2))
    duration = _env_float("BENCH_DB_SECS", 8.0)
    conc = int(_env_float("BENCH_DB_CONC", 8))
    slo_ms = _env_float("BENCH_DB_SLO_P99_MS", 250.0)
    min_ratio = _env_float("BENCH_DB_MIN_RATIO", 3.0)
    out_path = os.environ.get("BENCH_DB_COMPRESS_OUT",
                              "BENCH_db_compress.json")
    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    artifact = {
        "game": spec, "workers": workers, "concurrency": conc,
        "slo_p99_ms": slo_ms, "min_ratio": min_ratio, "ok": False,
    }

    def _serve_and_load(db: str, positions) -> dict:
        """Launch a fleet over one DB dir (_launch_fleet), drive
        load_gen, tear down. -> load record (qps/p50/p99/errors/
        mismatches/answers)."""
        fleet = _launch_fleet(db, workers)
        proc = fleet.get("proc")
        try:
            if "error" in fleet:
                return {"error": fleet["error"]}
            load = run_load(
                f"http://127.0.0.1:{fleet['port']}", positions,
                duration=duration, concurrency=conc,
            )
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
            return load
        finally:
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()

    t0 = time.perf_counter()
    try:
        with tempfile.TemporaryDirectory(prefix="bench_dbc_") as td:
            ckpt = os.path.join(td, "ckpt")
            v1, v2 = os.path.join(td, "v1"), os.path.join(td, "v2")
            solve = subprocess.run(
                [sys.executable, "-m", "gamesmanmpi_tpu.cli", spec,
                 "--checkpoint-dir", ckpt],
                timeout=deadline, capture_output=True, text=True,
            )
            if solve.returncode != 0:
                artifact["error"] = "solve failed: " + solve.stderr[-1000:]
                return artifact
            # Scrub GAMESMAN_DB_COMPRESS for the exports: the A/B is
            # meaningless unless the v1 arm REALLY writes v1 (the env
            # knob would silently flip it; v2's explicit --compress
            # wins either way).
            export_env = dict(os.environ)
            export_env.pop("GAMESMAN_DB_COMPRESS", None)
            for out_dir, extra in ((v1, []), (v2, ["--compress"])):
                export = subprocess.run(
                    [sys.executable, "-m", "gamesmanmpi_tpu.cli",
                     "export-db", spec, "--out", out_dir,
                     "--from-checkpoint", ckpt, *extra],
                    timeout=deadline, capture_output=True, text=True,
                    env=export_env,
                )
                if export.returncode != 0:
                    artifact["error"] = (
                        f"export-db {extra} failed: " + export.stderr[-1000:]
                    )
                    return artifact
            # Integrity + full v1-equality + per-level stats, in the
            # jax-capable child (the checker itself is numpy-only but
            # lives inside the package).
            stats_json = os.path.join(td, "stats.json")
            chk = subprocess.run(
                [sys.executable, os.path.join("tools", "check_db.py"),
                 v2, "--quiet", "--same-as", v1,
                 "--stats-json", stats_json],
                timeout=deadline, capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            # Distinct gates from one run: --same-as differences print
            # as "differs from" problem lines, integrity problems as
            # anything else — an operator triaging the artifact can see
            # WHICH gate failed without re-running. A checker that died
            # WITHOUT reporting (import error, usage error, traceback)
            # proves neither gate: both stay false, never vacuously
            # true.
            found = [l for l in chk.stderr.splitlines()
                     if l.startswith("PROBLEM: ")]
            reported = chk.returncode == 0 or bool(found)
            artifact["check_ok"] = reported and not any(
                "differs from" not in l for l in found
            )
            artifact["full_equal"] = reported and not any(
                "differs from" in l for l in found
            )
            if chk.returncode != 0:
                artifact["error"] = "check_db: " + chk.stderr[-1000:]
                return artifact
            with open(stats_json) as fh:
                stats = json.load(fh)
            v1_bytes = _dir_bytes(v1)
            v2_bytes = _dir_bytes(v2)
            artifact.update({
                "positions": stats["num_positions"],
                "levels": len(stats["levels"]),
                "v1_bytes": v1_bytes,
                "v2_bytes": v2_bytes,
                "ratio": v1_bytes / max(v2_bytes, 1),
                "manifest_ratio": stats["ratio"],
            })
            positions = _db_sample_positions(v1)
            for arm, db in (("v1", v1), ("v2", v2)):
                load = _serve_and_load(db, positions)
                load.pop("answers", None)
                artifact[arm] = {
                    k: load.get(k)
                    for k in ("qps", "ok", "p50_ms", "p95_ms", "p99_ms",
                              "errors", "mismatches", "shed", "dropped",
                              "error")
                    if k in load
                }
            artifact["ratio_ok"] = artifact["ratio"] >= min_ratio
            artifact["slo_ok"] = (
                artifact.get("v2", {}).get("p99_ms", 1e9) <= slo_ms
            )
            artifact["ok"] = bool(
                artifact["ratio_ok"] and artifact["slo_ok"]
                and artifact["full_equal"]
                and artifact.get("v1", {}).get("errors", 1) == 0
                and artifact.get("v2", {}).get("errors", 1) == 0
                and artifact.get("v1", {}).get("mismatches", 1) == 0
                and artifact.get("v2", {}).get("mismatches", 1) == 0
            )
    except Exception as e:  # noqa: BLE001 - the bench must survive this
        artifact["error"] = f"{type(e).__name__}: {e}"
    finally:
        artifact.setdefault("secs_wall", round(time.perf_counter() - t0, 3))
        try:
            with open(out_path, "w") as fh:
                json.dump(artifact, fh, indent=1)
            print(
                f"db-compress bench: wrote {out_path} "
                f"(ok={artifact['ok']}, "
                f"ratio={artifact.get('ratio', 0):.2f}x)",
                file=sys.stderr,
            )
        except OSError as e:
            print(f"db-compress bench: cannot write {out_path}: {e}",
                  file=sys.stderr)
    return artifact


def _dir_bytes(directory: str) -> int:
    """Total file bytes under one directory (non-recursive: DB dirs are
    flat)."""
    return sum(
        e.stat().st_size for e in os.scandir(directory) if e.is_file()
    )


def _db_sample_positions(db: str, per_level: int = 64,
                         cap: int = 512) -> list:
    """Sample query positions straight off the DB's key files (plain
    numpy mmap reads — no DbReader, no jax: this runs in the parent)."""
    import glob

    import numpy as np

    positions: list = []
    for path in sorted(glob.glob(os.path.join(db, "level_*.keys.npy"))):
        keys = np.load(path, mmap_mode="r")
        n = int(keys.shape[0])
        step = max(1, n // per_level)
        positions.extend(int(k) for k in keys[::step][:per_level])
    if not positions:
        # Format v2 (block-compressed) directory: no .npy key files to
        # mmap. Decode the key frames in a child (the real codec path
        # lives behind the package __init__, which imports jax — and
        # this parent never touches jax).
        positions = _db_sample_positions_v2(db, per_level)
    if not positions:
        # Last resort: the manifest's per-block first_keys are real
        # positions and already resident. A coarse sample (one position
        # per block) — fine for smoke, too small for cache-pressure
        # benches.
        try:
            with open(os.path.join(db, "manifest.json")) as fh:
                manifest = json.load(fh)
            for key in sorted(manifest.get("levels", {}), key=int):
                positions.extend(
                    int(k) for k in
                    manifest["levels"][key].get("first_keys", [])
                    [:per_level]
                )
        except (OSError, ValueError):
            pass  # caller's load run will surface the empty sample
    if len(positions) > cap:
        step = len(positions) // cap
        positions = positions[::step][:cap]
    return positions


#: Runs in a short-lived child: decode every level's key frames with the
#: real block codec and print a level-ordered stride sample. argv:
#: db_dir per_level.
_SAMPLE_V2_CHILD = """
import os, sys
import numpy as np
from gamesmanmpi_tpu.db.format import read_manifest
from gamesmanmpi_tpu.compress.blocks import decode_block
db, per_level = sys.argv[1], int(sys.argv[2])
m = read_manifest(db)
out = []
for lvl in sorted(m.get("levels", {}), key=int):
    rec = m["levels"][lvl]
    idx = rec.get("keys_blocks")
    if not idx:
        continue
    with open(os.path.join(db, rec["keys"]), "rb") as fh:
        stream = fh.read()
    offs = np.concatenate(([0], np.cumsum(idx["lengths"], dtype=np.int64)))
    nblocks = len(idx["lengths"])
    per_block = max(1, per_level // nblocks)
    for b in range(nblocks):
        arr = decode_block(idx, b, stream[offs[b]:offs[b + 1]])
        step = max(1, arr.shape[0] // per_block)
        out.extend(int(k) for k in arr[::step][:per_block])
print(" ".join(map(str, out)))
"""


def _db_sample_positions_v2(db: str, per_level: int) -> list:
    """Sample real keys from a v2 (block-compressed) DB, spread across
    every block of every level — so a zipf stream over the sample
    actually exercises block residency, not just each block's first key.
    Returns [] on any failure (caller falls back to first_keys)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SAMPLE_V2_CHILD, db, str(per_level)],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        if proc.returncode != 0:
            return []
        return [int(tok) for tok in proc.stdout.split()]
    except (OSError, subprocess.SubprocessError, ValueError):
        return []


def main() -> int:
    # The parent never touches jax — platform selection (GAMESMAN_PLATFORM)
    # is honored by the probe and measurement children, which inherit the
    # environment.
    fallback = False
    forced = bool(os.environ.get("GAMESMAN_PLATFORM"))
    if not forced:
        budget = _env_float("GAMESMAN_PROBE_TIMEOUT", 600.0)
        platform = _probe_accelerator(budget)
        if platform is None:
            print("accelerator probe failed/hung; falling back to CPU",
                  file=sys.stderr)
            fallback = True

    deadline = _env_float("GAMESMAN_BENCH_DEADLINE", 3000.0)
    record = None
    attempts = []
    if not fallback:
        # The child inherits the environment, so a forced GAMESMAN_PLATFORM
        # applies to it as-is; cpu=True only adds the CPU pin for fallback.
        attempts.append(
            f"{os.environ['GAMESMAN_PLATFORM']} (forced)" if forced
            else "accelerator"
        )
        record = _run_inner(deadline, cpu=False)
        if record is None and not forced:
            print("accelerator bench failed; re-running on CPU",
                  file=sys.stderr)
            fallback = True
    if record is None and fallback:
        attempts.append("cpu")
        record = _run_inner(deadline, cpu=True)
    if record is None:
        # Last resort: emit a valid record that says the bench could not
        # run, rather than nothing at all. (The metric name can't match a
        # successful run's exactly — that embeds the game object's name,
        # which needs jax — so carry the raw spec alongside.)
        spec = os.environ.get("BENCH_GAME", "connect4")
        # Full success-record schema (engine/timings/positions/efficiency
        # zeroed): consumers that index success keys unconditionally must
        # not break on exactly the path the always-emit design protects.
        record = {
            "metric": spec.split(":")[0] + "_positions_solved_per_sec_per_chip",
            "spec": spec,
            "value": 0.0, "unit": "positions/sec/chip",
            "vs_baseline": 0.0, "device": "none", "engine": "none",
            "secs_forward": 0.0, "secs_backward": 0.0, "positions": 0,
            "runs": {"n": 0, "median_pps": 0.0, "all_pps": [],
                     "warmup_pps": []},
            "dispatches": {"total": 0, "per_level": 0.0},
            "overlap_secs": 0.0, "fused": False, "io_wait_secs": 0.0,
            "efficiency": {
                "bytes_sorted": 0, "bytes_gathered": 0, "operand_gbps": 0.0,
            },
            "roofline": {
                "operand_gbps": 0.0, "pps_per_chip": 0.0,
                "dispatch_overhead_frac": 0.0,
            },
            "error": f"bench failed; attempted: {', '.join(attempts)}",
        }
    # The parent is authoritative for fallback_cpu: a forced CPU run is a
    # deliberate baseline, not a fallback.
    record["fallback_cpu"] = bool(fallback)
    mp = _multiprocess_smoke()
    if mp is not None:
        # Summary only — the per-rank level times live in the artifact
        # file (BENCH_PROCESSES_OUT); the one-line record stays one line.
        record["multiprocess"] = {
            k: mp.get(k) for k in
            ("processes", "shards", "ok", "positions",
             "positions_per_sec", "secs_wall", "error")
            if k in mp
        }
    dbc = _db_compress_bench()
    if dbc is not None:
        # Summary only — per-level ratios and both load arms live in the
        # artifact file (BENCH_DB_COMPRESS_OUT).
        record["db_compress"] = {
            k: dbc.get(k) for k in
            ("ratio", "ok", "ratio_ok", "slo_ok", "full_equal",
             "positions", "v1_bytes", "v2_bytes", "error")
            if k in dbc
        }
        for arm in ("v1", "v2"):
            if arm in dbc:
                record["db_compress"][f"{arm}_p99_ms"] = \
                    dbc[arm].get("p99_ms")
    sb = _store_bench()
    if sb is not None:
        # Summary only — the per-arm stats live in the artifact file
        # (BENCH_STORE_OUT); the one-line record stays one line.
        record["store"] = {
            k: sb.get(k) for k in
            ("ok", "io_wait_ok", "parity_ok", "io_wait_ratio", "error")
            if k in sb
        }
        for arm in ("sync", "prefetch"):
            if arm in sb and "io_wait_secs" in sb[arm]:
                record["store"][f"{arm}_io_wait_secs"] = \
                    sb[arm]["io_wait_secs"]
    gd = _gamedsl_bench()
    if gd is not None:
        # Summary only — per-arm run details live in the artifact file
        # (BENCH_GAMEDSL_OUT); the one-line record stays one line.
        record["gamedsl"] = {
            k: gd.get(k) for k in
            ("ok", "ratio_ok", "parity_ok", "value", "hand_pps",
             "compiled_pps", "error")
            if k in gd
        }
    cb = _campaign_bench()
    if cb is not None:
        # Summary only — the full ledger lives in the artifact file
        # (BENCH_CAMPAIGN_OUT); the one-line record stays one line.
        record["campaign"] = {
            k: cb.get(k) for k in
            ("ok", "chaos_ok", "parity_ok", "attempts", "causes",
             "campaign_rc", "campaign_secs", "error")
            if k in cb
        }
    eb = _campaign_elastic_bench()
    if eb is not None:
        # Summary only — the ledgers live in the artifact file
        # (BENCH_CAMPAIGN_ELASTIC_OUT); the one-line record stays one
        # line.
        record["campaign_elastic"] = {"ok": eb.get("ok")}
        for scenario in ("reshard", "oom"):
            if scenario in eb:
                record["campaign_elastic"][scenario] = {
                    k: v for k, v in eb[scenario].items()
                    if k != "ledger"
                }
        if "error" in eb:
            record["campaign_elastic"]["error"] = eb["error"]
    sv = _serve_bench()
    if sv is not None:
        # Summary only — the full load/chaos record lives in the
        # artifact file (BENCH_SERVE_OUT); the one-line record stays
        # one line.
        record["serve"] = {
            k: sv.get(k) for k in
            ("workers", "concurrency", "ok", "slo_ok", "qps",
             "p50_ms", "p99_ms", "shed", "dropped", "mismatches",
             "worker_restarts", "recovered_secs", "error")
            if k in sv
        }
        if "trace_ab" in sv:
            record["serve"]["trace_ab"] = {
                k: sv["trace_ab"].get(k)
                for k in ("ok", "delta_pct", "max_delta_pct", "error")
                if k in sv["trace_ab"]
            }
    sh = _serve_hot_bench()
    if sh is not None:
        # Summary only — arm details live in the artifact file
        # (BENCH_SERVE_HOT_OUT); the one-line record stays one line.
        shs = sh.get("serve_hot") or {}
        record["serve_hot"] = {
            k: shs.get(k) for k in
            ("ok", "clean", "perf_ok", "hits_ok", "get_ok",
             "book_hits", "shm_hits", "error")
            if k in shs
        }
        for arm in ("baseline", "hot"):
            if arm in shs:
                record["serve_hot"][arm] = {
                    k: shs[arm].get(k) for k in ("qps", "p99_ms")
                }
    rb = _registry_bench()
    if rb is not None:
        # Summary only — the full crash/resume/flip record lives in the
        # artifact file (BENCH_REGISTRY_OUT).
        record["registry"] = {
            "ok": rb.get("ok"),
            "runner_kill_rc": (rb.get("runner_kill") or {}).get("rc"),
            "resume_state": (rb.get("runner_resume") or {})
            .get("job_state"),
            "reloads_done": rb.get("reloads_done"),
        }
        if "error" in rb:
            record["registry"]["error"] = rb["error"]
    print(json.dumps(record))
    return 0


def inner() -> int:
    """The actual measurement: runs entirely in one child process."""
    from gamesmanmpi_tpu.utils.platform import apply_platform_env

    if (os.environ.get("BENCH_ENGINE") == "sharded"
            and not os.environ.get("GAMESMAN_FAKE_DEVICES")):
        # The sharded config needs a mesh; a CPU-pinned run fakes
        # BENCH_SHARDS host devices (a real accelerator mesh is used
        # as-is — make_solver clamps to the devices present). Parse and
        # clamp HERE too: exporting a malformed BENCH_SHARDS raw would
        # crash apply_platform_env's int() before any record prints,
        # while make_solver deliberately tolerates it with the same
        # try/except -> 8.
        try:
            shards = int(os.environ.get("BENCH_SHARDS", "8"))
        except ValueError:
            shards = 8
        os.environ["GAMESMAN_FAKE_DEVICES"] = str(max(1, shards))
    apply_platform_env()

    import gamesmanmpi_tpu  # noqa: F401  (enables x64 before first trace)
    import jax

    # Persistent compilation cache: round 1 showed first-run compiles
    # dominating (143k vs 813k pos/s run 0 vs 1). The cache dir lives in the
    # repo, so later benchmark rounds on the same platform skip compiles.
    cache_dir = os.environ.get(
        "GAMESMAN_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_compile_cache"),
    )
    if cache_dir != "0":
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.games.connect4 import Connect4
    from gamesmanmpi_tpu.solve import Solver

    dev = jax.devices()[0]
    print(f"bench device: {dev.platform} ({dev})", file=sys.stderr)

    # ISSUE 15 roofline accounting: measure THIS host's per-dispatch
    # overhead (a tiny compiled kernel round-tripped N times) and
    # publish it as GAMESMAN_DISPATCH_COST_SECS so every solve's
    # stats["roofline"]["dispatch_overhead_frac"] prices its dispatch
    # count against a measured figure instead of a guess. An inherited
    # operator value wins (a deliberate override for a known platform).
    if not os.environ.get("GAMESMAN_DISPATCH_COST_SECS"):
        import jax.numpy as jnp

        probe = jax.jit(lambda a: a + 1)
        arg = jnp.zeros((8,), dtype=jnp.int32)
        probe(arg).block_until_ready()  # compile outside the timing
        t0 = time.perf_counter()
        reps = 64
        for _ in range(reps):
            probe(arg).block_until_ready()
        cost = (time.perf_counter() - t0) / reps
        os.environ["GAMESMAN_DISPATCH_COST_SECS"] = f"{cost:.9f}"
        print(f"dispatch cost: {cost * 1e6:.1f} us/dispatch",
              file=sys.stderr)

    # Engine selection: the dense class-partitioned engine (solve/dense.py)
    # is the fast path for non-symmetric Connect-4 boards on the
    # accelerator; on the CPU fallback its VPU-shaped rank loops lose to
    # the classic engine, so auto resolves by platform. BENCH_ENGINE=
    # classic|dense pins one for comparison runs.
    bench_engine = os.environ.get("BENCH_ENGINE", "auto")
    if bench_engine == "auto":
        bench_engine = "classic" if dev.platform == "cpu" else "dense"

    def make_solver(game):
        nonlocal bench_engine
        if bench_engine == "sharded":
            # The owner-routed sharded engine over BENCH_SHARDS devices
            # (fake host devices on CPU — see the GAMESMAN_FAKE_DEVICES
            # defaulting at the top of inner()). This is the config the
            # edge-cached backward A/B runs against: GAMESMAN_BACKWARD=
            # edges|lookup selects the backward, and the record's
            # secs_backward + efficiency.bytes_sorted carry the delta.
            from gamesmanmpi_tpu.parallel import ShardedSolver

            try:
                shards = int(os.environ.get("BENCH_SHARDS", "8"))
            except ValueError:
                shards = 8
            have = len(jax.devices())
            if have < max(1, shards):
                # Unpinned CPU boxes land here (GAMESMAN_FAKE_DEVICES is
                # honored only under a GAMESMAN_PLATFORM pin): the solve
                # still runs, but an "8-shard" A/B on 1 shard would be a
                # silent lie — say so, and the record's `shards` field
                # (from the solver's stats) carries the truth.
                print(
                    f"sharded bench: only {have} device(s) available, "
                    f"requested {shards} shards — running {have}-shard "
                    "(pin GAMESMAN_PLATFORM=cpu to fake a mesh)",
                    file=sys.stderr,
                )
            shards = max(1, min(shards, have))
            return ShardedSolver(game, num_shards=shards,
                                 store_tables=False)
        # HybridSolver accepts sym=1 since r5 (its BFS region keeps the
        # mirror reduction; the dense region runs a sym-free twin), so the
        # secondary sym run benches the SAME engine as the primary instead
        # of silently demoting to classic (ADVICE r5).
        if bench_engine == "hybrid" and isinstance(game, Connect4):
            try:
                from gamesmanmpi_tpu.solve.hybrid import HybridSolver

                return HybridSolver(game, store_tables=False)
            except Exception as e:
                print(
                    f"hybrid engine setup failed "
                    f"({type(e).__name__}: {e}); demoting to the classic "
                    "engine",
                    file=sys.stderr,
                )
                bench_engine = "classic"
        if bench_engine == "dense" and isinstance(game, Connect4) \
                and not game.sym:
            # The reachable count is a per-board constant, not part of the
            # solve; sweep it NOW (make_solver runs before the timer) so
            # run 0's measurement isn't deflated by it. An import,
            # constructor, or sweep failure demotes to the classic engine
            # (same rationale as in run_solves).
            try:
                from gamesmanmpi_tpu.solve.dense import DenseSolver

                solver = DenseSolver(game, store_tables=False)
                solver.reachable_counts()
                return solver
            except Exception as e:
                print(
                    f"dense engine setup failed "
                    f"({type(e).__name__}: {e}); demoting to the classic "
                    "engine",
                    file=sys.stderr,
                )
                bench_engine = "classic"
        # store_tables=False: the metric measures SOLVING, not the
        # ~600 MB result download over the relay (VERDICT.md r2 weak #5);
        # the root's (value, remoteness) is still checked every run.
        return Solver(game, store_tables=False)

    # Default board: the largest that solves in benchmark-friendly time on
    # the platform that actually runs (BASELINE.md configs #3-#4 ladder).
    default_spec = (
        "connect4:w=5,h=4" if dev.platform == "cpu" else "connect4:w=5,h=5"
    )
    spec = os.environ.get("BENCH_GAME", default_spec)
    # >=3 on-chip: r04's 6x4 record was best-of-2 with an unexplained 5x
    # spread between its two runs (VERDICT r4 weak #1) — three repeats
    # plus a published median makes a one-off outlier visible in the
    # record itself. CPU keeps 2 (each run is minutes, and the CPU number
    # is a fallback diagnostic, not the tracked metric).
    smoke = os.environ.get("BENCH_SMOKE", "0") not in ("0", "", "off")
    repeats = int(os.environ.get(
        "BENCH_REPEATS",
        "1" if smoke else ("2" if dev.platform == "cpu" else "3")))
    # ISSUE 14: first-run compile time polluted r05's variance block
    # (all_pps [296k, 792k] — the median halved by a compile artifact).
    # An explicit warmup solve runs BEFORE the timed repeats and is
    # excluded from value/median; its raw rate stays in the artifact
    # (runs.warmup_pps) so nothing is hidden.
    warmup = int(os.environ.get("BENCH_WARMUP", "1"))

    def _core_record(name: str, best_pps: float, stats: dict,
                     pps_list: list, warmup_list: list = None) -> dict:
        """The FULL driver-format record, shared by the provisional
        records (printed after every primary run) and the final enriched
        one — one construction site so they can never silently diverge,
        and so a salvaged provisional carries every key a consumer may
        index unconditionally (the same schema invariant the zeroed
        bench-failed record upholds). The final path overwrites
        `efficiency` with the roofline-aware version."""
        traffic = (stats.get("bytes_sorted", 0)
                   + stats.get("bytes_gathered", 0))
        rec = {
            "metric": f"{name}_positions_solved_per_sec_per_chip",
            "value": round(best_pps, 1),
            "unit": "positions/sec/chip",
            "vs_baseline": round(best_pps / NORTH_STAR_PPS, 6),
            "device": dev.platform,
            "engine": stats.get("engine", "classic"),
            "secs_forward": round(stats.get("secs_forward", 0.0), 3),
            "secs_backward": round(stats.get("secs_backward", 0.0), 3),
            "positions": stats["positions"],
            # value is best-of-N (the warm rate); runs makes the spread
            # auditable — a median far below best flags a 6x4-style
            # outlier (VERDICT r4 weak #1) instead of hiding it. Warmup
            # runs are excluded from n/median/all_pps (compile time is
            # not throughput) but their raw rates are preserved.
            "runs": {
                "n": len(pps_list),
                "median_pps": round(statistics.median(pps_list), 1)
                if pps_list else 0.0,
                # First 16 only: repeats is normally 2-3; a stress run
                # with hundreds must not balloon the driver's one-line
                # record (n and median_pps stay exact over every run).
                "all_pps": [round(p, 1) for p in pps_list[:16]],
                "warmup_pps": [
                    round(p, 1) for p in (warmup_list or [])[:16]
                ],
            },
            "efficiency": {
                "bytes_sorted": stats.get("bytes_sorted", 0),
                "bytes_gathered": stats.get("bytes_gathered", 0),
                "operand_gbps": round(
                    traffic / max(stats.get("secs_total", 0.0), 1e-9)
                    / 1e9, 3),
            },
            # ISSUE 11: seconds the solve thread spent blocked on block-
            # store I/O (spill/checkpoint/edge loads + seal drains) —
            # 0.0 for in-memory solves; future BENCH_*.json track I/O
            # overlap alongside throughput.
            "io_wait_secs": round(stats.get("io_wait_secs", 0.0), 3),
            # ISSUE 14 dispatch economy: total/per-level device dispatches
            # the engine issued, the fused/pipeline gates that ran, and
            # the host seconds the pingpong pipeline overlapped with
            # device execution — the record proves dispatch count went
            # down, not just wall clock.
            "dispatches": {
                "total": stats.get("dispatches_total", 0),
                "per_level": stats.get("dispatches_per_level", 0.0),
            },
            "overlap_secs": round(stats.get("overlap_secs", 0.0), 3),
            "fused": bool(stats.get("fused", False)),
            # ISSUE 15 roofline fields: analytic HBM operand throughput,
            # the headline per-chip rate, and the wall fraction spent on
            # dispatch overhead (dispatch count x the calibrated
            # per-dispatch cost measured above) — what bench_compare
            # diffs across the committed BENCH_* trajectory.
            "roofline": {
                "operand_gbps": round(
                    traffic / max(stats.get("secs_total", 0.0), 1e-9)
                    / 1e9, 3),
                # Per CHIP, same rule as the engines' roofline_stats:
                # shards count as chips only on a real accelerator mesh
                # (a faked CPU mesh is one physical chip) — the record
                # and the solve stats must agree on this field's
                # denominator or an 8-shard TPU record inflates 8x.
                "pps_per_chip": round(
                    best_pps / (stats.get("shards", 1)
                                if dev.platform != "cpu" else 1), 1),
                "dispatch_overhead_frac": (
                    stats.get("roofline") or {}
                ).get("dispatch_overhead_frac", 0.0),
            },
        }
        if "shards" in stats:
            # Sharded engine only: the shard count that ACTUALLY ran (a
            # device-starved box clamps below BENCH_SHARDS — see
            # make_solver's warning; the record must not imply otherwise).
            rec["shards"] = stats["shards"]
        if "backward" in stats:
            rec["backward"] = stats["backward"]
        return rec

    def _fused_ab_run(game_spec: str) -> dict:
        """Fused-vs-unfused A/B (ISSUE 14): same board, same host, same
        classic engine; one warmup + one timed solve per arm. Parity is
        byte-level: sha256 over every level's (states, values, remoteness)
        arrays — the exact arrays --table-out serializes — must match
        between arms. The per-arm dispatches_per_level pair is the record's
        proof that the fused path dispatches less, not just runs faster."""
        import hashlib

        import numpy as np

        arms = (
            ("unfused", {"GAMESMAN_FUSED": "0",
                         "GAMESMAN_PIPELINE": "level"}),
            ("fused", {"GAMESMAN_FUSED": "1",
                       "GAMESMAN_PIPELINE": "pingpong"}),
        )
        out: dict = {"spec": game_spec}
        digests = {}
        for arm, env in arms:
            saved = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            try:
                game = get_game(game_spec)
                Solver(game, store_tables=True).solve()  # warm: compiles
                solver = Solver(game, store_tables=True)
                t0 = time.perf_counter()
                result = solver.solve()
                dt = time.perf_counter() - t0
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            h = hashlib.sha256()
            for lvl in sorted(result.levels):
                t = result.levels[lvl]
                h.update(np.asarray(t.states).tobytes())
                h.update(np.asarray(t.values).tobytes())
                h.update(np.asarray(t.remoteness).tobytes())
            digests[arm] = h.hexdigest()
            out[arm] = {
                "pps": round(result.num_positions / dt, 1),
                "secs_forward": round(result.stats["secs_forward"], 3),
                "secs_backward": round(result.stats["secs_backward"], 3),
                "dispatches_per_level":
                    result.stats.get("dispatches_per_level", 0.0),
                "dispatches_total":
                    result.stats.get("dispatches_total", 0),
                "overlap_secs": round(
                    result.stats.get("overlap_secs", 0.0), 3),
                "table_sha256": digests[arm],
            }
            print(
                f"fused A/B [{arm}]: {out[arm]['pps']:,.0f} pos/s, "
                f"{out[arm]['dispatches_per_level']} dispatches/level",
                file=sys.stderr,
            )
        out["parity_ok"] = digests["fused"] == digests["unfused"]
        out["speedup"] = round(
            out["fused"]["pps"] / max(out["unfused"]["pps"], 1e-9), 3)
        out["dispatch_reduction"] = round(
            out["unfused"]["dispatches_per_level"]
            / max(out["fused"]["dispatches_per_level"], 1e-9), 2)
        return out

    def run_solves(game_spec: str, nruns: int, provisional: bool = False,
                   nwarmup: int = 0):
        """Best-of-N solve of one board; returns (best pps, best stats,
        [per-run pps], [warmup pps]) — best is the headline (warm-rate),
        the per-run list feeds the published median so variance is
        auditable, and nwarmup compile-eating runs are excluded from both
        but reported raw (runs.warmup_pps).

        provisional=True (the PRIMARY spec only) prints a driver-format
        record line after EVERY completed run: the parent keeps the last
        JSON line it sees, so a deadline/relay death between repeats
        salvages best-of-the-completed-runs instead of discarding them
        (the r05 REPEATS=3 bump would otherwise raise that risk).

        A dense-engine failure demotes to the classic engine on the SAME
        platform for the remaining runs: the dense lowerings have not yet
        executed on a real chip (the relay died first), and a TPU number
        from the proven classic engine beats a CPU fallback.
        """
        nonlocal bench_engine
        game = get_game(game_spec)
        best_pps, best_stats = 0.0, None
        all_pps = []
        warm_pps = []
        nwarmup = max(nwarmup, 0)
        for i in range(-nwarmup, max(nruns, 1)):
            is_warm = i < 0
            solver = make_solver(game)
            t0 = time.perf_counter()
            try:
                result = solver.solve()
            except Exception as e:
                # Demote only when the FAILING solver was the dense one —
                # a classic failure (e.g. during the sym run, which always
                # uses classic) must propagate, not mislabel the dense
                # engine and silently demote the remaining runs.
                if type(solver).__name__ in ("DenseSolver",
                                             "HybridSolver"):
                    print(
                        f"{type(solver).__name__} failed "
                        f"({type(e).__name__}: {e}); demoting to the "
                        "classic engine",
                        file=sys.stderr,
                    )
                    bench_engine = "classic"
                    solver = make_solver(game)
                    t0 = time.perf_counter()
                    result = solver.solve()
                else:
                    raise
            dt = time.perf_counter() - t0
            pps = result.num_positions / dt
            print(
                f"run {'w' if is_warm else ''}{i} [{game.name}]: "
                f"{result.num_positions} positions "
                f"in {dt:.3f}s = {pps:,.0f} pos/s "
                f"(fwd {result.stats['secs_forward']:.1f}s / "
                f"bwd {result.stats['secs_backward']:.1f}s, "
                f"value={result.value}, remoteness={result.remoteness})",
                file=sys.stderr,
            )
            if is_warm:
                warm_pps.append(pps)
                continue
            all_pps.append(pps)
            if pps > best_pps:
                best_pps, best_stats = pps, dict(result.stats)
            if provisional:
                prov = _core_record(game.name, best_pps, best_stats,
                                    all_pps, warm_pps)
                prov["provisional"] = True
                print(json.dumps(prov), flush=True)
        return best_pps, best_stats, all_pps, warm_pps

    best, stats, runs_pps, warm_pps = run_solves(
        spec, repeats, provisional=True, nwarmup=warmup
    )

    # Roofline framing (SURVEY.md §5.5): analytic operand bytes of the
    # sort/gather kernels vs the chip's memory bandwidth. v5e HBM is
    # 819 GB/s; XLA's sort makes ~log2(n) passes, so true traffic is a
    # multiple of operand bytes — this fraction is a LOWER bound on
    # utilization (docs/ARCHITECTURE.md "Efficiency accounting"). The
    # denominator must describe the platform that actually RAN: a CPU
    # record against a TPU roofline is a misleading artifact (VERDICT r3
    # weak #4), so CPU runs omit the roofline fields entirely unless
    # GAMESMAN_HBM_GBPS supplies a measured host figure.
    traffic = stats.get("bytes_sorted", 0) + stats.get("bytes_gathered", 0)
    operand_gbps = traffic / max(stats["secs_total"], 1e-9) / 1e9
    efficiency = {
        "bytes_sorted": stats.get("bytes_sorted", 0),
        "bytes_gathered": stats.get("bytes_gathered", 0),
        "operand_gbps": round(operand_gbps, 3),
    }
    roofline = None
    roofline_env = os.environ.get("GAMESMAN_HBM_GBPS")
    if roofline_env is not None:
        try:
            roofline = float(roofline_env)
        except ValueError:
            # A malformed override must not resurrect the TPU default on a
            # CPU record — warn and fall through to the platform rule.
            print(f"GAMESMAN_HBM_GBPS={roofline_env!r} is not a number; "
                  "ignoring", file=sys.stderr)
    if roofline is None and dev.platform != "cpu":
        roofline = 819.0  # v5e HBM
    if roofline is not None:
        roofline = max(roofline, 1e-9)
        efficiency["hbm_roofline_gbps"] = roofline
        efficiency["roofline_frac"] = round(operand_gbps / roofline, 6)

    record = _core_record(get_game(spec).name, best, stats, runs_pps,
                          warm_pps)
    record["efficiency"] = efficiency  # roofline-aware upgrade
    # Publish the primary measurement NOW: if the relay dies/wedges during
    # the optional sym/ladder solves below, the parent salvages this line
    # instead of discarding a completed accelerator run (the enriched
    # record printed at the end wins when everything succeeds).
    print(json.dumps(record), flush=True)

    # ISSUE 14: fused/unfused A/B on the primary board — the standard
    # record carries the delta (speedup, per-level dispatch reduction,
    # table byte-parity) so every future bench round re-proves the fused
    # path instead of trusting an old one. BENCH_FUSED_AB=0 disables.
    fused_ab = None
    if os.environ.get("BENCH_FUSED_AB", "1") not in ("0", "off"):
        try:
            fused_ab = _fused_ab_run(spec)
        except Exception as e:  # pragma: no cover - diagnostic only
            print(f"fused A/B failed: {e!r}", file=sys.stderr)
            fused_ab = {"error": f"{type(e).__name__}: {e}"}
        record["fused_ab"] = fused_ab
        print(json.dumps(record), flush=True)

    # Secondary: the mirror-symmetry variant (halves the 6x6+ table; the
    # capacity plan depends on its throughput cost — VERDICT.md r2 item 7).
    sym = None
    if (os.environ.get("BENCH_SYM", "0" if smoke else "1")
            not in ("0", "off") and "sym" not in spec):
        try:
            sep = "," if ":" in spec else ":"
            # 2 runs: the sym kernels are a separate compile family, so the
            # first run is compile-dominated; best-of reports the warm rate.
            sym_pps, sym_stats, sym_runs, _ = run_solves(
                spec + sep + "sym=1", 2)
            sym = {
                "positions_per_sec": round(sym_pps, 1),
                "median_pps": round(statistics.median(sym_runs), 1),
                "positions": sym_stats["positions"],
                # The engine that ACTUALLY ran the sym solve (ADVICE r5):
                # engine-eligibility differs by sym, so without this field
                # a demoted sym run is indistinguishable from the primary's.
                "engine": sym_stats.get("engine", "classic"),
            }
        except Exception as e:  # pragma: no cover - diagnostic only
            print(f"sym bench failed: {e!r}", file=sys.stderr)

    # Board ladder (BASELINE.md configs #3-#4): one solve of the next
    # board up, recorded alongside the primary metric. Default 6x4 (~95M
    # positions, the widest uint32 board); BENCH_LADDER=0 disables,
    # BENCH_LADDER=<spec> overrides.
    ladder = None
    ladder_spec = os.environ.get(
        "BENCH_LADDER", "0" if smoke else "connect4:w=6,h=4")
    if (ladder_spec not in ("0", "off", "") and ladder_spec != spec
            and dev.platform != "cpu"):
        try:
            # Same repeat count as the primary: the on-chip default is 3
            # (median lands in the record), and an explicit BENCH_REPEATS
            # is respected rather than silently overridden.
            lad_pps, lad_stats, lad_runs, _ = run_solves(
                ladder_spec, repeats)
            ladder = {
                "game": lad_stats["game"],
                "positions": lad_stats["positions"],
                "positions_per_sec": round(lad_pps, 1),
                "median_pps": round(statistics.median(lad_runs), 1),
                "secs_forward": round(lad_stats["secs_forward"], 3),
                "secs_backward": round(lad_stats["secs_backward"], 3),
            }
        except Exception as e:  # pragma: no cover - diagnostic only
            print(f"ladder bench failed: {e!r}", file=sys.stderr)

    if sym is not None:
        record["sym"] = sym
    if ladder is not None:
        record["ladder"] = ladder
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(inner() if "--inner" in sys.argv else main())
