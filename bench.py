#!/usr/bin/env python
"""Benchmark: positions-solved/sec/chip (BASELINE.json tracked metric).

Runs a full strong solve of a Connect-4 board on the available accelerator
and reports throughput over the complete solve (forward discovery + backward
value/remoteness propagation, all reachable positions).

Board selection: BASELINE.json's primary-metric config is Connect-4 6x6 on a
v4-16; on a single chip we default to the largest board that solves in a
benchmark-friendly time and raise it as kernels speed up (override with
BENCH_GAME). The metric (positions/sec/chip) is comparable across boards.

`vs_baseline`: the reference publishes no numbers (BASELINE.md), so the ratio
is computed against the north-star-implied per-chip rate: 4.5e12 states in
1 hour on 32 chips = 39.06M positions/sec/chip. vs_baseline = value / 39.06e6.

Prints exactly ONE JSON line on stdout; everything else goes to stderr.
"""

import json
import os
import subprocess
import sys
import time


def _accelerator_alive(timeout: float = 180.0) -> bool:
    """Probe backend init in a throwaway subprocess.

    The container's TPU plugin tunnels device access; a wedged tunnel hangs
    at first backend touch *forever* (no error). Probing in a child keeps
    this process clean and lets us fall back to CPU instead of hanging the
    benchmark run.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=timeout, capture_output=True, text=True,
        )
        return proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    from gamesmanmpi_tpu.utils.platform import apply_platform_env, force_platform

    # Honor GAMESMAN_PLATFORM=cpu when the TPU tunnel is unavailable (the
    # driver leaves it unset, so real runs stay on the accelerator).
    apply_platform_env()
    if not os.environ.get("GAMESMAN_PLATFORM") and not _accelerator_alive():
        print("accelerator probe failed/hung; falling back to CPU",
              file=sys.stderr)
        force_platform("cpu")

    import gamesmanmpi_tpu  # noqa: F401  (enables x64 before first trace)
    import jax

    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.solve import Solver

    spec = os.environ.get("BENCH_GAME", "connect4:w=5,h=4")
    repeats = int(os.environ.get("BENCH_REPEATS", "2"))

    dev = jax.devices()[0]
    print(f"bench device: {dev.platform} ({dev})", file=sys.stderr)

    game = get_game(spec)
    best = None
    for i in range(max(repeats, 1)):
        solver = Solver(game)
        t0 = time.perf_counter()
        result = solver.solve()
        dt = time.perf_counter() - t0
        pps = result.num_positions / dt
        print(
            f"run {i}: {result.num_positions} positions in {dt:.3f}s "
            f"= {pps:,.0f} pos/s (value={result.value}, "
            f"remoteness={result.remoteness})",
            file=sys.stderr,
        )
        best = max(best or 0.0, pps)

    north_star_per_chip = 4.5e12 / 3600.0 / 32.0  # 39.06M pos/s/chip
    print(
        json.dumps(
            {
                "metric": f"{game.name}_positions_solved_per_sec_per_chip",
                "value": round(best, 1),
                "unit": "positions/sec/chip",
                "vs_baseline": round(best / north_star_per_chip, 6),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
