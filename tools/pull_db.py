#!/usr/bin/env python
"""Pull DB epochs from a gamesman registry onto this replica.

The replica half of DB distribution (ISSUE 19, docs/SERVING.md): fetch
the signed catalog, download each requested DB's blocks with resumable
ranged reads into a quarantine staging dir, verify every byte
(crc32 + sha256 against the published manifest) BEFORE the atomic
rename-install, and — with ``--fleet-manifest`` — land the new epochs
in the fleet manifest and trigger a rolling reload on the serving
supervisor, which keeps answering from the old epoch until the new one
passes its admission gate.

    # one-shot: install nim@<epoch> under ./dbs/
    python tools/pull_db.py http://registry:9200 nim --dest ./dbs

    # replica sync: pull, rewrite fleet manifest, rolling-reload
    python tools/pull_db.py http://registry:9200 nim subtract \
        --dest ./dbs --fleet-manifest fleet.json \
        --control-url http://127.0.0.1:9100

Returns 0 on success, 1 when any pull or the reload failed (the fleet
is left serving its old epoch), 2 on usage errors. Interrupted runs are
safe to re-run: verified staged bytes are resumed, not re-fetched.
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from gamesmanmpi_tpu.registry.pull import (  # noqa: E402
    PullError,
    ensure_db,
    pull_db,
    sync_fleet,
)


def _log(record):
    sys.stderr.write(json.dumps(record, default=str) + "\n")
    sys.stderr.flush()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="pull verified DB epochs from a gamesman registry",
    )
    ap.add_argument("registry", help="registry base URL, e.g. http://host:9200")
    ap.add_argument("names", nargs="+", help="DB names to pull")
    ap.add_argument("--dest", default="dbs",
                    help="install root; DBs land as <dest>/<name>@<epoch>")
    ap.add_argument("--fleet-manifest", default=None,
                    help="fleet manifest to rewrite with the pulled epochs")
    ap.add_argument("--control-url", default=None,
                    help="supervisor control URL to POST /reload after a "
                         "manifest landing (requires --fleet-manifest)")
    ap.add_argument("--solve", metavar="SPEC", default=None,
                    help="if the (single) name is not in the catalog, queue "
                         "a solve-on-demand job for this game spec instead "
                         "of failing")
    ap.add_argument("--timeout", type=float, default=None,
                    help="per-request timeout (default "
                         "GAMESMAN_REGISTRY_TIMEOUT_SECS)")
    ap.add_argument("--json", action="store_true",
                    help="print the result record as JSON on stdout")
    args = ap.parse_args(argv)

    if args.control_url and not args.fleet_manifest:
        ap.error("--control-url requires --fleet-manifest")
    if args.solve and len(args.names) != 1:
        ap.error("--solve takes exactly one name")

    try:
        if args.fleet_manifest:
            result = sync_fleet(
                args.registry, args.names, args.fleet_manifest, args.dest,
                control_url=args.control_url, timeout=args.timeout,
                log=_log,
            )
            ok = result["status"] in ("rolled", "manifest_landed") or (
                result["status"] == "nothing_pulled" and not result["failed"]
            )
        elif args.solve:
            result = ensure_db(
                args.registry, args.names[0], spec=args.solve,
                dest_root=args.dest, timeout=args.timeout, log=_log,
            )
            ok = True
        else:
            pulls = []
            ok = True
            for name in args.names:
                try:
                    pulls.append(
                        pull_db(args.registry, name, args.dest,
                                timeout=args.timeout, log=_log)
                    )
                except PullError as e:
                    _log({"phase": "registry_pull", "name": name,
                          "error": str(e)})
                    ok = False
            result = {"pulled": pulls}
    except PullError as e:
        _log({"phase": "registry_pull", "error": str(e)})
        result, ok = {"error": str(e)}, False

    if args.json:
        print(json.dumps(result, indent=2, default=str))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
