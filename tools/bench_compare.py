#!/usr/bin/env python
"""Gate a bench record against the committed BENCH_* trajectory.

"As fast as the hardware allows" (ROADMAP item 3) is only a measured
claim while someone diffs every new bench record against the history —
this tool makes that diff a one-command CI gate::

    python bench.py > BENCH_new.json
    python tools/bench_compare.py BENCH_new.json

It loads every committed ``BENCH_*.json`` at the repo root (the
trajectory; older rounds wrapped their record under a ``parsed`` key —
both shapes load), picks the comparable references — same ``metric``
and same ``device`` as the new record — and compares the new record's
headline value against the trajectory's best. The command exits
nonzero (status 1) when ``new / best < --min-ratio`` (default 0.6, env
``BENCH_COMPARE_MIN_RATIO``): a regression past the threshold fails
CI; a pass prints the ratio plus the roofline-field deltas
(operand_gbps, dispatches/level, dispatch_overhead_frac) so a
borderline run is explainable from the output alone. Usage errors
(unreadable/record-less input) exit with status 2; no comparable
reference (first record on a new metric or device) passes with a note
— there is nothing honest to gate against.

Stdlib-only and jax-free, like tools/obs_report.py (whose ``--json``
output covers per-level reports; this tool covers the one-line bench
records).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # tools/ scripts get sys.path[0]=tools/
    sys.path.insert(0, REPO)

from gamesmanmpi_tpu.utils.env import env_float  # noqa: E402


def load_record(path: str):
    """One bench record from a file: a plain record dict, a
    ``{"parsed": {...}}`` wrapper (the r01-r05 artifact shape), or the
    last record-bearing line of a JSONL stream (bench.py prints
    provisional records line by line). None when no record is found."""
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError:
        return None
    candidates = []
    try:
        candidates.append(json.loads(text))
    except ValueError:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                candidates.append(json.loads(line))
            except ValueError:
                continue
    for obj in reversed(candidates):
        if not isinstance(obj, dict):
            continue
        if isinstance(obj.get("parsed"), dict):
            obj = obj["parsed"]
        if "metric" in obj and "value" in obj:
            return obj
    return None


def load_trajectory(pattern: str):
    """Every record the glob yields, newest-name-last: [(path, rec)]."""
    out = []
    for path in sorted(glob.glob(pattern)):
        rec = load_record(path)
        if rec is not None:
            out.append((path, rec))
    return out


def _fmt_delta(label: str, new, ref) -> str:
    if new is None or ref is None:
        return f"  {label}: n/a"
    return f"  {label}: {new} (trajectory best run: {ref})"


def compare(new: dict, trajectory, min_ratio: float) -> tuple:
    """-> (ok, lines). Reference = best comparable trajectory value."""
    refs = [
        (path, rec) for path, rec in trajectory
        if rec.get("metric") == new.get("metric")
        and rec.get("device") == new.get("device")
        and float(rec.get("value") or 0.0) > 0
        and not rec.get("provisional")
    ]
    lines = []
    if not refs:
        lines.append(
            f"no comparable reference for metric={new.get('metric')!r} "
            f"device={new.get('device')!r} in the trajectory — "
            "nothing to gate against (pass)"
        )
        return True, lines
    best_path, best = max(refs, key=lambda pr: float(pr[1]["value"]))
    ratio = float(new.get("value") or 0.0) / float(best["value"])
    lines.append(
        f"{new['metric']}: new={float(new['value']):.1f} vs best "
        f"{float(best['value']):.1f} ({os.path.basename(best_path)}) "
        f"-> ratio {ratio:.3f} (min {min_ratio:.3f})"
    )
    nrf, brf = new.get("roofline") or {}, best.get("roofline") or {}
    neff = new.get("efficiency") or {}
    beff = best.get("efficiency") or {}
    lines.append(_fmt_delta(
        "operand_gbps",
        nrf.get("operand_gbps", neff.get("operand_gbps")),
        brf.get("operand_gbps", beff.get("operand_gbps")),
    ))
    lines.append(_fmt_delta(
        "dispatches_per_level",
        (new.get("dispatches") or {}).get("per_level"),
        (best.get("dispatches") or {}).get("per_level"),
    ))
    lines.append(_fmt_delta(
        "dispatch_overhead_frac",
        nrf.get("dispatch_overhead_frac"),
        brf.get("dispatch_overhead_frac"),
    ))
    ab_ok, ab_lines = check_trace_ab(new)
    lines.extend(ab_lines)
    hot_ok, hot_lines = check_serve_hot(new)
    lines.extend(hot_lines)
    if ratio < min_ratio:
        lines.append(
            f"REGRESSION: new value is {ratio:.2f}x the trajectory best "
            f"(threshold {min_ratio:.2f}x) — investigate before "
            "committing this record"
        )
        return False, lines
    if not ab_ok or not hot_ok:
        return False, lines
    lines.append("ok")
    return True, lines


def check_trace_ab(new: dict) -> tuple:
    """-> (ok, lines): the query-tracing overhead gate (ISSUE 17).

    A record carrying a serve trace A/B arm (bench.py's
    ``serve.trace_ab`` summary, or a BENCH_serve artifact's top-level
    ``trace_ab``) must show tracing-on p99 within the arm's recorded
    threshold of tracing-off — ``ok`` is computed by bench.py at
    measurement time; this gate makes CI refuse a record where sampling
    overhead crossed it. Records without the arm pass untouched.
    """
    ab = (new.get("serve") or {}).get("trace_ab") or new.get("trace_ab")
    if not isinstance(ab, dict):
        return True, []
    if "error" in ab:
        return False, [
            f"TRACE A/B BROKEN: {ab['error']} — the overhead arm never "
            "measured; rerun before committing this record"
        ]
    delta = ab.get("delta_pct")
    limit = ab.get("max_delta_pct")
    line = (
        f"trace_ab: tracing-on p99 delta {delta}% "
        f"(limit {limit}% + slack) -> "
        + ("ok" if ab.get("ok") else "OVER BUDGET")
    )
    if not ab.get("ok"):
        return False, [
            line,
            "TRACING OVERHEAD REGRESSION: tail-sampled query tracing "
            "costs more than the recorded p99 budget — investigate "
            "before committing this record",
        ]
    return True, [line]


def check_serve_hot(new: dict) -> tuple:
    """-> (ok, lines): the serving hot-path A/B gate (ISSUE 18).

    A record carrying a serve hot-path arm (bench.py's ``serve_hot``
    summary, or a BENCH_serve_hot artifact's top-level ``serve_hot``)
    must show the hot arm (opening book + cross-worker shared block
    cache + batcher dedup) beating the cold baseline on BOTH qps and
    p99 under the same zipf stream, with zero errors/mismatches on
    either arm, book AND shm hit counters above zero, and the
    conditional-GET pass revalidating clean — ``ok`` is computed by
    bench.py at measurement time; this gate makes CI refuse a record
    where the hot path stopped paying for itself (or stopped being
    exercised at all). Records without the arm pass untouched.
    """
    hot = new.get("serve_hot")
    if not isinstance(hot, dict):
        return True, []
    if "error" in hot:
        return False, [
            f"SERVE HOT A/B BROKEN: {hot['error']} — the hot-path arm "
            "never measured; rerun before committing this record"
        ]
    base_arm = hot.get("baseline") or {}
    hot_arm = hot.get("hot") or {}
    line = (
        f"serve_hot: hot {hot_arm.get('qps')} qps / "
        f"{hot_arm.get('p99_ms')} ms p99 vs baseline "
        f"{base_arm.get('qps')} qps / {base_arm.get('p99_ms')} ms p99, "
        f"book_hits={hot.get('book_hits')} shm_hits={hot.get('shm_hits')}"
        " -> " + ("ok" if hot.get("ok") else "FAILED")
    )
    if not hot.get("ok"):
        detail = ", ".join(
            g for g in ("clean", "perf_ok", "hits_ok", "get_ok")
            if not hot.get(g, True)
        ) or "gate flags missing"
        return False, [
            line,
            f"SERVE HOT PATH REGRESSION ({detail}): the book/shm/dedup "
            "stack no longer beats the cold baseline (or went "
            "unexercised) — investigate before committing this record",
        ]
    return True, [line]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench_compare",
        description="Diff a new bench record against the committed "
        "BENCH_* trajectory; nonzero status on regression past the "
        "threshold (docs/OBSERVABILITY.md \"Roofline fields\").",
    )
    p.add_argument("record", help="new bench record (bench.py stdout, "
                   "a BENCH_*.json artifact, or a JSONL stream)")
    p.add_argument("--trajectory", default=None, metavar="GLOB",
                   help="reference records (default: BENCH_*.json at "
                   "the repo root)")
    p.add_argument("--min-ratio", type=float, default=None,
                   help="fail when new/best falls below this (env "
                   "BENCH_COMPARE_MIN_RATIO, default 0.6)")
    args = p.parse_args(argv)
    min_ratio = (
        env_float("BENCH_COMPARE_MIN_RATIO", 0.6)
        if args.min_ratio is None else float(args.min_ratio)
    )
    new = load_record(args.record)
    if new is None:
        print(f"error: no bench record found in {args.record!r}",
              file=sys.stderr)
        return 2
    pattern = args.trajectory or os.path.join(REPO, "BENCH_*.json")
    trajectory = [
        (path, rec) for path, rec in load_trajectory(pattern)
        if os.path.abspath(path) != os.path.abspath(args.record)
    ]
    ok, lines = compare(new, trajectory, min_ratio)
    print("\n".join(lines))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
