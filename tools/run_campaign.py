#!/usr/bin/env python
"""Self-healing solve campaign driver (docs/DISTRIBUTED.md "Campaigns").

Wraps one solve — single-process, or a whole launch_multihost world —
in the auto-resume supervisor (resilience/campaign.py): every
crash/preemption/watchdog abort resumes from the latest consistent
checkpoint with bounded backoff, a no-progress breaker aborts with a
diagnosis bundle, ENOSPC degrades to GC-and-retry, and every attempt is
a fsync'd line in the append-only campaign ledger.

Elastic resume (docs/DISTRIBUTED.md "Elastic resume"): checkpoint
geometry is a resume-time choice — an attempt may run at a different
shard count or world size than the tree was sealed at (rows re-
partition through the owner hash on load). The campaign exploits it:
an oom-classified death escalates geometry for the next attempt
(--devices doubles under --max-shards, the store cache halves to
--cache-floor-mb), and with --elastic-ranks a lost-rank death retries
the world at W-1 ranks. Every geometry change is a ledger record.

Examples::

    # the ROADMAP item 1 staging ladder, one rung:
    python tools/run_campaign.py connect4:w=5,h=4 \
        --checkpoint-dir /data/c4_5x4 --processes 2 -- --devices 4

    # chaos proof: three injected kills, then driven to completion
    python tools/run_campaign.py connect4:w=5,h=4 \
        --checkpoint-dir /tmp/ck --processes 2 \
        --chaos sharded.forward:kill:3 \
        --chaos sharded.backward:kill:2 \
        --chaos store.writebehind:kill:1 -- --devices 4

Everything after ``--`` goes to the solve CLI verbatim (the campaign
adds ``--checkpoint-dir`` itself). Exit codes: 0 solved, 2 usage,
3 no-progress breaker / attempts exhausted, 4 disk hard floor,
75 campaign preempted (rerun the same command to continue).

This process never imports jax (startup is instant; the solve happens
in the attempt subprocesses), so it survives anything the attempt does
to its own runtime.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # tools/ scripts get sys.path[0]=tools/
    sys.path.insert(0, REPO)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="run_campaign",
        description="Drive one solve to completion across failures: "
        "auto-resume, preemption grace, disk-budget GC, append-only "
        "ledger (docs/DISTRIBUTED.md).",
    )
    p.add_argument("game", help="built-in game spec, e.g. "
                   "connect4:w=5,h=4 (passed to the solve CLI)")
    p.add_argument("--checkpoint-dir", required=True,
                   help="the campaign's one source of truth: attempts "
                   "resume from it, the ledger lives next to it")
    p.add_argument("--processes", type=int, default=1,
                   help="1 = single solve process; N>1 = a real "
                   "tools/launch_multihost.py jax.distributed world "
                   "per attempt")
    p.add_argument("--max-attempts", type=int, default=None,
                   help="attempt budget: past it, the next attempt "
                   "that seals nothing new aborts (progressing "
                   "attempts never die on the budget alone; env "
                   "GAMESMAN_CAMPAIGN_MAX_ATTEMPTS, default 8)")
    p.add_argument("--no-progress", type=int, default=None, metavar="K",
                   help="breaker: abort after K consecutive attempts "
                   "that seal nothing new (env "
                   "GAMESMAN_CAMPAIGN_NO_PROGRESS, default 3)")
    p.add_argument("--backoff-base-secs", type=float, default=None,
                   help="first inter-attempt backoff, doubling per "
                   "consecutive failure (env "
                   "GAMESMAN_CAMPAIGN_BACKOFF_BASE_SECS, default 1)")
    p.add_argument("--backoff-max-secs", type=float, default=None,
                   help="backoff ceiling (env "
                   "GAMESMAN_CAMPAIGN_BACKOFF_MAX_SECS, default 60)")
    p.add_argument("--attempt-timeout", type=float, default=None,
                   metavar="S",
                   help="kill an attempt running longer than S seconds "
                   "(env GAMESMAN_CAMPAIGN_ATTEMPT_SECS; 0 = none)")
    p.add_argument("--disk-soft-mb", type=float, default=None,
                   help="run retention GC when free space drops below "
                   "this (env GAMESMAN_CKPT_DISK_SOFT_MB; 0 = off)")
    p.add_argument("--disk-floor-mb", type=float, default=None,
                   help="abort cleanly (exit 4, prefix intact) when "
                   "free space is below this after GC (env "
                   "GAMESMAN_CKPT_DISK_FLOOR_MB; 0 = off)")
    p.add_argument("--ledger", default=None, metavar="FILE",
                   help="campaign ledger path (default "
                   "<checkpoint-dir>/campaign.jsonl)")
    p.add_argument("--log-dir", default=None,
                   help="per-attempt solve logs (default "
                   "<checkpoint-dir>/logs)")
    p.add_argument("--elastic-ranks", action="store_true", default=None,
                   help="retry a lost-rank death (killed/signal/"
                   "deadline-abort/timeout) at W-1 ranks, floor 1 — the "
                   "checkpoint tree is world-size-elastic (env "
                   "GAMESMAN_CAMPAIGN_ELASTIC_RANKS, default off)")
    p.add_argument("--no-oom-escalate", action="store_true",
                   help="disable the oom policy (an oom-classified "
                   "death otherwise doubles --devices under the "
                   "shard cap and halves GAMESMAN_STORE_CACHE_MB for "
                   "the next attempt; env "
                   "GAMESMAN_CAMPAIGN_OOM_ESCALATE, default on)")
    p.add_argument("--max-shards", type=int, default=None,
                   help="ceiling for oom shard escalation (env "
                   "GAMESMAN_CAMPAIGN_MAX_SHARDS, default 64)")
    p.add_argument("--cache-floor-mb", type=int, default=None,
                   help="floor for oom store-cache shrinking (env "
                   "GAMESMAN_CAMPAIGN_CACHE_FLOOR_MB, default 16)")
    p.add_argument("--chaos", action="append", default=None,
                   metavar="SPEC",
                   help="GAMESMAN_FAULTS spec armed for attempt i "
                   "(repeat per attempt; later attempts run clean; "
                   "multi-process worlds arm rank 0). The chaos-"
                   "campaign acceptance knob — not for production")
    p.add_argument("--local-devices", type=int, default=None,
                   help="multi-process: fake CPU devices per rank "
                   "(launch_multihost's knob)")
    p.add_argument("--status-port", type=int, default=None, metavar="P",
                   help="mission control: serve GET /status on this "
                   "port for the WHOLE campaign — attempt/backoff/"
                   "breaker state plus the live attempt's own solve "
                   "status proxied through, so one URL survives every "
                   "restart (env GAMESMAN_STATUS_PORT; 0 = ephemeral; "
                   "unset = off)")
    return p


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Split on the first bare "--" OURSELVES: argparse.REMAINDER after a
    # positional would swallow the campaign's own flags too.
    extra: list = []
    if "--" in argv:
        split = argv.index("--")
        argv, extra = argv[:split], argv[split + 1:]
    args = build_parser().parse_args(argv)
    if args.status_port is not None:
        # The flag is the env twin's CLI spelling, like the solve CLI's
        # capacity flags; Campaign reads GAMESMAN_STATUS_PORT itself.
        os.environ["GAMESMAN_STATUS_PORT"] = str(args.status_port)
    from gamesmanmpi_tpu.resilience.campaign import (
        Campaign,
        CampaignConfig,
    )

    for banned in ("--checkpoint-dir",):
        if banned in extra:
            print(f"error: {banned} is the campaign's to manage — set "
                  "it with the campaign flag", file=sys.stderr)
            return 2
    if args.processes < 1:
        print("error: --processes must be >= 1", file=sys.stderr)
        return 2
    cfg = CampaignConfig(
        solver_args=[args.game, *extra],
        checkpoint_dir=args.checkpoint_dir,
        processes=args.processes,
        max_attempts=args.max_attempts,
        no_progress_limit=args.no_progress,
        backoff_base_secs=args.backoff_base_secs,
        backoff_max_secs=args.backoff_max_secs,
        attempt_timeout_secs=args.attempt_timeout,
        disk_soft_mb=args.disk_soft_mb,
        disk_floor_mb=args.disk_floor_mb,
        oom_escalate=False if args.no_oom_escalate else None,
        max_shards=args.max_shards,
        cache_floor_mb=args.cache_floor_mb,
        elastic_ranks=args.elastic_ranks,
        ledger_path=args.ledger,
        log_dir=args.log_dir,
        chaos=list(args.chaos or []),
        local_devices=args.local_devices,
    )
    campaign = Campaign(cfg)
    restore = campaign.install_signal_handlers()
    try:
        return campaign.run()
    finally:
        restore()


if __name__ == "__main__":
    sys.exit(main())
