#!/usr/bin/env python
"""Static GameSpec validator (CI-runnable).

    python tools/spec_lint.py [SPEC.json ...]

With no arguments, lints every committed spec under examples/specs/.
Runs gamedsl's static validation (gamesmanmpi_tpu/gamedsl/spec.py) —
schema strictness, board-vs-encoding bit budgets (the 63-bit packing
limit and the 26-bit fused value-table `_bwdt` gate), unreachable or
dead win predicates, symmetry generators incompatible with the move
family, and symmetry-closure preservation of the win-line set — without
tracing a kernel or touching an accelerator.

One line per finding:

    examples/specs/bad.json: GS103 error: win predicate is unreachable...

Exit 0 = no errors (warnings are advisory), 1 = error findings,
2 = usage error. The same validation gates `gamesman solve --spec` at
compile time and runs over committed specs in gamesman-lint (GM901);
this tool is the standalone spelling for spec authors.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # tools/ scripts get sys.path[0]=tools/
    sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="spec_lint",
        description="Validate declarative GameSpec files "
        "(docs/GAMEDSL.md).",
    )
    p.add_argument("specs", nargs="*",
                   help="GameSpec .json files (default: examples/specs/*)")
    p.add_argument("--errors-only", action="store_true",
                   help="suppress warning-severity findings")
    args = p.parse_args(argv)

    from gamesmanmpi_tpu.gamedsl.spec import lint_file

    paths = args.specs or sorted(
        glob.glob(os.path.join(_REPO, "examples", "specs", "*.json"))
    )
    if not paths:
        print("error: no spec files to lint", file=sys.stderr)
        return 2
    errors = 0
    for path in paths:
        findings = lint_file(path)
        for f in findings:
            if args.errors_only and f["severity"] != "error":
                continue
            print(f"{path}: {f['code']} {f['severity']}: {f['message']}")
            if f["severity"] == "error":
                errors += 1
        if not findings:
            print(f"{path}: OK")
    if errors:
        print(f"{errors} error finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
