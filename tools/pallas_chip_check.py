#!/usr/bin/env python
"""Focused chip check for ops/pallas_gather.monotone_window_gather.

Answers, in under ~2 minutes of chip time, the CHIP_PLAN §1 question the
full microbench2 run spends 15 minutes around: does Mosaic accept the
kernel, is it CORRECT on silicon (vs the XLA gather), and does it beat
XLA's ~9-11 ns/element random-access gather on the dense engine's actual
access pattern (globally non-decreasing indices)?

Prints one human line per case plus a final JSON line
{"kernel_ok": bool, "best": {...}} for artifacts.

Single-client discipline: run ONLY when nothing else is on the relay.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from gamesmanmpi_tpu.utils.platform import apply_platform_env

# --smoke is by definition an off-chip run: force CPU ourselves rather
# than requiring the operator to remember GAMESMAN_PLATFORM=cpu — the
# container pins jax_platforms="axon,cpu", so a bare run with the relay
# down hangs dialing the dead backend.
if "--smoke" in sys.argv:
    os.environ.setdefault("GAMESMAN_PLATFORM", "cpu")
# Honor GAMESMAN_PLATFORM before the first backend touch.
apply_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from gamesmanmpi_tpu.ops.pallas_gather import monotone_window_gather  # noqa: E402


def timeit(fn, *args, n=3, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
        jax.block_until_ready(out)
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> int:
    # --smoke: the r4 session lost its only window slot to an untested
    # launcher (ModuleNotFoundError) — this flag runs the EXACT same
    # entrypoints off-relay (CPU interpret, tiny sizes) so the tool is
    # provably runnable before it ever costs chip time.
    smoke = "--smoke" in sys.argv
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev})"
          + (" [SMOKE: interpret, tiny]" if smoke else ""), flush=True)
    rng = np.random.default_rng(0)
    N = 64 * 1024 if smoke else 32 * 1024 * 1024
    M = 16 * 1024 if smoke else 8 * 1024 * 1024
    idx_np = np.sort(rng.integers(0, M, size=N)).astype(np.int32)
    idx = jnp.asarray(idx_np)

    results = []
    kernel_ok = True
    for dtype, hi in ((np.uint32, 1 << 30), (np.uint8, 256)):
        tb_np = rng.integers(0, hi, size=M, dtype=dtype)
        tb = jnp.asarray(tb_np)
        name = np.dtype(dtype).name

        secs_x, ref = timeit(lambda t, i: t[i], tb, idx)
        print(f"xla gather {name} [{N//1024}K from {M//1024}K]"
              f"      {secs_x*1e3:9.2f} ms", flush=True)
        ref_np = np.asarray(ref)

        for block, window in ((2048, 8192), (4096, 16384), (8192, 32768)):
            label = f"pallas monotone {name} b={block} w={window}"
            try:
                fn = jax.jit(lambda t, i: monotone_window_gather(
                    t, i, block=block, window=window, interpret=smoke))
                secs, (out, nmiss) = timeit(fn, tb, idx)
            except Exception as e:  # Mosaic rejection or runtime failure
                kernel_ok = False
                print(f"{label}  FAILED: {type(e).__name__}: {e}"[:220],
                      flush=True)
                continue
            nmiss = int(nmiss)
            good = bool((np.asarray(out) == ref_np).all()) and nmiss == 0
            print(f"{label}  {secs*1e3:9.2f} ms   miss={nmiss} "
                  f"correct={good}  speedup={secs_x/secs:5.2f}x", flush=True)
            if not good:
                kernel_ok = False
            results.append({"dtype": name, "block": block, "window": window,
                            "secs": round(secs, 4), "nmiss": nmiss,
                            "correct": good,
                            "xla_secs": round(secs_x, 4),
                            "speedup": round(secs_x / secs, 2)})

    # int64-idx leg (6x6+ flat spaces): same data, idx widened — must be
    # bit-identical and Mosaic-accepted (the kernel sees only block-local
    # int32 offsets; this proves the wrapper's claim on silicon).
    tb = jnp.asarray(rng.integers(0, 1 << 30, size=M, dtype=np.uint32))
    ref64 = np.asarray(tb[idx])
    try:
        fn64 = jax.jit(lambda t, i: monotone_window_gather(
            t, i, block=2048, window=8192, interpret=smoke))
        secs64, (out64, nm64) = timeit(fn64, tb, idx.astype(jnp.int64))
        good64 = (bool((np.asarray(out64) == ref64).all())
                  and int(nm64) == 0)
        print(f"pallas monotone uint32 i64-idx b=2048 w=8192  "
              f"{secs64*1e3:9.2f} ms   miss={int(nm64)} correct={good64}",
              flush=True)
        if not good64:
            kernel_ok = False
        results.append({"dtype": "uint32_i64idx", "block": 2048,
                        "window": 8192, "secs": round(secs64, 4),
                        "nmiss": int(nm64), "correct": good64})
    except Exception as e:
        kernel_ok = False
        print(f"pallas i64-idx leg FAILED: {type(e).__name__}: {e}"[:220],
              flush=True)

    best = max((r for r in results if r["correct"]),
               key=lambda r: r.get("speedup", 0.0), default=None)
    print(json.dumps({"kernel_ok": kernel_ok, "device": dev.platform,
                      "best": best}), flush=True)
    return 0 if kernel_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
