#!/usr/bin/env python
"""Watch the TPU relay port; run a chip-session phase the moment it lives.

The relay's observed MTBF is ~75 minutes and its revivals are driven by
an external supervisor on no announced schedule — so the chip plan's
remaining steps must launch themselves within a minute of the port
accepting connections, not when a human notices. Probe is TCP-only
(never a jax client: a probe client of its own can wedge a half-up
relay), with a settle delay and a re-probe before committing the session.

Usage:
    python tools/relay_watch.py [--phase3] [--max-hours 10]

Single-client discipline: this script launches chip_session.py in the
foreground of its own process; nothing else may touch the backend while
it runs (concurrent shells: GAMESMAN_PLATFORM=cpu).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# jax-free by design (watches the relay that jax clients wedge on), so
# it cannot import the package's env helpers.  # lint: disable=GM301
RELAY_PORT = int(os.environ.get("GAMESMAN_RELAY_PORT", "8103"))


def relay_up() -> bool:
    try:
        with socket.create_connection(("127.0.0.1", RELAY_PORT), timeout=5):
            return True
    except OSError:
        return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase3", action="store_true")
    ap.add_argument("--pallas-only", action="store_true")
    ap.add_argument("--max-hours", type=float, default=10.0)
    ap.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "chip_session_r05.jsonl"))
    ap.add_argument("--poll-secs", type=float, default=60.0)
    ap.add_argument("--settle-secs", type=float, default=45.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    while time.time() < deadline:
        if relay_up():
            # Settle, then re-probe: the port can flap while the relay's
            # device claim is still torn down from its previous life.
            time.sleep(args.settle_secs)
            if relay_up():
                argv = [sys.executable,
                        os.path.join(REPO, "tools", "chip_session.py"),
                        "--out", args.out]
                if args.pallas_only:
                    argv.append("--pallas-only")
                elif args.phase3:
                    argv.append("--phase3")
                print(f"[relay_watch] relay live; launching {argv}",
                      flush=True)
                rc = subprocess.call(argv, cwd=REPO)
                print(f"[relay_watch] chip_session exited rc={rc}",
                      flush=True)
                if rc == 0:
                    return 0
                # Aborted mid-plan (relay died again): resume watching —
                # chip_session records per step, so a re-run only costs
                # the re-measured steps.
        time.sleep(args.poll_secs)
    print("[relay_watch] deadline reached without a completed session",
          flush=True)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
