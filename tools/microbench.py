#!/usr/bin/env python
"""Microbenchmarks attributing TPU solve time to primitive ops.

Run on the real chip: `python tools/microbench.py`. Times the building blocks
of the solver hot path (sort, dedup-compaction variants, lookup variants,
gathers, host transfers, dispatch latency) so regressions like BENCH_r02's
TPU-slower-than-CPU result can be attributed instead of guessed at
(VERDICT.md round 2, "Next round" item 1).

NB: on the axon relay `block_until_ready` does NOT wait for device work;
every timed function therefore reduces its outputs to one scalar on device
and the harness fetches that scalar (a 4-byte download) to synchronize.
"""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(_REPO, ".jax_compile_cache"))

import gamesmanmpi_tpu  # noqa: F401  (x64 on)
import jax
import jax.numpy as jnp
import numpy as np


def _scalarize(r):
    leaves = jax.tree_util.tree_leaves(r)
    acc = jnp.uint32(0)
    for leaf in leaves:
        acc = acc + jnp.max(leaf).astype(jnp.uint32)
    return acc


def timeit(label, fn, *args, n=5, warmup=2):
    """fn must end in a scalar (use scalar=True wrappers below)."""
    f = jax.jit(lambda *a: _scalarize(fn(*a)))
    for _ in range(warmup):
        np.asarray(f(*args))
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(f(*args))
        ts.append(time.perf_counter() - t0)
    best = min(ts)
    med = sorted(ts)[len(ts) // 2]
    print(f"{label:48s} best {best*1e3:9.2f} ms  med {med*1e3:9.2f} ms",
          flush=True)
    return best


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev})", file=sys.stderr)

    N = 32 * 1024 * 1024  # ~ a big 5x5 level's children (cap*M)
    M = 8 * 1024 * 1024   # ~ a big solved-level table

    rng = np.random.default_rng(0)
    keys_np = rng.integers(0, 1 << 30, size=N, dtype=np.uint32)
    table_np = np.sort(rng.integers(0, 1 << 30, size=M, dtype=np.uint32))
    keys = jnp.asarray(keys_np)
    table = jnp.asarray(table_np)
    tvals = jnp.asarray(rng.integers(0, 4, size=M, dtype=np.uint8))
    trem = jnp.asarray(rng.integers(0, 40, size=M, dtype=np.int32))

    # 0. dispatch+sync latency: the floor for any timed op here
    tiny = jnp.arange(256, dtype=jnp.uint32)
    timeit("sync floor: tiny kernel + 4B fetch", lambda x: x + 1, tiny, n=20)

    # 1. sort
    timeit(f"sort u32 [{N>>20}M]", jnp.sort, keys)
    keys64 = keys.astype(jnp.uint64)
    timeit(f"sort u64 [{N>>20}M]", jnp.sort, keys64)

    # 2. dedup variants
    from gamesmanmpi_tpu.ops.dedup import sort_unique
    timeit(f"sort_unique (current impl)   [{N>>20}M]", sort_unique, keys)

    def sort_unique_scatter(states):
        """The rejected O(N) compaction: cumsum + scatter (r2's impl)."""
        sentinel = jnp.uint32(0xFFFFFFFF)
        s = jnp.sort(states)
        first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
        keep = first & (s != sentinel)
        idx = (jnp.cumsum(keep.astype(jnp.int32)) - 1)
        out = jnp.full(s.shape, sentinel, dtype=s.dtype)
        out = out.at[jnp.where(keep, idx, s.shape[0])].set(s, mode="drop")
        count = jnp.sum(keep).astype(jnp.int32)
        return out, count

    timeit(f"sort_unique (scatter compact)[{N>>20}M]", sort_unique_scatter,
           keys)

    def sort_unique_resort(states):
        sentinel = jnp.uint32(0xFFFFFFFF)
        s = jnp.sort(states)
        first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
        keep = first & (s != sentinel)
        marked = jnp.where(keep, s, sentinel)
        out = jnp.sort(marked)
        count = jnp.sum(keep).astype(jnp.int32)
        return out, count

    timeit(f"sort_unique (mark+resort)    [{N>>20}M]", sort_unique_resort, keys)

    def scatter_only(s):
        keep = (s & 1) == 0
        idx = (jnp.cumsum(keep.astype(jnp.int32)) - 1)
        out = jnp.full(s.shape, jnp.uint32(0xFFFFFFFF), dtype=s.dtype)
        return out.at[jnp.where(keep, idx, s.shape[0])].set(s, mode="drop")

    timeit(f"scatter compaction alone     [{N>>20}M]", scatter_only, keys)

    timeit(f"cumsum int32 [{N>>20}M]",
           lambda s: jnp.cumsum((s & 1).astype(jnp.int32)), keys)
    timeit(f"cumsum int64 [{N>>20}M]", lambda s: jnp.cumsum(s & 1), keys)

    # 2b. pair sort (provenance forward) vs packed-u64 single sort — the
    # open question for the next forward optimization: lax.sort with a
    # carried operand vs packing (key<<32 | origin) into one u64.
    origin = jnp.arange(N, dtype=jnp.int32)

    def pair_sort(k, o):
        return jax.lax.sort((k, o), num_keys=1, is_stable=False)

    def packed_sort(k, o):
        packed = (k.astype(jnp.uint64) << jnp.uint64(32)) | (
            o.astype(jnp.uint64)
        )
        s = jnp.sort(packed)
        return (s >> jnp.uint64(32)).astype(jnp.uint32), (
            s & jnp.uint64(0xFFFF_FFFF)
        ).astype(jnp.int32)

    timeit(f"pair sort (u32,i32) [{N>>20}M]", pair_sort, keys, origin, n=3)
    timeit(f"packed u64 sort     [{N>>20}M]", packed_sort, keys, origin, n=3)

    # 3. lookup variants
    timeit(f"searchsorted scan  [{N>>20}M in {M>>20}M]",
           lambda k, t: jnp.searchsorted(t, k).astype(jnp.uint32), keys, table,
           n=3)
    timeit(f"searchsorted sort  [{N>>20}M in {M>>20}M]",
           lambda k, t: jnp.searchsorted(t, k, method="sort").astype(jnp.uint32),
           keys, table, n=3)

    from gamesmanmpi_tpu.ops.lookup import lookup_sorted
    timeit(f"lookup_sorted (current) [{N>>20}M in {M>>20}M]", lookup_sorted,
           keys, table, tvals, trem, n=3)

    # 4. gather
    idx = jnp.asarray(rng.integers(0, M, size=N, dtype=np.int32))
    timeit(f"gather u32 [{N>>20}M from {M>>20}M]", lambda t, i: t[i], table,
           idx, n=3)

    # 5. transfers (latency + bandwidth)
    for mb in (1, 16, 256):
        big = jnp.zeros(mb * 256 * 1024, dtype=jnp.uint32)
        np.asarray(jnp.max(big))  # ensure materialized
        t0 = time.perf_counter()
        _ = np.asarray(big)
        dt = time.perf_counter() - t0
        print(f"{f'download {mb}MB device->host':48s} {dt*1e3:12.2f} ms "
              f"({mb/dt:.1f} MB/s)", flush=True)
    for mb in (1, 16, 256):
        host = np.zeros(mb * 256 * 1024, dtype=np.uint32)
        t0 = time.perf_counter()
        x = jnp.asarray(host)
        np.asarray(jnp.max(x))
        dt = time.perf_counter() - t0
        print(f"{f'upload {mb}MB host->device':48s} {dt*1e3:12.2f} ms "
              f"({mb/dt:.1f} MB/s)", flush=True)

    # 6. solver kernels (connect4 5x5)
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.solve.engine import expand_core, resolve_level

    g = get_game("connect4:w=5,h=5")
    B = 4 * 1024 * 1024
    states = jnp.asarray(rng.integers(0, 1 << 30, size=B, dtype=np.uint32))
    timeit(f"expand_core c4 5x5 [{B>>20}M]", lambda s: expand_core(g, s),
           states, n=3)

    wstates = jnp.asarray(np.sort(
        rng.integers(0, 1 << 30, size=B, dtype=np.uint32)))
    timeit(f"resolve_level c4 5x5 [{B>>20}M vs {B>>20}M]",
           lambda s, a, b, c: resolve_level(g, s, ((a, b, c),)), states,
           wstates, tvals[:B], trem[:B], n=3)

    # primitive/decompose alone
    timeit(f"primitive c4 5x5 [{B>>20}M]", lambda s: g.primitive(s), states,
           n=3)
    timeit(f"expand (no dedup) c4 5x5 [{B>>20}M]",
           lambda s: g.expand(s), states, n=3)


if __name__ == "__main__":
    main()
