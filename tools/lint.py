"""``python -m tools.lint`` — run gamesman-lint over this repo.

Thin wrapper: the implementation lives in
``gamesmanmpi_tpu.analysis.cli`` (also installed as the
``gamesman-lint`` console script); this module only defaults ``--root``
to the repository the file sits in, so the command works from any cwd.
"""

import os
import sys

from gamesmanmpi_tpu.analysis.cli import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    argv = sys.argv[1:]
    if "--root" not in argv:
        argv = ["--root", REPO, *argv]
    raise SystemExit(main(argv))
