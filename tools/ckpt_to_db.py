#!/usr/bin/env python
"""Convert an existing --checkpoint-dir into a servable solved-position DB.

Past solves (including big-run --no-tables solves, whose only durable
output IS the checkpoint directory) become queryable databases without
re-solving:

    python tools/ckpt_to_db.py CKPT_DIR OUT_DIR --game 'connect4:w=5,h=4'

Consumes classic-engine checkpoints — global per-level files or sharded
per-(level, shard) sets (shards are assembled and sorted per level, one
level at a time, so conversion memory is one level, not the table).
Dense-engine checkpoints are refused (see db/writer.export_checkpoint).
The --game spec must name the exact configuration the checkpoint was
solved with; the bound game name in the checkpoint manifest is validated
against it, so a sym=0 DB can never be built from a sym=1 checkpoint.

This tool is a positional-argument spelling of
`python -m gamesmanmpi_tpu.cli export-db GAME --out OUT --from-checkpoint
CKPT` and delegates to it — one conversion code path, two front doors.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("checkpoint_dir", help="existing --checkpoint-dir")
    p.add_argument("out_dir", help="DB output directory")
    p.add_argument(
        "--game",
        required=True,
        help="built-in game spec the checkpoint was solved with "
        "(e.g. tictactoe, 'connect4:w=5,h=4,sym=1')",
    )
    p.add_argument("--overwrite", action="store_true",
                   help="replace an existing DB in out_dir")
    p.add_argument("--compress", action="store_true",
                   help="write format v2 (block-compressed levels, "
                   "decompress-on-probe serving) — see export-db "
                   "--compress")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="print per-level progress to stderr")
    args = p.parse_args(argv)

    from gamesmanmpi_tpu.cli import main as cli_main

    forward = [
        "export-db", args.game,
        "--out", args.out_dir,
        "--from-checkpoint", args.checkpoint_dir,
    ]
    if args.overwrite:
        forward.append("--overwrite")
    if args.compress:
        forward.append("--compress")
    if args.verbose:
        forward.append("--verbose")
    return cli_main(forward)


if __name__ == "__main__":
    sys.exit(main())
