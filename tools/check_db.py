#!/usr/bin/env python
"""Solved-position DB integrity checker (CI-runnable).

    python tools/check_db.py DB_DIR [--quiet] [--stats-json F]
                                    [--same-as OTHER_DB]

Validates the manifest, per-shard sha256 checksums, key sortedness/
uniqueness/sentinel-freedom, cell dtypes and decided-ness — and, for
format v2 (block-compressed) directories, the whole block machinery:
index structure vs real stream sizes, per-block crc32, decoded position
counts, and the manifest's block-router first_keys (see
gamesmanmpi_tpu/db/check.py for the full list). After a clean check a
per-level size/ratio summary table prints (suppressed by --quiet):

    level  positions    stored_MB       raw_MB  ratio  codecs
        0          1          0.0          0.0   1.9x  keydelta,raw
    TOTAL       5478          0.1          0.3   4.2x

--same-as proves this DB logically identical (same levels, keys, cells)
to another directory regardless of storage version — the migration gate
for a compressed re-export. It screens with the sealed manifest sha256
digests first (matching digests = identical stored bytes, no decode at
all) and only streams the full decoded compare when the screen is
inconclusive — e.g. the two sides use different storage versions, where
digest inequality says nothing about the solved content. --deep forces
the streamed compare unconditionally. --stats-json dumps the db_stats
record for machine consumers (bench.py's BENCH_DB_COMPRESS gate).

When the manifest records an opening book (book.gmb), the structural
pass checks its seal/parse/sortedness — and then EVERY entry is
re-probed through a real DbReader (db/book.py verify_book): a book
answer that disagrees with the slow path it shadows is a wrong answer
waiting to be served, and exits 1 like any other problem.
--skip-book-probe keeps the run kernel-free (the structural seal check
still runs).

Exit 0 = clean, 1 = problems (printed one per line; any block-index or
cell-count mismatch is a problem), 2 = usage error. Pure numpy file
reads — no game construction, no kernels, no backend init — so it runs
in seconds even where accelerator bring-up is expensive or wedged; the
one exception is the opening-book deep probe above, which builds the
game's query kernels because proving answers requires answering.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # tools/ scripts get sys.path[0]=tools/
    sys.path.insert(0, _REPO)


def format_stats_table(stats: dict) -> str:
    """The per-level size/ratio table (db_stats record -> text)."""
    lines = [
        f"{'level':>5}  {'positions':>10}  {'stored_MB':>11}  "
        f"{'raw_MB':>11}  {'ratio':>6}  codecs"
    ]
    for row in stats["levels"]:
        lines.append(
            f"{row['level']:>5}  {row['count']:>10}  "
            f"{row['stored_bytes'] / 1e6:>11.2f}  "
            f"{row['raw_bytes'] / 1e6:>11.2f}  "
            f"{row['ratio']:>5.1f}x  {','.join(row['codecs'])}"
        )
    lines.append(
        f"{'TOTAL':>5}  {stats['num_positions']:>10}  "
        f"{stats['stored_bytes'] / 1e6:>11.2f}  "
        f"{stats['raw_bytes'] / 1e6:>11.2f}  "
        f"{stats['ratio']:>5.1f}x  (format v{stats['version']})"
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("db_dir", help="database directory (from export-db)")
    p.add_argument("--quiet", action="store_true",
                   help="print problems only — no per-level OK lines, "
                   "no summary table")
    p.add_argument("--stats-json", default=None, metavar="FILE",
                   help="also write the db_stats record (per-level "
                   "sizes/ratios) as JSON")
    p.add_argument("--same-as", default=None, metavar="OTHER_DB",
                   help="additionally require logical equality with "
                   "another DB directory (storage-version-agnostic; "
                   "the v1-vs-compressed migration gate). Fast path: "
                   "the sealed manifest sha256s are compared first — "
                   "matching digests prove equality with zero decode; "
                   "only an inconclusive screen falls back to the full "
                   "streamed compare")
    p.add_argument("--deep", action="store_true",
                   help="with --same-as: skip the manifest-digest fast "
                   "path and always run the full streamed decode "
                   "compare (paranoia mode — also proves the digests "
                   "themselves were honest)")
    p.add_argument("--skip-book-probe", action="store_true",
                   help="skip the opening-book deep re-probe (the only "
                   "check that builds game kernels); the structural "
                   "seal/parse check still runs")
    args = p.parse_args(argv)

    from gamesmanmpi_tpu.db.check import (
        check_db,
        db_equal,
        db_equal_fast,
        db_stats,
    )
    from gamesmanmpi_tpu.db.format import DbFormatError, read_manifest

    problems = check_db(
        args.db_dir, verbose=None if args.quiet else print
    )
    if args.same_as:
        verdict = "unknown"
        if not args.deep:
            verdict, fast_diffs = db_equal_fast(args.db_dir, args.same_as)
            if verdict == "same" and not args.quiet:
                print(f"same-as {args.same_as}: manifest digests match "
                      "(fast path)")
            elif verdict == "different":
                problems += [
                    f"differs from {args.same_as}: {d}" for d in fast_diffs
                ]
        if verdict == "unknown":
            # Inconclusive (or --deep): stream the actual tables.
            problems += [
                f"differs from {args.same_as}: {d}"
                for d in db_equal(args.db_dir, args.same_as)
            ]
    if not problems and not args.skip_book_probe:
        try:
            has_book = bool(read_manifest(args.db_dir).get("book"))
        except DbFormatError:
            has_book = False
        if has_book:
            # Deep half of the book gate: every sealed entry re-probed
            # through a real reader — a mismatch is a wrong answer the
            # hot path WOULD have served, so it fails the check outright.
            from gamesmanmpi_tpu.db.book import verify_book
            problems += verify_book(args.db_dir)
            if not args.quiet and not problems:
                print("book: deep re-probe OK (every entry matches the "
                      "reader)")
    for problem in problems:
        print(f"PROBLEM: {problem}", file=sys.stderr)
    if problems:
        print(f"{args.db_dir}: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    stats = None
    try:
        stats = db_stats(args.db_dir)
    except (DbFormatError, OSError) as e:
        # check_db passed, so this is a race (file vanished) — report it
        # as the problem it is rather than crashing the checker.
        print(f"PROBLEM: stats: {e}", file=sys.stderr)
        return 1
    if args.stats_json:
        with open(args.stats_json, "w") as fh:
            json.dump(stats, fh, indent=1)
    if not args.quiet:
        print(format_stats_table(stats))
        print(f"{args.db_dir}: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
