#!/usr/bin/env python
"""Solved-position DB integrity checker (CI-runnable).

    python tools/check_db.py DB_DIR [--quiet]

Validates the manifest, per-shard sha256 checksums, key sortedness/
uniqueness/sentinel-freedom, cell dtypes and decided-ness — everything a
serving process assumes but never re-verifies on the hot path (see
gamesmanmpi_tpu/db/check.py for the full list). Exit 0 = clean, 1 =
problems (printed one per line), 2 = usage error. Pure numpy file reads
— no game construction, no kernels, no backend init — so it runs in
seconds even where accelerator bring-up is expensive or wedged.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("db_dir", help="database directory (from export-db)")
    p.add_argument("--quiet", action="store_true",
                   help="print problems only, no per-level OK lines")
    args = p.parse_args(argv)

    from gamesmanmpi_tpu.db.check import check_db

    problems = check_db(
        args.db_dir, verbose=None if args.quiet else print
    )
    for problem in problems:
        print(f"PROBLEM: {problem}", file=sys.stderr)
    if problems:
        print(f"{args.db_dir}: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"{args.db_dir}: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
