#!/usr/bin/env python
"""Summarize a solve's JSONL metrics file into a per-level table.

The --jsonl stream (utils/metrics.JsonlLogger) already answers "where
did the level time go" — forward expand vs backward resolve, positions
and operand bytes per level — but only as raw records. This tool folds
it into the table an operator actually reads:

    python tools/obs_report.py m.jsonl

    level  positions   fwd_s   bwd_s  total_s      pos/s    sort_MB  gather_MB
        0          1   0.012   0.009    0.021      47.6        0.0        0.0
        ...
    TOTAL       5478   0.310   0.270    0.580    9444.8        6.2        1.1

    done: game=tictactoe positions=5478 pos/s=9444 ...

Works on any stream the engine writes (classic, sharded, dense all share
the phase/level/secs schema); serve_batch / heartbeat records are
counted and reported but excluded from the level table. No third-party
deps — stdlib only, CI-runnable (see tests/test_obs.py).

Multi-process runs write one rank-stamped stream per rank
(``m.rank0.jsonl``, ``m.rank1.jsonl`` — utils/metrics.RankLogger); pass
them all and the tool merges WITHOUT double-counting level times:

    python tools/obs_report.py m.rank*.jsonl

Each rank times the same wall-clock level (the step is a collective),
so within a rank seconds accumulate (a retried level really did run
twice) and across ranks the per-level figures take the slowest rank —
summing two ranks' timings of one level would report a 2-process solve
as twice as slow as it was. Rank-less records (single-process streams)
keep the pure accumulate behavior.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_records(path: str) -> list[dict]:
    """Parse a JSONL metrics file, skipping blank/torn lines (an aborted
    solve's file may end mid-record; the intact prefix is the point)."""
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records


def summarize_levels(records: list[dict]) -> list[dict]:
    """Fold forward/backward records into one row per level, sorted by
    level. Within one rank's stream repeated records for a level
    (retries re-log) accumulate seconds and keep the latest sizes;
    across ranks every figure takes the slowest rank (`_merge_ranks`) —
    the level ran ONCE in wall-clock, collectively."""
    by_rank: dict = {}
    for rec in records:
        by_rank.setdefault(rec.get("rank"), []).append(rec)
    if len(by_rank) > 1 or (by_rank and None not in by_rank):
        return _merge_ranks({
            rank: _fold_one_rank(recs) for rank, recs in by_rank.items()
        })
    return _fold_one_rank(records)


def _fold_one_rank(records: list[dict]) -> list[dict]:
    levels: dict[int, dict] = {}
    for rec in records:
        phase = rec.get("phase")
        # backward_edges is the sharded engine's edge-cached resolve of a
        # level (GAMESMAN_BACKWARD=edges) — same schema, same bwd column.
        # retry / ckpt_degraded are the resilience layer's per-level
        # records (absorbed transients, quarantined checkpoint levels):
        # folded into the retries column so an operator sees WHERE a
        # flaky run flaked.
        if (phase not in ("forward", "backward", "backward_edges",
                          "retry", "ckpt_degraded")
                or "level" not in rec):
            continue
        row = levels.setdefault(
            int(rec["level"]),
            {
                "level": int(rec["level"]),
                "positions": 0,
                "fwd_secs": 0.0,
                "bwd_secs": 0.0,
                "retries": 0,
                "bytes_sorted": 0,
                "bytes_gathered": 0,
                "io_wait_secs": 0.0,
            },
        )
        if phase in ("retry", "ckpt_degraded"):
            row["retries"] += 1
            continue
        secs = float(rec.get("secs", 0.0))
        row["bytes_sorted"] += int(rec.get("bytes_sorted", 0))
        row["bytes_gathered"] += int(rec.get("bytes_gathered", 0))
        # ISSUE 11: seconds this level's resolve spent blocked on block-
        # store I/O (spill/edge/checkpoint loads + seal drains) — the
        # prefetch overlap observable, per level.
        row["io_wait_secs"] += float(rec.get("io_wait_secs", 0.0))
        if phase == "forward":
            row["fwd_secs"] += secs
            # The frontier size IS the level's position count; backward's
            # n confirms it, and wins when present (forward records are
            # absent for resumed runs).
            if rec.get("frontier"):
                row["positions"] = max(row["positions"],
                                       int(rec["frontier"]))
        else:
            row["bwd_secs"] += secs
            if rec.get("n"):
                row["positions"] = max(row["positions"], int(rec["n"]))
    return [levels[k] for k in sorted(levels)]


def _merge_ranks(per_rank: dict) -> list[dict]:
    """Merge per-rank level tables into one wall-clock view: for every
    level take each column's MAX across the ranks that timed it.

    Max, not sum — N ranks timing one collective level is one level, and
    summing would report an N-process solve as N times slower than it
    was. Max, not rank 0's value — a retrying rank accumulates real
    extra seconds, and the retries criterion is that the counter AGREES
    across ranks, so the max is also the consensus value (a discrepancy
    shows up as the larger figure, never hidden)."""
    merged: dict[int, dict] = {}
    for rows in per_rank.values():
        for r in rows:
            row = merged.setdefault(r["level"], dict(r))
            for k, v in r.items():
                if k != "level":
                    row[k] = max(row[k], v)
    return [merged[k] for k in sorted(merged)]


def format_table(rows: list[dict]) -> str:
    header = (
        f"{'level':>5}  {'positions':>10}  {'fwd_s':>8}  {'bwd_s':>8}  "
        f"{'total_s':>8}  {'pos/s':>12}  {'retries':>7}  {'sort_MB':>9}  "
        f"{'gather_MB':>9}  {'io_s':>7}"
    )
    lines = [header]
    tot = {
        "positions": 0, "fwd_secs": 0.0, "bwd_secs": 0.0, "retries": 0,
        "bytes_sorted": 0, "bytes_gathered": 0, "io_wait_secs": 0.0,
    }
    for r in rows:
        total = r["fwd_secs"] + r["bwd_secs"]
        pps = r["positions"] / total if total > 0 else 0.0
        lines.append(
            f"{r['level']:>5}  {r['positions']:>10}  {r['fwd_secs']:>8.3f}  "
            f"{r['bwd_secs']:>8.3f}  {total:>8.3f}  {pps:>12.1f}  "
            f"{r.get('retries', 0):>7}  "
            f"{r['bytes_sorted'] / 1e6:>9.1f}  "
            f"{r['bytes_gathered'] / 1e6:>9.1f}  "
            f"{r.get('io_wait_secs', 0.0):>7.3f}"
        )
        for k in tot:
            tot[k] += r.get(k, 0)
    total = tot["fwd_secs"] + tot["bwd_secs"]
    pps = tot["positions"] / total if total > 0 else 0.0
    lines.append(
        f"{'TOTAL':>5}  {tot['positions']:>10}  {tot['fwd_secs']:>8.3f}  "
        f"{tot['bwd_secs']:>8.3f}  {total:>8.3f}  {pps:>12.1f}  "
        f"{tot['retries']:>7}  "
        f"{tot['bytes_sorted'] / 1e6:>9.1f}  "
        f"{tot['bytes_gathered'] / 1e6:>9.1f}  "
        f"{tot['io_wait_secs']:>7.3f}"
    )
    return "\n".join(lines)


def serving_summary(records: list[dict]) -> list[dict]:
    """Machine-readable per-worker serving rows (the --json form;
    ``summarize_serving`` renders them as text)."""
    by_worker: dict = {}

    def _row(worker):
        return by_worker.setdefault(
            worker,
            {"batches": 0, "requests": 0, "queries": 0, "secs": 0.0,
             "db_cache": {}, "routes": {}, "slo": None},
        )

    for rec in records:
        if rec.get("phase") == "serve_stats":
            # End-of-life summary a QueryServer logs at stop(): per-route
            # estimated latency quantiles (registry histogram snapshot —
            # the one estimate_quantiles derivation) + the SLO burn
            # snapshot. Cumulative, so the last record per worker wins.
            srow = _row(rec.get("worker"))
            srow["routes"] = rec.get("routes", {}) or {}
            srow["slo"] = rec.get("slo")
            continue
        if rec.get("phase") != "serve_batch":
            continue
        row = _row(rec.get("worker"))
        row["batches"] += 1
        row["requests"] += int(rec.get("requests", 0))
        row["queries"] += int(rec.get("batch_size", 0))
        row["secs"] += float(rec.get("secs", 0.0))
        if "db_cache_hits" in rec:
            # Cumulative counters, kept PER ROUTE (the record's db
            # field): the record with the largest total IS that route's
            # final figure (streams may interleave) — and a multi-DB
            # worker's cold route must not vanish behind its busy one.
            dbk = rec.get("db")
            cand = (int(rec["db_cache_hits"]),
                    int(rec.get("db_cache_misses", 0)))
            cur = row["db_cache"].get(dbk)
            if cur is None or sum(cand) > sum(cur):
                row["db_cache"][dbk] = cand
    rows = []
    for worker in sorted(by_worker, key=lambda w: (w is None, w)):
        row = by_worker[worker]
        rows.append({
            "worker": worker,
            "batches": row["batches"],
            "requests": row["requests"],
            "queries": row["queries"],
            "mean_batch": round(
                row["queries"] / max(row["batches"], 1), 3
            ),
            "secs": round(row["secs"], 6),
            "db_cache": {
                str(dbk): {
                    "hits": hits,
                    "misses": misses,
                    "hit_rate": round(hits / max(hits + misses, 1), 6),
                }
                for dbk, (hits, misses) in row["db_cache"].items()
            },
            "routes": row["routes"],
            "slo": row["slo"],
        })
    return rows


def summarize_serving(records: list[dict]) -> list[str]:
    """Per-worker serving lines from ``serve_batch`` records.

    A fleet run writes one worker-stamped JSONL stream per worker
    (``serve.worker0.jsonl`` — utils/metrics.TagLogger), the serving
    twin of the per-rank solve streams: pass them all and each worker's
    batching behavior reports separately (workers are independent
    processes — unlike ranks their batches never time the same event,
    so figures accumulate per worker and are never merged by max)."""
    lines = []
    for row in serving_summary(records):
        worker = row["worker"]
        label = "serve" if worker is None else f"serve[worker {worker}]"
        line = (
            f"{label}: batches={row['batches']} "
            f"requests={row['requests']} "
            f"queries={row['queries']} mean_batch={row['mean_batch']:.1f} "
            f"secs={row['secs']:.3f}"
        )
        for dbk in sorted(row["db_cache"], key=str):
            cell = row["db_cache"][dbk]
            # One route keeps the plain column names; several routes
            # qualify each with its db name.
            tag = "" if len(row["db_cache"]) == 1 else f"[{dbk}]"
            line += (
                f" db_cache_hits{tag}={cell['hits']} "
                f"db_cache_misses{tag}={cell['misses']} "
                f"db_cache_hit_rate{tag}={cell['hit_rate']:.3f}"
            )
        lines.append(line)
        # Estimated per-route latency quantiles (serve_stats record —
        # registry-histogram interpolation, not raw samples) + SLO burn.
        for route in sorted(row["routes"]):
            cell = row["routes"][route]
            qcols = " ".join(
                f"{k}={cell[k]:.3f}"
                for k in ("p50_ms", "p95_ms", "p99_ms") if k in cell
            )
            lines.append(
                f"{label} route[{route}]: count={cell.get('count', 0)}"
                + (f" {qcols}" if qcols else "")
            )
        slo = row.get("slo")
        if slo:
            burns = " ".join(
                f"{route}/{obj}={objs[obj]['burn_fast']:.2f}"
                for route, objs in sorted(slo.get("routes", {}).items())
                for obj in sorted(objs)
            )
            lines.append(
                f"{label} slo: fast_burn="
                f"{'FIRING' if slo.get('fast_burn') else 'ok'} "
                f"p99_target_ms={slo.get('p99_ms')}"
                + (f" burn[{burns}]" if burns else "")
            )
    return lines


def export_summary(records: list[dict]):
    """Machine-readable compression summary from ``export_db`` records
    (None when the stream has no compressed export)."""
    raw = stored = levels = 0
    for rec in records:
        if rec.get("phase") != "export_db" or "stored_bytes" not in rec:
            continue
        levels += 1
        raw += int(rec.get("raw_bytes", 0))
        stored += int(rec["stored_bytes"])
    if not levels:
        return None
    return {
        "levels": levels,
        "raw_bytes": raw,
        "stored_bytes": stored,
        "ratio": round(raw / max(stored, 1), 4),
    }


def summarize_export(records: list[dict]) -> list[str]:
    """Compression summary from ``export_db`` records: a compressed
    (format v2) export logs raw_bytes/stored_bytes per level, which
    fold into one whole-DB ratio line (absent for v1 exports — no
    ratio to report)."""
    s = export_summary(records)
    if s is None:
        return []
    return [
        f"export_db: levels={s['levels']} raw_MB={s['raw_bytes'] / 1e6:.1f} "
        f"stored_MB={s['stored_bytes'] / 1e6:.1f} "
        f"ratio={s['ratio']:.2f}x"
    ]


#: Ledger phases the campaign summary consumes (excluded from the aux
#: record counts — they have their own lines).
_CAMPAIGN_PHASES = (
    "campaign_start", "campaign_attempt", "campaign_backoff",
    "campaign_gc", "campaign_done", "campaign_abort",
    "campaign_preempted", "campaign_reshard", "campaign_degrade",
)


def campaign_summary(records: list[dict]):
    """Machine-readable campaign summary from a ``campaign.jsonl``
    ledger (None when the stream has no campaign records) — what
    ``bench_compare``/CI consume instead of screen-scraping the text
    line ``summarize_campaign`` renders from it."""
    attempts = [r for r in records if r.get("phase") == "campaign_attempt"]
    if not attempts:
        return None
    causes: dict = {}
    lost = 0.0
    resume_levels = []
    for rec in attempts:
        cause = rec.get("cause", "?")
        causes[cause] = causes.get(cause, 0) + 1
        resume_levels.append(rec.get("resume_level"))
        if cause != "complete":
            # A failed attempt's whole wall clock is restart loss: its
            # sealed progress survives, but the compute re-runs on
            # resume up to the level the seal reached.
            lost += float(rec.get("wall_secs", 0.0))
    backoff = sum(
        float(r.get("secs", 0.0)) for r in records
        if r.get("phase") == "campaign_backoff"
    )
    gc_bytes = sum(
        int(r.get("freed_bytes", 0)) for r in records
        if r.get("phase") == "campaign_gc"
    )
    # The ledger is append-only ACROSS reruns (preempt -> exit 75 ->
    # rerun appends a new campaign_start segment), so the ending comes
    # from the LAST terminal record — attempts/time-lost/backoff stay
    # whole-ledger totals, which is what "lost to restarts" means for
    # the endeavor — and multi-run ledgers say so.
    runs = sum(1 for r in records if r.get("phase") == "campaign_start")
    terminal = next(
        (r for r in reversed(records) if r.get("phase") in
         ("campaign_done", "campaign_abort", "campaign_preempted")),
        None,
    )
    if terminal is None:
        ending = {"state": "in_flight"}
    elif terminal["phase"] == "campaign_done":
        ending = {"state": "solved",
                  "wall_secs": float(terminal.get("wall_secs", 0.0))}
    elif terminal["phase"] == "campaign_abort":
        ending = {"state": "aborted",
                  "reason": terminal.get("reason", "?")}
    else:
        ending = {"state": "preempted"}
    # Geometry cells (elastic resume, docs/DISTRIBUTED.md): one per
    # attempt carrying geometry; `adopted` marks a reshard adoption
    # (the tree was sealed at a different shard count going in).
    geometry = []
    for rec in attempts:
        if not any(rec.get(k) is not None
                   for k in ("shards", "processes", "cache_mb")):
            continue
        sealed = rec.get("sealed_shards")
        geometry.append({
            "attempt": rec.get("attempt"),
            "shards": rec.get("shards"),
            "processes": rec.get("processes"),
            "cache_mb": rec.get("cache_mb"),
            "sealed_shards": sealed,
            "adopted": bool(
                sealed is not None and rec.get("shards") is not None
                and sealed != rec.get("shards")
            ),
        })
    reshards = sum(
        1 for r in records if r.get("phase") == "campaign_reshard"
    )
    degrades: dict = {}
    for r in records:
        if r.get("phase") == "campaign_reshard":
            degrades["oom"] = degrades.get("oom", 0) + 1
        elif r.get("phase") == "campaign_degrade":
            kind = r.get("kind", "?")
            degrades[kind] = degrades.get(kind, 0) + 1
    return {
        "attempts": len(attempts),
        "runs": runs,
        "ending": ending,
        "causes": causes,
        "resume_levels": resume_levels,
        "time_lost_restarts_secs": round(lost, 3),
        "backoff_secs": round(backoff, 3),
        "gc_reclaimed_bytes": gc_bytes,
        "geometry": geometry,
        "reshards": reshards,
        "degrades": degrades,
    }


def summarize_campaign(records: list[dict]) -> list[str]:
    """Campaign summary lines from a ``campaign.jsonl`` ledger
    (resilience/campaign.py): attempts with causes and resume levels,
    wall-clock lost to failed attempts + backoff, GC reclamation, and
    how the campaign ended. Pass the ledger alongside (or instead of)
    the solve streams — records interleave safely."""
    s = campaign_summary(records)
    if s is None:
        return []
    end = s["ending"]
    if end["state"] == "in_flight":
        ending = "in flight"
    elif end["state"] == "solved":
        ending = f"solved in {end['wall_secs']:.1f}s"
    elif end["state"] == "aborted":
        ending = f"ABORTED ({end['reason']})"
    else:
        ending = "preempted (resumable)"
    gc_bytes = s["gc_reclaimed_bytes"]
    lines = [
        f"campaign: attempts={s['attempts']}"
        + (f" runs={s['runs']}" if s["runs"] > 1 else "")
        + f" {ending} "
        f"causes=" + ",".join(
            f"{k}:{v}" for k, v in sorted(s["causes"].items())
        )
        + f" resume_levels={s['resume_levels']}"
        + f" time_lost_restarts={s['time_lost_restarts_secs']:.1f}s"
        + f" backoff={s['backoff_secs']:.1f}s"
        + (f" gc_reclaimed_MB={gc_bytes / 1e6:.1f}" if gc_bytes else "")
    ]
    geom_cells = [
        f"a{g['attempt']}:S={g['shards'] or '-'}"
        + ("!" if g["adopted"] else "")
        + f"/W={g['processes'] or '-'}"
        + (f"/cache={g['cache_mb']}MB" if g.get("cache_mb") else "")
        for g in s["geometry"]
    ]
    if geom_cells or s["reshards"] or s["degrades"]:
        lines.append(
            "campaign geometry: " + " ".join(geom_cells)
            + f" reshards={s['reshards']}"
            + (" degrades=" + ",".join(
                f"{k}:{v}" for k, v in sorted(s["degrades"].items())
            ) if s["degrades"] else "")
        )
    return lines


def _aux_counts(records: list[dict]) -> dict:
    aux: dict = {}
    for rec in records:
        phase = rec.get("phase")
        # retry/ckpt_degraded already rolled into the level table's
        # retries column; a retry without a level (serving) still lands
        # here. serve_batch has its own per-worker summary lines.
        if phase not in ("forward", "backward", "backward_edges", "done",
                         "serve_batch", "serve_stats") \
                and phase not in _CAMPAIGN_PHASES \
                and not (phase in ("retry", "ckpt_degraded")
                         and "level" in rec):
            aux[phase] = aux.get(phase, 0) + 1
    return aux


def report_json(records: list[dict]) -> dict:
    """The machine-readable report (``--json``): the same level table,
    worker merge, export/campaign summaries, and done records the text
    report renders — as one JSON document, so ``tools/bench_compare.py``
    and CI consume reports without screen-scraping the text table."""
    rows = summarize_levels(records)
    totals = {
        "positions": sum(r["positions"] for r in rows),
        "fwd_secs": round(sum(r["fwd_secs"] for r in rows), 6),
        "bwd_secs": round(sum(r["bwd_secs"] for r in rows), 6),
        "retries": sum(r.get("retries", 0) for r in rows),
        "bytes_sorted": sum(r["bytes_sorted"] for r in rows),
        "bytes_gathered": sum(r["bytes_gathered"] for r in rows),
        "io_wait_secs": round(
            sum(r.get("io_wait_secs", 0.0) for r in rows), 6
        ),
    }
    return {
        "levels": rows,
        "totals": totals,
        "done": [r for r in records if r.get("phase") == "done"],
        "serving": serving_summary(records),
        "export": export_summary(records),
        "campaign": campaign_summary(records),
        "other_records": _aux_counts(records),
    }


def report(records: list[dict]) -> str:
    """The full report: level table + done summary + serving summary +
    campaign summary + aux record counts."""
    out = [format_table(summarize_levels(records))]
    out.extend(summarize_serving(records))
    out.extend(summarize_export(records))
    out.extend(summarize_campaign(records))
    for rec in records:
        if rec.get("phase") == "done":
            keys = ("game", "positions", "levels", "secs_forward",
                    "secs_backward", "secs_total", "positions_per_sec")
            label = ("done" if rec.get("rank") is None
                     else f"done[rank {rec['rank']}]")
            out.append(
                f"{label}: " + " ".join(
                    f"{k}={rec[k]:.3f}" if isinstance(rec.get(k), float)
                    else f"{k}={rec.get(k)}"
                    for k in keys if k in rec
                )
            )
    aux = _aux_counts(records)
    if aux:
        out.append(
            "other records: " + " ".join(
                f"{k}={v}" for k, v in sorted(aux.items())
            )
        )
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Per-level time/volume table from a --jsonl metrics "
        "file (docs/OBSERVABILITY.md)."
    )
    p.add_argument("jsonl", nargs="+",
                   help="metrics file(s) written by --jsonl; pass every "
                   "per-rank file of a multi-process run and level times "
                   "merge wall-clock (max across ranks, not sum)")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable report (per-level "
                   "table, totals, worker merge, campaign summary) as "
                   "one JSON document instead of the text tables — the "
                   "form bench_compare and CI consume")
    args = p.parse_args(argv)
    try:
        records = [r for path in args.jsonl for r in load_records(path)]
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not records:
        print("error: no parseable records", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report_json(records), indent=1, default=str))
    else:
        print(report(records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
