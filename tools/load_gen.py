#!/usr/bin/env python
"""Serving load harness: concurrent query load with latency-SLO gates.

    python tools/load_gen.py http://127.0.0.1:8947 \
        --positions-file pos.txt --duration 10 --concurrency 8 \
        --slo-p99-ms 250 --json out.json

Drives POST /query traffic from N threads for a wall-clock duration and
reports request counts, shed/dropped/error classification, and latency
percentiles (p50/p95/p99). This is the measurement half of the fleet
chaos gate (tests/test_resilience.py, bench.py's serving mode): under a
worker SIGKILL mid-load the fleet must keep answering with zero dropped
requests beyond the in-flight shed budget and p99 within the SLO.

Classification per request:

* ``ok``       — HTTP 200 with every queried position found;
* ``not_modified`` — HTTP 304 from a conditional GET (``--get``): the
  client's cached copy revalidated against the server's ETag — cheaper
  than ok for both sides, and its OWN class so a cache-friendly
  workload is visible in the record rather than inflating ok;
* ``shed``     — HTTP 503 (deadline / load shed / breaker / draining):
  the server DEGRADED POLITELY; a well-behaved client retries;
* ``shed_retried`` — an HTTP 503 whose ``Retry-After`` header this
  client HONORED: the thread sleeps the advertised delay (bounded,
  delta-seconds form only) before its next request — the harness is
  the well-behaved client the serving docs promise, and honoring
  backpressure is its own class so a shed storm is visible as such
  rather than hammering a draining worker;
* ``errors``   — any other HTTP status, or a 200 carrying per-position
  errors/misses (would be wrong answers — the harness treats them as
  failures, not noise);
* ``dropped``  — connection-level failure (refused, reset mid-flight):
  the only class a crashing worker is allowed to inflict, bounded by
  its in-flight requests at death.

``--dist zipf:<s>`` resamples the position file rank-weighted
(probability of rank i ∝ 1/i^s) so a small head of hot positions
dominates — the shape real game traffic has, and the one that exercises
the serving hot path (opening book, shared block cache, batcher dedup).
``--get`` switches to single-position conditional GETs with a client-
side ETag cache, measuring the edge-cacheable form of the same answers.
``--duration-secs N`` is soak mode: the same load for N wall-clock
seconds with a cumulative ``[load_gen] t=..s requests=.. qps=..
p99=..ms`` progress line every ``--progress-secs`` — so latency drift
over a long run is visible live — ending in the usual summary record.

Answers are accumulated per position (value/remoteness/best of the last
successful response) and exposed for oracle comparison; ``mismatches``
counts positions whose answer ever CHANGED between responses — a fleet
serving one immutable DB must answer identically from every worker,
before, during, and after chaos.

Deliberately jax-free and stdlib-only (urllib + threads): bench.py's
parent process imports this module, and that parent must never touch
jax. One request per connection — no keep-alive — so a draining
worker's connection close between requests can never be miscounted as a
failed request.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.error
import urllib.request


def _mint_traceparent() -> tuple:
    """(trace_id, W3C traceparent header) minted client-side.

    Inlined (not imported from obs/qtrace.py) on purpose: this module's
    contract is stdlib-only so bench.py's jax-free parent can import it.
    Sending the header makes the CLIENT the trace root — a client-side
    p99 outlier in --out-jsonl joins its server-side sampled trace
    (worker GET /traces, fleet control GET /traces) by this id.
    """
    tid = os.urandom(16).hex()
    return tid, f"00-{tid}-{os.urandom(8).hex()}-01"


def zipf_sample(positions: list, s: float, *, n: int | None = None,
                seed: int = 0) -> list:
    """Rank-weighted resample: the position at (0-based) rank i is drawn
    with probability ∝ 1/(i+1)**s. Deterministic for a given seed, so
    two bench arms replay the IDENTICAL hot-head request stream."""
    if not positions:
        return []
    rng = random.Random(seed)
    if n is None:
        n = max(len(positions) * 4, 1024)
    weights = [1.0 / (i + 1) ** s for i in range(len(positions))]
    return rng.choices(positions, weights=weights, k=n)


def apply_dist(positions: list, dist: str | None, *, seed: int = 0) -> list:
    """``uniform`` (or None) passes through; ``zipf:<s>`` resamples."""
    if not dist or dist == "uniform":
        return positions
    if dist.startswith("zipf:"):
        return zipf_sample(positions, float(dist.split(":", 1)[1]),
                           seed=seed)
    raise ValueError(f"unknown dist {dist!r} (uniform | zipf:<s>)")


def percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile of an ascending list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class _Stats:
    """Shared accumulator; one lock, touched once per request."""

    def __init__(self, keep_records: bool = False):
        self.lock = threading.Lock()
        self.latencies = []  # guarded-by: lock
        self.ok = 0  # guarded-by: lock
        self.not_modified = 0  # guarded-by: lock (conditional-GET 304s)
        self.shed = 0  # guarded-by: lock
        self.shed_retried = 0  # guarded-by: lock (503 + honored Retry-After)
        self.errors = 0  # guarded-by: lock
        self.dropped = 0  # guarded-by: lock
        self.codes = {}  # guarded-by: lock
        self.answers = {}  # guarded-by: lock
        self.mismatches = 0  # guarded-by: lock
        self.keep_records = keep_records
        self.records = []  # guarded-by: lock (per-request, --out-jsonl)

    def note(self, kind: str, code, secs: float | None,
             results=None, trace_id: str | None = None) -> None:
        with self.lock:
            if secs is not None:
                self.latencies.append(secs)
            self.codes[str(code)] = self.codes.get(str(code), 0) + 1
            setattr(self, kind, getattr(self, kind) + 1)
            mismatch = False
            for rec in results or ():
                pos = rec.get("position")
                ans = (rec.get("value"), rec.get("remoteness"),
                       rec.get("best"))
                old = self.answers.get(pos)
                if old is not None and old != ans:
                    self.mismatches += 1
                    mismatch = True
                self.answers[pos] = ans
            if self.keep_records:
                self.records.append({
                    "trace_id": trace_id,
                    "kind": kind,
                    "code": code if isinstance(code, int) else str(code),
                    "latency_ms": round(secs * 1e3, 3)
                    if secs is not None else None,
                    "mismatch": mismatch,
                })


#: Upper bound on an honored Retry-After sleep: a server advertising a
#: huge delay must not park a load thread for the whole run.
_RETRY_AFTER_CAP_SECS = 5.0


def _retry_after_secs(err) -> float | None:
    """The bounded sleep a 503's Retry-After asks for, or None when the
    header is absent/unparseable (only the delta-seconds form counts —
    the HTTP-date form is not worth a clock comparison here)."""
    try:
        raw = err.headers.get("Retry-After")
    except AttributeError:
        return None
    if raw is None:
        return None
    try:
        secs = float(raw)
    except (TypeError, ValueError):
        return None
    return max(0.0, min(secs, _RETRY_AFTER_CAP_SECS))


def _get_loop(url: str, chunks: list, stats: _Stats, stop: threading.Event,
              timeout: float, offset: int, etags: dict) -> None:
    """Conditional-GET driver: one position per request, client-side
    ETag cache shared across threads (plain dict — CPython item
    assignment is atomic, and a lost race just costs one extra 200)."""
    i = offset
    while not stop.is_set():
        pos = chunks[i % len(chunks)][0]
        i += 1
        trace_id, traceparent = _mint_traceparent()
        headers = {"Connection": "close", "traceparent": traceparent}
        etag = etags.get(pos)
        if etag:
            headers["If-None-Match"] = etag
        req = urllib.request.Request(
            f"{url}/query?p={pos:#x}", headers=headers, method="GET",
        )
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                new_etag = resp.headers.get("ETag")
                payload = json.loads(resp.read())
            secs = time.perf_counter() - t0
            results = payload.get("results", [])
            clean = (
                len(results) == 1 and results[0].get("found")
                and "error" not in results[0]
            )
            if new_etag:
                etags[pos] = new_etag
            stats.note("ok" if clean else "errors", 200, secs,
                       results if clean else None, trace_id=trace_id)
        except urllib.error.HTTPError as e:
            secs = time.perf_counter() - t0
            if e.code == 304:
                stats.note("not_modified", 304, secs, trace_id=trace_id)
            else:
                delay = _retry_after_secs(e) if e.code == 503 else None
                if delay is not None:
                    stats.note("shed_retried", e.code, secs,
                               trace_id=trace_id)
                    stop.wait(delay)
                else:
                    stats.note("shed" if e.code == 503 else "errors",
                               e.code, secs, trace_id=trace_id)
        except Exception:  # noqa: BLE001 - URLError/socket/timeout: dropped
            stats.note("dropped", "conn", None, trace_id=trace_id)


def _worker_loop(url: str, chunks: list, stats: _Stats, stop: threading.Event,
                 timeout: float, offset: int) -> None:
    i = offset
    while not stop.is_set():
        chunk = chunks[i % len(chunks)]
        i += 1
        body = json.dumps({"positions": chunk}).encode()
        trace_id, traceparent = _mint_traceparent()
        req = urllib.request.Request(
            f"{url}/query", data=body,
            headers={"Content-Type": "application/json",
                     "Connection": "close",
                     "traceparent": traceparent},
            method="POST",
        )
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = json.loads(resp.read())
            secs = time.perf_counter() - t0
            results = payload.get("results", [])
            clean = all(
                r.get("found") and "error" not in r for r in results
            ) and len(results) == len(chunk)
            stats.note("ok" if clean else "errors", 200, secs,
                       results if clean else None, trace_id=trace_id)
        except urllib.error.HTTPError as e:
            secs = time.perf_counter() - t0
            delay = _retry_after_secs(e) if e.code == 503 else None
            if delay is not None:
                stats.note("shed_retried", e.code, secs, trace_id=trace_id)
                stop.wait(delay)
            else:
                stats.note("shed" if e.code == 503 else "errors", e.code,
                           secs, trace_id=trace_id)
        except Exception:  # noqa: BLE001 - URLError/socket/timeout: dropped
            stats.note("dropped", "conn", None, trace_id=trace_id)


def run_load(url: str, positions: list, *, duration: float = 5.0,
             concurrency: int = 4, chunk_size: int = 8,
             timeout: float = 10.0, stop_event=None,
             out_jsonl: str | None = None, dist: str | None = None,
             mode: str = "post", seed: int = 0,
             progress_secs: float | None = None, progress=None) -> dict:
    """Drive load; returns the stats record (see module docstring).

    positions: ints (or hex strings) assumed PRESENT in the served DB —
    a miss counts as an error by design. Each thread cycles through
    round-robin chunks at its own offset so concurrent threads overlap
    on hot positions (cache hits) AND spread over the whole set.

    out_jsonl: when set, one JSON line per request is written there —
    {trace_id, kind, code, latency_ms, mismatch} — so an outlier seen
    from the CLIENT side can be joined to its server-side sampled trace
    by trace_id (docs/SERVING.md "Debugging a slow query").

    progress_secs: soak mode — every that-many seconds a cumulative
    progress snapshot ({t_secs, requests, qps, p99_ms, errors, dropped,
    mismatches}) goes to ``progress`` (a callable; default prints one
    ``[load_gen]`` line to stderr), so an hours-long run shows drift
    (a leak, a degrading cache) AS it happens instead of only in the
    final record.
    """
    url = url.rstrip("/")
    positions = [int(p, 0) if isinstance(p, str) else int(p)
                 for p in positions]
    positions = apply_dist(positions, dist, seed=seed)
    chunk_size = 1 if mode == "get" else max(1, int(chunk_size))
    chunks = [
        positions[i:i + chunk_size]
        for i in range(0, len(positions), chunk_size)
    ] or [[0]]
    stats = _Stats(keep_records=out_jsonl is not None)
    stop = stop_event or threading.Event()
    etags: dict = {}
    if mode == "get":
        target, extra = _get_loop, (etags,)
    else:
        target, extra = _worker_loop, ()
    threads = [
        threading.Thread(
            target=target,
            args=(url, chunks, stats, stop, timeout,
                  i * max(1, len(chunks) // max(1, concurrency)), *extra),
            daemon=True,
        )
        for i in range(max(1, int(concurrency)))
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if progress_secs and progress_secs > 0:
        emit = progress if progress is not None else _print_progress
        deadline = t0 + duration
        while not stop.is_set():
            now = time.perf_counter()
            if now >= deadline:
                break
            stop.wait(min(float(progress_secs), deadline - now))
            if stop.is_set() or time.perf_counter() >= deadline:
                break
            with stats.lock:
                lat = sorted(stats.latencies)
                snap = {
                    "t_secs": round(time.perf_counter() - t0, 1),
                    "requests": stats.ok + stats.not_modified + stats.shed
                    + stats.shed_retried + stats.errors + stats.dropped,
                    "qps": round(
                        (stats.ok + stats.not_modified + stats.shed
                         + stats.shed_retried + stats.errors)
                        / max(time.perf_counter() - t0, 1e-9), 1),
                    "p99_ms": round(percentile(lat, 0.99) * 1e3, 3),
                    "errors": stats.errors,
                    "dropped": stats.dropped,
                    "mismatches": stats.mismatches,
                }
            emit(snap)
    else:
        stop.wait(duration)
    stop.set()
    for t in threads:
        t.join(timeout=timeout + 5)
    elapsed = time.perf_counter() - t0
    with stats.lock:
        lat = sorted(stats.latencies)
        record = {
            "url": url,
            "duration_secs": round(elapsed, 3),
            "concurrency": int(concurrency),
            "requests": stats.ok + stats.not_modified + stats.shed
            + stats.shed_retried + stats.errors + stats.dropped,
            "ok": stats.ok,
            "not_modified": stats.not_modified,
            "shed": stats.shed,
            "shed_retried": stats.shed_retried,
            "errors": stats.errors,
            "dropped": stats.dropped,
            "codes": dict(stats.codes),
            "mismatches": stats.mismatches,
            "qps": round((stats.ok + stats.not_modified + stats.shed
                          + stats.shed_retried + stats.errors)
                         / max(elapsed, 1e-9), 1),
            "p50_ms": round(percentile(lat, 0.50) * 1e3, 3),
            "p95_ms": round(percentile(lat, 0.95) * 1e3, 3),
            "p99_ms": round(percentile(lat, 0.99) * 1e3, 3),
            "answers": {
                str(pos): ans for pos, ans in stats.answers.items()
            },
        }
        records = list(stats.records)
    if out_jsonl:
        with open(out_jsonl, "w") as fh:
            for r in records:
                fh.write(json.dumps(r) + "\n")
    return record


def _print_progress(snap: dict) -> None:
    print(
        f"[load_gen] t={snap['t_secs']:.0f}s requests={snap['requests']} "
        f"qps={snap['qps']:.1f} p99={snap['p99_ms']:.1f}ms "
        f"errors={snap['errors']} dropped={snap['dropped']} "
        f"mismatches={snap['mismatches']}",
        file=sys.stderr, flush=True,
    )


def _read_positions(path: str) -> list:
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                out.append(int(line, 0))
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Concurrent POST /query load with latency-SLO gates "
        "(docs/SERVING.md fleet mode)."
    )
    p.add_argument("url", help="server base URL, e.g. http://127.0.0.1:8947")
    p.add_argument("--positions-file", required=True,
                   help="file of packed positions (decimal or 0x-hex, one "
                   "per line, # comments) known to be in the DB")
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--duration-secs", type=float, default=None,
                   metavar="SECS",
                   help="soak mode: run for this many wall-clock seconds "
                   "(overrides --duration) with a cumulative progress "
                   "line every --progress-secs — qps/p99 drift over an "
                   "hours-long run shows up live, not just in the final "
                   "summary record")
    p.add_argument("--progress-secs", type=float, default=5.0,
                   metavar="SECS",
                   help="soak progress-line interval (with "
                   "--duration-secs; default 5)")
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--chunk-size", type=int, default=8,
                   help="positions per request")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-request client timeout, seconds")
    p.add_argument("--dist", default="uniform", metavar="DIST",
                   help='request distribution: "uniform" (default) or '
                   '"zipf:<s>" — rank-weighted hot-head resample of the '
                   "positions file (rank i drawn ∝ 1/i^s)")
    p.add_argument("--get", action="store_true",
                   help="drive conditional GET /query?p=... (one position "
                   "per request, client-side ETag cache, 304s counted as "
                   "not_modified) instead of POST batches")
    p.add_argument("--seed", type=int, default=0,
                   help="RNG seed for --dist resampling (two arms with "
                   "the same seed replay the identical request stream)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="gate: exit 1 when p99 latency exceeds this")
    p.add_argument("--max-dropped", type=int, default=None,
                   help="gate: exit 1 when more requests were dropped "
                   "(connection failures) than this budget")
    p.add_argument("--json", default=None, metavar="OUT",
                   help="also write the full record to this file")
    p.add_argument("--out-jsonl", default=None, metavar="OUT",
                   help="write one JSON line per request: {trace_id, "
                   "kind, code, latency_ms, mismatch} — the trace_id is "
                   "the one sent as the W3C traceparent header, so a "
                   "client-observed outlier joins its server-side "
                   "sampled trace (GET /traces)")
    args = p.parse_args(argv)
    try:
        positions = _read_positions(args.positions_file)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not positions:
        print("error: no positions to query", file=sys.stderr)
        return 2
    try:
        soak = args.duration_secs is not None
        record = run_load(
            args.url, positions,
            duration=args.duration_secs if soak else args.duration,
            concurrency=args.concurrency, chunk_size=args.chunk_size,
            timeout=args.timeout, out_jsonl=args.out_jsonl,
            dist=args.dist, mode="get" if args.get else "post",
            seed=args.seed,
            progress_secs=args.progress_secs if soak else None,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    gates_ok = True
    if args.slo_p99_ms is not None and record["p99_ms"] > args.slo_p99_ms:
        print(f"SLO VIOLATION: p99 {record['p99_ms']:.1f}ms > "
              f"{args.slo_p99_ms:g}ms", file=sys.stderr)
        gates_ok = False
    if args.max_dropped is not None and record["dropped"] > args.max_dropped:
        print(f"DROP BUDGET EXCEEDED: {record['dropped']} > "
              f"{args.max_dropped}", file=sys.stderr)
        gates_ok = False
    if record["mismatches"]:
        print(f"ANSWER MISMATCHES: {record['mismatches']} positions "
              "changed answers mid-run", file=sys.stderr)
        gates_ok = False
    summary = {k: v for k, v in record.items() if k != "answers"}
    print(json.dumps(summary))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(record, fh, indent=1)
    return 0 if gates_ok else 1


if __name__ == "__main__":
    sys.exit(main())
