#!/usr/bin/env python
"""Real multi-process launcher: N OS processes, one jax.distributed world.

The reference runs ``mpirun -np N python solver_launcher.py game.py``;
this is that launcher for the JAX rebuild. It spawns N copies of the
stock solve CLI, wires the process group through the ENVIRONMENT
(``GAMESMAN_COORDINATOR`` / ``GAMESMAN_NUM_PROCESSES`` /
``GAMESMAN_PROCESS_ID`` — the CLI's ``init_distributed`` env fallback,
so children need no extra argv), enables CPU Gloo collectives via the
same path, and points every rank at the retry-consensus coordinator
(``GAMESMAN_COORD_ADDR``, rank 0 hosts it). Per-rank stdout/stderr go
to files — the children are coupled by cross-process collectives, so
blocking on one rank's unread pipe can stall the whole world and turn
any verbose failure into a bare timeout.

CLI::

    python tools/launch_multihost.py [--processes N] [--timeout S]
        [--log-dir DIR] -- connect4:w=3,h=3,connect=3 --devices 4 ...

Library (tests/test_multihost.py, bench.py)::

    from tools.launch_multihost import launch
    ranks = launch(["nim:heaps=2-3-4", "--devices", "4"], processes=2)
    for r in ranks: assert r.returncode == 0

Per-rank chaos: ``per_rank_env={1: {"GAMESMAN_FAULTS": "...:kill:2"}}``
arms a fault on ONE rank only — the rank-death scenarios of
tests/test_resilience.py. The equivalent env spelling
``GAMESMAN_FAULTS_RANK_<i>`` is honored for shell-driven chaos runs.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # tools/ scripts get sys.path[0]=tools/
    sys.path.insert(0, REPO)

#: Local (fake) CPU devices per process: 2 keeps the global mesh
#: genuinely multi-device AND multi-process at the smallest cost.
DEFAULT_LOCAL_DEVICES = 2


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class RankResult:
    rank: int
    returncode: Optional[int]  # None = still running when harness gave up
    stdout: str
    stderr: str


def _child_env(base: dict, rank: int, processes: int, coordinator: str,
               coord_addr: str, local_devices: int,
               per_rank: Optional[dict]) -> dict:
    env = dict(base)
    # The invoking suite's own fake-device flag must NOT leak: each child
    # fakes exactly `local_devices` CPU devices so the global mesh spans
    # processes.
    env.pop("XLA_FLAGS", None)
    env.setdefault("GAMESMAN_PLATFORM", "cpu")
    env["GAMESMAN_FAKE_DEVICES"] = str(local_devices)
    env["GAMESMAN_COORDINATOR"] = coordinator
    env["GAMESMAN_NUM_PROCESSES"] = str(processes)
    env["GAMESMAN_PROCESS_ID"] = str(rank)
    env["GAMESMAN_COORD_ADDR"] = coord_addr
    # GAMESMAN_FAULTS_RANK_<i> -> GAMESMAN_FAULTS for exactly rank i
    # (a fleet-wide GAMESMAN_FAULTS in the parent env would arm every
    # rank identically — almost never what a rank-death scenario wants).
    env.pop("GAMESMAN_FAULTS", None)
    ranked = base.get(f"GAMESMAN_FAULTS_RANK_{rank}")
    if ranked:
        env["GAMESMAN_FAULTS"] = ranked
    for k in list(env):
        if k.startswith("GAMESMAN_FAULTS_RANK_"):
            env.pop(k)
    if per_rank:
        env.update({k: str(v) for k, v in per_rank.items()})
    return env


class World:
    """A launched N-rank world the caller can signal and wait on.

    The campaign supervisor (resilience/campaign.py) needs more than
    ``launch()``'s run-to-completion contract: it forwards preemption
    signals to every rank mid-run and waits with its own policy. One
    ``World`` owns the rank processes and their log files; ``wait()``
    collects every rank (killing stragglers past the deadline) exactly
    like ``launch()`` always did.
    """

    def __init__(self, procs, files):
        self._procs = procs
        self._files = files
        self._results: Optional[List[RankResult]] = None

    def pids(self) -> List[int]:
        return [p.pid for p in self._procs]

    def send_signal(self, sig) -> None:
        """Deliver ``sig`` to every still-running rank (preemption
        grace forwards SIGTERM this way)."""
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass

    def wait(self, timeout: Optional[float]) -> List[RankResult]:
        """Block until every rank exits or `timeout` seconds pass, then
        kill stragglers (their returncode reports None — the caller
        decides whether a straggler is a failure or the scenario under
        test). ``None`` waits forever — the campaign's attempt-timeout-
        off contract; a silent cap here would SIGKILL exactly the
        multi-day world runs the campaign exists for. Idempotent: a
        second call returns the same results."""
        if self._results is not None:
            return self._results
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        results: List[RankResult] = []
        for rank, (p, (out_f, err_f)) in enumerate(
            zip(self._procs, self._files)
        ):
            rc: Optional[int] = None
            try:
                rc = p.wait(
                    timeout=None if deadline is None
                    else max(0.1, deadline - time.monotonic())
                )
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            out_f.seek(0)
            err_f.seek(0)
            results.append(RankResult(rank, rc, out_f.read(), err_f.read()))
            out_f.close()
            err_f.close()
        self._results = results
        return results


def start_world(solver_args: Sequence[str], *, processes: int = 2,
                log_dir: Optional[str] = None,
                local_devices: Optional[int] = None,
                coordinator: Optional[str] = None,
                env: Optional[dict] = None,
                per_rank_env: Optional[Dict[int, dict]] = None,
                ) -> World:
    """Spawn ``solve_launcher.py solver_args...`` as `processes` ranks
    and return immediately (see :class:`World`)."""
    if local_devices is None:
        local_devices = DEFAULT_LOCAL_DEVICES
    base = dict(os.environ)
    if env:
        base.update({k: str(v) for k, v in env.items()})
    if coordinator is None:
        coordinator = base.get("GAMESMAN_COORDINATOR") or \
            f"127.0.0.1:{free_port()}"
    host, _, port = coordinator.rpartition(":")
    coord_addr = base.get("GAMESMAN_COORD_ADDR") or \
        f"{host or '127.0.0.1'}:{free_port()}"
    log_dir = log_dir or "/tmp"
    os.makedirs(log_dir, exist_ok=True)
    tag = port
    procs, files = [], []
    for rank in range(processes):
        out_f = open(os.path.join(log_dir, f"rank{rank}_{tag}.out"), "w+")
        err_f = open(os.path.join(log_dir, f"rank{rank}_{tag}.err"), "w+")
        files.append((out_f, err_f))
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "solve_launcher.py"),
             *solver_args],
            cwd=REPO,
            env=_child_env(base, rank, processes, coordinator, coord_addr,
                           local_devices,
                           (per_rank_env or {}).get(rank)),
            stdout=out_f, stderr=err_f,
        ))
    return World(procs, files)


def launch(solver_args: Sequence[str], *, processes: int = 2,
           timeout: float = 240.0, log_dir: Optional[str] = None,
           local_devices: int = DEFAULT_LOCAL_DEVICES,
           coordinator: Optional[str] = None,
           env: Optional[dict] = None,
           per_rank_env: Optional[Dict[int, dict]] = None,
           ) -> List[RankResult]:
    """Run ``solve_launcher.py solver_args...`` as `processes` ranks and
    block for the results (start_world + World.wait)."""
    return start_world(
        solver_args, processes=processes, log_dir=log_dir,
        local_devices=local_devices, coordinator=coordinator, env=env,
        per_rank_env=per_rank_env,
    ).wait(timeout)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Spawn an N-process jax.distributed CPU solve "
        "(docs/DISTRIBUTED.md). Everything after -- goes to the solve "
        "CLI verbatim.",
    )
    p.add_argument("--processes", type=int, default=None,
                   help="world size (env GAMESMAN_NUM_PROCESSES; "
                   "default 2)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="harness deadline: stragglers are killed after "
                   "this many seconds")
    p.add_argument("--log-dir", default=None,
                   help="directory for per-rank stdout/stderr files "
                   "(default /tmp)")
    p.add_argument("--local-devices", type=int,
                   default=DEFAULT_LOCAL_DEVICES,
                   help="fake CPU devices per rank (the mesh is "
                   "processes x this)")
    p.add_argument("solver_args", nargs=argparse.REMAINDER,
                   help="-- then the solve CLI's arguments")
    args = p.parse_args(argv)
    solver_args = [a for a in args.solver_args if a != "--"] or None
    if not solver_args:
        p.error("no solver arguments (put them after --)")
    from gamesmanmpi_tpu.utils.env import env_int

    processes = (args.processes if args.processes is not None
                 else env_int("GAMESMAN_NUM_PROCESSES", 2))
    results = launch(
        solver_args, processes=processes, timeout=args.timeout,
        log_dir=args.log_dir, local_devices=args.local_devices,
    )
    worst = 0
    for r in results:
        rc = "killed" if r.returncode is None else r.returncode
        print(f"--- rank {r.rank}: rc={rc} ---")
        sys.stdout.write(r.stdout)
        if r.returncode != 0:
            sys.stderr.write(r.stderr[-4000:])
        worst = worst or (124 if r.returncode is None else r.returncode)
    return worst


if __name__ == "__main__":
    sys.exit(main())
