#!/usr/bin/env python
"""Mechanical executor for docs/CHIP_PLAN.md — run when the relay is live.

The TPU relay has died mid-session twice (rounds 3 and 4); every on-chip
decision this repo is waiting on (dense lowering A/B, hybrid cutover,
merge ladder, Pallas go/no-go, the board ladder) must therefore be
collectable in ONE pass with per-step failure isolation: each step runs
in a child process under a deadline, its JSON/text output is appended to
the artifact file IMMEDIATELY, and a dead relay aborts the remaining
steps while keeping everything already measured.

Usage:
    python tools/chip_session.py [--out artifacts/chip.jsonl] [--quick]

Single-client discipline (docs/ROUND3.md): nothing else may touch the
axon backend while this runs; concurrent work must set
GAMESMAN_PLATFORM=cpu. The relay is TCP-probed (never with a jax client)
before each step; refusal marks the remaining steps skipped.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# This tool must never import the package (its __init__ imports jax; the
# relay probe exists precisely for when jax would wedge), so the
# utils/env helpers are off limits here.  # lint: disable=GM301
RELAY_PORT = int(os.environ.get("GAMESMAN_RELAY_PORT", "8103"))


def relay_up() -> bool:
    try:
        with socket.create_connection(("127.0.0.1", RELAY_PORT), timeout=5):
            return True
    except OSError:
        return False


def _last_json(text: str) -> dict | None:
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


class Session:
    def __init__(self, out_path: str):
        self.out_path = out_path
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        self.aborted = False

    def record(self, **rec) -> None:
        rec["ts"] = round(time.time(), 1)
        with open(self.out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"[chip_session] {rec.get('step')}: "
              f"{rec.get('status', 'ok')}", file=sys.stderr)

    def step(self, name: str, argv: list[str], env: dict | None = None,
             timeout: float = 2400.0, parse_json: bool = True) -> dict | None:
        """One isolated child step; returns the parsed JSON record, if any."""
        if self.aborted:
            self.record(step=name, status="skipped", reason="session aborted")
            return None
        if not relay_up():
            self.aborted = True
            self.record(step=name, status="skipped",
                        reason=f"relay port {RELAY_PORT} refused")
            return None
        full_env = dict(os.environ)
        # The single-client discipline tells CONCURRENT shells to export
        # GAMESMAN_PLATFORM=cpu — if this script inherits that (or a
        # fake-device count), every "chip" measurement silently runs on
        # CPU with exit 0. Children get the real backend unless the step
        # itself asks otherwise.
        full_env.pop("GAMESMAN_PLATFORM", None)
        full_env.pop("GAMESMAN_FAKE_DEVICES", None)
        # tools/ scripts get sys.path[0]=tools/, not the repo root; make
        # the package importable regardless of the child's own hygiene.
        full_env["PYTHONPATH"] = REPO + (
            os.pathsep + full_env["PYTHONPATH"]
            if full_env.get("PYTHONPATH") else ""
        )
        full_env.update(env or {})
        if (any(a.endswith("bench.py") for a in argv)
                and "GAMESMAN_BENCH_DEADLINE" not in full_env):
            # The parent bench salvages its inner child's partial stdout
            # when ITS deadline fires — but only if this step's kill
            # arrives later. The parent's clock is probe (default 600s,
            # and it always runs here because GAMESMAN_PLATFORM was
            # popped) THEN the deadline-clocked inner child; cap both so
            # probe + deadline + margin < this step's timeout and every
            # timeout path ends with the parent printing
            # best-of-completed-runs instead of this step discarding all
            # measured repeats. Probe 300s is generous: this script
            # TCP-probed the relay seconds ago.
            probe = min(300, max(60, int(timeout) // 4))
            full_env.setdefault("GAMESMAN_PROBE_TIMEOUT", str(probe))
            try:
                probe = int(float(full_env["GAMESMAN_PROBE_TIMEOUT"]))
            except ValueError:
                # bench warns and falls back to ITS default (600s) — the
                # deadline must budget for the probe bench will actually
                # run, not the value we failed to parse.
                probe = 600
            # Clamp an ambient GAMESMAN_PROBE_TIMEOUT (e.g. bench's 600s
            # default exported in the shell) and WRITE IT BACK, so probe
            # + deadline + margin always fit inside this step's timeout
            # and the parent bench gets to print best-of-completed-runs
            # before our kill arrives (ADVICE r5 — the max(300, ...)
            # floor alone silently degraded that guarantee to the
            # partial-stdout salvage). Two bounds: half the step budget,
            # AND timeout - 420 so the deadline's own 300s floor + 120s
            # margin still fit (the tighter one wins; below a 480s step
            # nothing can honor the floors, and no step here is that
            # short).
            probe = min(probe, max(60, int(timeout) // 2),
                        max(60, int(timeout) - 420))
            full_env["GAMESMAN_PROBE_TIMEOUT"] = str(probe)
            full_env["GAMESMAN_BENCH_DEADLINE"] = str(
                max(300, int(timeout) - probe - 120))
        t0 = time.time()
        try:
            proc = subprocess.run(
                argv, cwd=REPO, env=full_env, timeout=timeout,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            out, err, rc = proc.stdout, proc.stderr, proc.returncode
        except subprocess.TimeoutExpired as e:
            # TimeoutExpired attaches partial output as BYTES even under
            # text=True — decode it; it is exactly the already-measured
            # data this script exists to preserve.
            def _txt(x):
                if isinstance(x, bytes):
                    return x.decode(errors="replace")
                return x or ""

            out, err, rc = _txt(e.stdout), _txt(e.stderr), -1
        secs = round(time.time() - t0, 1)
        rec = _last_json(out) if parse_json else None
        # Keep BOTH tails: bench's progress and tracebacks go to stderr,
        # but microbench2's measurement lines print to stdout — the §1
        # decision table's data would otherwise never reach the artifact.
        self.record(
            step=name, status="ok" if rc == 0 else f"rc={rc}",
            secs=secs, env={k: v for k, v in (env or {}).items()},
            record=rec,
            stdout_tail="\n".join((out or "").splitlines()[-80:]),
            stderr_tail="\n".join((err or "").splitlines()[-40:]),
        )
        if rc != 0 and not relay_up():
            self.aborted = True
            self.record(step=name + ".postmortem", status="relay died")
        return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "chip_session.jsonl"))
    ap.add_argument("--quick", action="store_true",
                    help="skip the board ladder (steps 3+)")
    ap.add_argument("--phase2", action="store_true",
                    help="run only what the r04 mid-plan relay death left: "
                         "pallas chip check, pallas-gather 5x5 A/B, hybrid "
                         "k16/k20, the board ladder, the full bench")
    ap.add_argument("--phase3", action="store_true",
                    help="run only what the r04 SECOND relay death left: "
                         "the fixed pallas kernel's chip check + 5x5 A/B, "
                         "the 6x5 board, the full bench")
    ap.add_argument("--pallas-only", action="store_true",
                    help="the ~15-minute tail of phase3 for short windows "
                         "(late revival near a round boundary): just the "
                         "fixed pallas kernel's chip check + its 5x5 A/B")
    args = ap.parse_args()
    s = Session(args.out)
    py = sys.executable

    if not relay_up():
        s.record(step="probe", status="skipped",
                 reason=f"relay port {RELAY_PORT} refused — nothing to do")
        return 1
    s.record(step="probe", status="ok")

    bench = [py, os.path.join(REPO, "bench.py")]
    # REPEATS=3 + bench's runs.median_pps: r04's 6x4 was best-of-2 with an
    # unexplained 5x spread — three runs make an outlier self-evident.
    b55 = {"BENCH_SYM": "0", "BENCH_LADDER": "0",
           "BENCH_GAME": "connect4:w=5,h=5", "BENCH_REPEATS": "3"}

    if args.pallas_only:
        s.step("pallas_chip_check",
               [py, os.path.join(REPO, "tools", "pallas_chip_check.py")],
               timeout=900, parse_json=False)
        s.step("dense_gather_pallas", bench,
               env={**b55, "GAMESMAN_DENSE_GATHER": "pallas"},
               timeout=900)
        s.record(step="done", status="aborted" if s.aborted else "complete")
        return 1 if s.aborted else 0

    if args.phase3:
        # Second relay death landed mid-6x5; the pallas kernel was ALSO
        # rewritten after this window's Mosaic rejection (2-D BlockSpecs,
        # no in-kernel reshape) — re-prove it before the remaining ladder.
        s.step("pallas_chip_check",
               [py, os.path.join(REPO, "tools", "pallas_chip_check.py")],
               timeout=1200, parse_json=False)
        s.step("dense_gather_pallas", bench,
               env={**b55, "GAMESMAN_DENSE_GATHER": "pallas"})
        # Re-prove the provisional 10.97M 6x4 headline: 3 runs, best AND
        # median land in the record (VERDICT r4 weak #1).
        s.step("dense_6x4", bench,
               env={**b55, "BENCH_GAME": "connect4:w=6,h=4"}, timeout=3000)
        # 6x5 run 0 alone ran 50 min in r04 before the relay died: two
        # repeats (cold+warm) is the most a realistic window holds, and
        # bench's provisional records salvage run 0 if run 1 never lands.
        s.step("dense_6x5", bench,
               env={**b55, "BENCH_GAME": "connect4:w=6,h=5",
                    "BENCH_REPEATS": "2"}, timeout=5400)
        s.step("bench_full", bench, env={}, timeout=3600)
        s.record(step="done", status="aborted" if s.aborted else "complete")
        return 1 if s.aborted else 0

    if args.phase2:
        # Only what the r04 mid-plan relay death left unmeasured; falls
        # through to the shared board-ladder / full-bench tail below.
        s.step("pallas_chip_check",
               [py, os.path.join(REPO, "tools", "pallas_chip_check.py")],
               timeout=1200, parse_json=False)
        s.step("dense_gather_pallas", bench,
               env={**b55, "GAMESMAN_DENSE_GATHER": "pallas"})
        hybrid_ks = (16, 20)
    else:
        # §1 primitive costs (microbench2's lines land in stdout_tail).
        s.step("microbench2",
               [py, os.path.join(REPO, "tools", "microbench2.py")],
               timeout=1800, parse_json=False)

        # §2 dense lowering A/B on 5x5.
        s.step("dense_default", bench, env=b55)
        s.step("dense_rank_fused", bench,
               env={**b55, "GAMESMAN_DENSE_RANK": "fused"})
        s.step("dense_gather_sorted", bench,
               env={**b55, "GAMESMAN_DENSE_GATHER": "sorted"})
        s.step("dense_fused_sorted", bench,
               env={**b55, "GAMESMAN_DENSE_RANK": "fused",
                    "GAMESMAN_DENSE_GATHER": "sorted"})
        s.step("dense_binom_take", bench,
               env={**b55, "GAMESMAN_DENSE_BINOM": "take"}, timeout=1800)
        s.step("classic_5x5", bench, env={**b55, "BENCH_ENGINE": "classic"})
        hybrid_ks = (12, 16, 20)

    # §2b hybrid cutover scan on 5x5.
    for k in hybrid_ks:
        s.step(f"hybrid_k{k}", bench,
               env={**b55, "BENCH_ENGINE": "hybrid",
                    "GAMESMAN_HYBRID_CUTOVER": str(k)})

    if not args.quick:
        # §3 board ladder.
        s.step("dense_6x4", bench,
               env={**b55, "BENCH_GAME": "connect4:w=6,h=4"}, timeout=3000)
        s.step("dense_6x5", bench,
               env={**b55, "BENCH_GAME": "connect4:w=6,h=5"}, timeout=5400)
        # §4 the full default bench (primary + sym + ladder) — the shape
        # the driver records.
        s.step("bench_full", bench, env={}, timeout=3600)

    s.record(step="done", status="aborted" if s.aborted else "complete")
    # Nonzero on a mid-plan relay death so a driver gating on the exit
    # code retries the unmeasured steps (same convention as the probe).
    return 1 if s.aborted else 0


if __name__ == "__main__":
    sys.exit(main())
