#!/usr/bin/env python
"""Cross-validate the uint64 dense path end-to-end on a full board (CPU).

The 6x5 chip target is the first uint64 board (w*(h+1) = 36 state bits)
any engine will solve on silicon — but until this script, NO uint64
board had been solved end-to-end anywhere: the u64 kernel path was
pinned only by rank/unrank roundtrip tests (tests/test_dense.py). 4x7
(32 bits — the uint64 cutoff) exercises that path at a CPU-tractable
size; this solves it with BOTH engines and requires bit-exact agreement
on the root, the per-level reachable counts, and a sampled cell set —
the same parity axes the 6x5 run will be judged by, executed where a
failure is debuggable.

Run CPU-pinned (GAMESMAN_PLATFORM=cpu); takes ~1-2 h on one core.
Prints one JSON line at the end for the artifacts.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from gamesmanmpi_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.dense import DenseSolver


def main() -> int:
    spec = sys.argv[1] if len(sys.argv) > 1 else "connect4:w=4,h=7"
    g = get_game(spec)
    assert np.dtype(g.state_dtype) == np.uint64, (
        f"{spec} is not a uint64 board ({g.state_dtype})"
    )

    t0 = time.perf_counter()
    rc = Solver(g).solve()
    t_classic = time.perf_counter() - t0
    print(f"classic: {rc.value}/{rc.remoteness} "
          f"{rc.num_positions} positions in {t_classic:.1f}s", flush=True)

    t0 = time.perf_counter()
    rd = DenseSolver(g).solve()
    t_dense = time.perf_counter() - t0
    print(f"dense:   {rd.value}/{rd.remoteness} "
          f"{rd.num_positions} positions in {t_dense:.1f}s", flush=True)

    ok = (rd.value, rd.remoteness) == (rc.value, rc.remoteness)
    ok &= rd.num_positions == rc.num_positions
    per_level_ok = True
    for L, n in rd.stats["reachable_per_level"].items():
        tab = rc.levels.get(L)
        classic_n = tab.states.shape[0] if tab is not None else 0
        if n != classic_n:
            # A level-set disagreement IS the divergence this tool
            # exists to catch — report it, never crash on it.
            per_level_ok = False
            print(f"LEVEL COUNT MISMATCH at {L}: dense {n} vs "
                  f"classic {classic_n}", flush=True)
    ok &= per_level_ok

    rng = np.random.default_rng(11)
    sampled = mismatches = 0
    for L, tab in rc.levels.items():
        n = tab.states.shape[0]
        if not n:
            continue
        for i in rng.choice(n, size=min(500, n), replace=False):
            s = int(tab.states[i])
            got = rd.lookup(s)
            want = (int(tab.values[i]), int(tab.remoteness[i]))
            sampled += 1
            if got != want:
                mismatches += 1
                if mismatches <= 5:
                    print(f"CELL MISMATCH {s:#x}: dense {got} vs "
                          f"classic {want}", flush=True)
    ok &= mismatches == 0

    print(json.dumps({
        "check": "u64_crosscheck", "board": spec,
        "value": rd.value, "remoteness": rd.remoteness,
        "positions": rd.num_positions,
        "per_level_counts_match": per_level_ok,
        "cells_sampled": sampled, "cell_mismatches": mismatches,
        "secs_classic": round(t_classic, 1),
        "secs_dense": round(t_dense, 1),
        "ok": ok,
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
