#!/usr/bin/env python
"""Build (or rebuild) the resident opening book of a finalized DB.

    python tools/build_book.py DB_DIR --plies N [--verify]

Enumerates every raw position within N plies of the game's initial
position (BFS through the reader's expand kernel), scores each through
``DbReader.lookup_best`` against the finished DB, and seals the table
as ``book.gmb`` recorded in the manifest (file + sha256) — see
gamesmanmpi_tpu/db/book.py and docs/SERVING.md "Hot path". The serving
fleet answers book hits entirely from resident arrays: no batcher
wait, no canonicalize, no block decode.

Sealing rewrites the manifest atomically, which bumps the DB epoch:
run this BEFORE pointing a fleet at the directory (or follow with
``POST /reload`` — the rolling reload swaps reader + book together and
every epoch-derived ETag flips with it). ``gamesman-db export-db
--book-plies N`` does the same build at export time; this tool exists
to add or resize a book on an already-exported DB without re-solving.

--verify re-probes EVERY sealed entry through the reader afterwards
(db/book.py verify_book, the same deep gate tools/check_db.py runs):
exit 1 on any mismatch. Exit 0 = sealed (and verified when asked),
1 = verification problems, 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # tools/ scripts get sys.path[0]=tools/
    sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("db_dir", help="finalized DB directory (from export-db)")
    p.add_argument("--plies", type=int, default=None, metavar="N",
                   help="book depth: every position within N plies of "
                   "the initial position. Default from GAMESMAN_BOOK_PLIES")
    p.add_argument("--verify", action="store_true",
                   help="after sealing, re-probe every book entry "
                   "through the reader and exit 1 on any mismatch")
    args = p.parse_args(argv)

    from gamesmanmpi_tpu.db.book import build_book, verify_book
    from gamesmanmpi_tpu.db.format import DbFormatError
    from gamesmanmpi_tpu.utils.env import env_int

    plies = (
        env_int("GAMESMAN_BOOK_PLIES", 0)
        if args.plies is None else int(args.plies)
    )
    if plies <= 0:
        print("error: --plies N (or GAMESMAN_BOOK_PLIES) must be > 0",
              file=sys.stderr)
        return 2
    try:
        rec = build_book(args.db_dir, plies)
    except (DbFormatError, FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(
        f"book sealed: {rec['count']} entries to {rec['plies']} plies "
        f"({rec['file']}, sha256 {rec['sha256'][:12]}…)"
    )
    if args.verify:
        problems = verify_book(args.db_dir)
        for problem in problems:
            print(f"PROBLEM: {problem}", file=sys.stderr)
        if problems:
            print(f"{args.db_dir}: {len(problems)} problem(s)",
                  file=sys.stderr)
            return 1
        print("book verified: every entry matches the reader")
    return 0


if __name__ == "__main__":
    sys.exit(main())
