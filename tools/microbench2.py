#!/usr/bin/env python
"""Round-3b microbenchmarks: find the next lever past 4.3M pos/s.

BENCH_r03's warm profile is sort-bound forward (XLA TPU sort ~0.85 GB/s)
and gather-bound backward. This harness measures the candidate
replacements on the real chip before any is built:

- elementwise bandwidth (the achievable roofline through the relay);
- XLA sort cost vs size (does a VMEM-resident row sort beat one big sort?);
- batched row sorts [R, C] (the "partition into buckets, sort buckets"
  plan needs per-row sorts to be much faster per element);
- u8-key pair sort (cost of a partition pass done via lax.sort);
- gather bandwidth vs table size (does a VMEM-sized table gather fast?);
- permutation-inversion: scatter vs pair-sort (expand_provenance sort #2);
- pure-JAX bitonic merge of two sorted halves (sorted-merge lever);
- a trivial Pallas kernel (does Pallas/Mosaic work over the axon relay?).

Usage: python tools/microbench2.py [--quick]
"""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(_REPO, ".jax_compile_cache"))

import gamesmanmpi_tpu  # noqa: F401  (x64 on)
from gamesmanmpi_tpu.utils.platform import apply_platform_env

apply_platform_env()  # GAMESMAN_PLATFORM=cpu for off-chip dry runs

import jax
import jax.numpy as jnp
import numpy as np


def _scalarize(r):
    leaves = jax.tree_util.tree_leaves(r)
    acc = jnp.uint32(0)
    for leaf in leaves:
        acc = acc + jnp.max(leaf).astype(jnp.uint32)
    return acc


def timeit(label, fn, *args, n=3, warmup=2, bytes_moved=None):
    f = jax.jit(lambda *a: _scalarize(fn(*a)))
    try:
        for _ in range(warmup):
            np.asarray(f(*args))
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            np.asarray(f(*args))
            ts.append(time.perf_counter() - t0)
    except Exception as e:  # pragma: no cover - chip-side diagnostics
        print(f"{label:52s} FAILED: {type(e).__name__}: {e}"[:200], flush=True)
        return None
    best = min(ts)
    bw = f"  {bytes_moved/best/1e9:8.2f} GB/s" if bytes_moved else ""
    print(f"{label:52s} best {best*1e3:9.2f} ms{bw}", flush=True)
    return best


def bitonic_merge(a, b):
    """Merge two sorted [N] arrays into one sorted [2N] array.

    concat(a, reverse(b)) is bitonic; log2(2N) compare-exchange stages
    sort a bitonic sequence. Each stage is a reshape + min/max — pure
    elementwise traffic, no sort network.
    """
    x = jnp.concatenate([a, b[::-1]])
    n = x.shape[0]
    s = n // 2
    while s >= 1:
        y = x.reshape(-1, 2, s)
        lo = jnp.minimum(y[:, 0, :], y[:, 1, :])
        hi = jnp.maximum(y[:, 0, :], y[:, 1, :])
        x = jnp.stack([lo, hi], axis=1).reshape(n)
        s //= 2
    return x


def main():
    quick = "--quick" in sys.argv
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev})", file=sys.stderr)

    from gamesmanmpi_tpu.utils.env import env_int

    N = env_int("GAMESMAN_MB_N", 32 * 1024 * 1024)
    rng = np.random.default_rng(0)
    keys_np = rng.integers(0, 1 << 30, size=N, dtype=np.uint32)
    keys = jnp.asarray(keys_np)

    # 0. sync floor + elementwise bandwidth (roofline through the relay)
    tiny = jnp.arange(256, dtype=jnp.uint32)
    timeit("sync floor", lambda x: x + 1, tiny, n=10)
    timeit(f"elementwise x+1 u32 [{N>>20}M]", lambda x: x + 1, keys,
           bytes_moved=2 * 4 * N)
    timeit(f"elementwise 5-op u32 [{N>>20}M]",
           lambda x: ((x * 3) ^ (x >> 7)) + (x << 2), keys,
           bytes_moved=2 * 4 * N)

    # 1. XLA sort scaling with size (is small-sort per-element cheaper?)
    for m in (1, 4, 32):
        sz = min(m * 1024 * 1024, N)
        timeit(f"sort u32 [{sz>>20}M]", jnp.sort, keys[:sz],
               bytes_moved=2 * 4 * sz)

    # 2. batched row sorts, constant total 32M
    for rows, cols in ((32, N // 32), (256, N // 256), (2048, N // 2048)):
        x = keys.reshape(rows, cols)
        timeit(f"row sort [{rows} x {cols>>10}K]",
               lambda v: jnp.sort(v, axis=-1), x, bytes_moved=2 * 4 * N)

    # 3. partition pass cost: u8-key pair sort (bucket id = top 8 bits)
    def bucket_sort(k):
        bid = (k >> jnp.uint32(22)).astype(jnp.uint8)
        return jax.lax.sort((bid, k), num_keys=1, is_stable=False)[1]

    timeit(f"u8-key pair sort (partition) [{N>>20}M]", bucket_sort, keys,
           bytes_moved=2 * 5 * N)

    # 4. gather bandwidth vs table size
    for m, label in ((64 * 1024, "64K"), (1024 * 1024, "1M"),
                     (8 * 1024 * 1024, "8M")):
        table = jnp.asarray(
            rng.integers(0, 1 << 30, size=m, dtype=np.uint32))
        idx = jnp.asarray(rng.integers(0, m, size=N, dtype=np.int32))
        timeit(f"gather u32 [{N>>20}M from {label}]",
               lambda t, i: t[i], table, idx, bytes_moved=4 * N)
    # sorted (monotone) indices: does locality help XLA's gather?
    table8 = jnp.asarray(rng.integers(0, 1 << 30, size=8 * 1024 * 1024,
                                      dtype=np.uint32))
    sidx = jnp.asarray(np.sort(
        rng.integers(0, 8 * 1024 * 1024, size=N, dtype=np.int32)))
    timeit(f"gather u32 sorted idx [{N>>20}M from 8M]",
           lambda t, i: t[i], table8, sidx, bytes_moved=4 * N)

    # 5. permutation inversion: scatter vs pair sort
    perm_np = rng.permutation(N).astype(np.int32)
    perm = jnp.asarray(perm_np)
    vals = jnp.asarray(rng.integers(0, 1 << 30, size=N, dtype=np.int32))

    def inv_scatter(p, v):
        return jnp.zeros_like(v).at[p].set(v, unique_indices=True)

    def inv_sort(p, v):
        return jax.lax.sort((p, v), num_keys=1, is_stable=False)[1]

    timeit(f"perm inversion scatter [{N>>20}M]", inv_scatter, perm, vals,
           bytes_moved=3 * 4 * N)
    timeit(f"perm inversion pair sort [{N>>20}M]", inv_sort, perm, vals,
           bytes_moved=3 * 4 * N)

    # 6. bitonic merge of two sorted 16M halves vs sorting 32M
    h = N // 2
    a = jnp.asarray(np.sort(keys_np[:h]))
    b = jnp.asarray(np.sort(keys_np[h:]))
    timeit(f"bitonic merge [{h>>20}M + {h>>20}M]", bitonic_merge, a, b,
           bytes_moved=2 * 4 * N * int(np.log2(N)))
    timeit(f"jnp.sort same total [{N>>20}M]", jnp.sort, keys,
           bytes_moved=2 * 4 * N)

    # 6b. the shipped merge-ladder sort (ops/mergesort.py) vs XLA's sort —
    # the GAMESMAN_SORT=merge decision is this pair of lines.
    from gamesmanmpi_tpu.ops.mergesort import merge_sort

    for row in (2048, 16 * 1024, 128 * 1024):
        os.environ["GAMESMAN_SORT_ROW"] = str(row)
        timeit(f"merge_sort u32 [{N>>20}M] row={row>>10}K", merge_sort,
               keys, bytes_moved=2 * 4 * N)
    os.environ.pop("GAMESMAN_SORT_ROW", None)
    origin_i32 = jnp.arange(N, dtype=jnp.int32)
    timeit(f"merge_sort u32+payload [{N>>20}M]",
           lambda k, o: merge_sort(k, o), keys, origin_i32,
           bytes_moved=2 * 8 * N)

    # 6c. searchsorted on u64 (the hybrid boundary join's primitive: child
    # packed states searched in the sorted sparse level-B table — the
    # GAMESMAN_SEARCH decision at the join's scale, and the per-element
    # cost the CHIP_PLAN §2b cutover arithmetic needs). Queries half-hit.
    M8 = 8 * 1024 * 1024
    tbl64 = jnp.asarray(np.sort(
        rng.integers(0, 1 << 60, size=M8, dtype=np.uint64)))
    q64 = jnp.asarray(np.where(
        rng.integers(0, 2, size=N).astype(bool),
        np.asarray(tbl64)[rng.integers(0, M8, size=N)],
        rng.integers(0, 1 << 60, size=N, dtype=np.uint64),
    ))
    for method in ("scan", "sort"):
        timeit(
            f"searchsorted u64 {method} [{N>>20}M in 8M]",
            lambda t, q, m=method: jnp.searchsorted(t, q, method=m),
            tbl64, q64, bytes_moved=8 * N,
        )

    # 7. does Pallas compile/run over this backend at all?
    if not quick:
        try:
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu  # noqa: F401 - availability probe

            def k_copy(x_ref, o_ref):
                o_ref[:] = x_ref[:] * jnp.uint32(2)

            def pallas_double(x):
                return pl.pallas_call(
                    k_copy,
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    grid=(x.shape[0] // (8 * 1024 * 128),),
                    in_specs=[pl.BlockSpec((8 * 1024 * 128,),
                                           lambda i: (i,))],
                    out_specs=pl.BlockSpec((8 * 1024 * 128,),
                                           lambda i: (i,)),
                )(x)

            timeit(f"pallas elementwise 2x [{N>>20}M]", pallas_double, keys,
                   bytes_moved=2 * 4 * N)
        except Exception as e:  # pragma: no cover
            print(f"pallas unavailable: {type(e).__name__}: {e}"[:200],
                  flush=True)

    # 7b. the monotone-window gather scaffold (ops/pallas_gather.py):
    # Mosaic go/no-go + throughput vs XLA's gather on the same sorted
    # indices — the dense backward's candidate kernel.
    if not quick:
        try:
            from gamesmanmpi_tpu.ops.pallas_gather import (
                monotone_window_gather,
            )

            m8 = 8 * 1024 * 1024
            tb = jnp.asarray(
                rng.integers(0, 1 << 30, size=m8, dtype=np.uint32)
            )
            # Sorted-random over the full table (NOT a cumsum, which would
            # saturate at m8 and degenerate into re-reading one element).
            mono = jnp.asarray(np.sort(
                rng.integers(0, m8, size=N)
            ).astype(np.int32))
            timeit(
                f"pallas monotone gather [{N>>20}M from 8M]",
                lambda t, i: monotone_window_gather(t, i)[0], tb, mono,
                bytes_moved=4 * N,
            )
            timeit(
                f"xla gather same monotone idx [{N>>20}M from 8M]",
                lambda t, i: t[i], tb, mono, bytes_moved=4 * N,
            )
        except Exception as e:  # pragma: no cover
            print(f"pallas monotone gather unavailable: "
                  f"{type(e).__name__}: {e}"[:200], flush=True)

    # 8. u64 sort (the 6x5+ board dtype)
    keys64 = keys.astype(jnp.uint64)
    timeit(f"sort u64 [{N>>20}M]", jnp.sort, keys64, bytes_moved=2 * 8 * N)


if __name__ == "__main__":
    main()
