"""compress/ + DB format v2: codecs, framing, cache, decompress-on-probe.

The acceptance axes of ISSUE 9:

* codec laws — every codec round-trips bit-exactly on its shapes,
  declines (None) off them, and raw passthrough wins when compression
  loses, so a block can never grow past raw;
* framing integrity — per-block crc32 catches torn/bit-rotted blocks,
  index-vs-stream mismatches are structural errors, and every failure
  is a ValueError (TORN_NPZ_ERRORS / DbFormatError speak it);
* decompress-on-probe — a v2 DB answers byte-identically to its v1
  twin through lookup/lookup_best, under a thread-hammered hot-block
  cache with a tiny budget (eviction correctness), and a corrupted
  block surfaces as DbFormatError at probe time (the serving breaker's
  food), never as a wrong answer;
* checkpoint blocks mode — GAMESMAN_CKPT_COMPRESS=blocks round-trips
  through _savez/_loadz, v1 npz files keep loading, resume reaches
  parity, and the sharded engine's spill/checkpoint files compress with
  byte-parity resume (its ckpt_bytes_* stats expose the saving).
"""

import json
import pathlib
import subprocess
import sys
import threading

import numpy as np
import pytest

from gamesmanmpi_tpu.compress import (
    CELL_CANDIDATES,
    CODECS,
    GENERIC_CANDIDATES,
    KEY_CANDIDATES,
    BlockCache,
    BlockCorruptError,
    decode_array,
    decode_block,
    encode_array,
    encode_best,
    index_offsets,
)
from gamesmanmpi_tpu.db import DbFormatError, DbReader, check_db, export_result
from gamesmanmpi_tpu.db.check import db_equal, db_stats
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.obs import MetricsRegistry
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.utils.checkpoint import (
    TORN_NPZ_ERRORS,
    LevelCheckpointer,
    _loadz,
    _savez,
)

from helpers import REF_GAMES, REPO, load_module

# Smoke tier: fast, compile-light, single-process-safe (see pyproject).
pytestmark = pytest.mark.smoke


# ------------------------------------------------------------------ codecs


def _sorted_keys(n, hi, dtype, seed=0):
    rng = np.random.default_rng(seed)
    return np.unique(rng.integers(0, hi, n, dtype=dtype))


def _cells(n, max_rem=40, seed=1):
    rng = np.random.default_rng(seed)
    v = rng.integers(1, 4, n).astype(np.uint32)
    r = rng.integers(0, max_rem + 1, n).astype(np.uint32)
    return (v | (r << np.uint32(2))).astype(np.uint32)


@pytest.mark.parametrize("dtype,hi", [
    (np.uint64, 1 << 50), (np.uint64, 1 << 10), (np.uint32, 1 << 31),
])
def test_keydelta_roundtrip_and_dtype(dtype, hi):
    keys = _sorted_keys(5000, hi, dtype)
    codec = CODECS["keydelta"]
    blob = codec.encode(keys)
    assert blob is not None
    out = codec.decode(blob, keys.dtype, keys.shape[0])
    assert out.dtype == keys.dtype
    assert np.array_equal(out, keys)


def test_keydelta_declines_unsorted_and_signed():
    codec = CODECS["keydelta"]
    assert codec.encode(np.array([5, 3, 9], dtype=np.uint64)) is None
    assert codec.encode(np.array([1, 2, 3], dtype=np.int32)) is None
    assert codec.encode(np.zeros(0, dtype=np.uint64)) is None
    # Equal neighbors are representable (non-descending), huge deltas too.
    dup = np.array([7, 7, 2**63], dtype=np.uint64)
    out = codec.decode(codec.encode(dup), np.uint64, 3)
    assert np.array_equal(out, dup)


def test_cellpack_roundtrip_all_widths():
    codec = CODECS["cellpack"]
    for max_rem in (0, 200, 70000, 1 << 20):
        cells = _cells(4097, max_rem=max_rem)
        out = codec.decode(codec.encode(cells), np.uint32, cells.shape[0])
        assert np.array_equal(out, cells), max_rem
    # Non-multiple-of-4 counts round-trip (padding never leaks).
    for n in (1, 2, 3, 5):
        cells = _cells(n)
        assert np.array_equal(
            codec.decode(codec.encode(cells), np.uint32, n), cells
        )


def test_zlib_and_raw_roundtrip():
    arr = np.arange(1000, dtype=np.int32)
    for name in ("zlib", "raw"):
        codec = CODECS[name]
        out = codec.decode(codec.encode(arr), np.int32, 1000)
        assert np.array_equal(out, arr)


def test_encode_best_raw_passthrough_when_compression_loses():
    junk = np.random.default_rng(3).integers(
        0, 1 << 63, 512, dtype=np.uint64
    )  # high-entropy unsorted: nothing beats raw
    name, blob = encode_best(junk, GENERIC_CANDIDATES)
    assert name == "raw"
    assert len(blob) == junk.nbytes
    keys = _sorted_keys(5000, 1 << 30, np.uint64)
    name, blob = encode_best(keys, KEY_CANDIDATES)
    assert name == "keydelta"
    assert len(blob) < keys.nbytes


# ----------------------------------------------------------------- framing


def test_block_framing_roundtrip_and_ragged_tail():
    keys = _sorted_keys(10000, 1 << 40, np.uint64)
    index, blobs = encode_array(keys, 1024, KEY_CANDIDATES)
    assert len(blobs) == (keys.shape[0] + 1023) // 1024
    stream = b"".join(blobs)
    assert np.array_equal(decode_array(index, stream), keys)
    # Single-block decode agrees with the slice.
    offs = index_offsets(index)
    b = len(blobs) - 1  # the ragged tail
    out = decode_block(index, b, stream[offs[b]:offs[b + 1]])
    assert np.array_equal(out, keys[b * 1024:])


def test_block_crc_catches_corruption_and_index_mismatch():
    cells = _cells(5000)
    index, blobs = encode_array(cells, 512, CELL_CANDIDATES)
    stream = bytearray(b"".join(blobs))
    stream[len(stream) // 2] ^= 0xFF
    with pytest.raises(BlockCorruptError, match="crc32"):
        decode_array(index, bytes(stream))
    # Truncated stream: the lengths-vs-stream check fires first.
    with pytest.raises(BlockCorruptError, match="lengths"):
        decode_array(index, b"".join(blobs)[:-3])
    # Index lists disagreeing in length are structural corruption.
    bad = dict(index, crc32=index["crc32"][:-1])
    with pytest.raises(BlockCorruptError, match="parallel"):
        decode_array(bad, b"".join(blobs))
    # BlockCorruptError must ride the checkpoint degrade tuple.
    assert issubclass(BlockCorruptError, ValueError)
    with pytest.raises(TORN_NPZ_ERRORS):
        decode_array(index, bytes(stream))


# ------------------------------------------------------------------- cache


def test_block_cache_lru_eviction_by_bytes():
    reg = MetricsRegistry()
    cache = BlockCache(1000, registry=reg)
    a, b, c = (np.zeros(50, np.uint64) for _ in range(3))  # 400 B each
    cache.put("a", a, a.nbytes)
    cache.put("b", b, b.nbytes)
    assert cache.get("a") is a  # refreshes recency: b is now LRU
    cache.put("c", c, c.nbytes)  # 1200 B > 1000: evicts b
    assert cache.get("b") is None
    assert cache.get("a") is a and cache.get("c") is c
    stats = cache.stats()
    assert stats["evictions"] == 1 and stats["blocks"] == 2
    assert stats["bytes"] == 800
    # An oversized value still admits (evicting the rest).
    big = np.zeros(500, np.uint64)
    cache.put("big", big, big.nbytes)
    assert cache.get("big") is big
    assert cache.stats()["blocks"] == 1
    snap = reg.snapshot()
    hits = snap["gamesman_db_cache_hits_total"]["values"][0]["value"]
    assert hits >= 3


def test_block_cache_thread_hammer_accounting():
    cache = BlockCache(1 << 16)
    payload = np.zeros(64, np.uint64)
    errors = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(500):
                key = int(rng.integers(0, 32))
                if cache.get(key) is None:
                    cache.put(key, payload, payload.nbytes)
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    stats = cache.stats()
    assert stats["hits"] + stats["misses"] == 8 * 500
    assert stats["bytes"] <= (1 << 16)


# ------------------------------------------------- DB format v2 (probe)


@pytest.fixture(scope="module")
def ttt_pair(tmp_path_factory):
    """One ttt solve exported both ways + the oracle: the A/B pair."""
    from gamesmanmpi_tpu.solve.oracle import oracle_solve

    d = tmp_path_factory.mktemp("v2db")
    spec = "tictactoe"
    result = Solver(get_game(spec)).solve()
    export_result(result, d / "v1", spec)
    export_result(result, d / "v2", spec, compress=True)
    _, _, oracle = oracle_solve(load_module(REF_GAMES / "tictactoe.py"))
    return d, oracle


def test_v2_db_checks_clean_equals_v1_and_compresses(ttt_pair):
    d, _ = ttt_pair
    assert check_db(d / "v1") == []
    assert check_db(d / "v2") == []
    assert db_equal(d / "v1", d / "v2") == []
    stats = db_stats(d / "v2")
    assert stats["version"] == 2
    # ttt keys/cells are highly structured; the whole-DB manifest ratio
    # must comfortably clear 2x even at this tiny scale.
    assert stats["ratio"] > 2.0
    v1_stats = db_stats(d / "v1")
    assert v1_stats["version"] == 1 and v1_stats["ratio"] == 1.0


def test_v2_lookup_matches_oracle_and_v1(ttt_pair, monkeypatch):
    d, oracle = ttt_pair
    # A tiny cache budget forces eviction mid-scan: answers must not
    # depend on residency.
    monkeypatch.setenv("GAMESMAN_DB_CACHE_MB", "1")
    positions = np.array(sorted(oracle), dtype=np.uint64)
    with DbReader(d / "v1") as r1, DbReader(d / "v2") as r2:
        assert r2.cache_stats() is not None
        assert r1.cache_stats() is None  # v1: no block cache
        a = r1.lookup(positions)
        b = r2.lookup(positions)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)
        assert b[2].all()
        for i, pos in enumerate(positions):
            assert (int(b[0][i]), int(b[1][i])) == oracle[int(pos)]
        # best-move parity through the same decompressing probe.
        ab = r1.lookup_best(positions[:256])
        bb = r2.lookup_best(positions[:256])
        for x, y in zip(ab, bb):
            assert np.array_equal(x, y)
        # Misses miss identically.
        miss = np.array([0b1_000000001, (1 << 18) - 1], dtype=np.uint64)
        assert not r2.lookup(miss)[2].any()
        stats = r2.cache_stats()
        assert stats["hits"] > 0 and stats["misses"] > 0


def test_v2_concurrent_probes_stay_exact(ttt_pair, monkeypatch):
    """The fleet's concurrency shape on one reader: flush + breaker +
    direct callers probing at once through a small, evicting cache."""
    d, oracle = ttt_pair
    monkeypatch.setenv("GAMESMAN_DB_CACHE_MB", "1")
    positions = np.array(sorted(oracle), dtype=np.uint64)
    expect = {int(p): oracle[int(p)] for p in positions}
    errors = []
    with DbReader(d / "v2") as reader:
        def hammer(seed):
            rng = np.random.default_rng(seed)
            try:
                for _ in range(10):
                    qs = rng.choice(positions, size=257, replace=True)
                    v, r, f = reader.lookup(qs)
                    assert f.all()
                    for i, q in enumerate(qs):
                        assert (int(v[i]), int(r[i])) == expect[int(q)]
            except Exception as e:  # noqa: BLE001 - collected
                errors.append(e)

        threads = [
            threading.Thread(target=hammer, args=(s,)) for s in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        stats = reader.cache_stats()
        assert stats["hits"] + stats["misses"] > 0


def test_v2_corrupt_block_is_a_reader_fault_not_a_wrong_answer(
        ttt_pair, tmp_path):
    import shutil

    d, oracle = ttt_pair
    bad = tmp_path / "bad"
    shutil.copytree(d / "v2", bad)
    manifest = json.loads((bad / "manifest.json").read_text())
    rec = max(
        manifest["levels"].values(), key=lambda r: r["stored_bytes"]
    )
    victim = bad / rec["cells"]
    raw = bytearray(victim.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    victim.write_bytes(raw)
    # check_db: caught both as a sha256 mismatch and a block problem.
    problems = check_db(bad)
    assert problems
    # The reader raises DbFormatError at probe (breaker food), and
    # db_equal refuses to call the directories identical.
    positions = np.array(sorted(oracle), dtype=np.uint64)
    with DbReader(bad) as reader:
        with pytest.raises(DbFormatError):
            reader.lookup(positions)
    assert db_equal(d / "v1", bad) != []


def test_v2_check_db_catches_index_and_router_tampering(ttt_pair, tmp_path):
    import shutil

    from gamesmanmpi_tpu.db.format import file_sha256, write_manifest

    d, _ = ttt_pair
    # Tamper 1: first_keys shifted — the probe router would misroute.
    bad = tmp_path / "router"
    shutil.copytree(d / "v2", bad)
    manifest = json.loads((bad / "manifest.json").read_text())
    key = next(k for k, r in manifest["levels"].items()
               if len(r["first_keys"]))
    manifest["levels"][key]["first_keys"][0] += 1
    write_manifest(bad, manifest)
    assert any("first_keys" in p for p in check_db(bad))

    # Tamper 2: block count that cannot hold the level (index mismatch
    # exits non-zero through the tool — the satellite contract).
    bad2 = tmp_path / "count"
    shutil.copytree(d / "v2", bad2)
    manifest = json.loads((bad2 / "manifest.json").read_text())
    rec = manifest["levels"][key]
    rec["keys_blocks"]["count"] = rec["keys_blocks"]["count"] + 1
    rec["count"] = rec["count"] + 1
    write_manifest(bad2, manifest)
    assert check_db(bad2)
    tool = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_db.py"),
         str(bad2), "--quiet"],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert tool.returncode == 1
    assert "PROBLEM" in tool.stderr


def test_check_db_tool_stats_table_and_same_as(ttt_pair, tmp_path):
    d, _ = ttt_pair
    stats_json = tmp_path / "stats.json"
    tool = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_db.py"),
         str(d / "v2"), "--same-as", str(d / "v1"),
         "--stats-json", str(stats_json)],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert tool.returncode == 0, tool.stderr
    assert "TOTAL" in tool.stdout and "format v2" in tool.stdout
    stats = json.loads(stats_json.read_text())
    assert stats["ratio"] > 2.0
    # Logical difference -> non-zero: compare against a different game.
    other = tmp_path / "other"
    export_result(
        Solver(get_game("subtract:total=10,moves=1-2")).solve(),
        other, "subtract:total=10,moves=1-2",
    )
    tool = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_db.py"),
         str(d / "v2"), "--quiet", "--same-as", str(other)],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert tool.returncode == 1
    assert "differs" in tool.stderr


def test_cli_export_compress_roundtrip(tmp_path, capsys):
    """export-db --compress end to end, plus GAMESMAN_DB_COMPRESS as the
    env default (the CLI flag wins when given)."""
    from gamesmanmpi_tpu.cli import main as cli_main

    spec = "subtract:total=10,moves=1-2"
    rc = cli_main(["export-db", spec, "--out", str(tmp_path / "db"),
                   "--compress"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "compressed:" in out
    assert check_db(tmp_path / "db") == []
    rc = cli_main(["query", str(tmp_path / "db"), "9"])
    assert rc == 0
    assert "value=LOSE remoteness=6" in capsys.readouterr().out


# -------------------------------------------- checkpoint blocks mode


def test_savez_blocks_roundtrip_and_v1_interop(tmp_path, monkeypatch):
    states = _sorted_keys(30000, 1 << 44, np.uint64)
    cells = _cells(states.shape[0])
    plain = tmp_path / "plain.npz"
    raw, stored = _savez(plain, states=states, cells=cells)
    monkeypatch.setenv("GAMESMAN_CKPT_COMPRESS", "blocks")
    blocked = tmp_path / "blocked.npz"
    raw_b, stored_b = _savez(blocked, states=states, cells=cells)
    assert raw_b == states.nbytes + cells.nbytes
    assert stored_b < raw_b / 2  # structured payload really compresses
    for path in (plain, blocked):
        with _loadz(path) as z:
            assert sorted(z.files) == ["cells", "states"]
            assert np.array_equal(z["states"], states)
            assert np.array_equal(z["cells"], cells)
    # Non-1-D members pass through uncompressed but load identically.
    m = np.ones((3, 4), np.float32)
    _savez(tmp_path / "mixed.npz", m=m, states=states)
    with _loadz(tmp_path / "mixed.npz") as z:
        assert z["m"].shape == (3, 4)
        assert np.array_equal(z["states"], states)


def test_blocks_checkpoint_resume_parity_and_quarantine(
        tmp_path, monkeypatch):
    monkeypatch.setenv("GAMESMAN_CKPT_COMPRESS", "blocks")
    spec = "subtract:total=21,moves=1-2-3"
    ck = LevelCheckpointer(str(tmp_path / "ck"))
    first = Solver(get_game(spec), checkpointer=ck).solve()
    resumed = Solver(
        get_game(spec), checkpointer=LevelCheckpointer(str(tmp_path / "ck"))
    ).solve()
    for lv, t in first.levels.items():
        r = resumed.levels[lv]
        assert np.array_equal(t.states, r.states)
        assert np.array_equal(t.values, r.values)
        assert np.array_equal(t.remoteness, r.remoteness)
    # Rot a sealed compressed level: load_level must raise into the
    # TORN tuple and quarantine, exactly like a v1 file.
    victim = sorted((tmp_path / "ck").glob("level_*.npz"))[2]
    raw = bytearray(victim.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    victim.write_bytes(raw)
    ck2 = LevelCheckpointer(str(tmp_path / "ck"))
    with pytest.raises(TORN_NPZ_ERRORS):
        ck2.load_level(2)
    assert list((tmp_path / "ck").glob("*.corrupt"))


def test_ckpt_to_db_compress_flag(tmp_path):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import ckpt_to_db
    finally:
        sys.path.pop(0)
    spec = "subtract:total=10,moves=1-2"
    ck = tmp_path / "ck"
    Solver(get_game(spec), checkpointer=LevelCheckpointer(str(ck))).solve()
    rc = ckpt_to_db.main(
        [str(ck), str(tmp_path / "db"), "--game", spec, "--compress"]
    )
    assert rc == 0
    stats = db_stats(tmp_path / "db")
    assert stats["version"] == 2
    assert check_db(tmp_path / "db") == []
