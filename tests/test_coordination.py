"""Cross-rank consensus primitive (resilience/coordination.py), no JAX.

The epoch barrier is the piece that turns PR 4's rank-local retry into a
fleet decision (docs/DISTRIBUTED.md): every rank proposes ok/retry/abort
for a shared epoch and blocks until the round resolves. These tests
drive the real server + real clients over loopback sockets — threads
standing in for ranks — and pin the four contractual behaviors ISSUE 6
names: happy-path consensus, deadline expiry, late-joiner rejection,
and coordinator death surfacing as an error within the deadline (never
a hang).
"""

import json
import socket
import threading
import time

import pytest

from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.resilience.coordination import (
    ABORT,
    OK,
    RETRY,
    CoordinatedAbort,
    CoordinationError,
    Coordination,
    CoordinatorServer,
    EpochBarrier,
    coordination_from_env,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def server():
    srv = CoordinatorServer(2, deadline=5.0)
    yield srv
    srv.close()


def _clients(server, n=2, **kw):
    kw.setdefault("deadline", server.deadline)
    return [EpochBarrier(server.address, r, **kw) for r in range(n)]


def _propose_all(clients, tag, verdicts):
    """Every client proposes concurrently; return the per-rank decisions
    (None where the client raised — the exception lands in errs)."""
    decisions = [None] * len(clients)
    errs = [None] * len(clients)

    def run(i):
        try:
            decisions[i] = clients[i].propose(tag, verdicts[i])
        except Exception as e:  # noqa: BLE001 - recorded for asserts
            errs[i] = e

    threads = [
        threading.Thread(target=run, args=(i,), daemon=True)
        for i in range(len(clients))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return decisions, errs


# ------------------------------------------------------------ happy path


def test_unanimous_ok_resolves_ok(server):
    a, b = _clients(server)
    decisions, errs = _propose_all([a, b], "fwd:L3", [OK, OK])
    assert errs == [None, None]
    assert decisions == [OK, OK]


def test_one_retry_makes_everyone_retry(server):
    """A transient on ONE rank must turn into a retry on EVERY rank —
    the collective-safety property."""
    a, b = _clients(server)
    decisions, errs = _propose_all([a, b], "fwd:L3", [RETRY, OK])
    assert errs == [None, None]
    assert decisions == [RETRY, RETRY]


def test_abort_beats_retry(server):
    a, b = _clients(server)
    decisions, errs = _propose_all([a, b], "fwd:L3", [ABORT, RETRY])
    assert errs == [None, None]
    assert decisions == [ABORT, ABORT]


def test_sequence_numbers_keep_rounds_apart(server):
    """The same tag proposed twice is two DIFFERENT epochs (the client
    seq is folded in): round 2 must not be answered by round 1's
    resolution."""
    a, b = _clients(server)
    d1, _ = _propose_all([a, b], "fwd:L3", [OK, OK])
    d2, _ = _propose_all([a, b], "fwd:L3", [RETRY, OK])
    assert d1 == [OK, OK]
    assert d2 == [RETRY, RETRY]
    assert a.seq == b.seq == 2


def test_barrier_agreement_and_divergence():
    srv = CoordinatorServer(2, deadline=0.5)
    try:
        a, b = _clients(srv)
        # Identical tags meet at one epoch: both pass.
        errs = [None, None]

        def run(i, cl):
            try:
                cl.barrier("resume:abc123")
            except Exception as e:  # noqa: BLE001
                errs[i] = e

        ts = [threading.Thread(target=run, args=(i, c), daemon=True)
              for i, c in enumerate((a, b))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert errs == [None, None]
        # Divergent tags land on different epochs -> both rounds expire
        # -> both ranks raise CoordinatedAbort instead of one proceeding
        # alone on a forked view.
        def run2(i, cl, tag):
            try:
                cl.barrier(tag)
            except Exception as e:  # noqa: BLE001
                errs[i] = e

        ts = [
            threading.Thread(target=run2, args=(0, a, "resume:abc"),
                             daemon=True),
            threading.Thread(target=run2, args=(1, b, "resume:DEF"),
                             daemon=True),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert all(isinstance(e, CoordinatedAbort) for e in errs), errs
    finally:
        srv.close()


# -------------------------------------------------------- deadline expiry


def test_deadline_expiry_aborts_the_present_rank():
    """A peer that never arrives (dead or wedged) must not hold the
    fleet: the round resolves ABORT at the deadline, and the waiting
    rank gets the answer within ~the deadline, not a hang."""
    srv = CoordinatorServer(2, deadline=0.3)
    try:
        (a,) = _clients(srv, n=1)
        t0 = time.monotonic()
        decision = a.propose("fwd:L9", OK)
        elapsed = time.monotonic() - t0
        assert decision == ABORT
        assert elapsed < 5.0  # resolved by the sweep, not the socket belt
    finally:
        srv.close()


def test_late_joiner_of_timed_out_round_aborts():
    """The laggard shows up after its peers gave up: it must abort too
    (reason 'late'), not proceed alone on a resolved-by-timeout round."""
    srv = CoordinatorServer(2, deadline=0.2)
    try:
        a, b = _clients(srv)
        assert a.propose("fwd:L1", OK) == ABORT  # round timed out
        # b's seq advances to the SAME epoch key; raw wire so the reason
        # is visible (propose() only returns the decision).
        b.seq += 1
        with socket.create_connection((srv.host, srv.port), timeout=5) as c:
            c.sendall((json.dumps({
                "op": "propose", "epoch": f"{b.seq}:fwd:L1", "rank": 1,
                "verdict": OK,
            }) + "\n").encode())
            reply = json.loads(c.makefile().readline())
        assert reply == {"decision": ABORT, "reason": "late"}
    finally:
        srv.close()


def test_late_joiner_of_consensus_round_gets_recorded_decision():
    """A rank that arrives AFTER a round resolved by full consensus gets
    the recorded decision — it was merely slow to ask, not absent."""
    srv = CoordinatorServer(1, deadline=5.0)  # world 1: instant rounds
    try:
        (a,) = _clients(srv, n=1)
        assert a.propose("fwd:L1", RETRY) == RETRY
        with socket.create_connection((srv.host, srv.port), timeout=5) as c:
            c.sendall((json.dumps({
                "op": "propose", "epoch": "1:fwd:L1", "rank": 0,
                "verdict": OK,
            }) + "\n").encode())
            reply = json.loads(c.makefile().readline())
        assert reply == {"decision": RETRY, "reason": "consensus"}
    finally:
        srv.close()


# ------------------------------------------------------- coordinator death


def test_coordinator_death_raises_within_deadline():
    """close() while participants are parked in a round: every one of
    them raises CoordinationError promptly (EOF on the round socket) —
    the failure mode is an error, never a hang."""
    srv = CoordinatorServer(3, deadline=30.0)
    clients = _clients(srv, n=2, deadline=30.0)
    errs = [None, None]

    def run(i):
        try:
            clients[i].propose("fwd:L2", OK)
        except Exception as e:  # noqa: BLE001
            errs[i] = e

    ts = [threading.Thread(target=run, args=(i,), daemon=True)
          for i in range(2)]
    for t in ts:
        t.start()
    time.sleep(0.3)  # both proposals parked (world is 3, only 2 arrive)
    t0 = time.monotonic()
    srv.close()
    for t in ts:
        t.join(timeout=10)
    assert time.monotonic() - t0 < 10
    assert all(isinstance(e, CoordinationError) for e in errs), errs


def test_dead_address_raises_not_hangs():
    with socket.socket() as s:  # a port nothing listens on
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cl = EpochBarrier(f"127.0.0.1:{port}", 0, deadline=1.0,
                      connect_timeout=0.5)
    t0 = time.monotonic()
    with pytest.raises(CoordinationError):
        cl.propose("fwd:L1", OK)
    assert time.monotonic() - t0 < 5


def test_junk_reply_is_an_error():
    """A coordinator replying garbage must not be interpreted as a
    decision."""
    srv_sock = socket.socket()
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.listen(1)
    port = srv_sock.getsockname()[1]

    def bad_server():
        conn, _ = srv_sock.accept()
        conn.recv(4096)
        conn.sendall(b'{"decision": "frobnicate"}\n')
        conn.close()

    t = threading.Thread(target=bad_server, daemon=True)
    t.start()
    try:
        cl = EpochBarrier(f"127.0.0.1:{port}", 0, deadline=2.0)
        with pytest.raises(CoordinationError):
            cl.propose("fwd:L1", OK)
    finally:
        srv_sock.close()


# --------------------------------------------------- fault points & env


def test_fault_points_fire_in_client_paths(server):
    """coord.handshake fires on dial, coord.barrier on every proposal —
    the distributed chaos matrix (tests/test_resilience.py) arms these."""
    (a,) = _clients(server, n=1)
    faults.configure("coord.handshake:transient:1")
    with pytest.raises(faults.TransientFault):
        a.propose("fwd:L1", OK)
    faults.clear()
    faults.configure("coord.barrier:fatal:1")
    with pytest.raises(faults.FatalFault):
        a.propose("fwd:L1", OK)


def test_coordination_from_env(monkeypatch):
    # Unconfigured or single-process: no handle — rank-local retry.
    monkeypatch.delenv("GAMESMAN_COORD_ADDR", raising=False)
    assert coordination_from_env(0, 2) is None
    monkeypatch.setenv("GAMESMAN_COORD_ADDR", "127.0.0.1:1")
    assert coordination_from_env(0, 1) is None
    # Rank 0 hosts the server at the configured port; peers dial it.
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("GAMESMAN_COORD_ADDR", f"127.0.0.1:{port}")
    monkeypatch.setenv("GAMESMAN_BARRIER_SECS", "7.5")
    c0 = coordination_from_env(0, 2)
    try:
        assert isinstance(c0, Coordination)
        assert c0.server is not None and c0.server.port == port
        assert c0.server.deadline == 7.5
        c1 = coordination_from_env(1, 2)
        assert c1.server is None and c1.client.rank == 1
        decisions, errs = _propose_all([c0, c1], "boot", [OK, OK])
        assert decisions == [OK, OK] and errs == [None, None]
    finally:
        c0.close()
        c0.close()  # idempotent
