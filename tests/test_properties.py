"""Property tests (SURVEY.md §4.2 axis 3, via hypothesis).

Laws that hold independent of any game: the value algebra (negate is an
involution; WIN iff some LOSE child), hash-partition totality (every state
owned by exactly one shard, identically on host and device), codec
round-trips, and dedup/lookup invariants.
"""

import numpy as np
import pytest

import jax.numpy as jnp

try:
    from hypothesis import given, settings, strategies as st
except ModuleNotFoundError:
    # This image has no hypothesis and cannot pip install; the laws
    # still run (deterministically) through the mini shim instead of
    # dying as a tier-1 collection error. See tests/_mini_hypothesis.py.
    from _mini_hypothesis import given, settings, st

from gamesmanmpi_tpu.core.bitops import SENTINEL32, SENTINEL64, sentinel_for
from gamesmanmpi_tpu.core.codec import pack_cells, unpack_cells
from gamesmanmpi_tpu.core.hashing import owner_shard, owner_shard_np
from gamesmanmpi_tpu.core.values import (
    LOSE,
    MAX_REMOTENESS,
    TIE,
    UNDECIDED,
    WIN,
    negate_np,
)
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.dedup import sort_unique
from gamesmanmpi_tpu.solve.oracle import combine_host

# Smoke tier: fast, compile-light, single-process-safe (see pyproject).
pytestmark = pytest.mark.smoke

VALUES = st.sampled_from([WIN, LOSE, TIE])
_SETTINGS = dict(max_examples=50, deadline=None)


@given(v=st.sampled_from([WIN, LOSE, TIE, UNDECIDED]))
@settings(**_SETTINGS)
def test_negate_involution(v):
    assert negate_np(negate_np(np.uint8(v))) == v


@given(
    children=st.lists(
        st.tuples(VALUES, st.integers(0, 1000)), min_size=1, max_size=16
    )
)
@settings(**_SETTINGS)
def test_combine_laws_host_vs_device(children):
    """The jnp combine kernel agrees with the host oracle combine, and both
    satisfy the negamax laws."""
    value, rem = combine_host(children)
    vals = [v for v, _ in children]
    if LOSE in vals:
        assert value == WIN
        assert rem == 1 + min(r for v, r in children if v == LOSE)
    elif TIE in vals:
        assert value == TIE
        assert rem == 1 + max(r for v, r in children if v == TIE)
    else:
        assert value == LOSE
        assert rem == 1 + max(r for _, r in children)
    M = len(children)
    cv = jnp.asarray(np.array([[v for v, _ in children]], np.uint8))
    cr = jnp.asarray(np.array([[r for _, r in children]], np.int32))
    mask = jnp.ones((1, M), bool)
    dv, dr = combine_children(cv, cr, mask)
    assert (int(dv[0]), int(dr[0])) == (value, rem)


@given(
    values=st.lists(st.sampled_from([WIN, LOSE, TIE, UNDECIDED]), min_size=1,
                    max_size=64),
    rems=st.data(),
)
@settings(**_SETTINGS)
def test_codec_roundtrip(values, rems):
    n = len(values)
    remoteness = np.array(
        [rems.draw(st.integers(0, MAX_REMOTENESS)) for _ in range(n)],
        np.int32,
    )
    v = jnp.asarray(np.array(values, np.uint8))
    r = jnp.asarray(remoteness)
    v2, r2 = unpack_cells(pack_cells(v, r))
    assert (np.asarray(v2) == values).all()
    assert (np.asarray(r2) == remoteness).all()


@given(
    states=st.lists(st.integers(0, 2**63 - 1), min_size=1, max_size=256),
    shards=st.integers(1, 16),
)
@settings(**_SETTINGS)
def test_owner_partition_total_and_consistent(states, shards):
    arr = np.array(states, np.uint64)
    host = owner_shard_np(arr, shards)
    dev = np.asarray(owner_shard(jnp.asarray(arr), shards))
    assert (host == dev).all()
    assert ((host >= 0) & (host < shards)).all()


@given(
    states=st.lists(st.integers(0, 2**31 - 2), min_size=1, max_size=128),
    dtype=st.sampled_from([np.uint32, np.uint64]),
)
@settings(**_SETTINGS)
def test_sort_unique_matches_numpy(states, dtype):
    arr = np.array(states, dtype)
    sentinel = sentinel_for(dtype)
    padded = np.concatenate([arr, np.full(7, sentinel, dtype)])
    out, count = sort_unique(jnp.asarray(padded))
    expect = np.unique(arr)
    assert int(count) == expect.shape[0]
    assert (np.asarray(out[: expect.shape[0]]) == expect).all()
    assert (np.asarray(out[expect.shape[0]:]) == sentinel).all()


def test_owner_u32_matches_u64_widening():
    """uint32 states must route to the same owner as their uint64 widening
    (the sharded path may see either dtype for the same logical state)."""
    rng = np.random.default_rng(1)
    s32 = rng.integers(0, 2**31, 1000, dtype=np.uint32)
    for shards in (2, 8, 13):
        a = owner_shard_np(s32, shards)
        b = owner_shard_np(s32.astype(np.uint64), shards)
        assert (a == b).all()
        dev = np.asarray(owner_shard(jnp.asarray(s32), shards))
        assert (dev == a).all()


def test_sentinels_sort_last():
    assert SENTINEL64 == np.iinfo(np.uint64).max
    assert SENTINEL32 == np.iinfo(np.uint32).max


# ------------------------------------------------------------------ canonicalize
# The contract games/base.py documents for overrides: canonicalize must be
# a game-automorphism projection. Checked for every registered game shape
# (sym on and off) AND every committed GameSpec compiled by gamedsl — the
# compiler derives its symmetry permutations from generators, so this is
# the law that keeps `sym=1` tables equal to unsymmetrized ones.

from helpers import REPO as _REPO  # noqa: E402
from gamesmanmpi_tpu.games import get_game as _get_game  # noqa: E402

_CANON_SPECS = [
    "tictactoe",
    "tictactoe:sym=1",
    "connect4:w=4,h=3",
    "connect4:w=4,h=3,sym=1",
    "nim:heaps=3-4-5",
    "subtract:total=10,moves=1-2",
    "chomp:w=3,h=3,sym=1",
] + sorted(
    str(p) for p in (_REPO / "examples" / "specs").glob("*.json")
)
_canon_games = {}


def _canon_game(spec):
    if spec not in _canon_games:
        _canon_games[spec] = _get_game(spec)
    return _canon_games[spec]


def _canon_child_multisets(game, states):
    """Per state: the sorted multiset of canonicalized legal children."""
    kids, mask = game.expand(jnp.asarray(states))
    canon = np.asarray(
        game.canonicalize(kids.reshape(-1)).reshape(kids.shape)
    )
    mask = np.asarray(mask)
    return [
        tuple(sorted(int(c) for c in canon[b][mask[b]]))
        for b in range(canon.shape[0])
    ]


@pytest.mark.parametrize(
    "spec",
    _CANON_SPECS,
    ids=[
        "spec-" + s.rsplit("/", 1)[-1].removesuffix(".json")
        if s.endswith(".json") else s
        for s in _CANON_SPECS
    ],
)
@given(seed=st.integers(0, 2**16 - 1))
@settings(max_examples=2, deadline=None)
def test_canonicalize_is_automorphism_projection(spec, seed):
    """Random-walk reachable states; canonicalize must be idempotent,
    preserve level and primitive value, and project child multisets:
    the canonical children of s equal the canonical children of
    canonicalize(s) — the exact law symmetry-reduced solves rely on."""
    game = _canon_game(spec)
    rng = np.random.default_rng(seed)
    frontier = np.asarray([game.initial_state()], dtype=game.state_dtype)
    seen = [frontier]
    for _ in range(5):
        prim = np.asarray(game.primitive(jnp.asarray(frontier)))
        frontier = frontier[prim == UNDECIDED]
        if frontier.size == 0:
            break
        kids, mask = game.expand(jnp.asarray(frontier))
        legal = np.unique(np.asarray(kids)[np.asarray(mask)])
        if legal.size == 0:
            break
        frontier = rng.choice(
            legal, size=min(legal.size, 8), replace=False
        ).astype(game.state_dtype)
        seen.append(frontier)
    states = np.unique(np.concatenate(seen)).astype(game.state_dtype)

    canon = np.asarray(game.canonicalize(jnp.asarray(states)))
    # Projection: applying twice changes nothing.
    assert (
        np.asarray(game.canonicalize(jnp.asarray(canon))) == canon
    ).all()
    # Class invariants: level and primitive value are symmetry-blind.
    assert (
        np.asarray(game.level_of(jnp.asarray(canon)))
        == np.asarray(game.level_of(jnp.asarray(states)))
    ).all()
    assert (
        np.asarray(game.primitive(jnp.asarray(canon)))
        == np.asarray(game.primitive(jnp.asarray(states)))
    ).all()
    # Automorphism projection: child classes match representative's.
    assert _canon_child_multisets(game, states) == _canon_child_multisets(
        game, canon
    )
