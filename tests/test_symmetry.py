"""Symmetry reduction (sym=1): observable equivalence + table shrink.

The reference has no symmetry reduction, so sym=1 must change nothing
observable — root value/remoteness and every queried position's answer —
while solving only class representatives (the Pentago/2507.05267-style
state-space reduction; SURVEY.md §7 capacity planning).
"""

import numpy as np
import pytest

import jax

from gamesmanmpi_tpu.core.values import TIE
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve import Solver

from helpers import full_table


def test_tictactoe_sym_root_and_canonical_count():
    plain = Solver(get_game("tictactoe"), paranoid=True).solve()
    sym = Solver(get_game("tictactoe:sym=1"), paranoid=True).solve()
    assert (sym.value, sym.remoteness) == (plain.value, plain.remoteness) == (TIE, 9)
    # 765 essentially-different positions is the classic 3x3 count.
    assert sym.num_positions == 765
    assert plain.num_positions == 5478


def test_tictactoe_sym_answers_match_plain_for_every_position():
    plain = Solver(get_game("tictactoe")).solve()
    sym = Solver(get_game("tictactoe:sym=1")).solve()
    # Every reachable raw position must answer identically through the
    # canonicalizing lookup.
    for pos, expected in full_table(plain).items():
        assert sym.lookup(pos) == expected


def test_connect4_sym_root_and_shrink():
    plain = Solver(get_game("connect4:w=4,h=4")).solve()
    sym = Solver(get_game("connect4:w=4,h=4,sym=1")).solve()
    assert (sym.value, sym.remoteness) == (plain.value, plain.remoteness)
    # Mirror symmetry roughly halves the table (self-symmetric states less).
    assert sym.num_positions < 0.6 * plain.num_positions
    # Spot-check: mirrored sibling positions answer identically.
    rng = np.random.default_rng(0)
    states = plain.levels[max(plain.levels)].states
    for pos in rng.choice(states, size=min(50, len(states)), replace=False):
        assert sym.lookup(pos) == plain.lookup(pos)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 (fake) devices")
def test_sharded_sym_invariance():
    from gamesmanmpi_tpu.parallel import ShardedSolver

    single = Solver(get_game("tictactoe:sym=1"), paranoid=True).solve()
    sharded = ShardedSolver(
        get_game("tictactoe:sym=1"), num_shards=4, paranoid=True
    ).solve()
    assert (sharded.value, sharded.remoteness) == (single.value, single.remoteness)
    assert sharded.num_positions == single.num_positions == 765
    assert full_table(sharded) == full_table(single)


def test_chomp_sym_transpose_square():
    plain = Solver(get_game("chomp:w=3,h=3")).solve()
    sym = Solver(get_game("chomp:w=3,h=3,sym=1"), paranoid=True).solve()
    assert (sym.value, sym.remoteness) == (plain.value, plain.remoteness)
    assert sym.num_positions < plain.num_positions
    for pos, expected in full_table(plain).items():
        assert sym.lookup(pos) == expected


def test_chomp_sym_rejects_non_square():
    with pytest.raises(ValueError, match="square"):
        get_game("chomp:w=4,h=3,sym=1")
