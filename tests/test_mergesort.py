"""Merge-ladder sort (ops/mergesort.py): equivalence with XLA's sort.

The merge backend is a perf candidate for the sort-bound engines
(GAMESMAN_SORT=merge); these tests pin its contract — same sorted keys,
key-aligned payloads, sentinel padding, non-power-of-two lengths — and
that the engines' dedup produces identical frontiers under either backend.
"""

import numpy as np
import pytest

from gamesmanmpi_tpu.ops.mergesort import merge_sort


@pytest.mark.parametrize("n", [1, 2, 7, 128, 1000, 4096, 10_000])
@pytest.mark.parametrize("dtype", [np.uint32, np.uint64])
def test_merge_sort_matches_numpy(n, dtype):
    rng = np.random.default_rng(n)
    x = rng.integers(0, 1 << 30, size=n, dtype=dtype)
    got = np.asarray(merge_sort(x))
    np.testing.assert_array_equal(got, np.sort(x))


def test_merge_sort_with_payload_alignment():
    rng = np.random.default_rng(5)
    n = 3000
    # Duplicate-heavy keys: payload must travel with SOME instance of its
    # key (stability is explicitly not promised).
    k = rng.integers(0, 64, size=n, dtype=np.uint32)
    v = np.arange(n, dtype=np.int32)
    ks, vs = merge_sort(k, v)
    ks, vs = np.asarray(ks), np.asarray(vs)
    np.testing.assert_array_equal(ks, np.sort(k))
    # Every (key, payload) pair in the output existed in the input.
    assert set(zip(ks.tolist(), vs.tolist())) == set(
        zip(k.tolist(), v.tolist())
    )


def test_merge_sort_payload_padding_never_displaces_real_pairs():
    # Non-power-of-two length + real sentinel keys carrying meaningful
    # payloads: internal padding is (sentinel, MAX payload) and must sort
    # strictly after every real pair, else truncation drops real origins
    # (this is the exact configuration expand_provenance hits on 5- and
    # 7-column boards under GAMESMAN_SORT=merge).
    sentinel = np.uint32(0xFFFFFFFF)
    n = 5 * 1024  # not a power of two
    rng = np.random.default_rng(9)
    k = rng.integers(0, 100, size=n, dtype=np.uint32)
    k[rng.choice(n, size=n // 3, replace=False)] = sentinel
    v = np.arange(n, dtype=np.int32)
    ks, vs = (np.asarray(a) for a in merge_sort(k, v))
    assert ks.shape == (n,)
    np.testing.assert_array_equal(ks, np.sort(k))
    # Every real pair survived: the payload multiset is exactly 0..n-1.
    np.testing.assert_array_equal(np.sort(vs), v)


def test_merge_sort_keeps_sentinels_last():
    sentinel = np.uint32(0xFFFFFFFF)
    x = np.array([5, sentinel, 3, sentinel, 9], dtype=np.uint32)
    got = np.asarray(merge_sort(x))
    np.testing.assert_array_equal(got, [3, 5, 9, sentinel, sentinel])


def test_sort_unique_same_under_both_backends(monkeypatch):
    from gamesmanmpi_tpu.ops import dedup

    rng = np.random.default_rng(11)
    x = rng.integers(0, 1 << 16, size=5000, dtype=np.uint32)
    x[::7] = 0xFFFFFFFF  # sentinel padding mixed in
    base_out, base_n = (np.asarray(a) for a in dedup.sort_unique(x))
    monkeypatch.setenv("GAMESMAN_SORT", "merge")
    m_out, m_n = (np.asarray(a) for a in dedup.sort_unique(x))
    np.testing.assert_array_equal(m_out, base_out)
    assert int(m_n) == int(base_n)


def test_classic_solve_matches_under_merge_backend(monkeypatch):
    # Whole-engine equivalence: the same board solved with each sort
    # backend must produce identical tables. get_kernel keys on the flag,
    # so the second solve really traces merge-backend kernels instead of
    # reusing the cached XLA-backend ones.
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.solve import Solver

    g = get_game("connect4:w=4,h=3")
    monkeypatch.delenv("GAMESMAN_SORT", raising=False)  # base = XLA for real
    base = Solver(g).solve()
    monkeypatch.setenv("GAMESMAN_SORT", "merge")
    merged = Solver(g).solve()
    assert (merged.value, merged.remoteness, merged.num_positions) == (
        base.value, base.remoteness, base.num_positions
    )
    for L, tab in base.levels.items():
        np.testing.assert_array_equal(merged.levels[L].states, tab.states)
        np.testing.assert_array_equal(merged.levels[L].values, tab.values)
        np.testing.assert_array_equal(
            merged.levels[L].remoteness, tab.remoteness
        )


def test_sharded_solve_matches_under_merge_backend(monkeypatch):
    # The sharded solver's local dedup goes through the same dispatch;
    # 4-shard solve under the merge backend must agree with single-device.
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices (fake CPU mesh)")
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.solve import Solver

    g = get_game("connect4:w=4,h=3")
    monkeypatch.delenv("GAMESMAN_SORT", raising=False)  # base = XLA for real
    base = Solver(g).solve()
    monkeypatch.setenv("GAMESMAN_SORT", "merge")
    sharded = ShardedSolver(g, num_shards=4).solve()
    assert (sharded.value, sharded.remoteness, sharded.num_positions) == (
        base.value, base.remoteness, base.num_positions
    )


def test_expand_provenance_same_under_both_backends(monkeypatch):
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.solve.engine import expand_provenance

    # 5 columns: flat children arrays have non-power-of-two length, so the
    # merge backend's internal padding path is exercised (a 4-column board
    # would make every length a power of two and miss it).
    g = get_game("connect4:w=5,h=4")
    # A real frontier: expand the initial position twice, then compare the
    # provenance outputs under both sort backends on the level-1 states.
    # uidx is backend-independent even with duplicate children: unstable
    # sorts may permute duplicate instances, but every instance of a run
    # shares the survivor's unique-index.
    states = np.array([g.initial_state()], dtype=g.state_dtype)
    import jax.numpy as jnp

    uniq, count, uidx, prim = (
        np.asarray(a) for a in expand_provenance(g, jnp.asarray(states))
    )
    lvl1 = uniq[: int(count)]
    base = [np.asarray(a)
            for a in expand_provenance(g, jnp.asarray(lvl1))]
    monkeypatch.setenv("GAMESMAN_SORT", "merge")
    merged = [np.asarray(a)
              for a in expand_provenance(g, jnp.asarray(lvl1))]
    for b, m in zip(base, merged):
        np.testing.assert_array_equal(b, m)