"""Fleet serving: supervisor/worker lifecycle over shared-socket DBs.

Acceptance axes (ISSUE 7):

* fork-after-open sharing — a CLI fleet whose supervisor opened every
  DbReader BEFORE forking answers oracle-exact from every worker
  (the mmap pages are the parent's, shared through the page cache);
* supervised lifecycle — a SIGKILLed worker is detected (pipe EOF),
  restarted with backoff, and re-verifies (check_db gate + self-probe)
  before rejoining the ready set; a crash-looping worker opens the
  restart-storm breaker instead of burning CPU; a mute worker is
  treated as hung and killed;
* rolling reload — POST /reload drains ONE worker at a time onto a
  re-read fleet manifest with zero failed requests; a junk manifest
  fails the reload and leaves the fleet serving untouched;
* drain correctness — QueryServer.stop() wakes handler threads parked
  in recv on idle keep-alive connections instead of waiting out their
  socket timeout (the server.py:414 accounting fix).

State-machine tests run against scripted fake workers
(helpers.FAKE_FLEET_WORKER — no jax, milliseconds); the end-to-end
tests run real workers through the CLI (fork mode) and in-process
supervisor (exec mode).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from gamesmanmpi_tpu.core.values import value_name
from gamesmanmpi_tpu.db import DbReader, export_result
from gamesmanmpi_tpu.db.check import DbFormatError, verify_for_serving
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.serve import (
    FleetEntry,
    QueryServer,
    ServeSupervisor,
    load_fleet_manifest,
    single_db_entries,
)
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.oracle import oracle_solve
from gamesmanmpi_tpu.utils.env import env_bool

from helpers import REF_GAMES, REPO, fake_fleet_spawn, load_module

_CLI = [sys.executable, "-m", "gamesmanmpi_tpu.cli"]


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _wait_for(pred, timeout=30.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def sub_db(tmp_path_factory):
    """Tiny subtract-game DB: the fleet tests' cheap routed artifact."""
    spec = "subtract:total=10,moves=1-2"
    d = tmp_path_factory.mktemp("fleetdb") / "sub"
    export_result(Solver(get_game(spec)).solve(), d, spec)
    return d


@pytest.fixture(scope="module")
def nim_db(tmp_path_factory):
    """nim_345 DB + oracle: the fork-mode oracle-exactness pair."""
    spec = "nim:heaps=3-4-5"
    d = tmp_path_factory.mktemp("fleetnim") / "nim"
    export_result(Solver(get_game(spec)).solve(), d, spec)
    _, _, oracle = oracle_solve(load_module(REF_GAMES / "nim_345.py"))
    return d, oracle


# ------------------------------------------------------- manifest / gates


def test_fleet_manifest_parses_and_resolves_relative(tmp_path, sub_db):
    mdir = tmp_path / "fleet"
    mdir.mkdir()
    (mdir / "dbs").mkdir()
    (mdir / "dbs" / "sub").symlink_to(sub_db)
    manifest = mdir / "fleet.json"
    manifest.write_text(json.dumps({
        "version": 1,
        "games": [{"name": "sub", "db": "dbs/sub"},
                  {"name": "abs", "db": str(sub_db)}],
    }))
    entries = load_fleet_manifest(manifest)
    assert [e.name for e in entries] == ["sub", "abs"]
    # Relative paths resolve against the manifest's own directory.
    assert entries[0].db == str(mdir / "dbs" / "sub")
    assert entries[1].db == str(sub_db)


@pytest.mark.parametrize("doc, why", [
    ("not json {", "junk"),
    ({"version": 2, "games": [{"name": "a", "db": "."}]}, "version"),
    ({"version": 1, "games": []}, "empty"),
    ({"version": 1, "games": [{"name": "a"}]}, "missing db"),
    ({"version": 1, "games": [{"name": "a/b", "db": "."}]}, "bad token"),
    ({"version": 1, "games": [{"name": "a", "db": "."},
                              {"name": "a", "db": "."}]}, "duplicate"),
    ({"version": 1, "games": [{"name": "a", "db": "nope"}]}, "no dir"),
])
def test_fleet_manifest_rejects_junk(tmp_path, doc, why):
    path = tmp_path / "fleet.json"
    path.write_text(doc if isinstance(doc, str) else json.dumps(doc))
    with pytest.raises(ValueError):
        load_fleet_manifest(path)


def test_verify_for_serving_gate(tmp_path, sub_db, monkeypatch):
    """The warm-start gate: clean DB verifies True, rot raises, and
    GAMESMAN_SERVE_VERIFY=0 skips (returning False, not True)."""
    assert verify_for_serving(sub_db) is True
    monkeypatch.setenv("GAMESMAN_SERVE_VERIFY", "0")
    assert verify_for_serving(sub_db) is False
    monkeypatch.setenv("GAMESMAN_SERVE_VERIFY", "junk")
    with pytest.warns(UserWarning):
        assert verify_for_serving(sub_db) is True  # warn-and-default
    monkeypatch.delenv("GAMESMAN_SERVE_VERIFY")
    import shutil

    rotted = tmp_path / "rot"
    shutil.copytree(sub_db, rotted)
    victim = next(rotted.glob("level_*.cells.npy"))
    data = bytearray(victim.read_bytes())
    data[-1] ^= 0xFF
    victim.write_bytes(bytes(data))
    with pytest.raises(DbFormatError):
        verify_for_serving(rotted)


def test_env_bool_contract(monkeypatch):
    for raw, want in [("0", False), ("off", False), ("FALSE", False),
                      ("no", False), ("1", True), ("on", True),
                      ("True", True), ("yes", True)]:
        monkeypatch.setenv("X_FLEET_FLAG", raw)
        assert env_bool("X_FLEET_FLAG", not want) is want, raw
    monkeypatch.delenv("X_FLEET_FLAG")
    assert env_bool("X_FLEET_FLAG", True) is True
    assert env_bool("X_FLEET_FLAG", False) is False


# ------------------------------------------------------ multi-DB routing


def test_query_server_routes_fleet(sub_db, nim_db):
    """One QueryServer, two DBs: /query/<name> routes per game, each
    route has its own batcher/breaker, /healthz carries the fleet map,
    and the bare /query 404s (two games -> no default route)."""
    nim_dir, oracle = nim_db
    with DbReader(sub_db) as sub_reader, DbReader(nim_dir) as nim_reader:
        with QueryServer(
            readers={"sub": sub_reader, "nim": nim_reader}
        ) as server:
            base = f"http://127.0.0.1:{server.port}"
            pos = sorted(oracle)[0]
            status, body = _post(base + "/query/nim",
                                 {"positions": [hex(pos)]})
            assert status == 200
            v, r = oracle[pos]
            rec = body["results"][0]
            assert (rec["value"], rec["remoteness"]) == (value_name(v), r)
            status, body = _post(base + "/query/sub", {"positions": [10]})
            assert status == 200
            assert body["results"][0]["found"]
            # Unknown names and the bare route list what IS routable.
            try:
                _post(base + "/query/nope", {"positions": [1]})
                raise AssertionError("unknown game did not 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
                assert json.loads(e.read())["games"] == ["nim", "sub"]
            try:
                _post(base + "/query", {"positions": [1]})
                raise AssertionError("bare /query did not 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
            _, health = _get(base + "/healthz")
            assert health["status"] == "ok"
            assert set(health["games"]) == {"nim", "sub"}
            assert health["games"]["nim"]["breaker"] == "ok"
            _, metrics = _get(base + "/metrics.json")
            assert set(metrics["games"]) == {"nim", "sub"}
            assert metrics["games"]["nim"]["batches"] >= 1
            # One-game fleets keep the bare /query default route.
            server.self_probe()  # also the worker warm-start path


def test_single_game_fleet_keeps_default_route(sub_db):
    with DbReader(sub_db) as reader:
        with QueryServer(readers={"sub": reader}) as server:
            base = f"http://127.0.0.1:{server.port}"
            status, body = _post(base + "/query", {"positions": [10]})
            assert status == 200
            status, body = _post(base + "/query/sub", {"positions": [10]})
            assert status == 200
            # Legacy flat identity fields survive for one-game servers.
            _, health = _get(base + "/healthz")
            assert health["game"] == reader.game.name
            assert health["positions"] == reader.num_positions


def test_stop_wakes_idle_keepalive_connections(sub_db):
    """The server.py:414 fix: an idle keep-alive connection parked in
    recv must not pin stop() until its 30 s socket timeout — the drain
    shuts idle connections down and returns promptly."""
    with DbReader(sub_db) as reader:
        server = QueryServer(reader)
        server.start()
        port = server.port
        conn = socket.create_connection(("127.0.0.1", port), timeout=10)
        body = json.dumps({"positions": [10]}).encode()
        conn.sendall(
            b"POST /query HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n"
            + body
        )
        first = conn.recv(65536)
        assert first.startswith(b"HTTP/1.1 200")
        # The connection is now IDLE keep-alive: its handler thread sits
        # in a blocking read for a next request that never comes.
        t0 = time.monotonic()
        server.stop()
        stop_secs = time.monotonic() - t0
        assert stop_secs < 4.0, (
            f"stop() took {stop_secs:.1f}s — idle keep-alive connections "
            "were not woken"
        )
        # The client sees a clean close (EOF), not a mid-response cut.
        conn.settimeout(5)
        rest = b"x"
        while rest:
            rest = conn.recv(65536)
        conn.close()


# ---------------------------------------- supervisor state machine (fakes)


def _fake_supervisor(sub_db, modes, **kw):
    kw.setdefault("workers", len(modes))
    kw.setdefault("control_port", None)
    kw.setdefault("heartbeat_secs", 0.05)
    kw.setdefault("heartbeat_timeout", 0.6)
    kw.setdefault("restart_base", 0.01)
    kw.setdefault("restart_max", 0.05)
    kw.setdefault("drain_grace", 5.0)
    return ServeSupervisor(
        single_db_entries(sub_db),
        spawn=fake_fleet_spawn(lambda i: modes[i]),
        **kw,
    )


def test_supervisor_restarts_killed_worker(sub_db):
    sup = _fake_supervisor(sub_db, ["ok", "ok"]).start()
    try:
        st = _wait_for(
            lambda: (s := sup.status())["status"] == "ok" and s,
            what="fleet ready",
        )
        victim = st["workers"]["0"]["pid"]
        os.kill(victim, signal.SIGKILL)
        _wait_for(
            lambda: (s := sup.status())["workers"]["0"]["restarts"] >= 1
            and s["workers"]["0"]["state"] == "ready"
            and s["workers"]["0"]["pid"] != victim,
            what="worker restarted after SIGKILL",
        )
        # The replacement re-reported its warm-start verification: a
        # restarted worker rejoins only through the verify gate.
        assert sup.status()["workers"]["0"]["verified"] == {"default": True}
    finally:
        sup.stop()
    assert all(w["state"] == "stopped"
               for w in sup.status()["workers"].values())


def test_supervisor_storm_breaker_opens_on_crash_loop(sub_db):
    """A slot that dies at every spawn trips the restart-storm breaker
    ('broken', breaker 'open') instead of restarting forever; the
    healthy worker keeps the fleet degraded-but-up."""
    sup = _fake_supervisor(
        sub_db, ["crash", "ok"], storm_restarts=3, storm_secs=60.0,
    ).start()
    try:
        st = _wait_for(
            lambda: (s := sup.status())["workers"]["0"]["breaker"] == "open"
            and s,
            what="storm breaker open",
        )
        assert st["workers"]["0"]["state"] == "broken"
        assert st["workers"]["0"]["restarts"] >= 3
        _wait_for(
            lambda: sup.status()["workers"]["1"]["state"] == "ready",
            what="healthy worker ready",
        )
        assert sup.status()["status"] == "degraded"
    finally:
        sup.stop()


def test_supervisor_kills_mute_worker_as_hung(sub_db):
    """A worker whose beats stop (but whose process lives) is hung: the
    liveness deadline SIGKILLs it into an ordinary restart."""
    sup = _fake_supervisor(sub_db, ["mute"]).start()
    try:
        st = _wait_for(
            lambda: (s := sup.status())["status"] == "ok" and s,
            what="fleet ready",
        )
        _wait_for(
            lambda: sup.status()["workers"]["0"]["restarts"] >= 1,
            what="hung worker restarted",
        )
    finally:
        sup.stop()


def test_supervisor_rolling_reload_and_failed_reload(tmp_path, sub_db):
    """A manifest reload rolls one worker at a time onto the new
    generation; a junk manifest fails the reload and leaves the running
    fleet untouched."""
    manifest = tmp_path / "fleet.json"
    manifest.write_text(json.dumps({
        "version": 1, "games": [{"name": "sub", "db": str(sub_db)}],
    }))
    sup = _fake_supervisor(
        sub_db, ["ok", "ok"], manifest_path=manifest,
    ).start()
    try:
        _wait_for(lambda: sup.status()["status"] == "ok",
                  what="fleet ready")
        pids = {w["pid"] for w in sup.status()["workers"].values()}
        sup.request_reload()
        st = _wait_for(
            lambda: (s := sup.status())["reloads_done"] == 1
            and s["status"] == "ok" and s,
            what="rolling reload done",
        )
        assert st["gen"] == 1
        assert all(w["gen"] == 1 for w in st["workers"].values())
        # Every worker was replaced (drained + respawned), none dropped:
        # a rolled worker exits 0, so restarts (death counter) stays 0.
        new_pids = {w["pid"] for w in st["workers"].values()}
        assert not (pids & new_pids)
        assert all(w["restarts"] == 0 for w in st["workers"].values())
        # Now rot the manifest: the reload must fail CLOSED.
        manifest.write_text("{ not json")
        sup.request_reload()
        st = _wait_for(
            lambda: (s := sup.status())["last_reload_error"] and s,
            what="failed reload reported",
        )
        assert "fleet manifest" in st["last_reload_error"]
        assert st["gen"] == 1  # nothing rolled
        assert st["status"] == "ok"
        assert st["reloads_done"] == 1
    finally:
        sup.stop()


# -------------------------------------------------- end-to-end (real CLI)


def test_cli_fleet_forks_after_open_and_survives_worker_kill(
        nim_db, tmp_path):
    """The ISSUE 7 chaos gate, tier-1 sized: a 2-worker CLI fleet (fork
    mode — the supervisor opened the DbReader BEFORE forking, so the
    workers share its mmap pages) answers the whole nim_345 oracle
    exactly; under load-gen traffic a SIGKILLed worker drops at most
    its in-flight requests while the fleet keeps answering; the
    replacement re-verifies before rejoining; a rolling reload then
    completes with zero failed requests."""
    nim_dir, oracle = nim_db
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_SERVE_RESTART_BASE_SECS"] = "0.1"
    env.pop("GAMESMAN_FAULTS", None)
    proc = subprocess.Popen(
        _CLI + ["serve", str(nim_dir), "--port", "0", "--workers", "2",
                "--control-port", "0",
                "--jsonl", str(tmp_path / "serve.jsonl")],
        stdout=subprocess.PIPE, text=True, env=env, cwd=str(REPO),
    )
    try:
        banner = proc.stdout.readline()
        assert "serving fleet" in banner, banner
        port = int(banner.split("http://127.0.0.1:")[1].split(" ")[0])
        cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
        base, control = (f"http://127.0.0.1:{port}",
                         f"http://127.0.0.1:{cport}")
        st = _wait_for(
            lambda: (s := _get(control + "/healthz")[1])["status"] == "ok"
            and s,
            timeout=120, what="fleet ready",
        )
        # Fork mode: the whole point of opening readers in the parent.
        assert st["spawn_mode"] == "fork"
        assert all(w["verified"] == {"default": True}
                   for w in st["workers"].values())

        # Oracle-exactness through the shared socket (both workers
        # accept from one queue; every answer must agree with the
        # oracle no matter which worker served it).
        positions = sorted(oracle)
        for i in range(0, len(positions), 64):
            chunk = [hex(p) for p in positions[i:i + 64]]
            status, body = _post(base + "/query", {"positions": chunk})
            assert status == 200
            for q, rec in zip(chunk, body["results"]):
                v, r = oracle[int(q, 0)]
                assert (rec["found"], rec["value"], rec["remoteness"]) \
                    == (True, value_name(v), r), q

        # Chaos mid-load: drive the load harness and SIGKILL one ready
        # worker halfway through.
        pos_file = tmp_path / "positions.txt"
        pos_file.write_text("\n".join(hex(p) for p in positions))
        out_json = tmp_path / "load.json"
        conc = 4
        load = subprocess.Popen(
            [sys.executable, str(REPO / "tools" / "load_gen.py"), base,
             "--positions-file", str(pos_file), "--duration", "6",
             "--concurrency", str(conc), "--slo-p99-ms", "5000",
             "--max-dropped", str(conc), "--json", str(out_json)],
            stdout=subprocess.PIPE, text=True, cwd=str(REPO),
        )
        time.sleep(2.0)
        st = _get(control + "/healthz")[1]
        victim = next(w for w in st["workers"].values()
                      if w["state"] == "ready")
        os.kill(victim["pid"], signal.SIGKILL)
        assert load.wait(timeout=120) == 0, load.stdout.read()
        record = json.loads(out_json.read_text())
        assert record["ok"] > 0
        assert record["errors"] == 0
        assert record["mismatches"] == 0
        assert record["dropped"] <= conc

        # The killed slot restarted AND re-verified before rejoining.
        st = _wait_for(
            lambda: (s := _get(control + "/healthz")[1])["status"] == "ok"
            and all(w["state"] == "ready"
                    for w in s["workers"].values()) and s,
            timeout=60, what="killed worker restarted",
        )
        assert sum(w["restarts"] for w in st["workers"].values()) == 1
        assert all(w["verified"] == {"default": True}
                   for w in st["workers"].values())

        # Rolling reload with zero request failures: queries in one
        # thread, POST /reload in another, every query must answer.
        failures = []
        done = threading.Event()

        def _hammer():
            while not done.is_set():
                try:
                    status, body = _post(
                        base + "/query", {"positions": [hex(positions[0])]},
                        timeout=10,
                    )
                    if status != 200 or not body["results"][0]["found"]:
                        failures.append(body)
                except Exception as e:  # noqa: BLE001 - collected
                    failures.append(e)

        t = threading.Thread(target=_hammer)
        t.start()
        try:
            urllib.request.urlopen(urllib.request.Request(
                control + "/reload", method="POST", data=b""), timeout=10)
            _wait_for(
                lambda: (s := _get(control + "/healthz")[1])
                ["reloads_done"] >= 1 and s["status"] == "ok",
                timeout=120, what="rolling reload done",
            )
        finally:
            done.set()
            t.join(timeout=30)
        assert not failures, failures[:3]
        st = _get(control + "/healthz")[1]
        assert st["gen"] == 1

        # Supervisor /metrics speaks Prometheus and carries the fleet
        # series.
        with urllib.request.urlopen(control + "/metrics",
                                    timeout=10) as resp:
            text = resp.read().decode()
        assert "gamesman_serve_worker_restarts_total" in text
        assert "gamesman_serve_reloads_total" in text

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_supervisor_exec_mode_serves_and_recovers(sub_db, monkeypatch):
    """In-process supervisor in a jax-initialized parent: the fork path
    is forbidden (XLA runtime does not survive fork), so workers
    re-exec — and the lifecycle contract (ready via verify+self-probe,
    SIGKILL -> restart) holds identically."""
    # The re-exec'd worker runs this container's sitecustomize afresh
    # (axon-pinned); the env knob is how a subprocess gets the CPU pin.
    monkeypatch.setenv("GAMESMAN_PLATFORM", "cpu")
    sup = ServeSupervisor(
        single_db_entries(sub_db), workers=1, control_port=None,
        restart_base=0.1, heartbeat_secs=0.2, heartbeat_timeout=30.0,
    ).start()
    try:
        assert sup.status()["spawn_mode"] == "exec"
        st = _wait_for(
            lambda: (s := sup.status())["status"] == "ok" and s,
            timeout=180, what="exec worker ready",
        )
        assert st["workers"]["0"]["verified"] == {"default": True}
        base = f"http://127.0.0.1:{sup.port}"
        status, body = _post(base + "/query", {"positions": [10]})
        assert status == 200
        assert body["results"][0]["found"]
        os.kill(st["workers"]["0"]["pid"], signal.SIGKILL)
        _wait_for(
            lambda: (s := sup.status())["workers"]["0"]["restarts"] >= 1
            and s["status"] == "ok",
            timeout=180, what="exec worker restarted",
        )
        status, body = _post(base + "/query", {"positions": [10]})
        assert status == 200
    finally:
        sup.stop()


def _post_with_headers(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def test_cli_fleet_fork_mode_propagates_traceparent(sub_db, tmp_path):
    """ISSUE 17 e2e, fork mode: a client traceparent survives the
    shared-socket fleet — the answering worker echoes the trace id on
    the response header, keeps the trace in its ring (head sampling
    pinned to keep-everything via env, which fork workers inherit), and
    ships it on a heartbeat beat to the supervisor, where the control
    port serves it fleet-wide (GET /traces) stamped with the worker
    index."""
    from gamesmanmpi_tpu.obs.qtrace import (
        format_traceparent,
        mint_trace_ids,
        parse_traceparent,
    )

    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_SERVE_RESTART_BASE_SECS"] = "0.1"
    env["GAMESMAN_SERVE_HEARTBEAT_SECS"] = "0.2"
    env["GAMESMAN_TRACE_HEAD_N"] = "1"
    env.pop("GAMESMAN_FAULTS", None)
    proc = subprocess.Popen(
        _CLI + ["serve", str(sub_db), "--port", "0", "--workers", "2",
                "--control-port", "0"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=str(REPO),
    )
    try:
        banner = proc.stdout.readline()
        assert "serving fleet" in banner, banner
        port = int(banner.split("http://127.0.0.1:")[1].split(" ")[0])
        cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
        base, control = (f"http://127.0.0.1:{port}",
                         f"http://127.0.0.1:{cport}")
        st = _wait_for(
            lambda: (s := _get(control + "/healthz")[1])["status"] == "ok"
            and s,
            timeout=120, what="fleet ready",
        )
        assert st["spawn_mode"] == "fork"

        tids = []
        # Distinct NON-initial positions: the worker's startup
        # self-probe warmed the answer cache for the initial position,
        # and a pure cache hit records no batcher/reader spans.
        for pos in (9, 8, 7, 6, 5, 4):
            tid, sid = mint_trace_ids()
            status, headers, body = _post_with_headers(
                base + "/query", {"positions": [pos]},
                headers={"traceparent": format_traceparent(tid, sid)},
            )
            assert status == 200 and body["results"][0]["found"]
            echoed = parse_traceparent(headers.get("traceparent"))
            assert echoed is not None and echoed[0] == tid
            assert echoed[1] != sid  # the server's own span id
            tids.append(tid)

        # Kept traces ride heartbeat beats into the supervisor's
        # fleet-wide ring; the control port serves the aggregate.
        def _ours():
            snap = _get(control + "/traces")[1]
            assert snap["kind"] == "qtrace_fleet"
            got = [t for t in snap["traces"]
                   if t.get("trace_id") in tids]
            return got or None

        got = _wait_for(_ours, timeout=60,
                        what="client traces on the control port")
        for rec in got:
            assert rec["status"] == "ok" and rec["code"] == 200
            assert rec["worker"] in (0, 1)  # supervisor-stamped slot
            assert rec["keep"] in ("head", "slow")
            names = {s["name"] for s in rec["spans"]}
            assert "queue_wait" in names

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_supervisor_exec_mode_propagates_traceparent(sub_db,
                                                     monkeypatch):
    """ISSUE 17 e2e, exec mode: the same traceparent contract when the
    worker was re-exec'd (env-inherited trace knobs, beat-shipped
    traces) — asserted through ServeSupervisor.traces(), the object
    backing control GET /traces."""
    from gamesmanmpi_tpu.obs.qtrace import (
        format_traceparent,
        mint_trace_ids,
        parse_traceparent,
    )

    monkeypatch.setenv("GAMESMAN_PLATFORM", "cpu")
    # Exec workers inherit os.environ (subprocess.Popen without env=):
    # this knob must reach the child or nothing below samples.
    monkeypatch.setenv("GAMESMAN_TRACE_HEAD_N", "1")
    sup = ServeSupervisor(
        single_db_entries(sub_db), workers=1, control_port=None,
        restart_base=0.1, heartbeat_secs=0.2, heartbeat_timeout=30.0,
    ).start()
    try:
        assert sup.status()["spawn_mode"] == "exec"
        _wait_for(
            lambda: sup.status()["status"] == "ok",
            timeout=180, what="exec worker ready",
        )
        base = f"http://127.0.0.1:{sup.port}"
        tid, sid = mint_trace_ids()
        # Non-initial position: the self-probe warmed the answer cache
        # for the initial one, and a cache hit records no spans.
        status, headers, body = _post_with_headers(
            base + "/query", {"positions": [7]},
            headers={"traceparent": format_traceparent(tid, sid)},
        )
        assert status == 200 and body["results"][0]["found"]
        echoed = parse_traceparent(headers.get("traceparent"))
        assert echoed is not None and echoed[0] == tid

        def _ours():
            snap = sup.traces()
            got = [t for t in snap["traces"]
                   if t.get("trace_id") == tid]
            return got or None

        (rec,) = _wait_for(_ours, timeout=60,
                           what="trace shipped over the exec beat")
        assert rec["parent_id"] == sid
        assert rec["worker"] == 0
        assert {s["name"] for s in rec["spans"]} >= {"queue_wait"}

        # The burn-rate snapshot rides the same beat: control /status
        # (sup.status()) shows the per-worker SLO view, not just the
        # degraded/ok flip it induces.
        def _slo_on_status():
            st = sup.status()
            slo = st["workers"]["0"].get("slo")
            return st if isinstance(slo, dict) and "routes" in slo else None

        st = _wait_for(_slo_on_status, timeout=30,
                       what="slo snapshot on the beat")
        assert st["slo_fast_burn"] is False
        assert "p99_ms" in st["workers"]["0"]["slo"]
    finally:
        sup.stop()


def test_workers_never_outlive_a_sigkilled_supervisor(sub_db, tmp_path):
    """No orphans: a worker wedged in WARM START (nothing written on
    the heartbeat pipe yet, so EPIPE can never tell it the supervisor
    died) must still notice the SIGKILLed supervisor — the reparent
    watchdog — and a ready worker notices via its next beat. Both gone
    within seconds, nobody left accept()ing on an unowned socket."""
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    # Stall worker 0's warm start at the spawn fault point.
    env["GAMESMAN_FAULTS_WORKER_0"] = "serve.worker_spawn:delay=60"
    env["GAMESMAN_SERVE_HEARTBEAT_SECS"] = "0.2"
    env.pop("GAMESMAN_FAULTS", None)
    proc = subprocess.Popen(
        _CLI + ["serve", str(sub_db), "--port", "0", "--workers", "2",
                "--control-port", "0"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=str(REPO),
    )
    try:
        banner = proc.stdout.readline()
        cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
        control = f"http://127.0.0.1:{cport}"
        st = _wait_for(
            lambda: (s := _get(control + "/healthz")[1])
            ["workers"]["0"]["state"] == "starting"
            and s["workers"]["0"]["pid"]
            and s["workers"]["1"]["state"] == "ready" and s,
            timeout=60, what="worker 0 wedged in warm start",
        )
        pids = [st["workers"]["0"]["pid"], st["workers"]["1"]["pid"]]
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)

        def _all_gone():
            for pid in pids:
                try:
                    os.kill(pid, 0)
                    return False
                except ProcessLookupError:
                    pass
            return True

        _wait_for(_all_gone, timeout=10, interval=0.25,
                  what="workers exiting after supervisor SIGKILL")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# ------------------------------------------------ review-round regressions


def test_reload_requested_mid_roll_is_queued_not_dropped(tmp_path, sub_db):
    """A reload asked for while a roll is in progress must run after
    that roll finishes — the 202 is a promise, not a maybe."""
    sup = _fake_supervisor(
        sub_db, ["slowdrain", "slowdrain"], drain_grace=10.0,
    ).start()
    try:
        _wait_for(lambda: sup.status()["status"] == "ok",
                  what="fleet ready")
        sup.request_reload()
        _wait_for(lambda: sup.status()["reload_in_progress"],
                  what="first roll started")
        sup.request_reload()  # mid-roll: must queue, not vanish
        st = _wait_for(
            lambda: (s := sup.status())["reloads_done"] == 2
            and s["status"] == "ok" and s,
            timeout=60, what="second (queued) reload completed",
        )
        assert st["gen"] == 2
    finally:
        sup.stop()


def test_half_open_probe_death_reopens_breaker(sub_db):
    """The storm breaker's cool-off buys ONE probe spawn: a dead probe
    re-opens the breaker immediately instead of granting a fresh
    storm budget of crash-loops per window."""
    sup = _fake_supervisor(sub_db, ["ok"], storm_restarts=3)
    slot = sup._slots[0]
    now = time.monotonic()
    # An ordinary first death backs off without breaking.
    sup._schedule_restart(slot, now, "exit rc=3")
    assert slot.state == "restarting"
    # The spawn after a broken hold-down is marked as the probe; its
    # death must go straight back to broken, window contents be damned.
    slot.half_open = True
    slot.recent = []
    sup._schedule_restart(slot, now + 1, "exit rc=3")
    assert slot.state == "broken"
    assert sup.status()["workers"]["0"]["breaker"] == "open"
    sup._shutdown()


def test_prehello_silence_gets_spawn_grace_not_beat_deadline(sub_db):
    """A freshly spawned worker that has not written its first byte yet
    (cold exec spawn: interpreter + jax import) is judged against the
    spawn grace, not the beat deadline; after its first byte the tight
    deadline applies."""

    class _Recorder:
        def __init__(self):
            self.signals = []

        def kill(self, sig):
            self.signals.append(sig)

        def poll(self):
            return None

    sup = _fake_supervisor(sub_db, ["ok"], heartbeat_timeout=0.5)
    assert sup.spawn_grace >= 60.0
    slot = sup._slots[0]
    slot.state = "starting"
    slot.proc = _Recorder()
    slot.last_msg = time.monotonic() - 5.0  # silent for 5 s
    slot.heard = False
    sup._check_liveness(time.monotonic())
    assert slot.proc.signals == []  # within spawn grace: left alone
    slot.heard = True  # first byte arrived; beat deadline now applies
    sup._check_liveness(time.monotonic())
    assert signal.SIGKILL in slot.proc.signals
    slot.proc = None
    sup._shutdown()


def test_cli_fleet_fork_shares_compressed_db_readers(nim_db, tmp_path):
    """ISSUE 9's fleet axis: a 2-worker fork-mode fleet over a
    block-compressed (format v2) DB — the supervisor opened the
    decompressing DbReader (and its block-stream fds) BEFORE forking —
    answers the whole nim_345 oracle exactly while each worker runs its
    own hot-block cache (copy-on-write after fork: no cross-worker
    corruption is possible, and the test proves the answers), with
    per-worker cache metrics observable on /metrics and db_cache_*
    figures riding the worker-stamped serve JSONL streams."""
    _, oracle = nim_db
    spec = "nim:heaps=3-4-5"
    v2 = tmp_path / "nimv2"
    export_result(Solver(get_game(spec)).solve(), v2, spec, compress=True)
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    # A 1 MB budget forces real decode + eviction traffic under load.
    env["GAMESMAN_DB_CACHE_MB"] = "1"
    env.pop("GAMESMAN_FAULTS", None)
    jsonl = tmp_path / "serve.jsonl"
    proc = subprocess.Popen(
        _CLI + ["serve", str(v2), "--port", "0", "--workers", "2",
                "--control-port", "0", "--jsonl", str(jsonl)],
        stdout=subprocess.PIPE, text=True, env=env, cwd=str(REPO),
    )
    try:
        banner = proc.stdout.readline()
        assert "serving fleet" in banner, banner
        port = int(banner.split("http://127.0.0.1:")[1].split(" ")[0])
        cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
        base = f"http://127.0.0.1:{port}"
        st = _wait_for(
            lambda: (s := _get(f"http://127.0.0.1:{cport}/healthz")[1])
            ["status"] == "ok" and s,
            timeout=120, what="fleet ready",
        )
        assert st["spawn_mode"] == "fork"
        # Both workers verified the COMPRESSED DB through the same
        # check_db gate (full block decode) before joining.
        assert all(w["verified"] == {"default": True}
                   for w in st["workers"].values())
        positions = sorted(oracle)
        for i in range(0, len(positions), 64):
            chunk = [hex(p) for p in positions[i:i + 64]]
            status, body = _post(base + "/query", {"positions": chunk})
            assert status == 200
            for q, rec in zip(chunk, body["results"]):
                v, r = oracle[int(q, 0)]
                assert (rec["found"], rec["value"], rec["remoteness"]) \
                    == (True, value_name(v), r), q
        # Worker-side cache series, worker-labeled (the serve port is
        # answered by whichever worker accepts; sample until one shows
        # its registry).
        def _cache_metrics():
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            return ("gamesman_db_cache_hits_total" in text
                    and 'worker="' in text) and text
        text = _wait_for(_cache_metrics, timeout=30,
                         what="worker cache metrics")
        assert "gamesman_db_block_decode_seconds" in text
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    # The worker-stamped JSONL streams carry the cache trajectory, and
    # obs_report folds them into per-worker hit-rate columns.
    records = []
    for path in tmp_path.glob("serve*.jsonl"):
        for line in path.read_text().splitlines():
            if line.strip():
                records.append(json.loads(line))
    batches = [r for r in records if r.get("phase") == "serve_batch"]
    assert batches
    assert any("db_cache_hits" in r for r in batches)
    assert {r.get("worker") for r in batches} - {None}
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    lines = obs_report.summarize_serving(records)
    assert any("db_cache_hit_rate=" in line for line in lines), lines


def test_cli_fleet_without_db_is_a_usage_error(tmp_path):
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    proc = subprocess.run(
        _CLI + ["serve", "--workers", "2"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
        timeout=120,
    )
    assert proc.returncode == 2
    assert "needs a DB directory" in proc.stderr


def test_external_sigterm_respawns_instead_of_parking(sub_db):
    """A worker SIGTERM'd by an operator (not the supervisor) drains
    and exits 0 — the slot must be replaced, not parked 'stopped':
    the supervisor owns the fleet size."""
    sup = _fake_supervisor(sub_db, ["ok", "ok"]).start()
    try:
        st = _wait_for(
            lambda: (s := sup.status())["status"] == "ok" and s,
            what="fleet ready",
        )
        victim = st["workers"]["0"]["pid"]
        os.kill(victim, signal.SIGTERM)  # external: no roll in progress
        st = _wait_for(
            lambda: (s := sup.status())["workers"]["0"]["state"] == "ready"
            and s["workers"]["0"]["pid"] != victim and s,
            what="externally drained worker respawned",
        )
        # A clean drain is not a death: no backoff restart was charged.
        assert st["workers"]["0"]["restarts"] == 0
        assert st["status"] == "ok"
    finally:
        sup.stop()


def test_wedged_teardown_does_not_cascade_to_siblings(sub_db):
    """A worker that closes its pipe but lingers (wedged teardown,
    SIGTERM-immune) is SIGKILLed promptly — and the reap must not
    starve the sibling's heartbeat reads into a phantom 'stall' that
    SIGKILLs the healthy half of the fleet."""
    sup = _fake_supervisor(sub_db, ["wedge", "ok"]).start()
    try:
        _wait_for(lambda: sup.status()["status"] == "ok",
                  what="fleet ready")
        # The wedge fires ~80 ms after ready; wait for its restart.
        _wait_for(
            lambda: sup.status()["workers"]["0"]["restarts"] >= 1,
            what="wedged worker reaped and restarted",
        )
        time.sleep(1.0)  # a cascade would kill worker 1 within this
        assert sup.status()["workers"]["1"]["restarts"] == 0, \
            "healthy sibling was killed during the wedge reap"
    finally:
        sup.stop()


def test_roll_aborts_and_rolls_back_when_replacement_cannot_warm_start(
        tmp_path, sub_db):
    """A structurally-valid manifest whose DB fails the worker verify
    gate passes the parent's reload checks — the roll must then ABORT
    and roll BACK to the pre-reload config instead of wedging forever
    at N-1 capacity with all future reloads blocked."""
    import subprocess as sp

    from gamesmanmpi_tpu.serve.supervisor import _ExecProc

    from helpers import FAKE_FLEET_WORKER

    manifest = tmp_path / "fleet.json"

    def write_manifest(name):
        manifest.write_text(json.dumps({
            "version": 1, "games": [{"name": name, "db": str(sub_db)}],
        }))

    write_manifest("good")

    def spawn(idx, cfg):
        # The fake analog of the verify gate: any worker built for the
        # "bad" game refuses to come up.
        mode = ("crash" if any(n == "bad" for n, _ in cfg["entries"])
                else "ok")
        r, w = os.pipe()
        proc = sp.Popen(
            [sys.executable, "-c", FAKE_FLEET_WORKER, str(w), mode],
            pass_fds=(w,),
        )
        os.close(w)
        return _ExecProc(proc), r

    sup = ServeSupervisor(
        load_fleet_manifest(manifest), workers=2, control_port=None,
        manifest_path=manifest, heartbeat_secs=0.05,
        heartbeat_timeout=0.6, restart_base=0.01, restart_max=0.05,
        storm_restarts=2, storm_secs=60.0, drain_grace=5.0, spawn=spawn,
    ).start()
    try:
        _wait_for(lambda: sup.status()["status"] == "ok",
                  what="fleet ready")
        write_manifest("bad")  # structurally valid; workers will refuse
        sup.request_reload()
        st = _wait_for(
            lambda: (s := sup.status())["last_reload_error"]
            and "aborted" in s["last_reload_error"] and s,
            timeout=60, what="roll aborted",
        )
        # ...and the rollback restores full capacity on the OLD config.
        st = _wait_for(
            lambda: (s := sup.status())["status"] == "ok"
            and not s["reload_in_progress"] and s,
            timeout=60, what="rollback roll completed",
        )
        assert "aborted" in st["last_reload_error"]
        assert sorted(e.name for e in sup.entries) == ["good"]
        # A corrective reload is NOT blocked by the aborted one.
        write_manifest("good2")
        sup.request_reload()
        st = _wait_for(
            lambda: (s := sup.status())["games"] == ["good2"]
            and s["status"] == "ok" and s,
            timeout=60, what="corrective reload",
        )
        assert st["last_reload_error"] is None
    finally:
        sup.stop()


def test_externally_drained_worker_that_wedges_is_killed(sub_db):
    """An operator SIGTERM whose teardown wedges after announcing
    'draining' still gets a drain deadline from the supervisor — the
    slot is SIGKILLed and replaced, never left lingering at N-1."""
    sup = _fake_supervisor(sub_db, ["stuckdrain"], drain_grace=1.0).start()
    try:
        st = _wait_for(
            lambda: (s := sup.status())["status"] == "ok" and s,
            what="fleet ready",
        )
        victim = st["workers"]["0"]["pid"]
        os.kill(victim, signal.SIGTERM)  # external; teardown will wedge
        st = _wait_for(
            lambda: (s := sup.status())["workers"]["0"]["state"] == "ready"
            and s["workers"]["0"]["pid"] != victim and s,
            timeout=30, what="wedged drain killed and replaced",
        )
        assert st["workers"]["0"]["restarts"] >= 1
    finally:
        sup.stop()
