"""gamedsl acceptance: spec validation, compiler byte-parity, staleness
plumbing, and the new pure-description games end-to-end.

The contract under test (ISSUE 16):

* compiled connect4/tictactoe specs produce solved tables sha256-equal
  to the hand-written modules (including the sym variants);
* the spec's canonical hash flows into the kernel cache key and the DB
  manifest, so a mutated spec provably misses the kernel cache and
  fails ``check_db --same-as``;
* two genuinely new games — exact-k gomoku and misere m,n,k — exist
  purely as .json descriptions and pass the same DB-oracle and serve
  round-trips as the hand-written games;
* the CLI solves/exports straight from ``--spec`` with zero Python;
* tools/spec_lint.py and the GM901 gamesman-lint checker reject broken
  specs with per-finding GS codes.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from gamesmanmpi_tpu.core.values import value_name
from gamesmanmpi_tpu.db import DbReader, export_result
from gamesmanmpi_tpu.db.check import db_equal
from gamesmanmpi_tpu.db.format import read_manifest
from gamesmanmpi_tpu.gamedsl import (
    GameSpec,
    SpecError,
    lint_file,
    load_spec,
    spec_problems,
)
from gamesmanmpi_tpu.gamedsl.compiler import compile_spec
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.serve import QueryServer
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.engine import _cache_key
from gamesmanmpi_tpu.solve.oracle import oracle_solve

from helpers import REF_GAMES, REPO, load_module, table_sha256

SPECS = REPO / "examples" / "specs"

#: (committed spec file, reference-style scalar twin) — the new games.
NEW_GAMES = [
    ("gomoku_4x3x3.json", "gomoku_4x3x3.py"),
    ("mnk_3x3x3_misere.json", "mnk_333_misere.py"),
]


def _doc(name="g", w=3, h=3, family="place", win=None, symmetry=None):
    doc = {
        "gamedsl": 1,
        "name": name,
        "board": {"width": w, "height": h},
        "moves": {"family": family},
        "win": win or {"kind": "k_in_line", "k": 3},
    }
    if symmetry is not None:
        doc["symmetry"] = symmetry
    return doc


# ------------------------------------------------------------ spec identity


def test_canonical_hash_stable_across_spellings():
    """Defaults, key order, and direction aliases never change the hash —
    only the rules do."""
    a = GameSpec.from_dict(_doc(win={"kind": "k_in_line", "k": 3,
                                     "directions": ["e", "n", "ne", "se"],
                                     "misere": False}))
    b = GameSpec.from_dict({
        "name": "g",
        "board": {"width": 3, "height": 3},
        "win": {"k": 3, "directions": ["w", "s", "sw", "nw"]},
    })
    assert a == b
    assert a.spec_hash == b.spec_hash
    for mutated in (
        _doc(win={"k": 2}),
        _doc(name="g2"),
        _doc(w=4),
        _doc(win={"k": 3, "misere": True}),
        _doc(win={"k": 3, "exact": True}),
        _doc(win={"k": 3, "directions": ["e", "n"]}),
        _doc(symmetry=["mirror_h", "transpose"]),
    ):
        assert GameSpec.from_dict(mutated).spec_hash != a.spec_hash, mutated


@pytest.mark.parametrize("breaker", [
    {"extra_key": 1},
    {"name": None},
    {"board": {"width": 3}},
    {"board": {"width": 3, "height": True}},
    {"board": {"width": 0, "height": 3}},
    {"moves": {"family": "slide"}},
    {"win": {"kind": "count", "k": 3}},   # schema-reserved, not compilable
    {"win": {"kind": "capture", "k": 3}},
    {"win": {"kind": "k_in_line", "k": 0}},
    {"win": {"k": 3, "directions": []}},
    {"win": {"k": 3, "directions": ["x"]}},
    {"symmetry": ["spiral"]},
    {"gamedsl": 99},
])
def test_from_dict_rejects(breaker):
    doc = _doc()
    doc.update(breaker)
    with pytest.raises(SpecError):
        GameSpec.from_dict(doc)


def test_spec_problem_catalogue():
    """Each GS finding fires on its minimal trigger, with the documented
    severity."""
    def codes(spec):
        return {(p["code"], p["severity"]) for p in spec_problems(spec)}

    # GS101: does not fit uint64 packing
    assert ("GS101", "error") in codes(
        GameSpec(name="g", width=8, height=8, family="drop", k=4))
    # GS102: fits, but outside the 26-bit fused value-table gate
    assert ("GS102", "warning") in codes(
        GameSpec(name="g", width=7, height=6, family="drop", k=4))
    # GS103: no direction fits a k-window
    assert ("GS103", "error") in codes(
        GameSpec(name="g", width=3, height=3, k=5))
    # GS104: some (not all) directions dead
    dead = codes(GameSpec(name="g", width=3, height=4, k=4))
    assert ("GS104", "warning") in dead and ("GS103", "error") not in dead
    # GS105: generator incompatible with gravity / non-square board
    assert ("GS105", "error") in codes(
        GameSpec(name="g", width=4, height=4, family="drop", k=4,
                 symmetry=("mirror_v",)))
    assert ("GS105", "error") in codes(
        GameSpec(name="g", width=4, height=3, k=3,
                 symmetry=("transpose",)))
    # GS106: generators don't preserve an asymmetric direction set
    assert ("GS106", "error") in codes(
        GameSpec(name="g", width=3, height=3, k=3, directions=("ne",),
                 symmetry=("mirror_h",)))
    # GS108: exact-k has no drop lowering
    assert ("GS108", "error") in codes(
        GameSpec(name="g", width=4, height=4, family="drop", k=3,
                 exact=True))
    # GS109: k=1 is trivially won
    assert ("GS109", "warning") in codes(
        GameSpec(name="g", width=3, height=3, k=1))
    # clean spec: no findings at all
    assert spec_problems(GameSpec(name="g", width=3, height=3, k=3)) == []


def test_committed_specs_are_clean():
    for path in sorted(SPECS.glob("*.json")):
        errors = [f for f in lint_file(str(path))
                  if f["severity"] == "error"]
        assert errors == [], path


def test_compile_refuses_error_specs():
    with pytest.raises(SpecError) as e:
        compile_spec(GameSpec(name="g", width=3, height=3, k=5))
    assert "GS103" in str(e.value)


# ------------------------------------------------------------- byte parity


#: hand-written registry spec vs equivalent GameSpec (committed file
#: where one exists; sym variants as inline docs).
PARITY_CASES = [
    ("tictactoe", str(SPECS / "tictactoe_3x3.json")),
    ("connect4:w=4,h=4", str(SPECS / "connect4_4x4.json")),
    ("tictactoe:sym=1",
     _doc(name="tictactoe_3x3x3_sym",
          symmetry=["mirror_h", "transpose"])),
    ("connect4:w=4,h=3,sym=1",
     _doc(name="connect4_4x3_sym", w=4, h=3, family="drop",
          win={"k": 4}, symmetry=["mirror_h"])),
]


@pytest.mark.parametrize(
    "hand_spec,compiled_src", PARITY_CASES,
    ids=[c[0] for c in PARITY_CASES])
def test_compiled_tables_byte_identical(hand_spec, compiled_src):
    """The acceptance bar: a compiled spec's solved table is sha256-equal
    to the hand-written module's — masks, smears, symmetry group and all."""
    if isinstance(compiled_src, str):
        game = compile_spec(load_spec(compiled_src))
    else:
        game = compile_spec(GameSpec.from_dict(compiled_src))
    hand = Solver(get_game(hand_spec)).solve()
    compiled = Solver(game).solve()
    assert table_sha256(hand) == table_sha256(compiled)
    assert (hand.value, hand.remoteness) == (
        compiled.value, compiled.remoteness)


def test_drop_strides_are_derived_not_hardcoded():
    """The compiler's smear strides come from the adjacency directions:
    the full compass on (w, h) must reproduce connect4's hand-derived
    {1, h, h+1, h+2}, and a direction subset must drop the matching
    strides."""
    full = compile_spec(GameSpec.from_dict(
        _doc(name="d", w=5, h=4, family="drop", win={"k": 4})))
    assert tuple(int(d) for d in full._dirs) == (1, 4, 5, 6)
    ortho = compile_spec(GameSpec.from_dict(
        _doc(name="d", w=5, h=4, family="drop",
             win={"k": 4, "directions": ["e", "n"]})))
    assert tuple(int(d) for d in ortho._dirs) == (1, 5)


# ------------------------------------------------------- staleness plumbing


def test_spec_hash_flows_into_kernel_cache_key(tmp_path):
    """A rules change (same name, same shapes) must miss the kernel
    cache: the canonical hash participates in engine._cache_key."""
    path = tmp_path / "game.json"
    path.write_text(json.dumps(_doc(name="mutant")))
    g1 = get_game(str(path))
    path.write_text(json.dumps(_doc(name="mutant", win={"k": 2})))
    g2 = get_game(str(path))
    assert g1.name == g2.name and g1.state_bits == g2.state_bits
    assert g1.cache_key != g2.cache_key
    k1 = _cache_key(g1, "forward", (1024,), None)
    k2 = _cache_key(g2, "forward", (1024,), None)
    assert k1 != k2
    # ... and an unchanged spec re-read from disk HITS the cache.
    g3 = get_game(str(path))
    assert g3.cache_key == g2.cache_key
    assert _cache_key(g3, "forward", (1024,), None) == k2


def test_mutated_spec_fails_check_db_same_as(tmp_path):
    """The DB half of the staleness contract: two exports of the same
    path with different rules disagree on spec_sha256, and the CLI gate
    (tools/check_db.py --same-as) exits nonzero."""
    path = tmp_path / "game.json"
    db1, db2 = tmp_path / "db1", tmp_path / "db2"
    path.write_text(json.dumps(_doc(name="mutant")))
    export_result(Solver(get_game(str(path))).solve(), db1, str(path))
    path.write_text(json.dumps(_doc(name="mutant", win={"k": 2})))
    export_result(Solver(get_game(str(path))).solve(), db2, str(path))
    diffs = db_equal(db1, db2)
    assert any(d.startswith("spec_sha256") for d in diffs), diffs
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_db.py"),
         str(db1), "--same-as", str(db2), "--quiet"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "spec_sha256" in proc.stderr
    # Sanity: the gate passes against itself.
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_db.py"),
         str(db1), "--same-as", str(db1), "--quiet"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0


# ------------------------------------------- new games: DB oracle + serve


@pytest.fixture(scope="module")
def gamedsl_db(tmp_path_factory):
    """Lazy per-spec cache: (SolveResult, DbReader, oracle table, dir)."""
    built = {}

    def get(spec_file, ref_file):
        if spec_file not in built:
            d = tmp_path_factory.mktemp("gamedsl_db")
            spec_path = str(SPECS / spec_file)
            result = Solver(get_game(spec_path)).solve()
            export_result(result, d, spec_path)
            _, _, oracle = oracle_solve(load_module(REF_GAMES / ref_file))
            built[spec_file] = (result, DbReader(d), oracle, d)
        return built[spec_file]

    yield get
    for _, reader, _, _ in built.values():
        reader.close()


@pytest.mark.parametrize("spec_file,ref_file", NEW_GAMES)
def test_new_game_db_roundtrip_matches_oracle(gamedsl_db, spec_file,
                                              ref_file):
    """The pure-description games clear the same bar as the hand-written
    ones: solve → export-db → lookup == scalar oracle for EVERY
    reachable position."""
    _, reader, oracle, _ = gamedsl_db(spec_file, ref_file)
    positions = np.array(sorted(oracle), dtype=np.uint64)
    values, rem, found = reader.lookup(positions)
    assert found.all(), "reachable positions missing from the DB"
    for i, pos in enumerate(positions):
        assert (int(values[i]), int(rem[i])) == oracle[int(pos)], (
            f"{spec_file}: mismatch at {int(pos):#x}"
        )


@pytest.mark.parametrize("spec_file,ref_file", NEW_GAMES)
def test_new_game_manifest_carries_spec_identity(gamedsl_db, spec_file,
                                                 ref_file):
    _, reader, _, d = gamedsl_db(spec_file, ref_file)
    spec = load_spec(str(SPECS / spec_file))
    manifest = read_manifest(d)
    assert manifest["spec_sha256"] == spec.spec_hash
    assert manifest["game_spec"] == spec.to_doc()
    assert reader.game.name == spec.name


@pytest.mark.parametrize("spec_file,ref_file", NEW_GAMES)
def test_new_game_serve_roundtrip(gamedsl_db, spec_file, ref_file):
    """POST /query answers a sample of every-Nth oracle position
    correctly for the compiled games (the serve path runs the compiled
    canonicalize/expand kernels)."""
    import urllib.request

    _, reader, oracle, _ = gamedsl_db(spec_file, ref_file)
    sample = sorted(oracle)[::max(1, len(oracle) // 128)]
    with QueryServer(reader) as server:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/query",
            data=json.dumps(
                {"positions": [hex(p) for p in sample]}
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            body = json.loads(resp.read())
    assert len(body["results"]) == len(sample)
    for pos, rec in zip(sample, body["results"]):
        v, r = oracle[pos]
        assert rec["found"], hex(pos)
        assert rec["value"] == value_name(v), hex(pos)
        assert rec["remoteness"] == r, hex(pos)


def test_reader_reconstructs_from_embedded_spec(tmp_path):
    """A gamedsl DB is self-describing: the reader rebuilds the game from
    the manifest's embedded canonical doc even after the original .json
    vanished."""
    path = tmp_path / "ephemeral.json"
    path.write_text((SPECS / "mnk_3x3x3_misere.json").read_text())
    d = tmp_path / "db"
    result = Solver(get_game(str(path))).solve()
    export_result(result, d, str(path))
    path.unlink()
    with DbReader(d) as reader:
        assert reader.game.name == "mnk_3x3x3_misere"
        root = int(np.asarray(reader.game.initial_state()))
        values, rem, found = reader.lookup(
            np.array([root], dtype=np.uint64))
        assert found.all()
        assert (int(values[0]), int(rem[0])) == (
            result.value, result.remoteness)


# ----------------------------------------------------------------- the CLI


def test_cli_solve_spec_flag(capsys):
    """`gamesman solve --spec game.json` solves with zero Python — and
    agrees with the engine's direct answer."""
    from gamesmanmpi_tpu import cli

    rc = cli.main(["solve", "--spec",
                   str(SPECS / "mnk_3x3x3_misere.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "game: mnk_3x3x3_misere" in out
    assert "value: TIE" in out


def test_cli_spec_and_game_are_exclusive(capsys):
    from gamesmanmpi_tpu import cli

    assert cli.main(["tictactoe", "--spec", "x.json"]) == 2
    assert "not both" in capsys.readouterr().err
    assert cli.main([]) == 2
    assert "--spec" in capsys.readouterr().err


def test_cli_export_db_spec(tmp_path, capsys):
    from gamesmanmpi_tpu import cli

    out = tmp_path / "db"
    rc = cli.main(["export-db", "--spec",
                   str(SPECS / "mnk_3x3x3_misere.json"),
                   "--out", str(out)])
    assert rc == 0
    manifest = read_manifest(out)
    spec = load_spec(str(SPECS / "mnk_3x3x3_misere.json"))
    assert manifest["spec_sha256"] == spec.spec_hash
    capsys.readouterr()
    assert cli.main(["export-db", "--out", str(tmp_path / "x")]) == 2
    assert "--spec" in capsys.readouterr().err


# ------------------------------------------------------------ lint tooling


def test_spec_lint_tool(tmp_path, capsys):
    spec_lint = load_module(REPO / "tools" / "spec_lint.py")
    # The committed specs lint clean.
    assert spec_lint.main([]) == 0
    capsys.readouterr()
    # A broken spec fails with its GS codes on stdout.
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(
        _doc(name="bad", w=8, h=8, family="drop", win={"k": 9})))
    assert spec_lint.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "GS101" in out and "GS103" in out
    # Unparseable JSON is a finding (GS001), not a crash.
    bad.write_text("{nope")
    assert spec_lint.main([str(bad)]) == 1
    assert "GS001" in capsys.readouterr().out


def test_gamesman_lint_flags_bad_committed_spec(tmp_path):
    """GM901: a broken spec under examples/specs/ fails gamesman-lint."""
    from gamesmanmpi_tpu.analysis.runner import run_project

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    specs = tmp_path / "examples" / "specs"
    specs.mkdir(parents=True)
    (specs / "bad.json").write_text(json.dumps(
        _doc(name="bad", win={"k": 9})))
    res = run_project(tmp_path)
    got = [(d.id, d.path) for d in res.new]
    assert ("GM901", "examples/specs/bad.json") in got
    # The message carries the underlying GS code.
    assert any("GS103" in d.message for d in res.new
               if d.id == "GM901")
    # Fixing the spec clears the finding.
    (specs / "bad.json").write_text(json.dumps(_doc(name="good")))
    res = run_project(tmp_path)
    assert not [d for d in res.new if d.id == "GM901"]
