"""Monotone-window gather (ops/pallas_gather.py), interpret mode.

These tests pin the kernel's SEMANTICS via the Pallas interpreter so the
on-chip run (tools/pallas_chip_check.py) only has to answer "does Mosaic
accept it and is it fast", not "is it correct". The round-4 chip session
proved Mosaic compiles Pallas over the relay; the kernel's own first
compile attempt exposed a trace-time int64 recursion (fixed — see
ops/pallas_gather._dyn_gather), after which TPU cross-lowering succeeds;
its on-chip timing is still pending.
"""

import numpy as np
import pytest

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
except ModuleNotFoundError:
    # No hypothesis on this image (and no pip install allowed): the
    # properties run through the deterministic mini shim instead of
    # failing tier-1 collection. See tests/_mini_hypothesis.py.
    from _mini_hypothesis import given, settings, st

from gamesmanmpi_tpu.ops.pallas_gather import monotone_window_gather

# Smoke tier: fast, compile-light, single-process-safe (see pyproject).
pytestmark = pytest.mark.smoke


def _case(m, n, seed, span=None):
    rng = np.random.default_rng(seed)
    table = rng.integers(0, 1 << 30, size=m, dtype=np.uint32)
    if span is None:
        idx = np.sort(rng.integers(0, m, size=n)).astype(np.int32)
    else:
        # Bounded local span: index i drifts forward like the dense child
        # gathers do (expansion ratio <= 2).
        steps = rng.integers(0, span, size=n)
        idx = np.minimum(np.cumsum(steps), m - 1).astype(np.int32)
    return table, idx


def test_matches_plain_gather_when_spans_fit():
    table, idx = _case(1 << 16, 5000, 0, span=3)
    out, nmiss = monotone_window_gather(table, idx, block=256, window=2048,
                                        interpret=True)
    assert int(nmiss) == 0
    np.testing.assert_array_equal(np.asarray(out), table[idx])


def test_int64_indices_match_int32():
    """int64 idx (6x6+ flat spaces) must produce bit-identical output:
    the wrapper reduces both dtypes to the same block-local int32
    offsets before Mosaic (r5 — VERDICT r4 #3)."""
    table, idx = _case(1 << 16, 5000, 3, span=3)
    out32, nm32 = monotone_window_gather(table, idx, block=256,
                                         window=2048, interpret=True)
    out64, nm64 = monotone_window_gather(table, idx.astype(np.int64),
                                         block=256, window=2048,
                                         interpret=True)
    assert int(nm32) == int(nm64) == 0
    np.testing.assert_array_equal(np.asarray(out32), np.asarray(out64))
    np.testing.assert_array_equal(np.asarray(out64), table[idx])


def test_int64_wide_jumps_miss_flagged():
    table, idx = _case(1 << 18, 4096, 4)
    out, nmiss = monotone_window_gather(table, idx.astype(np.int64),
                                        block=256, window=1024,
                                        interpret=True)
    assert int(nmiss) > 0
    ok = _reference_ok_mask(table, idx, block=256, window=1024)
    np.testing.assert_array_equal(np.asarray(out)[ok], table[idx[ok]])
    assert int(nmiss) == int((~ok).sum())


def test_wide_jumps_are_miss_flagged_not_wrong():
    # Random global indices jump across windows: misses must be counted,
    # and every non-missed element must still be correct.
    table, idx = _case(1 << 18, 4096, 1)
    out, nmiss = monotone_window_gather(table, idx, block=256, window=1024,
                                        interpret=True)
    assert int(nmiss) > 0  # adversarial case: spans exceed the window
    ok = _reference_ok_mask(table, idx, block=256, window=1024)
    np.testing.assert_array_equal(np.asarray(out)[ok], table[idx[ok]])
    assert int(nmiss) == int((~ok).sum())


def test_u8_table_gathers_as_i32_exactly():
    # The dense engine's tables are u8 cells; the kernel gathers them as
    # i32 in VMEM (Mosaic's dynamic_gather targets 32-bit lanes) and must
    # cast back exactly.
    rng = np.random.default_rng(7)
    table = rng.integers(0, 256, size=1 << 16, dtype=np.uint8)
    steps = rng.integers(0, 3, size=5000)
    idx = np.minimum(np.cumsum(steps), table.shape[0] - 1).astype(np.int32)
    out, nmiss = monotone_window_gather(table, idx, block=256, window=2048,
                                        interpret=True)
    assert int(nmiss) == 0
    assert np.asarray(out).dtype == np.uint8
    np.testing.assert_array_equal(np.asarray(out), table[idx])


def _reference_ok_mask(table, idx, block, window):
    """The kernel's hit predicate, recomputed independently: element i
    hits iff its offset from its block's clamped window base lies in
    [0, 2*window)."""
    n = idx.shape[0]
    ok = np.zeros(n, bool)
    nwin = max(-(-table.shape[0] // window), 2)
    for b in range(-(-n // block)):
        lo, hi = b * block, min((b + 1) * block, n)
        base = min(max(idx[lo] // window, 0), nwin - 2) * window
        off = idx[lo:hi] - base
        ok[lo:hi] = (off >= 0) & (off < 2 * window)
    return ok


@settings(max_examples=25, deadline=None)
@given(
    logm=st.integers(10, 16),
    n=st.integers(1, 4000),
    block=st.sampled_from([128, 256, 512]),
    window=st.sampled_from([1024, 2048, 4096]),
    local=st.booleans(),
    seed=st.integers(0, 2**31),
)
def test_property_hits_exact_misses_flagged(logm, n, block, window, local,
                                            seed):
    # For ANY table size, length, block/window config and ANY
    # non-decreasing index vector: (a) every non-missed element equals
    # table[idx]; (b) nmiss == 0 exactly when no real element misses;
    # (c) when misses exist, nmiss covers at least the real ones (tail
    # padding replicas may inflate it, per the contract).
    table, idx = _case(1 << logm, n, seed, span=3 if local else None)
    out, nmiss = monotone_window_gather(table, idx, block=block,
                                        window=window, interpret=True)
    ok = _reference_ok_mask(table, idx, block, window)
    np.testing.assert_array_equal(np.asarray(out)[ok], table[idx[ok]])
    real_misses = int((~ok).sum())
    if real_misses == 0:
        assert int(nmiss) == 0
    else:
        assert int(nmiss) >= real_misses


@pytest.mark.parametrize("n", [1, 255, 256, 257, 5000])
def test_ragged_lengths(n):
    table, idx = _case(1 << 14, n, n, span=2)
    out, nmiss = monotone_window_gather(table, idx, block=256, window=2048,
                                        interpret=True)
    assert int(nmiss) == 0
    np.testing.assert_array_equal(np.asarray(out), table[idx])
