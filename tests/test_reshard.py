"""Elastic resume (ISSUE 13): reshard-on-resume parity.

The shard-count-invariance contract (same tables for 1 and N shards,
`parallel/sharded.py`) extended ACROSS resume: a solve killed at S
shards must resume at S' shards — and at a different world size — to
the byte-identical table, because checkpoint geometry is a resume-time
choice, not a seal-time life sentence (docs/DISTRIBUTED.md "Elastic
resume").

Axes:

* unit matrix for the row re-partitioner itself (tier-1 fast, pure
  numpy): buckets recombine exactly, payload columns stay row-aligned,
  reshard(old->new) equals partitioning the global array at new
  directly;
* in-process resume parity (tier-1): fatal faults mid-forward /
  mid-backward at S=2, resumed at S' in {1, 4} — reshard adoption is
  observable (`resharded_from`), sealed edge shards degrade the level
  to the lookup backward, and the table matches the uninterrupted
  solve;
* strict mode: GAMESMAN_RESHARD=0 raises a geometry error NAMING the
  sealed vs requested geometry instead of adapting;
* whole-process matrix (slow): connect4 4x4 at S=8 SIGKILLed at a
  forward, backward, and mid-write-behind point, resumed at S' in
  {4, 16} and at W=2->1, `--table-out` byte parity throughout.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from gamesmanmpi_tpu.core.hashing import owner_shard_np
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.parallel import ShardedSolver
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.engine import SolverError
from gamesmanmpi_tpu.utils.checkpoint import (
    LevelCheckpointer,
    _loadz,
    repartition_rows,
    reshard_shard_stream,
    save_result_npz,
)

from helpers import REPO, full_table

_CLI = [sys.executable, "-m", "gamesmanmpi_tpu.cli"]
_C3 = "connect4:w=3,h=3,connect=3"
_C4 = "connect4:w=4,h=4"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def c3_clean():
    return Solver(get_game(_C3)).solve()


# ------------------------------------------------ re-partitioner (unit)


def _keys(n, seed=0):
    rng = np.random.RandomState(seed)
    return np.unique(rng.randint(0, 2 ** 62, n).astype(np.uint64))


@pytest.mark.parametrize("new_s", [1, 2, 3, 4, 8, 16])
def test_repartition_rows_recombines_exactly(new_s):
    states = _keys(999)
    payload = (states % np.uint64(251)).astype(np.int32)
    parts = repartition_rows(states, new_s, payload)
    assert sum(p[0].shape[0] for p in parts) == states.shape[0]
    for t, (st, pl) in enumerate(parts):
        assert (owner_shard_np(st, new_s) == t).all()
        # payload stays row-aligned through the partition
        assert np.array_equal(pl, (st % np.uint64(251)).astype(np.int32))
        assert st.dtype == states.dtype
    assert np.array_equal(
        np.sort(np.concatenate([p[0] for p in parts])), states
    )


@pytest.mark.parametrize("old_s", [1, 2, 3, 8])
@pytest.mark.parametrize("new_s", [1, 2, 4, 16])
def test_reshard_stream_matches_direct_partition(old_s, new_s):
    """reshard(old -> new) == partitioning the global sorted array at
    new directly: per-shard sorted, payload aligned, nothing dropped."""
    states = _keys(1234, seed=old_s * 31 + new_s)
    payload = (states * np.uint64(3)).astype(np.uint64)
    old = repartition_rows(states, old_s, payload)
    out = reshard_shard_stream(lambda s: old[s], old_s, new_s)
    direct = repartition_rows(states, new_s, payload)
    assert len(out) == new_s
    for t in range(new_s):
        assert np.array_equal(out[t][0], direct[t][0])  # already sorted
        assert np.array_equal(out[t][1], direct[t][1])
        assert (np.diff(out[t][0].astype(np.uint64)) > 0).all() \
            or out[t][0].shape[0] <= 1


def test_reshard_stream_bare_arrays():
    """Frontier files carry a single states member — load_shard may
    return a bare array, not a tuple."""
    states = _keys(500)
    old = [p[0] for p in repartition_rows(states, 4)]
    out = reshard_shard_stream(lambda s: old[s], 4, 2)
    direct = repartition_rows(states, 2)
    for t in range(2):
        assert np.array_equal(out[t][0], direct[t][0])


# ------------------------------------------- in-process resume (tier-1)


@pytest.mark.parametrize("new_s", [1, 4])
def test_backward_reshard_resume_parity(tmp_path, c3_clean, new_s):
    """Killed mid-backward at S=2 (consolidated frontier + some solved
    levels + edge shards sealed at 2), resumed at S': the frontier and
    solved levels reshard on load, the foreign-geometry edge shards
    degrade those levels to the lookup backward, and the table matches
    the uninterrupted solve byte for byte."""
    ck = LevelCheckpointer(tmp_path / "ck")
    faults.configure("sharded.backward:fatal:3")
    with pytest.raises(faults.FatalFault):
        ShardedSolver(get_game(_C3), num_shards=2,
                      checkpointer=ck).solve()
    faults.clear()
    resumed = ShardedSolver(
        get_game(_C3), num_shards=new_s,
        checkpointer=LevelCheckpointer(tmp_path / "ck"),
    ).solve()
    assert resumed.stats["resharded_from"] == 2
    if resumed.stats["backward"] == "edges":
        # The unsolved levels' edges were sealed at S=2: every one of
        # them must have taken the structural lookup fallback.
        assert resumed.stats["edges_geometry_fallback_levels"] >= 1
    assert full_table(resumed) == full_table(c3_clean)


def test_forward_prefix_reshard_resume_parity(tmp_path, c3_clean):
    """Killed mid-forward at S=2 (a partial per-level forward prefix),
    resumed at S=4: the prefix reshards per level, expansion continues
    from its deepest, and the solve reaches parity. The resumed run's
    own forward seals land at S=4 — a MIXED-count tree — which a second
    resume (at S=8) must also adopt."""
    ck = LevelCheckpointer(tmp_path / "ck")
    faults.configure("sharded.forward:fatal:3")
    with pytest.raises(faults.FatalFault):
        ShardedSolver(get_game(_C3), num_shards=2,
                      checkpointer=ck).solve()
    faults.clear()
    # Die once more mid-forward at the NEW count: levels 0..1 are now
    # sealed at 2, deeper levels at 4 — the mixed-count shape.
    faults.configure("sharded.forward:fatal:5")
    with pytest.raises(faults.FatalFault):
        ShardedSolver(get_game(_C3), num_shards=4,
                      checkpointer=LevelCheckpointer(tmp_path / "ck"),
                      ).solve()
    faults.clear()
    manifest = LevelCheckpointer(tmp_path / "ck").load_manifest()
    counts = {int(v) for v in
              manifest.get("forward_level_shards", {}).values()}
    assert counts == {2, 4}, counts
    resumed = ShardedSolver(
        get_game(_C3), num_shards=8,
        checkpointer=LevelCheckpointer(tmp_path / "ck"),
    ).solve()
    assert full_table(resumed) == full_table(c3_clean)


def test_reshard_disabled_raises_named_geometry(tmp_path, monkeypatch):
    """GAMESMAN_RESHARD=0: the mismatch is a loud error naming sealed
    vs requested (shards, world, epoch) — satellite: no more opaque
    resume refusals."""
    ck = LevelCheckpointer(tmp_path / "ck")
    faults.configure("sharded.backward:fatal:2")
    with pytest.raises(faults.FatalFault):
        ShardedSolver(get_game(_C3), num_shards=2,
                      checkpointer=ck).solve()
    faults.clear()
    monkeypatch.setenv("GAMESMAN_RESHARD", "0")
    with pytest.raises(SolverError) as ei:
        ShardedSolver(get_game(_C3), num_shards=4,
                      checkpointer=LevelCheckpointer(tmp_path / "ck"),
                      ).solve()
    msg = str(ei.value)
    assert "sealed at" in msg
    assert "shards=[2]" in msg and "shards=4" in msg
    assert "epoch=" in msg and "GAMESMAN_RESHARD" in msg
    # ...and the default (reshard on) resumes the same tree fine.
    monkeypatch.delenv("GAMESMAN_RESHARD")
    resumed = ShardedSolver(
        get_game(_C3), num_shards=4,
        checkpointer=LevelCheckpointer(tmp_path / "ck"),
    ).solve()
    assert resumed.stats["resharded_from"] == 2


def test_sealed_geometry_and_digest_normalization(tmp_path):
    """sealed_geometry reports the tree's shape; the resume digest is
    geometry-normalized under reshard mode (a W'/S' world barriers on
    the same digest a W/S world would), and strict mode keeps the
    requested count in the digest."""
    ck = LevelCheckpointer(tmp_path / "ck")
    assert ck.sealed_geometry()["shard_counts"] == []
    assert ck.check_resume_geometry(4)["status"] == "fresh"
    faults.configure("sharded.backward:fatal:2")
    with pytest.raises(faults.FatalFault):
        ShardedSolver(get_game(_C3), num_shards=2,
                      checkpointer=ck).solve()
    faults.clear()
    ck2 = LevelCheckpointer(tmp_path / "ck")
    geom = ck2.sealed_geometry()
    assert geom["num_shards"] == 2 and geom["shard_counts"] == [2]
    assert geom["epoch"] >= 1
    assert ck2.check_resume_geometry(2)["status"] == "match"
    assert ck2.check_resume_geometry(4)["status"] == "reshard"
    # Normalized digests: requested geometry drops out.
    assert ck2.resume_digest(2) == ck2.resume_digest(4)
    os.environ["GAMESMAN_RESHARD"] = "0"
    try:
        assert ck2.resume_digest(2) != ck2.resume_digest(4)
    finally:
        del os.environ["GAMESMAN_RESHARD"]


# ------------------------------------------- whole-process matrix (slow)


def _run_cli(args, extra_env=None, fake_devices=None, timeout=600):
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env.pop("GAMESMAN_FAULTS", None)
    if fake_devices is not None:
        # The invoking suite's XLA_FLAGS pins 8 host devices and wins
        # over GAMESMAN_FAKE_DEVICES — drop it so the child really gets
        # `fake_devices` (16-shard resumes need it).
        env.pop("XLA_FLAGS", None)
        env["GAMESMAN_FAKE_DEVICES"] = str(fake_devices)
    env.update(extra_env or {})
    return subprocess.run(
        _CLI + list(args), capture_output=True, text=True,
        timeout=timeout, env=env, cwd=str(REPO),
    )


def _assert_tables_equal(a, b):
    with _loadz(a) as za, _loadz(b) as zb:
        assert sorted(za.files) == sorted(zb.files)
        for f in za.files:
            assert np.array_equal(za[f], zb[f]), f


@pytest.fixture(scope="module")
def c4_clean_table(tmp_path_factory):
    path = tmp_path_factory.mktemp("golden") / "c4.npz"
    save_result_npz(
        path, ShardedSolver(get_game(_C4), num_shards=2).solve()
    )
    return path


@pytest.mark.slow
@pytest.mark.parametrize("point,resume_s", [
    ("sharded.forward:kill:3", 4),
    ("sharded.backward:kill:2", 4),
    ("store.writebehind:kill:1", 16),
    ("sharded.backward:kill:2", 16),
])
def test_chaos_kill_at_s8_resume_elastic(tmp_path, c4_clean_table,
                                         point, resume_s):
    """The acceptance matrix: solve at S=8, SIGKILL at a forward /
    backward / mid-write-behind point, resume at S' in {4, 16} —
    `--table-out` byte parity with the uninterrupted solve."""
    ck = tmp_path / "ck"
    killed = _run_cli(
        [_C4, "--devices", "8", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": point}, fake_devices=8,
    )
    assert killed.returncode != 0, killed.stdout[-500:]
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        [_C4, "--devices", str(resume_s), "--checkpoint-dir", str(ck),
         "--table-out", str(out)],
        fake_devices=resume_s,
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    _assert_tables_equal(out, c4_clean_table)


_NO_BACKEND = "Multiprocess computations aren't implemented"


@pytest.mark.slow
def test_chaos_world_shrink_2_to_1_resume_parity(tmp_path,
                                                 c4_clean_table):
    """W elasticity: a 2-process world (4 shards) killed on rank 0
    mid-forward, resumed by a SINGLE process at the same shard count —
    the W-rank tree adopted after the (normalized) consistency
    barrier, table byte parity."""
    if str(REPO) not in sys.path:
        sys.path.insert(0, str(REPO))
    from tools.launch_multihost import launch

    ck = tmp_path / "ck"
    results = launch(
        [_C4, "--devices", "4", "--checkpoint-dir", str(ck)],
        processes=2, log_dir=str(tmp_path / "logs"),
        env={"GAMESMAN_BARRIER_SECS": "20",
             "GAMESMAN_COLLECTIVE_TIMEOUT": "60"},
        per_rank_env={0: {"GAMESMAN_FAULTS": "sharded.forward:kill:3"}},
    )
    logs = " ".join((r.stderr or "") + (r.stdout or "") for r in results)
    if _NO_BACKEND in logs:
        pytest.skip("backend cannot run multiprocess collectives")
    assert results[0].returncode == faults.KILL_EXIT_CODE, logs[-2000:]
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        [_C4, "--devices", "4", "--checkpoint-dir", str(ck),
         "--table-out", str(out)],
        fake_devices=4,
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    _assert_tables_equal(out, c4_clean_table)
