"""Dense class-partitioned engine (solve/dense.py): parity + machinery.

The dense engine solves a superset of the reachable space through perfect
combinadic indexing; these tests pin (a) the rank/unrank machinery, (b)
full-value parity against the BFS engine on boards small enough to solve
both ways in CI, and (c) the exact reachable counts — 4x4's 161,029 is
Tromp's published count, so the reachability sweep is externally anchored
the same way the BFS engine's 5x5 count is.
"""

import numpy as np
import pytest

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.dense import (
    DenseSolver,
    DenseTables,
    n1_of_level,
)


def test_rank_unrank_roundtrip():
    rng = np.random.default_rng(0)
    for w, h in ((4, 3), (3, 4), (4, 4)):
        t = DenseTables(w, h)
        for L in (0, 1, 2, w * h // 2, w * h - 1, w * h):
            P = len(t.profiles[L])
            C = t.class_size[L]
            for _ in range(10):
                row = int(rng.integers(P))
                rank = int(rng.integers(C))
                bits = t.unrank_np(L, row, rank)
                assert bin(bits).count("1") == n1_of_level(L)
                assert t.rank_np(L, row, bits) == rank


def test_locate_roundtrips_reachable_states():
    g = get_game("connect4:w=3,h=3,connect=3")
    t = DenseTables(3, 3, 3)
    r = Solver(g).solve()
    for L, tab in r.levels.items():
        for s in tab.states[:50]:
            level, row, rank = t.locate(int(s))
            assert level == L
            # unrank must reproduce the player-1 stones of the state
            bits = t.unrank_np(level, row, rank)
            assert t.rank_np(level, row, bits) == rank


def test_dense_full_parity_3x3c3():
    g = get_game("connect4:w=3,h=3,connect=3")
    rc = Solver(g).solve()
    rd = DenseSolver(g).solve()
    assert (rd.value, rd.remoteness) == (rc.value, rc.remoteness)
    # Exact reachable count: the sweep must agree with BFS discovery.
    assert rd.num_positions == rc.num_positions
    checked = 0
    for _, tab in rc.levels.items():
        for s, v, rem in zip(tab.states, tab.values, tab.remoteness):
            assert rd.lookup(int(s)) == (int(v), int(rem))
            checked += 1
    assert checked == rc.num_positions


def test_dense_checkpoint_resume(tmp_path):
    """Restart-from-level for the backward sweep: a run that died after
    saving levels K..nc must rechain from K's cells without recomputing
    them, and a fully-checkpointed rerun must compute nothing."""
    from gamesmanmpi_tpu.utils import LevelCheckpointer

    g = get_game("connect4:w=3,h=3,connect=3")
    full = DenseSolver(g).solve()
    nc = full._tables.ncells

    # Simulate an interrupted run: persist only the top 4 levels.
    d = str(tmp_path / "dense_ck")
    ck = LevelCheckpointer(d)
    ck.bind_game(g.name + ":dense")
    for L in range(nc - 3, nc + 1):
        ck.save_dense_level(L, full.cells[L])

    resumed_solver = DenseSolver(g, checkpointer=LevelCheckpointer(d))
    orig = resumed_solver._backward_level

    def guarded(L, child_flat):
        assert L <= nc - 4, f"resume recomputed checkpointed level {L}"
        return orig(L, child_flat)

    resumed_solver._backward_level = guarded
    resumed = resumed_solver.solve()
    assert (resumed.value, resumed.remoteness, resumed.num_positions) == (
        full.value, full.remoteness, full.num_positions
    )
    for L in full.cells:
        assert np.array_equal(
            np.asarray(full.cells[L]), np.asarray(resumed.cells[L])
        ), L

    # Everything is now on disk: a second resume computes NOTHING.
    final_solver = DenseSolver(g, checkpointer=LevelCheckpointer(d))

    def poisoned(L, child_flat):
        raise AssertionError(f"fully-resumed solve recomputed level {L}")

    final_solver._backward_level = poisoned
    final = final_solver.solve()
    assert (final.value, final.remoteness) == (full.value, full.remoteness)

    # A different game must be refused loudly.
    with pytest.raises(ValueError, match="belongs to game"):
        DenseSolver(get_game("connect4:w=4,h=4"),
                    checkpointer=LevelCheckpointer(d)).solve()


def test_pallas_mesh_falls_back_until_chip_proven(monkeypatch):
    """devices>1 + gather_mode=pallas is exercised only in CPU interpret
    mode; on a real accelerator the Mosaic custom call's behaviour under
    auto-SPMD is chip-unproven (ADVICE r4), so the constructor must fall
    back to the plain XLA gather — with an env escape hatch for the
    chip-session step that will prove it."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 fake devices")
    import gamesmanmpi_tpu.solve.dense as dense_mod

    g = get_game("connect4:w=3,h=3,connect=3")
    monkeypatch.setenv("GAMESMAN_DENSE_GATHER", "pallas")
    monkeypatch.setattr(dense_mod.jax, "default_backend", lambda: "tpu")
    with pytest.warns(UserWarning, match="not yet chip-proven"):
        s = DenseSolver(g, devices=2)
    assert s.gather_mode == "plain"
    monkeypatch.setenv("GAMESMAN_DENSE_GATHER_PALLAS_MESH", "1")
    s2 = DenseSolver(g, devices=2)
    assert s2.gather_mode == "pallas"
    # Single-device pallas is chip-provable independently; no fallback.
    monkeypatch.delenv("GAMESMAN_DENSE_GATHER_PALLAS_MESH")
    assert DenseSolver(g).gather_mode == "pallas"


def test_dense_sharded_parity_3x3c3():
    """devices=4 partitions every level kernel's rank axis over the mesh;
    cells must be BIT-identical to the single-device engine (the same
    programs, just partitioned — any drift means the sharding changed
    semantics, not layout)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    g = get_game("connect4:w=3,h=3,connect=3")
    r1 = DenseSolver(g).solve()
    r4 = DenseSolver(g, devices=4).solve()
    assert (r4.value, r4.remoteness, r4.num_positions) == (
        r1.value, r1.remoteness, r1.num_positions
    )
    for L in r1.cells:
        assert np.array_equal(
            np.asarray(r1.cells[L]), np.asarray(r4.cells[L])
        ), L
    # Uneven split: a mesh width that does NOT divide the class sizes
    # exercises the rank-axis round-up padding.
    r3 = DenseSolver(g, devices=3).solve()
    assert (r3.value, r3.remoteness, r3.num_positions) == (
        r1.value, r1.remoteness, r1.num_positions
    )
    for L in r1.cells:
        assert np.array_equal(
            np.asarray(r1.cells[L]), np.asarray(r3.cells[L])
        ), L


@pytest.mark.slow
def test_dense_parity_4x4():
    g = get_game("connect4:w=4,h=4")
    rc = Solver(g).solve()
    rd = DenseSolver(g).solve()
    assert (rd.value, rd.remoteness) == (rc.value, rc.remoteness)
    # 161,029 is Tromp's published 4x4 legal-position count.
    assert rd.num_positions == rc.num_positions == 161029
    # Per-LEVEL reachable counts must match BFS discovery exactly, not
    # just the total (a compensating over/undercount pair would pass the
    # sum).
    for L, n in rd.stats["reachable_per_level"].items():
        assert n == rc.levels[L].states.shape[0], (L, n)
    rng = np.random.default_rng(7)
    for _, tab in rc.levels.items():
        n = tab.states.shape[0]
        for i in rng.choice(n, size=min(200, n), replace=False):
            assert rd.lookup(int(tab.states[i])) == (
                int(tab.values[i]), int(tab.remoteness[i])
            )


def test_device_rank_unrank_match_host_u64_board():
    # 4x7 needs (7+1)*4 = 32 > 31 state bits -> the uint64 kernel path the
    # 6x5 board ladder uses. A full u64 solve is too big for CI, but the
    # rank/unrank kernels themselves are one call.
    import jax
    import jax.numpy as jnp

    from gamesmanmpi_tpu.solve.dense import _rank_bits, _unrank_bits

    t = DenseTables(4, 7)
    assert t.bits_dtype == np.uint64
    L = 9
    P = len(t.profiles[L])
    C = t.class_size[L]
    rng = np.random.default_rng(3)
    cb = 32
    ranks = rng.integers(0, C, size=(P, cb), dtype=np.uint32)
    cellidx = np.ascontiguousarray(
        t.cellidx_rows(L).astype(np.int32).T
    )  # [ncells, P]
    binom = t.binom.astype(np.uint32)

    bits = jax.jit(lambda r: _unrank_bits(
        r, n1_of_level(L), jnp.asarray(binom), jnp.asarray(cellidx),
        [int(b) for b in t.bitpos], jnp.uint64, jnp.uint32, False,
    ))(jnp.asarray(ranks))
    back = jax.jit(lambda b: _rank_bits(
        b, jnp.asarray(binom), jnp.asarray(cellidx),
        [int(b2) for b2 in t.bitpos], jnp.uint64, jnp.uint32, False,
    ))(bits)
    bits_np = np.asarray(bits)
    back_np = np.asarray(back)
    for p in range(P):
        for i in range(cb):
            assert int(bits_np[p, i]) == t.unrank_np(L, p, int(ranks[p, i]))
            assert int(back_np[p, i]) == int(ranks[p, i])


def test_dense_full_parity_tall_board():
    # Taller-than-wide: every solve-parity board so far had w >= h; this
    # pins the (column, row) indexing asymmetry end to end.
    g = get_game("connect4:w=3,h=4,connect=3")
    rc = Solver(g).solve()
    rd = DenseSolver(g).solve()
    assert (rd.value, rd.remoteness) == (rc.value, rc.remoteness)
    assert rd.num_positions == rc.num_positions
    checked = 0
    for _, tab in rc.levels.items():
        for s, v, rem in zip(tab.states, tab.values, tab.remoteness):
            assert rd.lookup(int(s)) == (int(v), int(rem))
            checked += 1
    assert checked == rc.num_positions


def test_dense_rejects_sym_and_non_connect4():
    with pytest.raises(ValueError):
        DenseSolver(get_game("connect4:w=4,h=4,sym=1"))
    with pytest.raises(TypeError):
        DenseSolver(get_game("tictactoe"))


def test_dense_no_tables_mode():
    g = get_game("connect4:w=3,h=3,connect=3")
    rd = DenseSolver(g, store_tables=False).solve()
    assert rd.cells is None
    assert (rd.value, rd.remoteness) == (3, 9)  # TIE, remoteness 9
    with pytest.raises(KeyError):
        rd.lookup(int(g.initial_state()))


@pytest.mark.slow  # ~47 s CPU: full-solve A/B of the fused rank lowering
def test_dense_fused_rank_matches_simple(monkeypatch):
    # GAMESMAN_DENSE_RANK=fused is a pure lowering change (one walk for
    # all moves instead of per-move walks): every table cell must match.
    g = get_game("connect4:w=3,h=3,connect=3")
    simple = DenseSolver(g).solve()
    monkeypatch.setenv("GAMESMAN_DENSE_RANK", "fused")
    fused = DenseSolver(g).solve()
    assert (fused.value, fused.remoteness) == (simple.value,
                                              simple.remoteness)
    for L, cells in simple.cells.items():
        np.testing.assert_array_equal(fused.cells[L], cells)
    # And on a rectangular 5-column board (p1/p2 parity + wider fan-out),
    # level tables again identical.
    g2 = get_game("connect4:w=5,h=2")
    f2 = DenseSolver(g2).solve()
    monkeypatch.delenv("GAMESMAN_DENSE_RANK")
    s2 = DenseSolver(g2).solve()
    assert (f2.value, f2.remoteness, f2.num_positions) == (
        s2.value, s2.remoteness, s2.num_positions
    )
    for L, cells in s2.cells.items():
        np.testing.assert_array_equal(f2.cells[L], cells)


@pytest.mark.slow  # ~42 s CPU: full-solve A/B of the sorted-gather lowering
def test_dense_sorted_gather_matches_plain(monkeypatch):
    # GAMESMAN_DENSE_GATHER=sorted is a lowering hint (monotone fill for
    # invalid rows + pad lanes, indices_are_sorted gather): every cell of
    # every level table must match the plain gather, including with
    # blocking forced (pad lanes in every tail block).
    g = get_game("connect4:w=5,h=2")
    plain = DenseSolver(g).solve()
    monkeypatch.setenv("GAMESMAN_DENSE_GATHER", "sorted")
    srt = DenseSolver(g).solve()
    blocked = DenseSolver(g, block_elems=64).solve()
    # Both lowering flags together compose into a distinct program — the
    # combination a chip measurement run would plausibly enable.
    monkeypatch.setenv("GAMESMAN_DENSE_RANK", "fused")
    both = DenseSolver(g).solve()
    for L, cells in plain.cells.items():
        np.testing.assert_array_equal(srt.cells[L], cells)
        np.testing.assert_array_equal(blocked.cells[L], cells)
        np.testing.assert_array_equal(both.cells[L], cells)


@pytest.mark.slow  # ~147 s: pallas kernel emulated on CPU, full-solve A/B
def test_dense_pallas_gather_matches_plain(monkeypatch):
    # GAMESMAN_DENSE_GATHER=pallas routes the monotone fill through the
    # Mosaic monotone-window gather (interpret mode on CPU) with the
    # lax.cond miss fallback; every cell of every level table must match
    # the plain-gather solve. block_elems sized so the big 4x4 levels get
    # cblock >= PALLAS_BLOCK (the rounded, row-aligned fast path) while
    # small levels take the fallback — both paths in one solve.
    g = get_game("connect4:w=4,h=4")
    plain = DenseSolver(g, block_elems=150_000).solve()
    monkeypatch.setenv("GAMESMAN_DENSE_GATHER", "pallas")
    pal = DenseSolver(g, block_elems=150_000).solve()
    assert (pal.value, pal.remoteness, pal.num_positions) == (
        plain.value, plain.remoteness, plain.num_positions
    )
    for L, cells in plain.cells.items():
        np.testing.assert_array_equal(pal.cells[L], cells)


@pytest.mark.slow  # ~85 s: pallas int64 path emulated on CPU, full-solve A/B
def test_dense_pallas_gather_int64_flat_matches_plain(monkeypatch):
    # int64 flat index spaces (6x6+, where the gather win matters most)
    # are pallas-eligible since r5: the kernel wrapper derives
    # block-local int32 offsets outside Mosaic. A real int64 board does
    # not fit CI, so force the 6x6+ flat dtype on a 4x4 — the kernels are
    # keyed and built from _flat_dtype, so every index computation runs
    # the int64 program end to end.
    import jax.numpy as jnp

    g = get_game("connect4:w=4,h=4")
    plain = DenseSolver(g, block_elems=150_000).solve()
    monkeypatch.setenv("GAMESMAN_DENSE_GATHER", "pallas")
    pal64 = DenseSolver(g, block_elems=150_000)
    assert pal64.gather_mode == "pallas"
    assert pal64._flat_dtype == jnp.int32  # 4x4 is natively int32
    pal64._flat_dtype = jnp.int64
    r = pal64.solve()
    assert (r.value, r.remoteness, r.num_positions) == (
        plain.value, plain.remoteness, plain.num_positions
    )
    for L, cells in plain.cells.items():
        np.testing.assert_array_equal(r.cells[L], cells)


def test_dense_blocked_levels_match_unblocked():
    # Tiny block_elems forces nblk > 1 on every non-trivial level,
    # exercising the block concat + tail-slice path end to end.
    g = get_game("connect4:w=3,h=3,connect=3")
    whole = DenseSolver(g).solve()
    blocked = DenseSolver(g, block_elems=64).solve()
    assert (blocked.value, blocked.remoteness) == (whole.value,
                                                  whole.remoteness)
    for L, cells in whole.cells.items():
        np.testing.assert_array_equal(blocked.cells[L], cells)


def test_dense_lookup_refuses_garbage_positions():
    # 3x3 connect-3, level 6 (player 1 to move): player 1 already owns all
    # of column 0 (a vertical line) — not a position; the table cell there
    # is a placeholder and lookup must refuse it rather than serve it.
    g = get_game("connect4:w=3,h=3,connect=3")
    rd = DenseSolver(g).solve()
    garbage = 0b1111 | (1 << 6) | (1 << 9)  # heights (3,2,1), p1 = col 0
    with pytest.raises(KeyError):
        rd.lookup(garbage)
    # ...while a real position at the same level still answers.
    assert rd.lookup(int(g.initial_state())) == (rd.value, rd.remoteness)


def test_dense_counts_file_roundtrip(tmp_path, monkeypatch):
    from gamesmanmpi_tpu.solve import dense as dmod

    path = tmp_path / "counts.json"
    monkeypatch.setenv("GAMESMAN_DENSE_COUNTS_FILE", str(path))
    key = (3, 3, 3)
    counts = {0: 1, 1: 3, 2: 12}
    dmod._store_cached_counts(key, counts)
    assert dmod._load_cached_counts(key) == counts
    assert dmod._load_cached_counts((9, 9, 4)) is None

    # The sidecar feeds the benchmark numerator, so records are stamped:
    # an unstamped/foreign record (old engine, hand edit) must be refused
    # and re-swept, not trusted.
    import json

    data = json.loads(path.read_text())
    tag = dmod._counts_tag(key)
    assert data[tag]["version"] == dmod._COUNTS_SCHEMA_VERSION
    assert data[tag]["board"] == tag

    for tamper in (
        {tag: {"0": 1, "1": 3}},  # pre-stamp format
        {tag: {**data[tag], "version": -1}},  # wrong engine version
        {tag: {**data[tag], "board": "9x9x9"}},  # copied entry
        {tag: {**data[tag], "counts": {"0": 2, "1": 3}}},  # bad invariant
        {tag: {**data[tag], "counts": {"99": 5, "0": 1}}},  # level > cells
    ):
        path.write_text(json.dumps(tamper))
        assert dmod._load_cached_counts(key) is None

    # Disabled cache reads/writes nothing.
    monkeypatch.setenv("GAMESMAN_DENSE_COUNTS_FILE", "0")
    assert dmod._load_cached_counts(key) is None


def test_dense_count_cached_across_instances():
    g = get_game("connect4:w=3,h=3,connect=3")
    a = DenseSolver(g).solve()
    b = DenseSolver(g).solve()
    assert b.stats["secs_count_reachable"] == 0.0  # second solve reuses it
    assert a.num_positions == b.num_positions
