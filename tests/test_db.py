"""db/: solved-position database round-trip, conversion, integrity.

The DB is the persistence contract of SURVEY.md §1's by-product claim
("every reachable position is solved"): for each covered game,
solve → export → DbReader.lookup must reproduce the pure-Python oracle
exactly, for every reachable position, through the packed-cell codec.
"""

import json
import sys

import numpy as np
import pytest

from gamesmanmpi_tpu.core.values import MAX_REMOTENESS, WIN
from gamesmanmpi_tpu.db import (
    DbFormatError,
    DbReader,
    DbWriter,
    check_db,
    export_checkpoint,
    export_result,
)
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.oracle import oracle_solve
from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

from helpers import REF_GAMES, load_module

# (registry spec, reference-style scalar twin) — the oracle-parity axis.
CASES = [
    ("tictactoe", "tictactoe.py"),
    ("nim:heaps=3-4-5", "nim_345.py"),
    ("chomp:w=3,h=3", "chomp_33.py"),
]


@pytest.fixture(scope="module")
def solved(tmp_path_factory):
    """Lazy per-spec cache: (SolveResult, DbReader, oracle table, dir)."""
    built = {}

    def get(spec, ref_file):
        if spec not in built:
            d = tmp_path_factory.mktemp("db")
            result = Solver(get_game(spec)).solve()
            export_result(result, d, spec)
            _, _, oracle = oracle_solve(load_module(REF_GAMES / ref_file))
            built[spec] = (result, DbReader(d), oracle, d)
        return built[spec]

    yield get
    for _, reader, _, _ in built.values():
        reader.close()


@pytest.mark.parametrize("spec,ref_file", CASES)
def test_db_roundtrip_matches_oracle(solved, spec, ref_file):
    """solve → export-db → lookup == oracle for EVERY reachable position,
    remoteness included (the full range each game produces round-trips
    through pack_cells/unpack_cells)."""
    _, reader, oracle, _ = solved(spec, ref_file)
    positions = np.array(sorted(oracle), dtype=np.uint64)
    values, rem, found = reader.lookup(positions)
    assert found.all(), "reachable positions missing from the DB"
    for i, pos in enumerate(positions):
        assert (int(values[i]), int(rem[i])) == oracle[int(pos)], (
            f"{spec}: mismatch at {int(pos):#x}"
        )
    assert reader.num_positions == len(oracle)


@pytest.mark.parametrize("spec,ref_file", [CASES[0], CASES[1]])
def test_inprocess_query_and_db_agree(solved, spec, ref_file):
    """Regression for the unified canonicalize→probe path: the in-process
    --query route (SolveResult.lookup) and the DB route answer identically
    for every reachable position."""
    result, reader, oracle, _ = solved(spec, ref_file)
    positions = np.array(sorted(oracle), dtype=np.uint64)
    values, rem, found = reader.lookup(positions)
    assert found.all()
    for i, pos in enumerate(positions):
        assert result.lookup(int(pos)) == (int(values[i]), int(rem[i]))


@pytest.mark.parametrize("spec,ref_file", CASES)
def test_compressed_db_answers_identically(solved, tmp_path, spec,
                                           ref_file):
    """Format v2 (ISSUE 9) acceptance, per game: a block-compressed
    re-export is logically identical to the v1 DB (db_equal — levels,
    keys, cells) AND answers every reachable position identically
    through the decompress-on-probe reader."""
    from gamesmanmpi_tpu.db import DbReader, export_result
    from gamesmanmpi_tpu.db.check import db_equal

    result, v1_reader, oracle, v1_dir = solved(spec, ref_file)
    v2_dir = tmp_path / "v2"
    export_result(result, v2_dir, spec, compress=True)
    assert check_db(v2_dir) == []
    assert db_equal(v1_dir, v2_dir) == []
    positions = np.array(sorted(oracle), dtype=np.uint64)
    with DbReader(v2_dir) as v2_reader:
        a = v1_reader.lookup(positions)
        b = v2_reader.lookup(positions)
        for x, y in zip(a, b):
            assert np.array_equal(x, y), spec
        assert b[2].all()


def test_db_lookup_misses_and_empty(solved):
    _, reader, oracle, _ = solved(*CASES[0])
    # Unreachable (overlapping X/O planes) and out-of-table patterns miss.
    values, rem, found = reader.lookup(
        np.array([0b1_000000001, (1 << 18) - 1], dtype=np.uint64)
    )
    assert not found.any()
    assert (values == 0).all() and (rem == 0).all()
    v, r, f = reader.lookup(np.array([], dtype=np.uint64))
    assert v.shape == (0,) and r.shape == (0,) and f.shape == (0,)


def test_db_sym_reduced_answers_all_members(tmp_path):
    """A sym=1 DB stores only class representatives but must answer for
    every raw position: queries canonicalize before probing."""
    spec = "tictactoe:sym=1"
    result = Solver(get_game(spec)).solve()
    export_result(result, tmp_path / "db", spec)
    _, _, oracle = oracle_solve(load_module(REF_GAMES / "tictactoe.py"))
    module = load_module(REF_GAMES / "tictactoe.py")
    with DbReader(tmp_path / "db") as reader:
        assert reader.num_positions < len(oracle)  # genuinely reduced
        positions = np.array(sorted(oracle), dtype=np.uint64)
        values, rem, found = reader.lookup(positions)
        assert found.all()
        for i, pos in enumerate(positions):
            assert (int(values[i]), int(rem[i])) == oracle[int(pos)]
        # Best moves must be LEGAL from the raw queried position (not its
        # class representative) and optimal: remoteness steps down by 1.
        bvals, brem, bfound, best = reader.lookup_best(positions[:512])
        sentinel = int(reader.game.sentinel)
        legal_checked = 0
        for i, pos in enumerate(positions[:512]):
            b = int(best[i])
            if b == sentinel:
                assert oracle[int(pos)][1] == 0  # terminal: no move
                continue
            legal = {
                module.do_move(int(pos), mv)
                for mv in module.gen_moves(int(pos))
            }
            assert b in legal, f"best {b:#x} illegal from {int(pos):#x}"
            assert oracle[b][1] == oracle[int(pos)][1] - 1
            legal_checked += 1
        assert legal_checked > 100


def test_db_best_move_is_optimal(solved):
    """lookup_best returns a child realizing the parent's value/remoteness
    per the combine rules (WIN -> LOSE child at rem-1; LOSE/TIE -> max-
    remoteness child of the right value at rem-1)."""
    _, reader, oracle, _ = solved(*CASES[0])
    positions = np.array(sorted(oracle), dtype=np.uint64)
    values, rem, found, best = reader.lookup_best(positions)
    sentinel = int(reader.game.sentinel)
    checked = 0
    for i, pos in enumerate(positions):
        v, r = oracle[int(pos)]
        if r == 0:  # terminal: no move
            assert int(best[i]) == sentinel
            continue
        b = int(best[i])
        assert b != sentinel
        bv, br, bf = reader.lookup(np.array([b], dtype=np.uint64))
        assert bf[0]
        want_child = {1: 2, 2: 1, 3: 3}[v]  # WIN->LOSE, LOSE->WIN, TIE->TIE
        assert int(bv[0]) == want_child
        assert int(br[0]) == r - 1
        checked += 1
    assert checked > 100


def test_boundary_remoteness_roundtrip(tmp_path):
    """MAX_REMOTENESS survives the packed cell (30-bit field) bit-exactly;
    one past it is refused at write time rather than clipped."""
    game = get_game("tictactoe")
    w = DbWriter(tmp_path / "db", game, "tictactoe")
    states = np.array([0], dtype=game.state_dtype)  # level_of(0) == 0
    w.add_level(
        0,
        states,
        np.array([WIN], dtype=np.uint8),
        np.array([MAX_REMOTENESS], dtype=np.int32),
    )
    w.finalize()
    with DbReader(tmp_path / "db") as reader:
        values, rem, found = reader.lookup(states)
    assert found[0] and int(values[0]) == WIN
    assert int(rem[0]) == MAX_REMOTENESS

    w2 = DbWriter(tmp_path / "db2", game, "tictactoe")
    with pytest.raises(DbFormatError, match="remoteness"):
        w2.add_level(
            0,
            states,
            np.array([WIN], dtype=np.uint8),
            np.array([MAX_REMOTENESS + 1], dtype=np.int64),
        )


def test_writer_enforces_probe_invariants(tmp_path):
    game = get_game("tictactoe")
    w = DbWriter(tmp_path / "db", game, "tictactoe")
    with pytest.raises(DbFormatError, match="ascending"):
        w.add_level(
            1,
            np.array([2, 1], dtype=game.state_dtype),
            np.zeros(2, np.uint8) + 1,
            np.zeros(2, np.int32),
        )
    with pytest.raises(DbFormatError, match="dtype"):
        w.add_level(
            1,
            np.array([1], dtype=np.uint64),  # game is uint32
            np.ones(1, np.uint8),
            np.zeros(1, np.int32),
        )
    with pytest.raises(DbFormatError, match="sentinel"):
        w.add_level(
            1,
            np.array([0xFFFF_FFFF], dtype=np.uint32),
            np.ones(1, np.uint8),
            np.zeros(1, np.int32),
        )
    with pytest.raises(DbFormatError, match="empty"):
        w.finalize()
    # A real level seals it; a second writer refuses without overwrite.
    w.add_level(
        0, np.array([0], dtype=game.state_dtype),
        np.ones(1, np.uint8), np.zeros(1, np.int32),
    )
    w.finalize()
    with pytest.raises(DbFormatError, match="finalized"):
        DbWriter(tmp_path / "db", game, "tictactoe")
    # Overwrite stages into a sibling dir: until the new export FINALIZES,
    # the old database keeps serving (a crash mid-re-solve must not
    # destroy it); the swap replaces it wholesale, stale shards included.
    w3 = DbWriter(tmp_path / "db", game, "tictactoe", overwrite=True)
    assert (tmp_path / "db" / "manifest.json").exists()  # old DB intact
    w3.add_level(
        0, np.array([0], dtype=game.state_dtype),
        np.full(1, 3, np.uint8), np.zeros(1, np.int32),
    )
    w3.finalize()
    assert not list(tmp_path.glob("db.staging*"))  # swap cleaned up
    with DbReader(tmp_path / "db") as r:
        values, _, found = r.lookup(np.array([0], dtype=np.uint64))
    assert found[0] and int(values[0]) == 3  # the NEW cells serve
    # A FAILED overwrite export (abort before finalize) leaves the old
    # DB serving and no staging orphan.
    w4 = DbWriter(tmp_path / "db", game, "tictactoe", overwrite=True)
    w4.add_level(
        0, np.array([0], dtype=game.state_dtype),
        np.ones(1, np.uint8), np.zeros(1, np.int32),
    )
    w4.abort()
    assert not list(tmp_path.glob("db.staging*"))
    with DbReader(tmp_path / "db") as r:
        values, _, found = r.lookup(np.array([0], dtype=np.uint64))
    assert found[0] and int(values[0]) == 3  # still the w3 export


def test_reader_rejects_wrong_game_and_missing_manifest(solved, tmp_path):
    _, _, _, d = solved(*CASES[0])
    with pytest.raises(DbFormatError, match="belongs to game"):
        DbReader(d, game=get_game("tictactoe:m=4,n=4,k=4"))
    with pytest.raises(DbFormatError, match="manifest"):
        DbReader(tmp_path / "empty")


def test_export_checkpoint_conversion(tmp_path):
    """A past solve's --checkpoint-dir becomes a servable DB without
    re-solving, via the standalone tool; answers match the live result."""
    sys.path.insert(0, str(REF_GAMES.parent.parent / "tools"))
    try:
        import ckpt_to_db
    finally:
        sys.path.pop(0)
    ckpt_dir = tmp_path / "ckpt"
    result = Solver(
        get_game("tictactoe"), checkpointer=LevelCheckpointer(str(ckpt_dir))
    ).solve()
    rc = ckpt_to_db.main(
        [str(ckpt_dir), str(tmp_path / "db"), "--game", "tictactoe"]
    )
    assert rc == 0
    with DbReader(tmp_path / "db") as reader:
        assert reader.num_positions == result.num_positions
        for level, table in result.levels.items():
            values, rem, found = reader.lookup(table.states)
            assert found.all()
            assert (values == table.values).all()
            assert (rem == table.remoteness).all()
    # Wrong spec must be refused (the bound game name disagrees).
    rc = ckpt_to_db.main(
        [str(ckpt_dir), str(tmp_path / "db2"), "--game", "nim:heaps=3-4-5"]
    )
    assert rc == 2


def test_export_checkpoint_refuses_dense(tmp_path):
    ckpt = LevelCheckpointer(str(tmp_path / "dense"))
    ckpt.save_dense_level(0, np.zeros(4, dtype=np.uint8))
    with pytest.raises(DbFormatError, match="dense"):
        export_checkpoint(
            ckpt, get_game("connect4:w=3,h=3,k=3"),
            "connect4:w=3,h=3,k=3", tmp_path / "db",
        )


def test_cli_export_db_and_query(tmp_path, capsys):
    from gamesmanmpi_tpu.cli import main as cli_main

    d = str(tmp_path / "db")
    rc = cli_main(
        ["export-db", "subtract:total=10,moves=1-2", "--out", d]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "database written" in out
    assert "positions: 11" in out
    rc = cli_main(["query", d, "9", "0x3", "77", "zz"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "query 9: value=LOSE remoteness=6 best=0x8" in out
    assert "query 0x3: value=LOSE" in out
    assert "query 77: invalid position" in out  # outside 4-bit state space
    assert "query zz: invalid position" in out
    # Existing DB refused without --overwrite, replaced with it.
    rc = cli_main(
        ["export-db", "subtract:total=10,moves=1-2", "--out", d]
    )
    assert rc == 2
    assert "already holds" in capsys.readouterr().err
    rc = cli_main(
        ["export-db", "subtract:total=10,moves=1-2", "--out", d,
         "--overwrite"]
    )
    assert rc == 0


def test_cli_export_db_from_checkpoint(tmp_path, capsys):
    from gamesmanmpi_tpu.cli import main as cli_main

    ckpt = str(tmp_path / "ckpt")
    rc = cli_main(
        ["subtract:total=10,moves=1-2", "--checkpoint-dir", ckpt]
    )
    assert rc == 0
    capsys.readouterr()
    rc = cli_main(
        ["export-db", "subtract:total=10,moves=1-2",
         "--out", str(tmp_path / "db"), "--from-checkpoint", ckpt]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "positions: 11" in out
    rc = cli_main(["query", str(tmp_path / "db"), "10"])
    assert rc == 0
    assert "value=WIN remoteness=7" in capsys.readouterr().out


def test_flat_cli_unchanged_by_subcommands(capsys):
    """The flat solve CLI parses exactly as before the subcommands."""
    from gamesmanmpi_tpu.cli import main as cli_main

    rc = cli_main(["subtract:total=10,moves=1-2", "--query", "9"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "value: WIN" in out
    assert "query 9: value=LOSE" in out


def test_check_db_catches_corruption(solved, tmp_path, capsys):
    sys.path.insert(0, str(REF_GAMES.parent.parent / "tools"))
    try:
        import check_db as check_db_tool
    finally:
        sys.path.pop(0)
    _, _, _, good = solved(*CASES[0])
    assert check_db(good) == []
    assert check_db_tool.main([str(good), "--quiet"]) == 0

    # Copy then corrupt one cells byte: the checksum must catch it.
    import shutil

    bad = tmp_path / "bad"
    shutil.copytree(good, bad)
    manifest = json.loads((bad / "manifest.json").read_text())
    victim = bad / next(iter(manifest["levels"].values()))["cells"]
    raw = bytearray(victim.read_bytes())
    raw[-1] ^= 0xFF
    victim.write_bytes(raw)
    problems = check_db(bad)
    assert problems and "checksum" in problems[0]
    assert check_db_tool.main([str(bad), "--quiet"]) == 1

    # Unsorted keys (with refreshed checksum) caught by the sort check.
    bad2 = tmp_path / "bad2"
    shutil.copytree(good, bad2)
    manifest = json.loads((bad2 / "manifest.json").read_text())
    rec = manifest["levels"]["1"]
    keys_path = bad2 / rec["keys"]
    keys = np.load(keys_path)
    np.save(keys_path, keys[::-1].copy())
    from gamesmanmpi_tpu.db.format import file_sha256, write_manifest

    rec["keys_sha256"] = file_sha256(keys_path)
    write_manifest(bad2, manifest)
    assert any("ascending" in p for p in check_db(bad2))


def test_jsonl_logger_context_manager(tmp_path):
    """The logger closes its handle on exceptions (satellite: context
    manager), and TeeLogger propagates the close."""
    from gamesmanmpi_tpu.utils.metrics import JsonlLogger, TeeLogger

    path = tmp_path / "m.jsonl"
    with pytest.raises(RuntimeError):
        with JsonlLogger(str(path)) as logger:
            logger.log({"phase": "x"})
            raise RuntimeError("boom")
    assert logger._fh.closed
    assert "x" in path.read_text()

    inner = JsonlLogger(str(tmp_path / "t.jsonl"))
    with TeeLogger(inner, None) as tee:
        tee.log({"phase": "y"})
    assert inner._fh.closed
