"""ISSUE 14: fused level megakernel + ping-pong pipeline.

Byte-parity matrix over ttt/nim/chomp/connect4 on the single-device
engine and the sharded engine (both backward modes), ops-level parity of
the fused rank/sort+dedup stage against its unfused twins (both
lowerings), the connect4 bitboard decompose A/B, and the dispatch-economy
asserts: the fused fast path spends exactly ONE forward megakernel
dispatch per level (zero extra, via the new counter) and at least halves
dispatches-per-level against the unfused arm.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.ops.dedup import sort_unique
from gamesmanmpi_tpu.ops.fused import (
    fused_dedup_provenance,
    fused_sort_unique,
)
from gamesmanmpi_tpu.ops.mergesort import sort_rank
from gamesmanmpi_tpu.ops.provenance import dedup_provenance
from gamesmanmpi_tpu.solve import Solver

from helpers import full_table


def _fused_env(monkeypatch, pipeline="pingpong"):
    monkeypatch.setenv("GAMESMAN_FUSED", "1")
    monkeypatch.setenv("GAMESMAN_PIPELINE", pipeline)


# ------------------------------------------------------------- ops parity


def _rand_children(n=4096, dup_space=512, seed=7, dtype=np.uint32):
    rng = np.random.default_rng(seed)
    flat = rng.integers(0, dup_space, size=n).astype(dtype)
    sent = np.iinfo(dtype).max
    flat[rng.random(n) < 0.15] = sent  # masked-move sentinels
    return flat, sent


@pytest.mark.parametrize("method", ["callback", "scatterinv"])
def test_fused_sort_unique_parity(method):
    flat, _ = _rand_children()
    base_u, base_c = jax.jit(sort_unique)(jnp.asarray(flat))
    fu, fc = jax.jit(
        lambda f: fused_sort_unique(f, None, method)
    )(jnp.asarray(flat))
    assert int(base_c) == int(fc)
    np.testing.assert_array_equal(np.asarray(base_u), np.asarray(fu))


@pytest.mark.parametrize("method", ["callback", "scatterinv"])
def test_fused_dedup_provenance_parity(method):
    flat, _ = _rand_children(seed=11)
    bu, bc, bi = jax.jit(dedup_provenance)(jnp.asarray(flat))
    fu, fc, fi = jax.jit(
        lambda f: fused_dedup_provenance(f, None, method)
    )(jnp.asarray(flat))
    assert int(bc) == int(fc)
    np.testing.assert_array_equal(np.asarray(bu), np.asarray(fu))
    np.testing.assert_array_equal(np.asarray(bi), np.asarray(fi))


def test_fused_callback_count_limit():
    """nvalid: slots past the count must be ignored by the callback dedup
    exactly as sentinel slots are (the engines guarantee they ARE
    sentinel; here we plant garbage to prove the limit is real)."""
    flat, sent = _rand_children(seed=13)
    n = 1000
    garbage = flat.copy()
    garbage[n:] = 123456789  # non-sentinel garbage beyond the count
    ref = flat.copy()
    ref[n:] = sent
    bu, bc = jax.jit(sort_unique)(jnp.asarray(ref))
    fu, fc = jax.jit(
        lambda f, nn: fused_sort_unique(f, nn, "callback")
    )(jnp.asarray(garbage), jnp.int32(n))
    assert int(bc) == int(fc)
    np.testing.assert_array_equal(np.asarray(bu), np.asarray(fu))


def test_sort_rank_inverts_permutation():
    flat, _ = _rand_children(seed=17)
    s, rank_back = jax.jit(sort_rank)(jnp.asarray(flat))
    s, rank_back = np.asarray(s), np.asarray(rank_back)
    # s must be the sorted input, and rank_back must route every input
    # slot to its own value's position in s.
    np.testing.assert_array_equal(s, np.sort(flat))
    np.testing.assert_array_equal(s[rank_back], flat)


# --------------------------------------------------- engine parity matrix


ENGINE_SPECS = [
    "tictactoe",                 # fast path, dihedral symmetry
    "connect4:w=4,h=4",          # fast path, value-table backward
    "connect4:w=4,h=3,sym=1",    # fast path + mirror canonicalize
    "nim:heaps=3-4-5",           # generic path (multi-jump)
    "chomp:w=3,h=3",             # generic path, widest max_moves
]


@pytest.mark.parametrize("spec", ENGINE_SPECS)
def test_engine_fused_full_parity(monkeypatch, spec):
    base = Solver(get_game(spec), paranoid=True).solve()
    _fused_env(monkeypatch)
    fused = Solver(get_game(spec), paranoid=True).solve()
    assert (fused.value, fused.remoteness) == (base.value, base.remoteness)
    assert fused.num_positions == base.num_positions
    assert full_table(fused) == full_table(base)
    assert fused.stats["fused"] is True


#: The gamedsl acceptance matrix (ISSUE 16): description-only games must
#: survive BOTH fused dedup lowerings byte-for-byte, not just the default
#: one for the platform — a compiled game is only "wired through" if the
#: megakernel path treats it exactly like a hand-written module.
GAMEDSL_SPECS = [
    "examples/specs/gomoku_4x3x3.json",      # place family, exact-k
    "examples/specs/mnk_3x3x3_misere.json",  # misere + symmetry group
    "examples/specs/connect4_4x4.json",      # drop family
]
_gamedsl_base = {}  # unfused reference solves, shared across the matrix


@pytest.mark.parametrize("dedup", ["callback", "scatterinv"])
@pytest.mark.parametrize("relpath", GAMEDSL_SPECS)
def test_engine_fused_gamedsl_parity(monkeypatch, relpath, dedup):
    import pathlib

    from helpers import REPO, table_sha256

    spec = str(pathlib.Path(REPO) / relpath)
    if relpath not in _gamedsl_base:
        _gamedsl_base[relpath] = Solver(get_game(spec),
                                        paranoid=True).solve()
    base = _gamedsl_base[relpath]
    _fused_env(monkeypatch)
    monkeypatch.setenv("GAMESMAN_FUSED_DEDUP", dedup)
    fused = Solver(get_game(spec), paranoid=True).solve()
    assert (fused.value, fused.remoteness) == (base.value, base.remoteness)
    assert fused.num_positions == base.num_positions
    assert table_sha256(fused) == table_sha256(base)
    assert fused.stats["fused"] is True


def test_engine_fused_level_pipeline_parity(monkeypatch):
    """GAMESMAN_PIPELINE=level under fusion: same tables, no deferral."""
    base = Solver(get_game("connect4:w=4,h=4")).solve()
    _fused_env(monkeypatch, pipeline="level")
    fused = Solver(get_game("connect4:w=4,h=4"), paranoid=True).solve()
    assert full_table(fused) == full_table(base)
    assert fused.stats["overlap_secs"] == 0.0


def test_engine_fused_store_tables_false(monkeypatch):
    """Big-run mode (the bench config): root-only materialization."""
    base = Solver(get_game("connect4:w=4,h=4")).solve()
    _fused_env(monkeypatch)
    lean = Solver(get_game("connect4:w=4,h=4"), store_tables=False).solve()
    assert (lean.value, lean.remoteness) == (base.value, base.remoteness)
    assert lean.num_positions == base.num_positions
    assert len(lean.levels) == 1  # root only


def test_engine_fused_provenance_mode_parity(monkeypatch):
    """Games outside the value-table gate (or with it disabled) take the
    fused forward + gather-only provenance backward; tables must still be
    byte-identical."""
    base = Solver(get_game("connect4:w=4,h=4")).solve()
    _fused_env(monkeypatch)
    monkeypatch.setenv("GAMESMAN_FUSED_TABLE_BITS", "0")  # force off
    fused = Solver(get_game("connect4:w=4,h=4"), paranoid=True).solve()
    assert full_table(fused) == full_table(base)


def test_engine_fused_blocked_backward_parity(monkeypatch):
    """Wide levels resolve in column blocks against the same cells table."""
    base = Solver(get_game("tictactoe")).solve()
    _fused_env(monkeypatch)
    blocked = Solver(get_game("tictactoe"), paranoid=True)
    blocked.backward_block = 256
    result = blocked.solve()
    assert full_table(result) == full_table(base)


# ------------------------------------------------------- dispatch economy


def test_fused_forward_single_dispatch_per_level(monkeypatch):
    """The megakernel claim, asserted via the new counter: the fused fast
    path spends exactly ONE forward megakernel dispatch per discovered
    level — zero extra dispatches — and the backward resolve is one
    table kernel per level."""
    _fused_env(monkeypatch)
    solver = Solver(get_game("connect4:w=4,h=4"), store_tables=False)
    solver.solve()
    # store_tables=False keeps only the root level table; count levels
    # from the per-level dispatch breakdown instead.
    fwd_levels = {lvl for ph, lvl in solver.level_dispatches if
                  ph == "forward"}
    assert solver.dispatch_by_kind["fwdm"] == len(fwd_levels)
    # one bwdt per non-checkpointed level (no bwdc here), no bwd/bwdp
    assert solver.dispatch_by_kind.get("bwd", 0) == 0
    assert solver.dispatch_by_kind.get("bwdp", 0) == 0
    assert solver.dispatch_by_kind["bwdt"] == len(fwd_levels)


def test_fused_halves_dispatches_per_level(monkeypatch):
    """Acceptance gate: >= 2x fewer dispatches per level than unfused."""
    unfused = Solver(get_game("connect4:w=4,h=4"), store_tables=False)
    ru = unfused.solve()
    _fused_env(monkeypatch)
    fused = Solver(get_game("connect4:w=4,h=4"), store_tables=False)
    rf = fused.solve()
    assert rf.stats["dispatches_per_level"] * 2 \
        <= ru.stats["dispatches_per_level"]
    assert rf.stats["dispatches_total"] * 2 <= ru.stats["dispatches_total"]


def test_dispatch_counter_registry_series():
    """gamesman_dispatches_total{phase} grows with a solve."""
    from gamesmanmpi_tpu.obs import default_registry

    reg = default_registry()
    game = get_game("tictactoe")
    before = reg.counter(
        "gamesman_dispatches_total",
        phase="forward", game=game.name,
    ).value
    Solver(game).solve()
    after = reg.counter(
        "gamesman_dispatches_total",
        phase="forward", game=game.name,
    ).value
    assert after > before


# ----------------------------------------------------------- sharded

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (fake) devices"
)


@needs_mesh
@pytest.mark.parametrize("backward", ["lookup", "edges"])
@pytest.mark.parametrize("spec", ["connect4:w=4,h=3", "nim:heaps=3-4-5"])
def test_sharded_fused_parity(monkeypatch, spec, backward):
    from gamesmanmpi_tpu.parallel import ShardedSolver

    base = Solver(get_game(spec), paranoid=True).solve()
    monkeypatch.setenv("GAMESMAN_BACKWARD", backward)
    _fused_env(monkeypatch)
    fused = ShardedSolver(get_game(spec), num_shards=4,
                          paranoid=True).solve()
    assert (fused.value, fused.remoteness) == (base.value, base.remoteness)
    assert full_table(fused) == full_table(base)
    assert fused.stats["fused"] is True


# ------------------------------------------------------ checkpoint paths


def test_fused_checkpoint_and_resume_parity(monkeypatch, tmp_path):
    """Fused solves checkpoint like unfused ones, and a second run over
    the same tree resumes through the bwdc cell-scatter path (loaded
    levels fold into the value table without resolving) to identical
    tables."""
    from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

    base = Solver(get_game("connect4:w=4,h=3"), paranoid=True).solve()
    _fused_env(monkeypatch)
    ck = LevelCheckpointer(str(tmp_path / "ck"))
    first = Solver(get_game("connect4:w=4,h=3"), paranoid=True,
                   checkpointer=ck).solve()
    assert full_table(first) == full_table(base)
    # Resume: every level completed — the backward must LOAD, not solve.
    ck2 = LevelCheckpointer(str(tmp_path / "ck"))
    resumed_solver = Solver(get_game("connect4:w=4,h=3"), paranoid=True,
                            checkpointer=ck2)
    resumed = resumed_solver.solve()
    assert full_table(resumed) == full_table(base)
    assert resumed_solver.dispatch_by_kind.get("bwdt", 0) == 0  # all loaded
    assert resumed_solver.dispatch_by_kind.get("bwdc", 0) > 0


# ------------------------------------------------- connect4 bitboard A/B


@pytest.mark.parametrize("wh", [(4, 4), (5, 4), (7, 6)])
def test_connect4_bitboard_decompose_parity(wh):
    """The whole-word masked-smear decompose must be bit-identical to the
    per-column msb loop on every REACHABLE state shape (random playouts;
    garbage lanes are out of contract — the engines mask them)."""
    w, h = wh
    game = get_game(f"connect4:w={w},h={h}")
    rng = np.random.default_rng(3)
    states = [int(game.initial_state())]
    frontier = [int(game.initial_state())]
    for _ in range(min(w * h, 12)):
        batch = np.asarray(frontier, dtype=game.state_dtype)
        kids, mask = jax.jit(game.expand)(jnp.asarray(batch))
        kids, mask = np.asarray(kids), np.asarray(mask)
        nxt = list(np.unique(kids[mask]))
        if not nxt:
            break
        rng.shuffle(nxt)
        frontier = nxt[:256]
        states.extend(frontier)
    batch = jnp.asarray(np.asarray(states, dtype=game.state_dtype))
    fast = jax.jit(game._decompose)(batch)
    ref = jax.jit(game._decompose_loop)(batch)
    for a, b, name in zip(fast, ref,
                          ("guards", "filled", "current", "opponent")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_connect4_bitboard_solve_parity(monkeypatch):
    """End-to-end: bitboard on/off produce identical full tables (the
    flag is part of cache_key, so kernels cannot cross-contaminate)."""
    base = Solver(get_game("connect4:w=4,h=3"), paranoid=True).solve()
    monkeypatch.setenv("GAMESMAN_C4_BITBOARD", "0")
    loop = Solver(get_game("connect4:w=4,h=3"), paranoid=True).solve()
    assert full_table(loop) == full_table(base)
