"""utils/: metrics JSONL, checkpoint/resume, profiler hook, CLI."""

import json

import numpy as np
import pytest

from gamesmanmpi_tpu.cli import main as cli_main
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.utils import JsonlLogger, LevelCheckpointer, maybe_profile
from gamesmanmpi_tpu.utils.checkpoint import save_result_npz

from helpers import REF_GAMES


def test_jsonl_logger_and_solver_records(tmp_path):
    path = tmp_path / "metrics.jsonl"
    logger = JsonlLogger(str(path))
    result = Solver(get_game("subtract:total=10,moves=1-2"), logger=logger).solve()
    logger.close()
    records = [json.loads(line) for line in path.read_text().splitlines()]
    phases = {r["phase"] for r in records}
    assert {"forward", "backward", "done"} <= phases
    done = [r for r in records if r["phase"] == "done"][0]
    assert done["positions"] == result.num_positions
    assert done["positions_per_sec"] > 0


def test_checkpoint_roundtrip(tmp_path):
    ckpt = LevelCheckpointer(str(tmp_path / "ckpt"))
    result = Solver(get_game("tictactoe"), checkpointer=ckpt).solve()
    assert sorted(ckpt.completed_levels()) == sorted(result.levels.keys())
    for level, table in result.levels.items():
        loaded = ckpt.load_level(level)
        assert (loaded.states == table.states).all()
        assert (loaded.values == table.values).all()
        assert (loaded.remoteness == table.remoteness).all()


def test_save_result_npz(tmp_path):
    result = Solver(get_game("subtract:total=10,moves=1-2")).solve()
    out = tmp_path / "table.npz"
    save_result_npz(str(out), result)
    with np.load(out) as z:
        names = set(z.files)
    assert any(n.startswith("states_") for n in names)
    assert any(n.startswith("cells_") for n in names)


def test_maybe_profile_noop_and_trace(tmp_path):
    with maybe_profile(None):
        pass
    with maybe_profile(str(tmp_path / "trace")):
        Solver(get_game("subtract:total=5,moves=1-2")).solve()
    assert any((tmp_path / "trace").iterdir())


def test_cli_builtin_game(capsys):
    rc = cli_main(["tictactoe"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "value: TIE" in out
    assert "remoteness: 9" in out
    assert "positions: 5478" in out


def test_cli_sharded(capsys):
    rc = cli_main(["subtract:total=10,moves=1-2", "--devices", "4"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "value: WIN" in out


def test_cli_compat_module(capsys):
    rc = cli_main([str(REF_GAMES / "tictactoe.py")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "value: TIE" in out
    assert "remoteness: 9" in out


def test_checkpoint_resume_skips_recompute(tmp_path):
    """Restart-from-level: a resumed solve must not re-expand or re-resolve."""
    d = str(tmp_path / "resume")
    first = Solver(get_game("tictactoe"), checkpointer=LevelCheckpointer(d)).solve()
    resumed_solver = Solver(
        get_game("tictactoe"), checkpointer=LevelCheckpointer(d)
    )
    # Poison the compute paths: resume must never touch them.
    def _poisoned(*a, **k):
        raise AssertionError("resume recomputed a level")

    resumed_solver._fwdp = _poisoned
    resumed_solver._fwd_generic = _poisoned
    resumed_solver._bwd = _poisoned
    resumed_solver._bwdp = _poisoned
    resumed = resumed_solver.solve()
    assert resumed.value == first.value
    assert resumed.remoteness == first.remoteness
    assert resumed.num_positions == first.num_positions


def test_manifest_writes_are_atomic(tmp_path):
    """A peer process may read the manifest while process 0 seals levels;
    every read must parse (old or new content, never torn). The
    truncate-in-place write this replaces crashed a two-process run with
    JSONDecodeError mid-seal (round 4). Threads stand in for processes —
    same file, same syscalls."""
    import threading

    ckpt = LevelCheckpointer(str(tmp_path / "atomic"))
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                ckpt.load_manifest()
            except Exception as e:  # torn read
                errors.append(e)
                return

    t = threading.Thread(target=reader)
    t.start()
    try:
        for i in range(300):
            ckpt.finish_forward_level(i, 4)
    finally:
        stop.set()
        t.join()
    assert not errors
    assert len(ckpt.load_manifest()["forward_level_shards"]) == 300
    # No temp files left behind.
    assert not list((tmp_path / "atomic").glob("*.tmp"))


def test_forward_checkpoint_resume_mid_forward(tmp_path):
    """A run killed mid-DISCOVERY resumes from the deepest saved frontier.

    Forward alone is a multi-hour phase at big-board scale — longer than
    the environment's relay MTBF — so frontiers are checkpointed per level
    as discovered, not only after the sweep completes (the r04 gap the 6x6
    feasibility analysis named). The kill lands after level 3's save; the
    resumed run must re-expand only from level 3 down and still match the
    uncheckpointed solve exactly.
    """
    d = str(tmp_path / "fwd_resume")
    full = Solver(get_game("tictactoe")).solve()

    class _Die(Exception):
        pass

    ckpt = LevelCheckpointer(d)
    orig = LevelCheckpointer.save_frontier_level

    def dying(level, states):
        orig(ckpt, level, states)
        if level >= 3:
            raise _Die()

    ckpt.save_frontier_level = dying
    with pytest.raises(_Die):
        Solver(get_game("tictactoe"), checkpointer=ckpt).solve()
    assert LevelCheckpointer(d).load_manifest()["forward_levels"] == [0, 1, 2, 3]

    resumed_ckpt = LevelCheckpointer(d)
    saved_during_resume = []

    def recording(level, states):
        saved_during_resume.append(level)
        orig(resumed_ckpt, level, states)

    resumed_ckpt.save_frontier_level = recording
    resumed = Solver(get_game("tictactoe"), checkpointer=resumed_ckpt).solve()
    # Levels 0-3 came from disk: only 4+ are newly discovered and saved.
    assert saved_during_resume and min(saved_during_resume) == 4
    assert (resumed.value, resumed.remoteness) == (full.value, full.remoteness)
    assert resumed.num_positions == full.num_positions
    for level, table in full.levels.items():
        rt = resumed.levels[level]
        assert (rt.states == table.states).all()
        assert (rt.values == table.values).all()
        assert (rt.remoteness == table.remoteness).all()
    # A third run resumes the COMPLETED forward without any discovery.
    assert LevelCheckpointer(d).load_frontiers() is not None


def test_sharded_forward_checkpoint_resume_mid_forward(tmp_path):
    """Sharded analog of the mid-discovery resume: per-(level, shard)
    frontier files keep the prefix; completion consolidates into the
    per-shard snapshot and drops the now-redundant incremental files."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    from gamesmanmpi_tpu.parallel import ShardedSolver

    d = str(tmp_path / "fwd_resume_sharded")
    full = ShardedSolver(get_game("tictactoe"), num_shards=4).solve()

    class _Die(Exception):
        pass

    ckpt = LevelCheckpointer(d)
    orig = LevelCheckpointer.save_forward_level_shard

    def dying(level, shard, states):
        orig(ckpt, level, shard, states)
        # Level 3's files all land but the level is never SEALED — the
        # resume must treat it as absent and re-expand from level 2.
        if level >= 3 and shard == 3:
            raise _Die()

    ckpt.save_forward_level_shard = dying
    with pytest.raises(_Die):
        ShardedSolver(get_game("tictactoe"), num_shards=4,
                      checkpointer=ckpt).solve()
    sealed = LevelCheckpointer(d).load_manifest()["forward_level_shards"]
    assert sorted(int(k) for k in sealed) == [0, 1, 2]

    resumed_ckpt = LevelCheckpointer(d)
    saved_during_resume = []

    def recording(level, shard, states):
        saved_during_resume.append(level)
        orig(resumed_ckpt, level, shard, states)

    resumed_ckpt.save_forward_level_shard = recording
    resumed = ShardedSolver(get_game("tictactoe"), num_shards=4,
                            checkpointer=resumed_ckpt).solve()
    assert saved_during_resume and min(saved_during_resume) == 3
    assert (resumed.value, resumed.remoteness) == (full.value, full.remoteness)
    assert resumed.num_positions == full.num_positions
    # Completion consolidated the snapshot and dropped the incrementals.
    manifest = LevelCheckpointer(d).load_manifest()
    assert manifest.get("frontier_shards") == 4
    assert "forward_level_shards" not in manifest
    import os as _os

    assert not [f for f in _os.listdir(d) if f.startswith("frontier_")]


def test_checkpoint_resume_sharded(tmp_path):
    import jax

    if len(jax.devices()) < 4:
        import pytest

        pytest.skip("needs 4 fake devices")
    from gamesmanmpi_tpu.parallel import ShardedSolver

    d = str(tmp_path / "resume_sharded")
    first = ShardedSolver(
        get_game("nim:heaps=3-4-5"), num_shards=4,
        checkpointer=LevelCheckpointer(d),
    ).solve()
    resumed = ShardedSolver(
        get_game("nim:heaps=3-4-5"), num_shards=4,
        checkpointer=LevelCheckpointer(d),
    )
    # Poison the step builders: resume must not recompile/run any level.
    def _poisoned(*a, **k):
        raise AssertionError("sharded resume recomputed a level")

    resumed._forward_fn = _poisoned
    resumed._backward_fn = _poisoned
    result = resumed.solve()
    assert (result.value, result.remoteness) == (first.value, first.remoteness)


def test_forward_level_shards_torn_dir_keeps_intact_prefix(tmp_path):
    """A manifest that seals a level whose shard files are gone (torn
    directory) must not abort the resumed solve with FileNotFoundError
    (ADVICE r4); it degrades to the intact contiguous-from-root prefix —
    at big-run scale that prefix is hours of re-discovery."""
    ckpt = LevelCheckpointer(str(tmp_path / "torn"))
    for level in range(3):
        for s in range(2):
            ckpt.save_forward_level_shard(
                level, s, np.arange(4, dtype=np.uint32))
        ckpt.finish_forward_level(level, 2)
    assert set(ckpt.load_forward_level_shards(2)) == {0, 1, 2}
    (ckpt.dir / "frontier_0001.shard_0000.npz").unlink()
    # Torn at level 1: level 0 survives, 1+ (and anything above the tear)
    # re-run. The result stays contiguous-from-root — _forward_fast's
    # resume contract.
    assert set(ckpt.load_forward_level_shards(2)) == {0}
    (ckpt.dir / "frontier_0000.shard_0001.npz").unlink()
    assert ckpt.load_forward_level_shards(2) == {}


def test_drop_forward_level_shards_manifest_before_unlink(tmp_path):
    """drop must pop the manifest entries and persist BEFORE unlinking:
    a death in between leaves orphan files (harmless), never sealed
    entries pointing at deleted files (ADVICE r4). Simulated by making
    the first unlink die."""
    from pathlib import Path

    ckpt = LevelCheckpointer(str(tmp_path / "drop_order"))
    for s in range(2):
        ckpt.save_forward_level_shard(0, s, np.arange(4, dtype=np.uint32))
    ckpt.finish_forward_level(0, 2)

    class _Die(Exception):
        pass

    orig_unlink = Path.unlink

    def dying_unlink(self, *a, **k):
        if self.name.startswith("frontier_"):
            raise _Die()
        return orig_unlink(self, *a, **k)

    Path.unlink = dying_unlink
    try:
        with pytest.raises(_Die):
            ckpt.drop_forward_level_shards()
    finally:
        Path.unlink = orig_unlink
    fresh = LevelCheckpointer(str(tmp_path / "drop_order"))
    # Manifest entries are gone even though the files survive; a resumed
    # run re-runs forward instead of crashing on the sealed entries.
    assert "forward_level_shards" not in fresh.load_manifest()
    assert fresh.load_forward_level_shards(2) == {}


def test_paranoid_catches_zero_move_undecided():
    """A non-primitive position with no legal moves must trip --paranoid."""
    import pytest

    from gamesmanmpi_tpu.games.subtract import Subtract
    from gamesmanmpi_tpu.core.values import UNDECIDED
    import jax.numpy as jnp

    class BrokenGame(Subtract):
        # primitive() never fires, so position 0 is UNDECIDED with no moves.
        def primitive(self, states):
            return jnp.full(states.shape, UNDECIDED, dtype=jnp.uint8)

    from gamesmanmpi_tpu.solve.engine import SolverError

    with pytest.raises(SolverError, match="consistency"):
        Solver(BrokenGame(total=4, moves=(1, 2)), paranoid=True).solve()


def test_tensorized_module_requires_level_fn():
    """level_of cannot be auto-derived (a global invariant, see
    compat.solve_module_jitted); max_moves CAN (probe + grow-and-retry)."""
    import pytest

    from gamesmanmpi_tpu.compat import TensorizedModule, load_game_module

    module = load_game_module(REF_GAMES / "ten_to_zero.py")
    with pytest.raises(ValueError, match="level"):
        TensorizedModule(module)


def test_cli_compat_warns_on_unsupported_flags(tmp_path, capsys):
    rc = cli_main(
        [
            str(REF_GAMES / "ten_to_zero.py"),
            "--devices",
            "4",
            "--table-out",
            str(tmp_path / "t.npz"),
            "--jsonl",
            str(tmp_path / "m.jsonl"),
        ]
    )
    captured = capsys.readouterr()
    assert rc == 0
    assert "needs the tensorized compat path" in captured.err
    assert (tmp_path / "t.npz").exists()
    assert "done" in (tmp_path / "m.jsonl").read_text()


def test_cli_tensorized_compat_module(tmp_path, capsys):
    """A module declaring level_of + max_moves drives the real engine
    (solver flags work; no host-solve warning)."""
    mod = tmp_path / "ttz_t.py"
    mod.write_text(
        "initial_position = 10\n"
        "max_moves = 2\n"
        "max_level_jump = 2\n"
        "def level_of(pos):\n    return 10 - pos\n"
        "def gen_moves(pos):\n    return [m for m in (1, 2) if pos >= m]\n"
        "def do_move(pos, move):\n    return pos - move\n"
        "def primitive(pos):\n    return 'LOSE' if pos == 0 else None\n"
    )
    rc = cli_main([str(mod), "--paranoid"])
    captured = capsys.readouterr()
    assert rc == 0
    assert "value: WIN" in captured.out
    assert "remoteness: 7" in captured.out
    assert "warning" not in captured.err


def test_cli_coordinator_flag_plumbing(monkeypatch, capsys):
    """--coordinator must drive jax.distributed.initialize (mocked) before
    the solve, with the CLI's process-group arguments passed through."""
    import gamesmanmpi_tpu.parallel.mesh as mesh_mod

    calls = {}

    def fake_init(**kwargs):
        calls.update(kwargs)

    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", fake_init)
    rc = cli_main(
        [
            "subtract:total=6,moves=1-2",
            "--coordinator", "10.0.0.1:8476",
            "--num-processes", "1",
            "--process-id", "0",
        ]
    )
    assert rc == 0
    assert calls == {
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 1,
        "process_id": 0,
    }


def test_cli_coordinator_requires_process_args(capsys):
    rc = cli_main(["tictactoe", "--coordinator", "10.0.0.1:8476"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "--num-processes" in captured.err


def test_cli_query_flag(capsys):
    rc = cli_main(
        ["subtract:total=10,moves=1-2", "--query", "9", "--query", "0x3",
         "--query", "99"]
    )
    captured = capsys.readouterr()
    assert rc == 0
    assert "query 9: value=LOSE remoteness=" in captured.out
    assert "query 0x3: value=LOSE" in captured.out  # 3 % 3 == 0 -> LOSE
    assert "query 99: not reachable" in captured.out


def test_cli_query_flag_compat_host(capsys):
    rc = cli_main(
        [str(REF_GAMES / "ten_to_zero.py"), "--query", "3", "--query", "77"]
    )
    captured = capsys.readouterr()
    assert rc == 0
    assert "query 3: value=LOSE" in captured.out
    assert "query 77: not reachable" in captured.out


def test_sharded_checkpoint_per_shard_files(tmp_path):
    """Sharded checkpoints are per-shard npz files — no global level or
    frontier arrays are assembled to write them (VERDICT r2 item 4) — and
    resume works shard-to-shard at the same shard count AND via
    repartition at a different one."""
    import jax

    if len(jax.devices()) < 4:
        import pytest

        pytest.skip("needs 4 fake devices")
    import pathlib

    from gamesmanmpi_tpu.parallel import ShardedSolver

    d = str(tmp_path / "shard_ckpt")
    first = ShardedSolver(
        get_game("tictactoe"), num_shards=4, store_tables=False,
        checkpointer=LevelCheckpointer(d),
    ).solve()
    files = {p.name for p in pathlib.Path(d).iterdir()}
    assert any(".shard_" in f and f.startswith("level_") for f in files)
    assert any(f.startswith("frontiers.shard_") for f in files)
    # Big-run mode + checkpoint must not write any GLOBAL level/frontier
    # file (the single-host bottleneck the per-shard format removes).
    assert not any(
        f.startswith("level_") and ".shard_" not in f for f in files
    )
    assert "frontiers.npz" not in files

    # Same-shard-count resume: shard-to-shard, and no recompute.
    same = ShardedSolver(
        get_game("tictactoe"), num_shards=4, store_tables=False,
        checkpointer=LevelCheckpointer(d),
    )

    def _poisoned(*a, **k):
        raise AssertionError("resume recomputed a level")

    same._forward_fn = _poisoned
    same._backward_fn = _poisoned
    r_same = same.solve()
    assert (r_same.value, r_same.remoteness) == (first.value, first.remoteness)

    # Different shard count: assemble + repartition fallback.
    r_other = ShardedSolver(
        get_game("tictactoe"), num_shards=2,
        checkpointer=LevelCheckpointer(d),
    ).solve()
    assert (r_other.value, r_other.remoteness) == (
        first.value, first.remoteness,
    )


def test_sharded_checkpoint_single_shard(tmp_path):
    """num_shards=1 checkpoints and resumes (a 1-device sharding reports
    shard index slice(None) — start None — which must map to shard 0)."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    d = str(tmp_path / "one_shard")
    first = ShardedSolver(
        get_game("subtract:total=10,moves=1-2"), num_shards=1,
        checkpointer=LevelCheckpointer(d),
    ).solve()
    resumed = ShardedSolver(
        get_game("subtract:total=10,moves=1-2"), num_shards=1,
        checkpointer=LevelCheckpointer(d),
    ).solve()
    assert (resumed.value, resumed.remoteness) == (first.value, first.remoteness)


def test_force_platform_noop_and_epoch_keying(monkeypatch):
    """Chip-session discipline regression (VERDICT r3 weak #1): every
    in-process CLI run calls apply_platform_env; with GAMESMAN_PLATFORM=cpu
    set (the documented rule while a chip session runs elsewhere) that used
    to clear_backends even though CPU was already active, poisoning sharded
    kernels cached on the old device objects. force_platform must (a)
    no-op when the requested platform is already the default backend, and
    (b) when a clear IS genuine, bump the backend epoch so kernel caches
    and dense device-const caches rebuild instead of reusing stale
    executables."""
    import jax

    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.utils import platform as plat

    game = get_game("subtract:total=10,moves=1-2")
    first = ShardedSolver(game, num_shards=2).solve()

    # (a) Re-forcing the active platform must not clear backends: the same
    # device objects remain valid and a cached sharded kernel still runs.
    devices_before = jax.devices()
    epoch_before = plat.backend_epoch()
    plat.force_platform("cpu", fake_devices=len(devices_before))
    assert plat.backend_epoch() == epoch_before
    assert jax.devices() == devices_before
    again = ShardedSolver(game, num_shards=2).solve()
    assert (again.value, again.remoteness) == (first.value, first.remoteness)

    # (b) A genuine clear bumps the epoch; epoch-keyed caches rotate.
    from gamesmanmpi_tpu.solve.engine import _cache_key
    from gamesmanmpi_tpu.solve.dense import DenseTables

    key_old = _cache_key(game, "k", (1,), lowering=())
    tables = DenseTables(3, 3)
    tables._dev_binom = object()
    tables._dev_consts[(0, False)] = object()
    monkeypatch.setattr(plat, "_BACKEND_EPOCH", plat.backend_epoch() + 1)
    assert _cache_key(game, "k", (1,), lowering=()) != key_old
    tables.drop_stale_device_caches()
    assert tables._dev_binom is None and not tables._dev_consts


def test_cli_query_from_dense_checkpoints_no_tables(tmp_path, capsys):
    """The dense analog of the big-run query contract: --engine dense
    --no-tables --checkpoint-dir holds every solved cell as per-level
    dense_NNNN.npz; --query must locate the cell by perfect index in one
    level file, not report 'not reachable'."""
    from gamesmanmpi_tpu.core.values import value_name

    full = Solver(get_game("connect4:w=3,h=3,k=3")).solve()
    picks = []
    for level in sorted(full.levels):
        states = full.levels[level].states
        if states.shape[0] and level > 0:
            picks.append(int(states[states.shape[0] // 2]))
        if len(picks) == 5:
            break
    assert len(picks) == 5

    d = str(tmp_path / "densebig")
    argv = ["connect4:w=3,h=3,k=3", "--engine", "dense", "--no-tables",
            "--checkpoint-dir", d]
    for s in picks:
        argv += ["--query", hex(s)]
    rc = cli_main(argv)
    out = capsys.readouterr().out
    assert rc == 0
    for s in picks:
        v, r = full.lookup(s)
        assert (
            f"query {hex(s)}: value={value_name(v)} remoteness={r}" in out
        )
    assert "not reachable" not in out


def test_cli_query_from_shard_checkpoints_no_tables(tmp_path, capsys):
    """SURVEY §1's by-product contract at big-run scale (VERDICT r3
    missing #4): with --no-tables nothing is materialized in host memory,
    but --checkpoint-dir holds every solved cell as per-(level, shard)
    npz — --query must answer from those files (one shard read, chosen by
    the owner hash), not report 'not reachable'."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    from gamesmanmpi_tpu.core.values import value_name

    # Reference answers from a full in-memory solve.
    full = Solver(get_game("tictactoe")).solve()
    picks = []
    for level in sorted(full.levels):
        states = full.levels[level].states
        if states.shape[0] and level > 0:
            picks.append(int(states[states.shape[0] // 2]))
        if len(picks) == 5:
            break
    assert len(picks) == 5

    d = str(tmp_path / "bigrun")
    argv = ["tictactoe", "--devices", "4", "--no-tables",
            "--checkpoint-dir", d]
    for s in picks:
        argv += ["--query", hex(s)]
    rc = cli_main(argv)
    out = capsys.readouterr().out
    assert rc == 0
    for s in picks:
        v, r = full.lookup(s)
        assert (
            f"query {hex(s)}: value={value_name(v)} remoteness={r}" in out
        )
    assert "not reachable" not in out
