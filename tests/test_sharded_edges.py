"""Edge-cached provenance backward for the sharded engine (ISSUE 3).

Contracts under test, on the faked 8-device CPU mesh:

* A/B parity: GAMESMAN_BACKWARD=edges and =lookup produce byte-identical
  (value, remoteness) tables — and both match the single-device solver,
  whose own tables are oracle-tested in test_engine/test_games — on the
  fast path (tictactoe, connect4 4x4) and the generic multi-jump path
  (nim, chomp), where edges structurally fall back to lookup.
* The edges backward does NO sorting: per-level backward bytes_sorted is
  exactly zero (the forward pays the provenance pair sorts instead).
* Edge-spill resume: a run killed after forward resumes from the sealed
  frontier snapshot AND the per-(level, shard) edge files, running the
  edge-cached backward — not the lookup join — from disk.
* Structural fallback: a pre-edge checkpoint (no edge files) resumes via
  the lookup backward without error.
* Checkpoint atomicity (ADVICE r5): _savez never leaves a torn file
  visible, and a corrupted sealed npz degrades resume to the intact
  prefix instead of raising BadZipFile.
"""

import numpy as np
import pytest

import jax

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.parallel import ShardedSolver
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.engine import SolverError
from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

from helpers import full_table

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (fake) devices"
)


class _RecordingLogger:
    def __init__(self):
        self.records = []

    def log(self, rec):
        self.records.append(rec)


def _phase_sum(records, phases, key):
    return sum(r.get(key, 0) for r in records if r.get("phase") in phases)


@pytest.mark.parametrize(
    "spec",
    ["tictactoe", "connect4:w=4,h=4", "nim:heaps=3-4-5", "chomp:w=3,h=3"],
)
def test_edges_lookup_ab_parity(spec, monkeypatch):
    """Byte-identical tables across both backward modes and the oracle-
    exact single-device solver; edges actually ran where they can."""
    single = Solver(get_game(spec), paranoid=True).solve()
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    se = ShardedSolver(get_game(spec), num_shards=8, paranoid=True)
    redges = se.solve()
    monkeypatch.setenv("GAMESMAN_BACKWARD", "lookup")
    sl = ShardedSolver(get_game(spec), num_shards=8, paranoid=True)
    rlookup = sl.solve()
    assert sl.backward_edges_levels == 0
    fast = bool(get_game(spec).uniform_level_jump)
    if fast:
        # Every level but the deepest (no deeper window to point into).
        assert se.backward_edges_levels == len(redges.levels) - 1
    else:
        # Generic multi-jump path: structural fallback, no edges at all.
        assert se.backward_edges_levels == 0
    t_edges, t_lookup, t_single = (
        full_table(redges), full_table(rlookup), full_table(single)
    )
    assert t_edges == t_lookup
    assert t_edges == t_single
    assert (redges.value, redges.remoteness) == (single.value,
                                                 single.remoteness)


def test_edges_backward_sorts_nothing(monkeypatch):
    """The roofline contract: backward levels contribute ZERO sort bytes
    in edges mode (lookup mode's join sorts are the comparison), and the
    per-level records say which backward ran (docs/OBSERVABILITY.md)."""
    monkeypatch.setenv("GAMESMAN_SEARCH", "sort")  # join = sort bytes
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    log_e = _RecordingLogger()
    ShardedSolver(
        get_game("tictactoe"), num_shards=8, logger=log_e
    ).solve()
    bwd = [r for r in log_e.records
           if r["phase"] in ("backward", "backward_edges")]
    assert any(r["phase"] == "backward_edges" for r in bwd)
    assert all(r["mode"] == "edges" for r in bwd
               if r["phase"] == "backward_edges")
    assert _phase_sum(bwd, ("backward", "backward_edges"),
                      "bytes_sorted") == 0

    monkeypatch.setenv("GAMESMAN_BACKWARD", "lookup")
    log_l = _RecordingLogger()
    ShardedSolver(
        get_game("tictactoe"), num_shards=8, logger=log_l
    ).solve()
    assert _phase_sum(log_l.records, ("backward",), "bytes_sorted") > 0


def test_edges_precompile_scheduling_parity(monkeypatch):
    """GAMESMAN_PRECOMPILE=1 schedules the edge-backward shapes as
    background AOT compiles (sharded avals); the fetched executables must
    produce the same tables as inline jit — this is the only CPU coverage
    the accelerator-default scheduling path gets."""
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    monkeypatch.setenv("GAMESMAN_PRECOMPILE", "1")
    single = Solver(get_game("tictactoe")).solve()
    solver = ShardedSolver(get_game("tictactoe"), num_shards=8)
    assert solver.precompile
    r = solver.solve()
    assert solver.backward_edges_levels > 0
    assert full_table(r) == full_table(single)


def test_edges_strict_knob_parse(monkeypatch):
    monkeypatch.setenv("GAMESMAN_BACKWARD", "fast")
    with pytest.raises(SolverError, match="GAMESMAN_BACKWARD"):
        ShardedSolver(get_game("tictactoe"), num_shards=2)


def test_edges_with_window_streaming_and_store_tables_false(monkeypatch):
    """Big-run composition: host-spilled windows stream their cell blocks
    through the edge gather (window_stream_blocks observable), and
    nothing but the root answer leaves the devices."""
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    single = Solver(get_game("tictactoe")).solve()
    solver = ShardedSolver(
        get_game("tictactoe"), num_shards=8, store_tables=False
    )
    solver.window_block = 128
    r = solver.solve()
    assert solver.backward_edges_levels > 0
    assert solver.window_stream_blocks > 0
    assert (r.value, r.remoteness) == (single.value, single.remoteness)
    assert len(r.levels) == 0


def test_edges_device_budget_spill_parity(monkeypatch):
    """Edges evicted from the device-store budget spill to host, count in
    edges_bytes_spilled, re-upload for backward, and stay exact."""
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    single = Solver(get_game("tictactoe")).solve()
    solver = ShardedSolver(get_game("tictactoe"), num_shards=8)
    solver.device_store_bytes = 0  # evict everything, edges included
    r = solver.solve()
    assert solver.edges_bytes_spilled > 0
    assert solver.backward_edges_levels > 0
    assert full_table(r) == full_table(single)


def _killed_after_forward(spec, ckpt_dir, num_shards=8):
    """Run a checkpointed solve whose backward dies — the mid-run death
    the resume machinery exists for. Returns the solver."""
    solver = ShardedSolver(
        get_game(spec), num_shards=num_shards,
        checkpointer=LevelCheckpointer(str(ckpt_dir)),
    )

    def boom(*a, **k):
        raise RuntimeError("killed after forward")

    solver._backward = boom
    with pytest.raises(RuntimeError, match="killed after forward"):
        solver.solve()
    return solver


def test_edge_spill_resume_runs_edges_backward(tmp_path, monkeypatch):
    """Kill after forward; the resumed run must load the per-(level,
    shard) edge files and run the edge-cached backward from disk."""
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    single = Solver(get_game("tictactoe")).solve()
    _killed_after_forward("tictactoe", tmp_path / "ck")
    resumed = ShardedSolver(
        get_game("tictactoe"), num_shards=8,
        checkpointer=LevelCheckpointer(str(tmp_path / "ck")),
    )
    r = resumed.solve()
    # Edges came from the spilled files (the in-memory ones died with the
    # first process): every level but the deepest resolves via edges.
    assert resumed.backward_edges_levels == len(r.levels) - 1
    assert full_table(r) == full_table(single)


def test_pre_edge_checkpoint_falls_back_to_lookup(tmp_path, monkeypatch):
    """A checkpoint written before edges existed (simulated by a lookup-
    mode run, which stores none) must resume via the lookup backward
    without error — the structural fallback contract."""
    single = Solver(get_game("tictactoe")).solve()
    monkeypatch.setenv("GAMESMAN_BACKWARD", "lookup")
    _killed_after_forward("tictactoe", tmp_path / "ck")
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    resumed = ShardedSolver(
        get_game("tictactoe"), num_shards=8,
        checkpointer=LevelCheckpointer(str(tmp_path / "ck")),
    )
    r = resumed.solve()
    assert resumed.backward_edges_levels == 0  # no edge files: fallback
    assert full_table(r) == full_table(single)


def test_torn_edge_files_degrade_to_lookup(tmp_path, monkeypatch):
    """Sealed-but-corrupt edge files (death mid-resave before _savez was
    atomic, disk trouble) degrade that level to the lookup join instead
    of killing the resume."""
    monkeypatch.setenv("GAMESMAN_BACKWARD", "edges")
    single = Solver(get_game("tictactoe")).solve()
    _killed_after_forward("tictactoe", tmp_path / "ck")
    for p in (tmp_path / "ck").glob("edges_*.shard_*.npz"):
        p.write_bytes(b"not a zip")
    resumed = ShardedSolver(
        get_game("tictactoe"), num_shards=8,
        checkpointer=LevelCheckpointer(str(tmp_path / "ck")),
    )
    r = resumed.solve()
    assert resumed.backward_edges_levels == 0
    assert full_table(r) == full_table(single)


def test_savez_atomic_and_torn_recovery(tmp_path):
    """ADVICE r5: _savez writes tmp + os.replace (no torn file ever at
    the final name), and a sealed forward level whose npz is corrupt
    truncates the resumable prefix instead of raising BadZipFile."""
    from gamesmanmpi_tpu.utils.checkpoint import _savez

    path = tmp_path / "x.npz"
    _savez(path, a=np.arange(4, dtype=np.uint32))
    assert path.exists()
    assert not list(tmp_path.glob("*.tmp.npz"))  # no tmp left behind
    with np.load(path) as z:
        assert (z["a"] == np.arange(4)).all()

    ck = LevelCheckpointer(str(tmp_path / "ck"))
    for level in (0, 1, 2):
        for s in (0, 1):
            ck.save_forward_level_shard(
                level, s, np.arange(level + 1, dtype=np.uint64)
            )
        ck.finish_forward_level(level, 2)
    # Corrupt level 1's shard 0: levels 1 and 2 drop, level 0 survives.
    (tmp_path / "ck" / "frontier_0001.shard_0000.npz").write_bytes(b"xx")
    out = ck.load_forward_level_shards(2)
    assert sorted(out) == [0]
