"""Test environment: CPU backend faking an 8-device mesh.

SURVEY.md §4.2 axis 2: shard-count invariance is the TPU analog of the
reference's `mpirun -np 1` vs `-np 8`, and the fake-backend mechanism for CI
is XLA's host-platform device-count flag.

This container's sitecustomize registers an experimental TPU PJRT plugin
("axon") and forces `jax_platforms="axon,cpu"` at interpreter start, which
both ignores a JAX_PLATFORMS=cpu env var and hangs CPU-only runs. The
workaround lives in one place — utils/platform.force_platform — which must
run before the first backend use.
"""

from gamesmanmpi_tpu.utils.platform import force_platform

force_platform("cpu", fake_devices=8)

# The dense engine's cross-process reachable-count cache must not satisfy
# the parity tests from a previous run's file — counts there must come
# from a real sweep regardless of the invoking shell's env (the file path
# itself is covered by a dedicated test, which monkeypatches this).
import os  # noqa: E402

os.environ["GAMESMAN_DENSE_COUNTS_FILE"] = "0"

# Runtime lock-order witness (docs/ANALYSIS.md "lockdep"): under
# GAMESMAN_LOCKDEP=1 every obs/serve/resilience lock records its
# acquisition edges, and a witnessed lock-order cycle fails the run at
# session teardown — the dynamic validation of the GM2xx/GM6xx static
# lock model.
from gamesmanmpi_tpu.analysis import lockdep  # noqa: E402

if lockdep.enabled_by_env():
    lockdep.install()

# Runtime wire-conformance witness (docs/ANALYSIS.md "wirecheck"):
# under GAMESMAN_WIRECHECK=1 every live response from a watched fleet
# handler is checked against the statically extracted GM10xx contract
# (status codes, Retry-After/Cache-Control/traceparent rules), and a
# violation fails the run at session teardown.
from gamesmanmpi_tpu.analysis import wirecheck  # noqa: E402

if wirecheck.enabled_by_env():
    wirecheck.install()


def pytest_sessionfinish(session, exitstatus):
    if lockdep.enabled_by_env():
        try:
            lockdep.assert_acyclic()
        except lockdep.LockOrderError as e:
            import sys

            print(f"\nGAMESMAN_LOCKDEP: {e}", file=sys.stderr)
            session.exitstatus = 3
    if wirecheck.enabled_by_env():
        try:
            wirecheck.assert_conformant()
        except wirecheck.WireConformanceError as e:
            import sys

            print(f"\nGAMESMAN_WIRECHECK: {e}", file=sys.stderr)
            session.exitstatus = 3
