"""Block-store engine tests (ISSUE 11).

Four promises under test, each mapped to a failure the async refactor
could have introduced:

* **Tiered-cache integrity under concurrency** — 8 threads hammering
  one byte-budget cache keep exact hit/miss/eviction accounting and
  never exceed the budget (the fleet's flush/breaker/caller shape).
* **Prefetch correctness** — a hinted block is a later cache hit; a
  hinted-but-EVICTED block degrades to a synchronous sealed read and
  still returns the right bytes (slower, never wrong); an error raised
  by a background loader re-raises on the CONSUMING thread, where the
  quarantine/degrade machinery lives.
* **Write-behind ordering** — payload writes land before any seal can
  run (drain-before-seal), failures surface at the drain, and the
  whole pipeline is invisible to resume (chaos kill mid-queue lives in
  tests/test_resilience.py at the ``store.writebehind`` point).
* **Solve parity** — the same spill-forcing sharded solve (device
  budget 0, host tier squeezed so edges hit the disk tier) produces
  byte-identical tables with prefetch/write-behind on and off, on
  ttt, nim, and connect4 4x4 — the A/B `BENCH_store_r11.json` commits.
"""

import threading
import time

import numpy as np
import pytest

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.store import (
    BlockStore,
    TieredCache,
    default_store,
    file_key,
)

# ----------------------------------------------------------- tiered cache


def test_tiered_cache_thread_hammer_accounting():
    cache = TieredCache(1 << 16)
    payload = np.zeros(64, np.uint64)
    errors = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(500):
                key = int(rng.integers(0, 32))
                if cache.get(key) is None:
                    cache.put(key, payload, payload.nbytes)
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    stats = cache.stats()
    assert stats["hits"] + stats["misses"] == 8 * 500
    assert stats["bytes"] <= (1 << 16)
    # contains() is a pure peek: accounting must not move.
    before = cache.stats()
    cache.contains(0)
    cache.contains("never-inserted")
    after = cache.stats()
    assert (before["hits"], before["misses"]) == (
        after["hits"], after["misses"]
    )


def test_store_read_hammer_stays_exact():
    """8 threads reading a churning key space through one store: every
    read returns the loader's value for ITS key — eviction and inflight
    races may cost extra loads, never a wrong answer."""
    store = BlockStore(cache=TieredCache(1 << 14), prefetch_threads=2,
                       writebehind=False)
    errors = []

    def loader_for(key):
        return lambda: np.full(32, key, dtype=np.int64)

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(300):
                key = int(rng.integers(0, 24))
                if rng.integers(0, 2):
                    store.hint(("k", key), loader_for(key))
                val = store.read(("k", key), loader_for(key))
                assert (val == key).all()
        except Exception as e:  # noqa: BLE001 - collected
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    stats = store.stats()
    assert stats["prefetch_hits"] + stats["prefetch_misses"] == 8 * 300
    store.close()


# -------------------------------------------------------------- prefetch


def test_hint_becomes_cache_hit_and_loader_runs_once():
    store = BlockStore(cache=TieredCache(1 << 20), prefetch_threads=2,
                       writebehind=False)
    calls = []

    def loader():
        calls.append(1)
        return np.arange(100)

    store.hint(("a",), loader)
    deadline = time.monotonic() + 5
    while not store.cache.contains(("a",)) and time.monotonic() < deadline:
        time.sleep(0.005)
    val, hit = store.read_ex(("a",), loader)
    assert hit and len(calls) == 1 and val.shape == (100,)
    assert store.stats()["prefetch_hit_rate"] == 1.0
    store.close()


def test_hinted_but_evicted_degrades_to_sync_read():
    """The readahead-miss fallback: a hint whose decoded value was
    evicted by the byte budget degrades to a synchronous load — the
    answer is still exactly right."""
    store = BlockStore(cache=TieredCache(256), prefetch_threads=1,
                       writebehind=False)
    store.hint(("victim",), lambda: np.full(64, 7, np.int64))  # 512 B > 256
    deadline = time.monotonic() + 5
    while store.stats()["prefetch_issued"] == 0 \
            and time.monotonic() < deadline:
        time.sleep(0.005)
    # Force churn so the hinted entry (oversized anyway) is gone.
    store.read(("churn",), lambda: np.zeros(64, np.int64))
    val, hit = store.read_ex(("victim",), lambda: np.full(64, 7, np.int64))
    assert (val == 7).all()  # correctness regardless of residency
    store.close()


def test_background_loader_error_reraises_on_consuming_thread():
    store = BlockStore(cache=TieredCache(1 << 20), prefetch_threads=1,
                       writebehind=False)

    def torn():
        raise ValueError("crc32 mismatch — torn block")

    store.hint(("bad",), torn)
    with pytest.raises(ValueError, match="torn block"):
        # Whether the pool already failed or the read races it, the
        # error must surface HERE, on the reader's thread.
        for _ in range(100):
            store.read(("bad",), torn)
    store.close()


def test_file_key_invalidates_on_rewrite_and_missing(tmp_path):
    p = tmp_path / "payload.bin"
    p.write_bytes(b"v1" * 100)
    k1 = file_key(p)
    assert k1 is not None
    time.sleep(0.01)
    p.write_bytes(b"v2" * 200)
    k2 = file_key(p)
    assert k1 != k2  # a rewritten file can never serve stale cache
    p.unlink()
    assert file_key(p) is None  # bypass → loader raises the honest error


# ----------------------------------------------------------- write-behind


def test_writebehind_executes_in_order_and_drain_barriers(tmp_path):
    store = BlockStore(cache=TieredCache(1 << 20), prefetch_threads=0,
                       writebehind=True)
    order = []

    def job(i):
        def run():
            (tmp_path / f"f{i}").write_bytes(b"x" * 10)
            order.append(i)
            return (10, 10)
        return run

    tickets = [store.write(job(i), path=str(tmp_path / f"f{i}"))
               for i in range(8)]
    store.drain()
    assert order == list(range(8))  # FIFO: payload-before-seal depends on it
    assert all(t.result() == (10, 10) for t in tickets)
    assert all((tmp_path / f"f{i}").exists() for i in range(8))
    assert store.stats()["writebehind_queue_depth"] == 0
    assert store.stats()["writebehind_queue_depth_peak"] >= 1
    store.close()


def test_writebehind_failure_surfaces_at_drain_once():
    store = BlockStore(cache=TieredCache(1 << 20), prefetch_threads=0,
                       writebehind=True)

    def boom():
        raise OSError("disk full")

    t = store.write(boom, path="doomed")
    with pytest.raises(OSError, match="disk full"):
        store.drain()
    with pytest.raises(OSError, match="disk full"):
        t.result()
    store.drain()  # the error must not poison later, unrelated seals
    store.close()


def test_writebehind_injected_transient_resolves_ticket_not_daemon():
    """An armed transient at store.writebehind must behave like a write
    failure — ticket resolved, surfaced at the drain — and must NOT
    kill the write-behind daemon (which would wedge every later seal
    behind an unresolved ticket)."""
    from gamesmanmpi_tpu.resilience import faults

    store = BlockStore(cache=TieredCache(1 << 20), prefetch_threads=0,
                       writebehind=True)
    faults.configure("store.writebehind:transient")
    try:
        t = store.write(lambda: (1, 1), path="x")
        with pytest.raises(faults.TransientFault):
            store.drain()
        with pytest.raises(faults.TransientFault):
            t.result()
        # The daemon survives the injection: later writes still land.
        t2 = store.write(lambda: (2, 2), path="y")
        store.drain()
        assert t2.result() == (2, 2)
    finally:
        faults.clear()
        store.close()


def test_sync_mode_counts_inline_write_as_io_wait():
    store = BlockStore(cache=TieredCache(1 << 20), prefetch_threads=0,
                       writebehind=False)
    t = store.write(lambda: (time.sleep(0.02), (1, 1))[1], path=None)
    assert t.done() and t.result() == (1, 1)
    assert store.stats()["io_wait_secs"] >= 0.02
    store.close()


def test_default_store_rebuilds_on_env_change(monkeypatch):
    monkeypatch.setenv("GAMESMAN_STORE_CACHE_MB", "7")
    s1 = default_store()
    assert s1.cache.budget_bytes == 7 << 20
    assert default_store() is s1  # stable while the knobs are stable
    monkeypatch.setenv("GAMESMAN_STORE_CACHE_MB", "9")
    s2 = default_store()
    assert s2 is not s1 and s2.cache.budget_bytes == 9 << 20
    # A consumer holding the replaced store stays correct: late writes
    # degrade to inline execution instead of queueing behind a dead
    # worker.
    t = s1.write(lambda: (5, 5), path=None)
    assert t.result() == (5, 5)


# ------------------------------------------- prefetch-vs-sync byte parity


def _solve_tables(spec, tmp_path, tag, monkeypatch, *, threads, wb):
    """One spill-forcing checkpointed sharded solve; -> (result, stats)."""
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

    # Spill-forcing: nothing resident between phases, host tier too
    # small for the edge arrays (they drop to the disk tier when a
    # checkpointer seals them), 4 MB of decoded readahead cache.
    monkeypatch.setenv("GAMESMAN_DEVICE_STORE_MB", "0")
    monkeypatch.setenv("GAMESMAN_STORE_CACHE_MB", "4")
    monkeypatch.setenv("GAMESMAN_STORE_PREFETCH_THREADS", str(threads))
    monkeypatch.setenv("GAMESMAN_STORE_WRITEBEHIND", "1" if wb else "0")
    solver = ShardedSolver(
        get_game(spec), num_shards=2,
        checkpointer=LevelCheckpointer(str(tmp_path / tag)),
    )
    result = solver.solve()
    return result, result.stats


@pytest.mark.parametrize(
    "spec", ["tictactoe", "nim:heaps=3-4-5", "connect4:w=4,h=4"]
)
def test_prefetch_vs_sync_byte_parity(spec, tmp_path, monkeypatch):
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 fake devices")
    sync, s_stats = _solve_tables(spec, tmp_path, "sync", monkeypatch,
                                  threads=0, wb=False)
    pref, p_stats = _solve_tables(spec, tmp_path, "pref", monkeypatch,
                                  threads=2, wb=True)
    assert (pref.value, pref.remoteness) == (sync.value, sync.remoteness)
    assert pref.num_positions == sync.num_positions
    assert sorted(pref.levels) == sorted(sync.levels)
    for k in sync.levels:
        a, b = sync.levels[k], pref.levels[k]
        assert np.array_equal(a.states, b.states), f"level {k} states"
        assert np.array_equal(a.values, b.values), f"level {k} values"
        assert np.array_equal(a.remoteness, b.remoteness), f"level {k}"
    # The sync arm must really have been synchronous, and the prefetch
    # arm must really have overlapped (hits only count when a hinted /
    # cached value served a read).
    assert s_stats["prefetch_hits"] == 0
    if p_stats["prefetch_misses"] + p_stats["prefetch_hits"] > 0:
        assert p_stats["prefetch_hits"] > 0
    assert s_stats["writebehind_writes"] > 0  # inline writes still count


def test_resume_after_prefetch_run_hits_cache(tmp_path, monkeypatch):
    """A resumed solve reads the whole sealed prefix through the store:
    the batched resume readahead should serve most of it from cache."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 fake devices")
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

    monkeypatch.setenv("GAMESMAN_STORE_CACHE_MB", "64")
    monkeypatch.setenv("GAMESMAN_STORE_PREFETCH_THREADS", "2")
    d = str(tmp_path / "ck")
    first = ShardedSolver(
        get_game("nim:heaps=3-4-5"), num_shards=2,
        checkpointer=LevelCheckpointer(d),
    ).solve()
    resumed = ShardedSolver(
        get_game("nim:heaps=3-4-5"), num_shards=2,
        checkpointer=LevelCheckpointer(d),
    ).solve()
    assert (resumed.value, resumed.remoteness) == (
        first.value, first.remoteness
    )
    assert resumed.stats["prefetch_hits"] > 0
    assert resumed.stats["prefetch_hit_rate"] > 0.5
