"""solve/: end-to-end solves with oracle parity (SURVEY.md §4.2 axis 1).

Golden values (SURVEY.md §4.2 table): 3x3 tic-tac-toe is a TIE with
remoteness 9; normal-play Nim is a first-player WIN iff XOR of heaps != 0;
1-2-10 subtraction follows mod-3 arithmetic.
"""

import numpy as np
import pytest

from gamesmanmpi_tpu.core.values import WIN, LOSE, TIE
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve import Solver, oracle_solve

from helpers import REF_GAMES, load_module, assert_table_parity, full_table


def _solve_both(spec, ref_file, **solver_kw):
    result = Solver(get_game(spec), paranoid=True, **solver_kw).solve()
    _, _, oracle_table = oracle_solve(load_module(REF_GAMES / ref_file))
    return result, oracle_table


def test_tictactoe_3x3_full_parity():
    result, oracle_table = _solve_both("tictactoe", "tictactoe.py")
    assert result.value == TIE
    assert result.remoteness == 9
    assert result.num_positions == 5478  # classic reachable count
    assert_table_parity(result, oracle_table)


def test_subtract_1210_parity_and_closed_form():
    result, oracle_table = _solve_both(
        "subtract:total=10,moves=1-2", "ten_to_zero.py"
    )
    # 10 % 3 == 1 -> first player WIN (takes 1, leaves a multiple of 3).
    assert result.value == WIN
    assert_table_parity(result, oracle_table)
    # Closed form for every position: LOSE iff pos % 3 == 0.
    for pos, (value, _) in full_table(result).items():
        assert value == (LOSE if pos % 3 == 0 else WIN)


def test_subtract_misere():
    game = get_game("subtract:total=10,moves=1-2,misere=1")
    result = Solver(game, paranoid=True).solve()
    # Misère: LOSE iff pos % 3 == 1; 10 % 3 == 1 -> first player LOSE.
    assert result.value == LOSE


def test_nim_345_parity_and_xor_rule():
    result, oracle_table = _solve_both("nim:heaps=3-4-5", "nim_345.py")
    assert result.value == WIN  # 3 ^ 4 ^ 5 == 2 != 0
    assert_table_parity(result, oracle_table)
    # XOR rule across the whole table (normal play).
    game = get_game("nim:heaps=3-4-5")
    for pos, (value, _) in full_table(result).items():
        heaps = [(pos >> (i * game.bits)) & ((1 << game.bits) - 1) for i in range(3)]
        x = heaps[0] ^ heaps[1] ^ heaps[2]
        assert value == (LOSE if x == 0 else WIN), f"XOR rule broken at {heaps}"


def test_connect4_4x4_full_parity():
    result, oracle_table = _solve_both("connect4:w=4,h=4", "connect4_4x4.py")
    assert_table_parity(result, oracle_table)


def test_result_lookup():
    result = Solver(get_game("tictactoe"), paranoid=True).solve()
    value, rem = result.lookup(result.game.initial_state())
    assert (value, rem) == (TIE, 9)
    with pytest.raises(KeyError):
        # An unreachable "position": both players on the same cell.
        result.lookup(np.uint64(1 | (1 << 9)))


def test_blocked_backward_parity():
    """Wide levels resolved in column blocks (GAMESMAN_BACKWARD_BLOCK bound)
    must produce the identical table."""
    from helpers import full_table

    base = Solver(get_game("tictactoe")).solve()
    blocked = Solver(get_game("tictactoe"), paranoid=True)
    blocked.backward_block = 256  # well below the widest level's capacity
    result = blocked.solve()
    assert full_table(result) == full_table(base)


def test_chomp_parity_and_strategy_stealing():
    """Chomp 3x3: full-table oracle parity; every board >1x1 is a
    first-player WIN (strategy stealing), the closed-form anchor."""
    result, oracle_table = _solve_both("chomp:w=3,h=3", "chomp_33.py")
    assert result.value == WIN
    assert_table_parity(result, oracle_table)


def test_chomp_boards_win_and_1x1_loses():
    assert Solver(get_game("chomp:w=4,h=3")).solve().value == WIN
    assert Solver(get_game("chomp:w=2,h=2")).solve().value == WIN
    # 1x1 is the poison-only position itself: primitive LOSE, remoteness 0.
    r = Solver(get_game("chomp:w=1,h=1")).solve()
    assert r.value == LOSE and r.remoteness == 0


def test_store_tables_false_root_only():
    """Big-run mode: same root answer and position count, only the root
    level materialized (fast and generic paths)."""
    for spec in ("tictactoe", "subtract:total=10,moves=1-2"):
        full = Solver(get_game(spec)).solve()
        lean = Solver(get_game(spec), store_tables=False).solve()
        assert (lean.value, lean.remoteness) == (full.value, full.remoteness)
        assert lean.num_positions == full.num_positions
        assert len(lean.levels) == 1  # root only


def test_platform_conditional_paths_parity(monkeypatch):
    """The platform-auto lowerings (provenance forward + speculation,
    searchsorted method, dedup compaction) resolve differently on CPU vs
    accelerator; on the CPU suite the accelerator-default side would
    otherwise go untested end to end. Force each non-default side and
    assert full-table parity with the default solve."""
    from helpers import full_table

    g = "connect4:w=4,h=3"
    base = Solver(get_game(g), paranoid=True).solve()
    base_tab = full_table(base)

    forced = {
        "GAMESMAN_PROVENANCE": "1",   # TPU default: provenance forward
        "GAMESMAN_SPECULATE": "1",    # TPU default: speculative dispatch
        "GAMESMAN_SEARCH": "sort",    # TPU default: sort-merge join lookup
        "GAMESMAN_COMPACT": "resort", # TPU default: re-sort compaction
    }
    for var, val in forced.items():
        monkeypatch.setenv(var, val)
        r = Solver(get_game(g), paranoid=True).solve()
        assert (r.value, r.remoteness) == (base.value, base.remoteness), var
        assert full_table(r) == base_tab, var
        monkeypatch.delenv(var)
    # All four at once (the exact accelerator configuration).
    for var, val in forced.items():
        monkeypatch.setenv(var, val)
    r = Solver(get_game(g), paranoid=True).solve()
    assert (r.value, r.remoteness) == (base.value, base.remoteness)
    assert full_table(r) == base_tab
