"""ops/: padding, dedup, lookup, combine kernels."""

import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.bitops import SENTINEL64 as SENTINEL
from gamesmanmpi_tpu.core.values import WIN, LOSE, TIE, UNDECIDED
from gamesmanmpi_tpu.ops import (
    bucket_size,
    pad_to_bucket,
    sort_unique,
    lookup_sorted,
    lookup_window,
    combine_children,
)


def test_bucket_size():
    assert bucket_size(0) == 256
    assert bucket_size(256) == 256
    assert bucket_size(257) == 512
    assert bucket_size(1000) == 1024


def test_pad_to_bucket():
    out = pad_to_bucket(np.array([5, 3], dtype=np.uint64))
    assert out.shape == (256,)
    assert out[0] == 5 and out[1] == 3
    assert (out[2:] == SENTINEL).all()


def test_sort_unique():
    x = np.array([7, 3, 7, SENTINEL, 3, 1, SENTINEL], dtype=np.uint64)
    s, count = sort_unique(jnp.asarray(x))
    assert int(count) == 3
    assert list(np.asarray(s[:3])) == [1, 3, 7]
    assert (np.asarray(s[3:]) == SENTINEL).all()


def _table(states, values, rems):
    states = np.asarray(states, np.uint64)
    order = np.argsort(states)
    return (
        jnp.asarray(states[order]),
        jnp.asarray(np.asarray(values, np.uint8)[order]),
        jnp.asarray(np.asarray(rems, np.int32)[order]),
    )


def test_lookup_sorted_hits_and_misses():
    ts, tv, tr = _table([10, 20, 30], [WIN, LOSE, TIE], [1, 2, 3])
    keys = jnp.asarray(np.array([20, 5, 30, 99, SENTINEL], dtype=np.uint64))
    v, r, hit = lookup_sorted(keys, ts, tv, tr)
    assert list(np.asarray(hit)) == [True, False, True, False, False]
    assert list(np.asarray(v)) == [LOSE, UNDECIDED, TIE, UNDECIDED, UNDECIDED]
    assert list(np.asarray(r)) == [2, 0, 3, 0, 0]


def test_lookup_window_multi_level():
    w1 = _table([10, 20], [WIN, LOSE], [1, 2])
    w2 = _table([30, 40], [TIE, WIN], [3, 4])
    keys = jnp.asarray(np.array([40, 10, 77], dtype=np.uint64))
    v, r, hit = lookup_window(keys, (w1, w2))
    assert list(np.asarray(hit)) == [True, True, False]
    assert list(np.asarray(v)) == [WIN, WIN, UNDECIDED]
    assert list(np.asarray(r)) == [4, 1, 0]


def test_combine_children_rules():
    # Rows: (child values, child rems, mask) -> expected (value, rem).
    cv = jnp.asarray(
        np.array(
            [
                [LOSE, WIN, LOSE],  # WIN: 1 + min(LOSE rems 5, 2) = 3
                [WIN, TIE, WIN],  # TIE: 1 + max(TIE rems) = 8
                [WIN, WIN, WIN],  # LOSE: 1 + max(all rems) = 10
                [LOSE, LOSE, LOSE],  # masked lanes ignored
            ],
            dtype=np.uint8,
        )
    )
    cr = jnp.asarray(np.array([[5, 9, 2], [1, 7, 3], [4, 9, 6], [5, 1, 9]], np.int32))
    mask = jnp.asarray(
        np.array(
            [
                [True, True, True],
                [True, True, True],
                [True, True, True],
                [True, False, False],
            ]
        )
    )
    v, r = combine_children(cv, cr, mask)
    assert list(np.asarray(v)) == [WIN, TIE, LOSE, WIN]
    assert list(np.asarray(r)) == [3, 8, 10, 6]


def test_combine_children_no_children():
    cv = jnp.zeros((1, 3), jnp.uint8)
    cr = jnp.zeros((1, 3), jnp.int32)
    mask = jnp.zeros((1, 3), bool)
    v, r = combine_children(cv, cr, mask)
    assert int(v[0]) == LOSE and int(r[0]) == 0
