"""ops/: padding, dedup, lookup, combine kernels."""

import pytest
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.bitops import SENTINEL64 as SENTINEL
from gamesmanmpi_tpu.core.values import WIN, LOSE, TIE, UNDECIDED

# Smoke tier: fast, compile-light, single-process-safe (see pyproject).
pytestmark = pytest.mark.smoke
from gamesmanmpi_tpu.ops import (
    bucket_size,
    pad_to_bucket,
    sort_unique,
    lookup_sorted,
    lookup_window,
    combine_children,
)


def test_bucket_size():
    assert bucket_size(0) == 256
    assert bucket_size(256) == 256
    assert bucket_size(257) == 512
    assert bucket_size(1000) == 1024


def test_pad_to_bucket():
    out = pad_to_bucket(np.array([5, 3], dtype=np.uint64))
    assert out.shape == (256,)
    assert out[0] == 5 and out[1] == 3
    assert (out[2:] == SENTINEL).all()


def test_sort_unique():
    x = np.array([7, 3, 7, SENTINEL, 3, 1, SENTINEL], dtype=np.uint64)
    s, count = sort_unique(jnp.asarray(x))
    assert int(count) == 3
    assert list(np.asarray(s[:3])) == [1, 3, 7]
    assert (np.asarray(s[3:]) == SENTINEL).all()


def _table(states, values, rems):
    states = np.asarray(states, np.uint64)
    order = np.argsort(states)
    return (
        jnp.asarray(states[order]),
        jnp.asarray(np.asarray(values, np.uint8)[order]),
        jnp.asarray(np.asarray(rems, np.int32)[order]),
    )


def test_lookup_sorted_hits_and_misses():
    ts, tv, tr = _table([10, 20, 30], [WIN, LOSE, TIE], [1, 2, 3])
    keys = jnp.asarray(np.array([20, 5, 30, 99, SENTINEL], dtype=np.uint64))
    v, r, hit = lookup_sorted(keys, ts, tv, tr)
    assert list(np.asarray(hit)) == [True, False, True, False, False]
    assert list(np.asarray(v)) == [LOSE, UNDECIDED, TIE, UNDECIDED, UNDECIDED]
    assert list(np.asarray(r)) == [2, 0, 3, 0, 0]


def test_lookup_window_multi_level():
    w1 = _table([10, 20], [WIN, LOSE], [1, 2])
    w2 = _table([30, 40], [TIE, WIN], [3, 4])
    keys = jnp.asarray(np.array([40, 10, 77], dtype=np.uint64))
    v, r, hit = lookup_window(keys, (w1, w2))
    assert list(np.asarray(hit)) == [True, True, False]
    assert list(np.asarray(v)) == [WIN, WIN, UNDECIDED]
    assert list(np.asarray(r)) == [4, 1, 0]


def test_combine_children_rules():
    # Rows: (child values, child rems, mask) -> expected (value, rem).
    cv = jnp.asarray(
        np.array(
            [
                [LOSE, WIN, LOSE],  # WIN: 1 + min(LOSE rems 5, 2) = 3
                [WIN, TIE, WIN],  # TIE: 1 + max(TIE rems) = 8
                [WIN, WIN, WIN],  # LOSE: 1 + max(all rems) = 10
                [LOSE, LOSE, LOSE],  # masked lanes ignored
            ],
            dtype=np.uint8,
        )
    )
    cr = jnp.asarray(np.array([[5, 9, 2], [1, 7, 3], [4, 9, 6], [5, 1, 9]], np.int32))
    mask = jnp.asarray(
        np.array(
            [
                [True, True, True],
                [True, True, True],
                [True, True, True],
                [True, False, False],
            ]
        )
    )
    v, r = combine_children(cv, cr, mask)
    assert list(np.asarray(v)) == [WIN, TIE, LOSE, WIN]
    assert list(np.asarray(r)) == [3, 8, 10, 6]


def test_combine_children_no_children():
    cv = jnp.zeros((1, 3), jnp.uint8)
    cr = jnp.zeros((1, 3), jnp.int32)
    mask = jnp.zeros((1, 3), bool)
    v, r = combine_children(cv, cr, mask)
    assert int(v[0]) == LOSE and int(r[0]) == 0


def test_route_by_owner_roundtrip():
    """The owner-bucketing primitive: every non-sentinel element lands in
    exactly its owner's row, counts are exact, and (s_owner, pos, order)
    invert the permutation — the contract the backward reply routing
    depends on."""
    import jax

    from gamesmanmpi_tpu.core.hashing import owner_shard_np
    from gamesmanmpi_tpu.parallel.sharded import _route_by_owner

    rng = np.random.default_rng(7)
    S, cap = 4, 64
    flat = rng.integers(0, 1 << 40, size=100, dtype=np.uint64)
    flat[::7] = SENTINEL  # padding lanes
    send, counts, s_owner, pos, order = jax.jit(
        lambda x: _route_by_owner(x, S, cap, SENTINEL),
        static_argnums=(),
    )(jnp.asarray(flat))
    send = np.asarray(send)
    counts = np.asarray(counts)
    owners = owner_shard_np(flat, S)
    real = flat != SENTINEL
    # counts per destination are exact
    for s in range(S):
        assert counts[s] == int((owners[real] == s).sum())
        got = send[s][send[s] != SENTINEL]
        want = np.sort(flat[real & (owners == s)])
        assert sorted(got.tolist()) == sorted(want.tolist())
    # the inverse permutation reconstructs the original layout
    s_owner = np.asarray(s_owner)
    pos = np.asarray(pos)
    order = np.asarray(order)
    recon = np.empty_like(flat)
    gathered = np.where(
        s_owner < S, send[np.clip(s_owner, 0, S - 1), pos], SENTINEL
    )
    recon[order] = gathered
    assert (recon == flat).all()


def test_route_by_owner_overflow_drops_and_counts():
    """Overflowed elements drop from the send buffer but counts still report
    the true demand (what the host retry loop keys on)."""
    import jax

    from gamesmanmpi_tpu.parallel.sharded import _route_by_owner

    flat = jnp.asarray(np.arange(100, dtype=np.uint64))
    send, counts, _, _, _ = jax.jit(
        lambda x: _route_by_owner(x, 2, 8, SENTINEL)
    )(flat)
    counts = np.asarray(counts)
    assert counts.sum() == 100  # true demand, not the truncated buffer
    assert counts.max() > 8  # the overflow the host must detect
    send = np.asarray(send)
    assert (send != SENTINEL).sum() == 16  # buffer capped at S*cap


def test_expand_provenance_contract():
    """expand_provenance must agree with expand_core on (uniq, count), its
    prim with game.primitive, and uidx must map every real child slot to
    that child's index in the uniq prefix (-1 exactly on invalid slots) —
    the invariant the gather-only backward pass rests on."""
    import jax

    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.solve.engine import (
        canonical_children,
        expand_core,
        expand_provenance,
        undecided_mask,
    )

    for spec in ("tictactoe", "connect4:w=4,h=4", "chomp:w=3,h=3"):
        game = get_game(spec)
        # A frontier with real states, duplicates of children guaranteed
        # (siblings share children via transpositions), and sentinel pads.
        rng = np.random.default_rng(7)
        init = game.initial_state()
        kids, _ = jax.jit(lambda s: game.expand(s))(
            jnp.asarray([init], dtype=game.state_dtype)
        )
        pool = np.unique(np.asarray(kids).reshape(-1))
        pool = pool[pool != game.sentinel]
        states = np.full(64, game.sentinel, dtype=game.state_dtype)
        states[: pool.shape[0]] = pool
        states_j = jnp.asarray(states)

        uniq_c, count_c = jax.jit(lambda s: expand_core(game, s))(states_j)
        uniq_p, count_p, uidx, prim = jax.jit(
            lambda s: expand_provenance(game, s)
        )(states_j)
        assert int(count_c) == int(count_p)
        assert (np.asarray(uniq_c) == np.asarray(uniq_p)).all()
        assert (
            np.asarray(prim) == np.asarray(jax.jit(game.primitive)(states_j))
        ).all()

        children, mask = jax.jit(
            lambda s: canonical_children(game, s, undecided_mask(game, s))
        )(states_j)
        flat = np.asarray(children).reshape(-1)
        m = np.asarray(mask).reshape(-1)
        ui = np.asarray(uidx)
        uq = np.asarray(uniq_p)
        n = int(count_p)
        for slot in range(flat.shape[0]):
            if flat[slot] == game.sentinel:
                assert ui[slot] == -1
            else:
                assert m[slot]
                assert 0 <= ui[slot] < n
                assert uq[ui[slot]] == flat[slot]
