"""Shared test helpers."""

import importlib.util
import pathlib

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent.parent
REF_GAMES = REPO / "examples" / "ref_games"


def load_module(path):
    """Import a reference-style scalar game module from a file path."""
    path = pathlib.Path(path)
    spec = importlib.util.spec_from_file_location(path.stem, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def full_table(result):
    """Flatten a SolveResult's per-level tables into {pos: (value, rem)}."""
    out = {}
    for table in result.levels.values():
        for s, v, r in zip(table.states, table.values, table.remoteness):
            out[int(s)] = (int(v), int(r))
    return out


def assert_table_parity(result, oracle_table):
    engine_table = full_table(result)
    assert len(engine_table) == len(oracle_table), (
        f"reachable-set size mismatch: engine {len(engine_table)} "
        f"vs oracle {len(oracle_table)}"
    )
    mismatches = []
    for pos, expected in oracle_table.items():
        got = engine_table.get(int(pos))
        if got != expected:
            mismatches.append((pos, expected, got))
            if len(mismatches) > 5:
                break
    assert not mismatches, f"value/remoteness mismatches: {mismatches}"
