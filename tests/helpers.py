"""Shared test helpers."""

import importlib.util
import pathlib

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent.parent
REF_GAMES = REPO / "examples" / "ref_games"


def load_module(path):
    """Import a reference-style scalar game module from a file path."""
    path = pathlib.Path(path)
    spec = importlib.util.spec_from_file_location(path.stem, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def full_table(result):
    """Flatten a SolveResult's per-level tables into {pos: (value, rem)}."""
    out = {}
    for table in result.levels.values():
        for s, v, r in zip(table.states, table.values, table.remoteness):
            out[int(s)] = (int(v), int(r))
    return out


def table_sha256(result):
    """sha256 over a SolveResult's level tables (states, values,
    remoteness, in level order) — the byte-parity fingerprint the
    gamedsl acceptance tests compare."""
    import hashlib

    h = hashlib.sha256()
    for level in sorted(result.levels):
        t = result.levels[level]
        h.update(np.asarray(t.states).tobytes())
        h.update(np.asarray(t.values).tobytes())
        h.update(np.asarray(t.remoteness).tobytes())
    return h.hexdigest()


def parse_prometheus_text(text):
    """Strict-enough parser for text exposition format v0.0.4: the test
    oracle for GET /metrics and render_prometheus(). Returns
    {family: {"type": kind, "help": str|None,
              "samples": [(name, {label: value}, float)]}}.
    Raises ValueError on anything a Prometheus scraper would reject
    (unknown line shape, sample before TYPE, unparseable value)."""
    import re

    families = {}
    current = None
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
    )
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            families.setdefault(
                name, {"type": None, "help": None, "samples": []}
            )["help"] = help_
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ValueError(f"bad TYPE line: {line!r}")
            families.setdefault(
                name, {"type": None, "help": None, "samples": []}
            )["type"] = kind
            current = name
        elif line.startswith("#"):
            continue
        else:
            m = sample_re.match(line)
            if not m:
                raise ValueError(f"unparseable sample line: {line!r}")
            name, labelstr, value = m.groups()
            fam = name
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[: -len(suffix)] if name.endswith(suffix) else None
                if base and base in families:
                    fam = base
                    break
            if fam not in families or families[fam]["type"] is None:
                # A lone HELP line does not make a family scrapeable.
                raise ValueError(f"sample {name!r} before its TYPE line")
            labels = dict(label_re.findall(labelstr or ""))
            if value == "+Inf":
                v = float("inf")
            elif value == "-Inf":
                v = float("-inf")
            else:
                v = float(value)  # raises ValueError on junk
            families[fam]["samples"].append((name, labels, v))
    if current is None and families:
        raise ValueError("no TYPE lines")
    return families


def assert_table_parity(result, oracle_table):
    engine_table = full_table(result)
    assert len(engine_table) == len(oracle_table), (
        f"reachable-set size mismatch: engine {len(engine_table)} "
        f"vs oracle {len(oracle_table)}"
    )
    mismatches = []
    for pos, expected in oracle_table.items():
        got = engine_table.get(int(pos))
        if got != expected:
            mismatches.append((pos, expected, got))
            if len(mismatches) > 5:
                break
    assert not mismatches, f"value/remoteness mismatches: {mismatches}"


# --------------------------------------------------- serving-fleet fakes

#: A scripted stand-in for serve/worker.py that speaks the heartbeat-pipe
#: protocol without importing jax or opening a DB, so supervisor
#: state-machine tests run in milliseconds. Modes: "ok" (ready + beats,
#: SIGTERM -> draining + exit 0), "crash" (die before ready — the
#: storm-breaker shape), "mute" (go ready, then stop beating — the
#: hang shape the liveness deadline kills), "slowdrain" (like "ok" but
#: takes a beat to exit after SIGTERM — keeps a rolling reload IN
#: PROGRESS long enough for tests to race it), "stuckdrain" (announces
#: draining on SIGTERM, then never exits — the wedged-teardown shape
#: the drain deadline must catch), "wedge" (closes its pipe mid-life
#: but lingers, SIGTERM-immune).
FAKE_FLEET_WORKER = r"""
import json, os, signal, sys, time
fd = int(sys.argv[1]); mode = sys.argv[2]
stop = []
signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
def send(**m):
    os.write(fd, (json.dumps(m) + "\n").encode())
send(type="hello", pid=os.getpid())
if mode == "crash":
    sys.exit(3)
send(type="ready", pid=os.getpid(), verified={"default": True},
     warmup_secs=0.01, games=["default"])
beats = 0
while not stop:
    time.sleep(0.02)
    beats += 1
    if mode == "mute" and beats > 3:
        continue
    if mode == "wedge" and beats > 3:
        # The wedged-teardown shape: pipe closed (EOF at the
        # supervisor) but the process lingers, ignoring SIGTERM.
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        os.close(fd)
        time.sleep(600)
    send(type="beat", status="ok")
send(type="draining")
if mode == "slowdrain":
    time.sleep(0.5)
if mode == "stuckdrain":
    time.sleep(600)
sys.exit(0)
"""


def fake_fleet_spawn(mode_for):
    """Build a ServeSupervisor ``spawn=`` hook running FAKE_FLEET_WORKER
    subprocesses; ``mode_for(slot_idx)`` picks each slot's script mode."""
    import os
    import subprocess
    import sys

    from gamesmanmpi_tpu.serve.supervisor import _ExecProc

    def spawn(slot_idx, cfg):
        r, w = os.pipe()
        proc = subprocess.Popen(
            [sys.executable, "-c", FAKE_FLEET_WORKER, str(w),
             mode_for(slot_idx)],
            pass_fds=(w,),
        )
        os.close(w)
        return _ExecProc(proc), r

    return spawn
