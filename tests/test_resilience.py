"""Resilience subsystem: fault injection, retry, integrity, degradation.

Acceptance axes (ISSUE 4):

* chaos parity — a solve KILLED at any registered fault point and then
  resumed produces a byte-identical table to an uninterrupted solve
  (subprocess tests, marked slow; ttt single-device + sharded connect4);
* transient recovery — an injected transient runtime error at each
  engine fault point is absorbed by retry (retry counter >= 1) with
  oracle-exact results, while an injected fatal error still fails fast
  with the checkpoint prefix intact (fast in-process tests, tier-1);
* checkpoint integrity — a sealed level whose bytes rot fails its
  manifest crc32, is quarantined (.corrupt) and recomputed from the
  intact prefix;
* serving degradation — reader faults trip the circuit breaker (503 +
  /healthz "degraded", never a hang past the request deadline) and the
  background half-open re-probe recovers to "ok" without a restart;
* fleet chaos (ISSUE 7) — the serve.worker_spawn / serve.heartbeat /
  serve.reload fault points: a crash-looping worker opens the
  restart-storm breaker, a heartbeat stall is killed and restarted,
  and a faulted rolling reload fails closed with the fleet untouched
  (lifecycle + load chaos live in tests/test_serve_fleet.py).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.resilience.faults import FatalFault, TransientFault
from gamesmanmpi_tpu.resilience.retry import is_transient, retry_call
from gamesmanmpi_tpu.resilience.supervisor import Watchdog
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.utils.checkpoint import (
    LevelCheckpointer,
    _loadz,
    file_crc32,
    save_result_npz,
)

from helpers import REPO, full_table

_CLI = [sys.executable, "-m", "gamesmanmpi_tpu.cli"]


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Every test starts and ends disarmed, with fast retry backoff."""
    monkeypatch.setenv("GAMESMAN_RETRY_BASE_SECS", "0.01")
    faults.clear()
    yield
    faults.clear()


#: The in-process tests' game: the 3x3 connect-3 board (694 positions,
#: uniform level jump -> fast path) — every engine/checkpoint code path
#: the resilience layer touches, at a fraction of tictactoe's cost. The
#: chaos subprocess tests below keep full tictactoe (the acceptance
#: game).
_C3 = "connect4:w=3,h=3,connect=3"


@pytest.fixture(scope="module")
def c3_clean():
    """Uninterrupted connect-3 solve: the in-process parity baseline."""
    return Solver(get_game(_C3)).solve()


@pytest.fixture(scope="module")
def ttt_clean():
    """Uninterrupted tictactoe solve: the chaos parity baseline."""
    return Solver(get_game("tictactoe")).solve()


# ----------------------------------------------------------- faults (unit)


def test_fault_spec_parsing_and_schedule():
    faults.configure("engine.forward:transient:2")
    faults.fire("engine.forward")  # visit 1: nothing
    with pytest.raises(TransientFault):
        faults.fire("engine.forward")  # visit 2: fires
    faults.fire("engine.forward")  # visit 3: nothing (one-shot schedule)

    faults.configure("db.probe:fatal:always")
    for _ in range(3):
        with pytest.raises(FatalFault):
            faults.fire("db.probe")

    # Seeded Bernoulli schedules replay identically.
    def sequence():
        faults.configure("serve.flush:transient:p0.5@7")
        fired = []
        for i in range(20):
            try:
                faults.fire("serve.flush")
                fired.append(False)
            except TransientFault:
                fired.append(True)
        return fired

    a, b = sequence(), sequence()
    assert a == b and any(a) and not all(a)

    with pytest.raises(ValueError):
        faults.configure("no.such.point:kill")
    with pytest.raises(ValueError):
        faults.configure("db.probe:frobnicate")
    faults.clear()
    faults.fire("db.probe")  # disarmed: free and silent


def test_transient_classification():
    assert is_transient(TransientFault("x"))
    assert not is_transient(FatalFault("x"))
    assert is_transient(RuntimeError("UNAVAILABLE: socket closed"))
    assert is_transient(RuntimeError("DEADLINE_EXCEEDED: relay stall"))
    assert not is_transient(RuntimeError("RESOURCE_EXHAUSTED: OOM"))
    assert not is_transient(ValueError("UNAVAILABLE"))  # not a runtime error
    assert not is_transient(KeyboardInterrupt())


def test_retry_call_reset_and_exhaustion():
    calls = []

    def flaky():
        calls.append("call")
        if len([c for c in calls if c == "call"]) < 3:
            raise TransientFault("injected transient")
        return "done"

    assert retry_call(
        flaky, point="t", reset=lambda: calls.append("reset"),
        attempts=3, base_secs=0,
    ) == "done"
    assert calls == ["call", "reset", "call", "reset", "call"]

    with pytest.raises(TransientFault):
        retry_call(lambda: (_ for _ in ()).throw(TransientFault("x")),
                   point="t", attempts=2, base_secs=0)
    with pytest.raises(FatalFault):  # fatal: no second call
        n = []
        retry_call(lambda: n.append(1) or (_ for _ in ()).throw(
            FatalFault("x")), point="t", attempts=3, base_secs=0)


# ------------------------------------------- transient recovery (engines)


@pytest.mark.parametrize(
    "point", ["engine.forward", "engine.dedup", "engine.backward"]
)
def test_transient_absorbed_at_engine_points(point, c3_clean):
    """An injected transient at each engine fault point is absorbed by
    retry (counter >= 1) with results identical to a clean solve."""
    faults.configure(f"{point}:transient:2")
    result = Solver(get_game(_C3)).solve()
    assert result.stats["retries"] >= 1
    assert (result.value, result.remoteness) == (
        c3_clean.value, c3_clean.remoteness
    )
    assert full_table(result) == full_table(c3_clean)


@pytest.mark.parametrize("point", ["sharded.forward", "sharded.backward"])
def test_transient_absorbed_at_sharded_points(point, c3_clean):
    from gamesmanmpi_tpu.parallel import ShardedSolver

    faults.configure(f"{point}:transient:2")
    result = ShardedSolver(get_game(_C3), num_shards=2).solve()
    assert result.stats["retries"] >= 1
    assert full_table(result) == full_table(c3_clean)


def test_transient_absorbed_generic_path():
    """Multi-jump (generic-path) forward/backward retry too."""
    from gamesmanmpi_tpu.solve.oracle import oracle_solve
    from helpers import REF_GAMES, load_module

    faults.configure("engine.forward:transient:1,engine.backward:transient:1")
    result = Solver(get_game("nim:heaps=3-4-5")).solve()
    assert result.stats["retries"] >= 2
    _, _, oracle = oracle_solve(load_module(REF_GAMES / "nim_345.py"))
    assert full_table(result) == oracle


def _fire_counts(game_spec, num_shards=2):
    """Per-point fault fire sequence of one clean sharded solve —
    locates specific retried units by visit index."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    seq = []
    real_fire = faults.fire

    def recording_fire(point, **kw):
        seq.append(point)
        return real_fire(point, **kw)

    faults.fire = recording_fire
    try:
        result = ShardedSolver(get_game(game_spec), num_shards=num_shards
                               ).solve()
    finally:
        faults.fire = real_fire
    return result, seq


def test_transient_absorbed_generic_sharded_check_merge():
    """GM603 regression (lint round 10): the generic forward path's
    level-check and merge dispatches are collective-safe-retried. Visit
    2 of sharded.forward on a multi-jump game is the first level's
    check step (visit 1 is its frontier expansion) — a transient there
    must be absorbed oracle-exact, not crash the solve."""
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.solve.oracle import oracle_solve
    from helpers import REF_GAMES, load_module

    clean, seq = _fire_counts("nim:heaps=3-4-5")
    forward_fires = seq.count("sharded.forward")
    # the generic path must fire MORE than once per level now that the
    # check/merge units are routed through _retry
    assert forward_fires > clean.stats["levels"], (forward_fires, seq)
    faults.configure("sharded.forward:transient:2")
    result = ShardedSolver(get_game("nim:heaps=3-4-5"), num_shards=2
                           ).solve()
    assert result.stats["retries"] >= 1
    _, _, oracle = oracle_solve(load_module(REF_GAMES / "nim_345.py"))
    assert full_table(result) == oracle


def test_transient_absorbed_at_sharded_root_step(c3_clean):
    """GM603 regression (lint round 10): the backward root-answer
    dispatch (a psum across shards) is retried too. The LAST
    sharded.backward fire of a solve is the root step — inject a
    transient exactly there and require absorption with the exact
    root answer."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    _, seq = _fire_counts(_C3)
    last_backward_visit = seq.count("sharded.backward")
    assert last_backward_visit > 0
    faults.configure(
        f"sharded.backward:transient:{last_backward_visit}"
    )
    result = ShardedSolver(get_game(_C3), num_shards=2).solve()
    assert result.stats["retries"] >= 1
    assert (result.value, result.remoteness) == (
        c3_clean.value, c3_clean.remoteness
    )
    assert full_table(result) == full_table(c3_clean)


def test_fatal_fails_fast_with_checkpoint_prefix_intact(tmp_path, c3_clean):
    """A fatal error mid-backward aborts immediately; the levels sealed
    before it remain loadable and the next run resumes to parity."""
    ck = LevelCheckpointer(tmp_path / "ck")
    faults.configure("engine.backward:fatal:3")
    with pytest.raises(FatalFault):
        Solver(get_game(_C3), checkpointer=ck).solve()
    # Prefix intact: forward discovery complete, >= 2 levels sealed
    # (visits 1-2 resolved + saved before visit 3 died).
    assert ck.load_manifest().get("frontiers_complete")
    sealed = ck.completed_levels()
    assert len(sealed) >= 2
    for k in sealed:
        ck.load_level(k)  # loads clean (atomic saves, valid crc)
    faults.clear()
    resumed = Solver(get_game(_C3), checkpointer=ck).solve()
    assert full_table(resumed) == full_table(c3_clean)


# -------------------------------------------------- checkpoint integrity


def _flip_byte(path, offset_frac=0.5):
    size = os.path.getsize(path)
    off = max(0, int(size * offset_frac))
    with open(path, "r+b") as fh:
        fh.seek(off)
        b = fh.read(1)
        fh.seek(off)
        fh.write(bytes([b[0] ^ 0xFF]))


@pytest.mark.parametrize("ckpt_mode", ["auto", "blocks"])
def test_crc_quarantines_corrupt_level_and_recomputes(tmp_path, c3_clean,
                                                      monkeypatch,
                                                      ckpt_mode):
    """Silent bit-rot in a sealed level: crc mismatch on resume ->
    quarantine (.corrupt) -> the level recomputes from the intact
    prefix -> parity. Parametrized over the block-compressed checkpoint
    format (ISSUE 9): torn compressed blocks must quarantine-and-degrade
    exactly like v1 files."""
    monkeypatch.setenv("GAMESMAN_CKPT_COMPRESS", ckpt_mode)
    ck = LevelCheckpointer(tmp_path / "ck")
    Solver(get_game(_C3), checkpointer=ck).solve()
    sealed = ck.completed_levels()
    victim = sealed[len(sealed) // 2]
    victim_file = tmp_path / "ck" / f"level_{victim:04d}.npz"
    recorded = ck.load_manifest()["crc"][victim_file.name]
    _flip_byte(victim_file)
    assert file_crc32(victim_file) != recorded  # the rot is real
    resumed = Solver(get_game(_C3),
                     checkpointer=LevelCheckpointer(tmp_path / "ck")).solve()
    assert full_table(resumed) == full_table(c3_clean)
    corrupt = list((tmp_path / "ck").glob("*.corrupt"))
    assert any(victim_file.name in p.name for p in corrupt)
    # The recompute re-sealed the level with a fresh crc.
    ck2 = LevelCheckpointer(tmp_path / "ck")
    assert victim in ck2.completed_levels()
    assert ck2.load_manifest()["crc"][victim_file.name] == \
        file_crc32(victim_file)


def test_crc_quarantines_corrupt_frontier_and_reexpands(tmp_path, c3_clean):
    """Bit-rot in a frontier file degrades the forward snapshot to the
    intact prefix (re-expansion resumes from its deepest level)."""
    ck = LevelCheckpointer(tmp_path / "ck")
    Solver(get_game(_C3), checkpointer=ck).solve()
    frontier = tmp_path / "ck" / "frontier_0004.npz"
    _flip_byte(frontier)
    resumed = Solver(get_game(_C3),
                     checkpointer=LevelCheckpointer(tmp_path / "ck")).solve()
    assert full_table(resumed) == full_table(c3_clean)
    assert (tmp_path / "ck" / "frontier_0004.npz.corrupt").exists()


def test_crc_verify_can_be_disabled(tmp_path, monkeypatch):
    ck = LevelCheckpointer(tmp_path / "ck")
    Solver(get_game(_C3), checkpointer=ck).solve()
    monkeypatch.setenv("GAMESMAN_CKPT_VERIFY", "0")
    # With verification off a rotted file is only caught if the zip
    # itself breaks — the knob exists for read-heavy resumes on trusted
    # storage. Just assert the clean path still loads.
    for k in ck.completed_levels():
        ck.load_level(k)


# -------------------------------------------------------------- watchdog


def test_watchdog_expires_on_stall_and_dumps_diagnostics(capfd):
    fired = threading.Event()
    records = []

    class Log:
        def log(self, rec):
            records.append(rec)

    prog = {"phase": "backward", "level": 3}
    wd = Watchdog(lambda: prog, min_secs=0.1, factor=2.0, poll=0.02,
                  action=fired.set, logger=Log()).start()
    try:
        assert fired.wait(5.0)
    finally:
        wd.stop()
    assert wd.expired
    assert records and records[0]["phase"] == "watchdog_abort"
    assert records[0]["progress"] == prog
    err = capfd.readouterr().err
    assert "stall detected" in err
    # Thread stacks were dumped (faulthandler output).
    assert "Current thread" in err or "Thread" in err


def test_watchdog_tracks_progress_and_adapts_deadline():
    fired = threading.Event()
    prog = {"phase": "forward", "level": 0}
    wd = Watchdog(lambda: prog, min_secs=0.2, factor=3.0, poll=0.02,
                  action=fired.set).start()
    try:
        for lvl in range(1, 4):  # steady progress: no expiry
            time.sleep(0.05)
            prog = {"phase": "forward", "level": lvl}
        assert not fired.is_set()
        assert wd.deadline() >= 0.2
    finally:
        wd.stop()


# ------------------------------------------------- serving degradation


@pytest.fixture(scope="module")
def nim_reader(tmp_path_factory):
    # Any registry game works for the degradation tests; the subtraction
    # game is the cheapest DB in the catalog.
    from gamesmanmpi_tpu.db import DbReader, export_result

    spec = "subtract:total=21,moves=1-2-3"
    d = tmp_path_factory.mktemp("resdb")
    export_result(Solver(get_game(spec)).solve(), d, spec)
    with DbReader(d) as reader:
        yield reader


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_breaker_opens_on_reader_faults_and_self_heals(nim_reader):
    """Batcher-level: consecutive reader faults trip the breaker; misses
    fail fast; the background half-open re-probe closes it once the
    reader heals — no restart, no client request spent probing."""
    from gamesmanmpi_tpu.obs import MetricsRegistry
    from gamesmanmpi_tpu.serve import Batcher, BatcherTripped

    pos = int(nim_reader.game.initial_state())
    batcher = Batcher(
        nim_reader, window=0.002, cache_size=0, breaker_threshold=2,
        breaker_cooldown=0.1, request_timeout=5.0,
        registry=MetricsRegistry(),
    )
    try:
        faults.configure("db.probe:fatal:always")
        for _ in range(2):  # two faulted flushes open the circuit
            with pytest.raises(FatalFault):
                batcher.submit([pos])
        deadline = time.monotonic() + 5
        while batcher.state == "ok" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert batcher.state != "ok"
        with pytest.raises(BatcherTripped) as e:
            batcher.submit([pos])
        assert e.value.retry_after >= 1
        assert batcher.metrics()["breaker_opens"] >= 1
        # Reader heals: the worker's half-open probe closes the circuit.
        faults.clear()
        deadline = time.monotonic() + 10
        while batcher.state != "ok" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert batcher.state == "ok"
        out = batcher.submit([pos])
        assert out[0][2] is True  # found again
    finally:
        batcher.close()


def test_server_degrades_and_recovers_over_http(nim_reader):
    """HTTP-level acceptance: injected reader faults -> 503 (never a
    hang past the deadline), /healthz 'degraded', breaker recovery to
    'ok' without a restart."""
    from gamesmanmpi_tpu.serve import QueryServer

    pos = int(nim_reader.game.initial_state())
    with QueryServer(
        nim_reader, window=0.002, cache_size=0,
        breaker_threshold=2, breaker_cooldown=0.1, request_timeout=2.0,
    ) as server:
        base = f"http://127.0.0.1:{server.port}"
        assert _get(base + "/healthz")[1]["status"] == "ok"
        faults.configure("db.probe:fatal:always")
        codes = []
        for _ in range(3):
            try:
                t0 = time.monotonic()
                _post(base + "/query", {"positions": [pos]})
                codes.append(200)
            except urllib.error.HTTPError as e:
                codes.append(e.code)
                if e.code == 503:
                    assert e.headers["Retry-After"] is not None
            assert time.monotonic() - t0 < 5  # never hangs
        assert 500 in codes  # the raw reader faults
        deadline = time.monotonic() + 5
        while (_get(base + "/healthz")[1]["status"] != "degraded"
               and time.monotonic() < deadline):
            time.sleep(0.02)
        health = _get(base + "/healthz")[1]
        assert health["status"] == "degraded"
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base + "/query", {"positions": [pos]})
        assert e.value.code == 503
        # Heal the reader; the breaker closes in the background.
        faults.clear()
        deadline = time.monotonic() + 10
        while (_get(base + "/healthz")[1]["status"] != "ok"
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert _get(base + "/healthz")[1]["status"] == "ok"
        status, body = _post(base + "/query", {"positions": [pos]})
        assert status == 200 and body["results"][0]["found"]
        metrics = _get(base + "/metrics.json")[1]
        assert metrics["reader_faults"] >= 2
        assert metrics["breaker_opens"] >= 1


def test_request_deadline_times_out_as_503(nim_reader):
    """A wedged flush (injected delay) must answer 503 + Retry-After
    within the request deadline, not hang the client."""
    from gamesmanmpi_tpu.serve import QueryServer

    pos = int(nim_reader.game.initial_state())
    faults.configure("serve.flush:delay=0.5:always")
    with QueryServer(
        nim_reader, window=0.001, cache_size=0, request_timeout=0.05,
    ) as server:
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"http://127.0.0.1:{server.port}/query",
                  {"positions": [pos]})
        assert e.value.code == 503
        assert e.value.headers["Retry-After"] is not None
        assert time.monotonic() - t0 < 2
        assert server.metrics()["timeouts"] >= 1


def test_chaos_slow_block_decode_dominates_trace_and_burns_slo(
        tmp_path_factory, monkeypatch):
    """ISSUE 17 chaos acceptance, single-server sized: arm a
    ``serve.block_decode`` delay fault on a compressed (v2) DB and (1)
    the sampled trace — joined by the CLIENT's minted trace id — must
    attribute the latency to the decode span, and (2) the latency SLO's
    fast-window burn rate must cross fast-burn (healthz 'degraded')
    during the fault and recover after it without a restart."""
    from gamesmanmpi_tpu.db import DbReader, export_result
    from gamesmanmpi_tpu.obs.qtrace import (
        format_traceparent,
        mint_trace_ids,
    )
    from gamesmanmpi_tpu.serve import QueryServer

    spec = "subtract:total=21,moves=1-2-3"
    d = tmp_path_factory.mktemp("chaosv2")
    export_result(Solver(get_game(spec)).solve(), d, spec, compress=True)
    # Keep-everything head sampling is NOT needed: the delayed queries
    # are kept as "slow". Shrink the SLO windows/volume gate so a
    # handful of slow requests trips fast-burn and a few seconds of
    # health recovers it (BUCKET_SECS=1 makes that honest).
    monkeypatch.setenv("GAMESMAN_TRACE_SLOW_MS", "60")
    monkeypatch.setenv("GAMESMAN_SLO_P99_MS", "60")
    monkeypatch.setenv("GAMESMAN_SLO_MIN_REQUESTS", "4")
    monkeypatch.setenv("GAMESMAN_SLO_FAST_WINDOW_SECS", "4")
    # One slow request among seven is a ~14x burn on the 1% latency
    # budget — just under the 14.4 default, so declare the paging
    # threshold this test means to cross.
    monkeypatch.setenv("GAMESMAN_SLO_FAST_BURN", "5")
    delay_ms = 150.0
    # Positions in DISTINCT solve levels: each level is its own v2
    # block stream, so every query forces a fresh (delayed) decode —
    # same-level repeats would hit the decoded-block cache and be fast.
    positions = [20, 17, 14, 11, 8, 5, 2]
    with DbReader(d) as reader, QueryServer(
        reader, window=0.001, cache_size=0, request_timeout=10.0,
    ) as server:
        base = f"http://127.0.0.1:{server.port}"
        faults.configure(f"serve.block_decode:delay={delay_ms / 1e3}"
                         ":always")
        tid, sid = mint_trace_ids()
        req = urllib.request.Request(
            base + "/query",
            data=json.dumps({"positions": [positions[0]]}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": format_traceparent(tid, sid)},
            method="POST",
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 200
            body = json.loads(resp.read())
        client_ms = (time.perf_counter() - t0) * 1e3
        assert body["results"][0]["found"]
        assert client_ms >= delay_ms  # the fault really ran

        # Join the server-side sampled trace by the client's trace id.
        rec = next(t for t in _get(base + "/traces")[1]["traces"]
                   if t["trace_id"] == tid)
        assert rec["keep"] == "slow" and rec["parent_id"] == sid
        decode_ms = sum(s["dur_ms"] for s in rec["spans"]
                        if s["name"] == "block_decode")
        assert decode_ms >= delay_ms * 0.9
        # The decode span dominates the trace, and the traced duration
        # accounts for the client-observed latency (within the HTTP +
        # loopback overhead).
        assert decode_ms >= 0.5 * rec["dur_ms"]
        assert rec["dur_ms"] <= client_ms

        # Burn the latency budget: every remaining cold-level query
        # eats the decode delay, all inside the 2s fast window.
        for pos in positions[1:]:
            status, body = _post(base + "/query", {"positions": [pos]})
            assert status == 200 and body["results"][0]["found"]
        health = _get(base + "/healthz")[1]
        lat = health["slo"]["routes"]["default"]["latency"]
        assert lat["fast_burn"] and lat["burn_fast"] > 5.0
        assert health["status"] == "degraded"  # pre-emptive amber

        # The fault ends; the decoded blocks are cached, traffic is
        # fast again, and the fast window forgets the bad second.
        faults.clear()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            for pos in positions:
                _post(base + "/query", {"positions": [pos]})
            health = _get(base + "/healthz")[1]
            if health["status"] == "ok":
                break
            time.sleep(0.25)
        assert health["status"] == "ok"
        assert not health["slo"]["routes"]["default"]["latency"][
            "fast_burn"]


def test_drain_flips_healthz_and_refuses_new_queries(nim_reader):
    from gamesmanmpi_tpu.serve import QueryServer

    pos = int(nim_reader.game.initial_state())
    with QueryServer(nim_reader) as server:
        base = f"http://127.0.0.1:{server.port}"
        assert _post(base + "/query", {"positions": [pos]})[0] == 200
        server.begin_drain()
        assert _get(base + "/healthz")[1]["status"] == "draining"
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base + "/query", {"positions": [pos]})
        assert e.value.code == 503


# -------------------------------------------------- chaos (subprocess)


def _run_cli(args, extra_env=None, timeout=600):
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env.pop("GAMESMAN_FAULTS", None)
    env.update(extra_env or {})
    return subprocess.run(
        _CLI + list(args), capture_output=True, text=True,
        timeout=timeout, env=env, cwd=str(REPO),
    )


def _assert_tables_equal(a, b):
    # _loadz, not np.load: byte-parity means LOGICAL table equality, and
    # a blocks-mode run's --table-out is block-framed on disk (the
    # ckpt_mode chaos parametrization compares against a plain golden).
    with _loadz(a) as za, _loadz(b) as zb:
        assert sorted(za.files) == sorted(zb.files)
        for f in za.files:
            assert np.array_equal(za[f], zb[f]), f


@pytest.fixture(scope="module")
def ttt_clean_table(tmp_path_factory, ttt_clean):
    path = tmp_path_factory.mktemp("golden") / "ttt.npz"
    save_result_npz(path, ttt_clean)
    return path


#: Every solve-path fault point a single-device run visits. This is the
#: systematized chaos surface: killing at each, resuming, and asserting
#: byte parity is the whole-failure-surface generalization of PR 3's
#: one-off edge-spill-resume test.
_SINGLE_POINTS = [
    "engine.forward", "engine.dedup", "engine.backward",
    "ckpt.save_frontier", "ckpt.save_level",
]


@pytest.mark.slow
@pytest.mark.parametrize("point", _SINGLE_POINTS)
def test_chaos_kill_and_resume_parity_ttt(point, tmp_path, ttt_clean_table):
    ck = tmp_path / "ck"
    killed = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": f"{point}:kill:2"},
    )
    assert killed.returncode == faults.KILL_EXIT_CODE, (
        f"{point}: expected injected death, got rc={killed.returncode}\n"
        + killed.stderr[-2000:]
    )
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck), "--table-out", str(out)]
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    assert "positions: 5478" in resumed.stdout
    _assert_tables_equal(out, ttt_clean_table)


@pytest.mark.slow
@pytest.mark.parametrize("ckpt_mode", ["auto", "blocks"])
def test_chaos_torn_seal_and_resume_parity(tmp_path, ttt_clean_table,
                                           ckpt_mode):
    """The torn-write kind: a sealed level file is truncated and the
    process dies. Resume must quarantine (crc/zip failure) and
    recompute to parity — identically when the checkpoint is
    block-compressed (GAMESMAN_CKPT_COMPRESS=blocks, ISSUE 9): a torn
    compressed file is just one more TORN_NPZ_ERRORS shape."""
    ck = tmp_path / "ck"
    killed = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": "ckpt.save_level:torn:2",
         "GAMESMAN_CKPT_COMPRESS": ckpt_mode},
    )
    assert killed.returncode == faults.TORN_EXIT_CODE, killed.stderr[-2000:]
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck), "--table-out", str(out)],
        {"GAMESMAN_CKPT_COMPRESS": ckpt_mode},
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    _assert_tables_equal(out, ttt_clean_table)
    assert list(ck.glob("*.corrupt")), "torn file was not quarantined"


@pytest.mark.slow
def test_chaos_double_death_resume(tmp_path, ttt_clean_table):
    """Die mid-backward, then die again during the resume's level load,
    then finish: two deaths, one checkpoint directory, exact parity."""
    ck = tmp_path / "ck"
    first = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": "engine.backward:kill:3"},
    )
    assert first.returncode == faults.KILL_EXIT_CODE, first.stderr[-2000:]
    second = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": "ckpt.load_level:kill:1"},
    )
    assert second.returncode == faults.KILL_EXIT_CODE, second.stderr[-2000:]
    out = tmp_path / "resumed.npz"
    final = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck), "--table-out", str(out)]
    )
    assert final.returncode == 0, final.stderr[-2000:]
    _assert_tables_equal(out, ttt_clean_table)


_C4 = "connect4:w=4,h=4"


@pytest.fixture(scope="module")
def c4_clean_table(tmp_path_factory):
    from gamesmanmpi_tpu.parallel import ShardedSolver

    path = tmp_path_factory.mktemp("golden") / "c4.npz"
    save_result_npz(
        path, ShardedSolver(get_game(_C4), num_shards=2).solve()
    )
    return path


@pytest.mark.slow
@pytest.mark.parametrize(
    "point",
    [
        "sharded.forward", "sharded.backward", "ckpt.save_level",
        # ISSUE 11: death on the write-behind worker right after a
        # queued payload write lands, BEFORE its seal can run — the
        # unsealed stray must be invisible to resume (the solve thread
        # may already be a level ahead when the kill fires).
        "store.writebehind",
    ],
)
def test_chaos_kill_and_resume_parity_sharded_c4(point, tmp_path,
                                                 c4_clean_table):
    ck = tmp_path / "ck"
    killed = _run_cli(
        [_C4, "--devices", "2", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": f"{point}:kill:3"},
    )
    assert killed.returncode == faults.KILL_EXIT_CODE, (
        f"{point}: expected injected death, got rc={killed.returncode}\n"
        + killed.stderr[-2000:]
    )
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        [_C4, "--devices", "2", "--checkpoint-dir", str(ck),
         "--table-out", str(out)]
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    _assert_tables_equal(out, c4_clean_table)


@pytest.mark.slow
def test_chaos_watchdog_aborts_wedged_solve(tmp_path):
    """A wedged level (injected long delay) under the watchdog exits 124
    with diagnostics; the checkpoint prefix resumes to completion."""
    import signal as _signal  # noqa: F401 - documents the non-signal abort

    ck = tmp_path / "ck"
    wedged = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck), "--watchdog-secs", "1"],
        {"GAMESMAN_FAULTS": "engine.backward:delay=120:2",
         "GAMESMAN_WATCHDOG_FACTOR": "1"},
        timeout=300,
    )
    assert wedged.returncode == 124, (
        f"rc={wedged.returncode}\n" + wedged.stderr[-2000:]
    )
    assert "stall detected" in wedged.stderr
    resumed = _run_cli(["tictactoe", "--checkpoint-dir", str(ck)])
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    assert "positions: 5478" in resumed.stdout


# ------------------------------------ distributed chaos (ISSUE 6)
#
# The rank-death scenarios: under REAL 2-process execution (tools/
# launch_multihost.py) a transient at a collective fault point must be
# retried by ALL ranks together, a dead rank must turn into a
# coordinated abort (every survivor exits 124 within the barrier
# deadline, checkpoint prefix intact), and a full restart must resume
# to byte-parity — never a hang. Fault points covered here:
# sharded.collective (collective entry), coord.barrier (epoch-barrier
# proposal), coord.handshake (coordinator dial).


def _coordinated_world1_solver(game_spec, num_shards=2):
    """A sharded solver driven through the collective-safe retry
    protocol with a real (world-1) consensus service: every retry
    decision is a genuine epoch round over a loopback socket, in one
    process — the tier-1 way to exercise _retry_collective."""
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.resilience.coordination import (
        Coordination,
        CoordinatorServer,
        EpochBarrier,
    )

    solver = ShardedSolver(get_game(game_spec), num_shards=num_shards)
    srv = CoordinatorServer(1, deadline=10.0)
    solver.coord = Coordination(
        EpochBarrier(srv.address, 0, deadline=10.0), srv
    )
    return solver


def test_coordinated_retry_at_collective_point(c3_clean):
    """A transient at sharded.collective (the collective-entry fault
    point) resolves through a consensus round into a coordinated retry:
    counter bumped, results oracle-exact."""
    faults.configure("sharded.collective:transient:2")
    result = _coordinated_world1_solver(_C3).solve()
    assert result.stats["retries"] >= 1
    assert full_table(result) == full_table(c3_clean)


def test_coordinated_abort_on_fatal_at_collective_point():
    """A fatal at the collective entry aborts through the same round —
    fail fast, no retry loop, coordination torn down cleanly."""
    faults.configure("sharded.collective:fatal:2")
    solver = _coordinated_world1_solver(_C3)
    with pytest.raises(FatalFault):
        solver.solve()
    assert solver.retries == 0


def test_coordinated_abort_attribution():
    """ABORT decisions must attribute correctly: a rank whose own
    verdict was ABORT fails fast with ITS error; a rank that proposed
    retry (or was healthy) aborts because of a PEER and must raise
    CoordinatedAbort — the exception the CLI maps to exit 124 — never
    its own retryable error as if the fleet had refused a retry."""
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.resilience.coordination import (
        ABORT,
        RETRY,
        CoordinatedAbort,
    )

    solver = ShardedSolver(get_game(_C3), num_shards=2)
    fatal = FatalFault("own fatal")
    with pytest.raises(FatalFault):
        solver._coordinated_abort("sharded.forward", 3, fatal, ABORT)
    flaky = TransientFault("flaky link")
    with pytest.raises(CoordinatedAbort) as ei:
        solver._coordinated_abort("sharded.forward", 3, flaky, RETRY)
    assert ei.value.__cause__ is flaky
    assert "proposed retry" in str(ei.value)
    with pytest.raises(CoordinatedAbort, match="healthy"):
        solver._coordinated_abort("sharded.forward", 3, None, ABORT)


def _launch_world(args, tmp, per_rank_env=None, env=None, timeout=240):
    from tools import launch_multihost

    return launch_multihost.launch(
        list(args), processes=2, timeout=timeout, log_dir=str(tmp),
        per_rank_env=per_rank_env, env=env,
    )


_NO_BACKEND = "Multiprocess computations aren't implemented"


def _skip_unless_world_spawned(ranks):
    if any(r.returncode != 0 and _NO_BACKEND in r.stderr for r in ranks):
        pytest.skip("backend cannot run multiprocess collectives "
                    "(no CPU Gloo) — the harness cannot spawn a world")


@pytest.mark.slow
def test_chaos_rank_death_coordinated_abort_and_resume(tmp_path):
    """THE rank-death acceptance scenario: SIGKILL one rank mid-level on
    a 2-process sharded connect4 solve. The survivor must abort within
    the barrier deadline (exit 124, not a harness kill), the checkpoint
    prefix must stay intact, and a full restart must resume to
    byte-parity with an uninterrupted solve."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    ck = tmp_path / "ck"
    t0 = time.monotonic()
    ranks = _launch_world(
        [_C4, "--devices", "4", "--checkpoint-dir", str(ck)],
        tmp_path,
        env={"GAMESMAN_BARRIER_SECS": "10",
             "GAMESMAN_COLLECTIVE_TIMEOUT": "60"},
        per_rank_env={1: {"GAMESMAN_FAULTS": "sharded.forward:kill:3"}},
        timeout=150,
    )
    elapsed = time.monotonic() - t0
    _skip_unless_world_spawned(ranks)
    by = {r.rank: r for r in ranks}
    assert by[1].returncode == faults.KILL_EXIT_CODE, (
        by[1].returncode, by[1].stderr[-2000:]
    )
    # The survivor exited THROUGH the coordinated-abort contract — 124
    # within the deadline — not None (a straggler the harness killed).
    assert by[0].returncode == 124, (
        by[0].returncode, by[0].stderr[-2000:]
    )
    assert elapsed < 150, "survivor did not abort within the deadline"
    assert "coordinated abort" in by[0].stderr.lower()
    # Prefix intact: whatever sealed before the death loads clean.
    ck_obj = LevelCheckpointer(ck)
    for k in ck_obj.completed_levels():
        ck_obj.load_level(k)
    # Full restart reaches byte-parity with an uninterrupted 4-shard run.
    ranks2 = _launch_world(
        [_C4, "--devices", "4", "--checkpoint-dir", str(ck),
         "--table-out", str(tmp_path / "resumed.npz")],
        tmp_path,
    )
    for r in ranks2:
        assert r.returncode == 0, (r.rank, r.stderr[-2000:])
    golden = tmp_path / "golden.npz"
    save_result_npz(
        golden, ShardedSolver(get_game(_C4), num_shards=4).solve()
    )
    _assert_tables_equal(tmp_path / "resumed.rank0.npz", golden)


@pytest.mark.slow
def test_chaos_transient_on_one_rank_retries_on_all_ranks(tmp_path):
    """Acceptance: a transient injected at the collective fault point on
    ONE rank is retried consistently on all ranks — the solve completes
    and gamesman_retries_total agrees across ranks."""
    ranks = _launch_world(
        [_C3, "--devices", "4",
         "--metrics-out", str(tmp_path / "metrics.json")],
        tmp_path,
        env={"GAMESMAN_BARRIER_SECS": "20"},
        per_rank_env={1: {"GAMESMAN_FAULTS": "sharded.collective:transient:2"}},
    )
    _skip_unless_world_spawned(ranks)
    for r in ranks:
        assert r.returncode == 0, (r.rank, r.stderr[-2000:])
        assert "value: TIE" in r.stdout and "remoteness: 9" in r.stdout
    retries = []
    for rank in range(2):
        snap = json.loads(
            (tmp_path / f"metrics.rank{rank}.json").read_text()
        )
        rows = snap["gamesman_retries_total"]["values"]
        assert all(row["labels"]["rank"] == str(rank) for row in rows)
        retries.append(sum(int(row["value"]) for row in rows))
    # The faulted rank AND the healthy rank absorbed the same retry —
    # the whole point of the consensus round.
    assert retries[0] == retries[1] >= 1, retries


@pytest.mark.slow
@pytest.mark.parametrize(
    "spec", ["coord.handshake:kill:1", "coord.barrier:kill:2"]
)
def test_chaos_rank_death_at_coordination_points(spec, tmp_path):
    """A rank dying INSIDE the coordination layer itself (dialing the
    coordinator; at an epoch-barrier proposal) still resolves into a
    coordinated abort of the survivors within the deadline, and a clean
    restart completes."""
    ck = tmp_path / "ck"
    ranks = _launch_world(
        [_C3, "--devices", "4", "--checkpoint-dir", str(ck)],
        tmp_path,
        env={"GAMESMAN_BARRIER_SECS": "8"},
        per_rank_env={1: {"GAMESMAN_FAULTS": spec}},
        timeout=150,
    )
    _skip_unless_world_spawned(ranks)
    by = {r.rank: r for r in ranks}
    assert by[1].returncode == faults.KILL_EXIT_CODE, (
        by[1].returncode, by[1].stderr[-2000:]
    )
    assert by[0].returncode == 124, (
        by[0].returncode, by[0].stderr[-2000:]
    )
    ranks2 = _launch_world(
        [_C3, "--devices", "4", "--checkpoint-dir", str(ck)], tmp_path
    )
    for r in ranks2:
        assert r.returncode == 0, (r.rank, r.stderr[-2000:])
        assert "value: TIE" in r.stdout


@pytest.mark.slow
def test_serve_sigterm_drains_gracefully(tmp_path):
    """`cli serve` under SIGTERM: drains (stderr says so) and exits 0
    instead of dying mid-request with no teardown."""
    from gamesmanmpi_tpu.db import export_result

    spec = "subtract:total=10,moves=1-2"
    db = tmp_path / "db"
    export_result(Solver(get_game(spec)).solve(), db, spec)
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env.pop("GAMESMAN_FAULTS", None)
    proc = subprocess.Popen(
        _CLI + ["serve", str(db), "--port", "0",
                "--jsonl", str(tmp_path / "serve.jsonl")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(REPO),
    )
    try:
        line = proc.stdout.readline()
        assert "serving" in line, line
        port = int(line.split("http://127.0.0.1:")[1].split(" ")[0].strip())
        status, health = _get(f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert health["status"] == "ok"
        proc.send_signal(subprocess.signal.SIGTERM)
        rc = proc.wait(timeout=60)
        assert rc == 0
        assert "draining" in proc.stderr.read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# ------------------------------------ preemption grace + enospc (ISSUE 12)
#
# Two new failure classes above the kill/torn matrix: SIGTERM/SIGUSR1
# must drain the solve to a level boundary and exit 75 with everything
# complete sealed (grace), and an injected OSError(ENOSPC) —
# GAMESMAN_FAULTS kind `enospc`, incl. at store.writebehind — must fail
# fast exactly like a torn write: prefix intact, resume to byte-parity,
# never a wrong answer. The campaign layer above both lives in
# tests/test_campaign.py.


def _arm_preempt_on_fire(point, visit):
    """Deliver SIGUSR1 to ourselves at the `visit`th fire of `point`:
    a deterministic mid-solve preemption (the handler runs on the main
    thread before the next bytecode, so the flag is set before the next
    level boundary)."""
    import signal as _signal

    state = {"n": 0}
    real_fire = faults.fire

    def firing(p, **kw):
        if p == point:
            state["n"] += 1
            if state["n"] == visit:
                _signal.raise_signal(_signal.SIGUSR1)
        return real_fire(p, **kw)

    faults.fire = firing
    return lambda: setattr(faults, "fire", real_fire)


def test_preempt_drains_at_boundary_and_resumes_parity(tmp_path, c3_clean):
    """In-process grace: SIGUSR1 mid-backward raises
    PreemptionRequested at the next level boundary; sealed levels load
    clean and the resumed solve reaches parity."""
    from gamesmanmpi_tpu.resilience import preempt

    ck = LevelCheckpointer(tmp_path / "ck")
    restore = preempt.install_grace_handler()
    unfire = _arm_preempt_on_fire("engine.backward", 2)
    try:
        with pytest.raises(preempt.PreemptionRequested):
            Solver(get_game(_C3), checkpointer=ck).solve()
    finally:
        unfire()
        restore()  # also resets the flag + disarms the deadline
    assert not preempt.requested()
    sealed = ck.completed_levels()
    assert sealed  # backward visit 2 resolved+sealed at least one level
    for k in sealed:
        ck.load_level(k)
    resumed = Solver(get_game(_C3),
                     checkpointer=LevelCheckpointer(tmp_path / "ck")).solve()
    assert full_table(resumed) == full_table(c3_clean)


def test_preempt_sharded_coordinated_round(tmp_path, c3_clean):
    """The sharded boundary check is a consensus round (world-1 here):
    a preempted solve unwinds through PreemptionRequested with pending
    seals flushed, and resumes to parity."""
    from gamesmanmpi_tpu.resilience import preempt

    ck = LevelCheckpointer(tmp_path / "ck")
    solver = _coordinated_world1_solver(_C3)
    solver.checkpointer = ck
    restore = preempt.install_grace_handler()
    unfire = _arm_preempt_on_fire("sharded.backward", 2)
    try:
        with pytest.raises(preempt.PreemptionRequested):
            solver.solve()
    finally:
        unfire()
        restore()
    for k in ck.completed_levels():
        ck.load_level(k)
    from gamesmanmpi_tpu.parallel import ShardedSolver

    resumed = ShardedSolver(
        get_game(_C3), num_shards=2,
        checkpointer=LevelCheckpointer(tmp_path / "ck"),
    ).solve()
    assert full_table(resumed) == full_table(c3_clean)


def test_preempt_not_transient_and_resets():
    from gamesmanmpi_tpu.resilience import preempt

    assert not is_transient(preempt.PreemptionRequested("x"))
    preempt.reset()
    assert not preempt.requested()
    preempt.check("forward", level=0)  # disarmed: no raise


def test_enospc_fault_kind_fails_fast_prefix_intact(tmp_path, c3_clean):
    """`enospc` at a sealed-level write point: OSError(ENOSPC), never
    retried (a full disk refills), prefix intact, resume to parity —
    the torn-write degrade contract."""
    import errno

    ck = LevelCheckpointer(tmp_path / "ck")
    faults.configure("ckpt.save_level:enospc:2")
    with pytest.raises(OSError) as ei:
        Solver(get_game(_C3), checkpointer=ck).solve()
    assert ei.value.errno == errno.ENOSPC
    assert not is_transient(ei.value)  # retrying ENOSPC is wrong
    sealed = ck.completed_levels()
    for k in sealed:
        ck.load_level(k)  # whatever sealed before the death loads clean
    faults.clear()
    resumed = Solver(get_game(_C3),
                     checkpointer=LevelCheckpointer(tmp_path / "ck")).solve()
    assert full_table(resumed) == full_table(c3_clean)


def test_oom_fault_kind_fails_fast_prefix_intact(tmp_path, c3_clean):
    """`oom` at a backward point: MemoryError carrying the
    RESOURCE_EXHAUSTED marker (the campaign classifier's food), never
    retried (an OOM at a fixed shape OOMs again), prefix intact,
    resume to parity — the enospc contract for memory."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    ck = LevelCheckpointer(tmp_path / "ck")
    faults.configure("sharded.backward:oom:2")
    with pytest.raises(MemoryError) as ei:
        ShardedSolver(get_game(_C3), num_shards=2,
                      checkpointer=ck).solve()
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    assert not is_transient(ei.value)  # retrying an OOM is wrong
    sealed = ck.completed_levels()
    for k in sealed:
        ck.load_level(k)  # whatever sealed before the death loads clean
    faults.clear()
    resumed = ShardedSolver(
        get_game(_C3), num_shards=2,
        checkpointer=LevelCheckpointer(tmp_path / "ck"),
    ).solve()
    assert full_table(resumed) == full_table(c3_clean)


def test_memguard_trips_at_boundary_and_resume_parity(tmp_path, c3_clean,
                                                      monkeypatch):
    """The host-memory guard: past GAMESMAN_HOST_MEM_LIMIT_MB the solve
    raises HostMemoryExceeded at the NEXT level boundary — prefix
    sealed, resume (limit lifted) to parity; off by default."""
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.resilience import memguard

    memguard.check("forward", level=0)  # disarmed: no raise
    ck = LevelCheckpointer(tmp_path / "ck")
    monkeypatch.setenv("GAMESMAN_HOST_MEM_LIMIT_MB", "1")  # any RSS trips
    with pytest.raises(memguard.HostMemoryExceeded) as ei:
        ShardedSolver(get_game(_C3), num_shards=2,
                      checkpointer=ck).solve()
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    assert not is_transient(ei.value)
    monkeypatch.delenv("GAMESMAN_HOST_MEM_LIMIT_MB")
    resumed = ShardedSolver(
        get_game(_C3), num_shards=2,
        checkpointer=LevelCheckpointer(tmp_path / "ck"),
    ).solve()
    assert full_table(resumed) == full_table(c3_clean)


@pytest.mark.slow
@pytest.mark.parametrize("point", ["sharded.forward:oom:3",
                                   "sharded.backward:oom:2"])
def test_chaos_oom_resumes_parity(tmp_path, c4_clean_table, point):
    """oom injected in a whole process at a forward and a backward
    point (the chaos-matrix entries for the `oom` kind): the process
    dies with classifiable RESOURCE_EXHAUSTED/out-of-memory
    diagnostics on stderr — what the campaign's death classifier reads
    as `oom` — and resume reaches byte-parity."""
    ck = tmp_path / "ck"
    died = _run_cli(
        [_C4, "--devices", "2", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": point},
    )
    assert died.returncode != 0
    assert "out of memory" in died.stderr
    assert "RESOURCE_EXHAUSTED" in died.stderr
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        [_C4, "--devices", "2", "--checkpoint-dir", str(ck),
         "--table-out", str(out)]
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    _assert_tables_equal(out, c4_clean_table)


@pytest.mark.slow
def test_chaos_enospc_mid_writebehind_resumes_parity(tmp_path,
                                                     c4_clean_table):
    """enospc injected on the write-behind worker (store.writebehind):
    the ticket failure surfaces at the seal on the solve thread, the
    process dies with the prefix intact — an unsealed stray at worst —
    and resume reaches byte-parity. The enospc chaos-matrix entry for
    the sharded engine."""
    ck = tmp_path / "ck"
    died = _run_cli(
        [_C4, "--devices", "2", "--checkpoint-dir", str(ck)],
        {"GAMESMAN_FAULTS": "store.writebehind:enospc:3"},
    )
    assert died.returncode != 0
    assert "No space left on device" in died.stderr
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        [_C4, "--devices", "2", "--checkpoint-dir", str(ck),
         "--table-out", str(out)]
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    _assert_tables_equal(out, c4_clean_table)


@pytest.mark.slow
def test_chaos_sigterm_preempts_single_process(tmp_path, ttt_clean_table):
    """Whole-process grace: SIGTERM mid-backward -> exit 75 within the
    grace deadline, 'preempted' diagnostics on stderr, resume to
    byte-parity."""
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_FAULTS"] = "engine.backward:delay=0.7:always"
    env["GAMESMAN_PREEMPT_GRACE_SECS"] = "60"
    ck = tmp_path / "ck"
    proc = subprocess.Popen(
        _CLI + ["tictactoe", "--checkpoint-dir", str(ck)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(REPO),
    )
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if list(ck.glob("level_*.npz")):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("solve never sealed a level")
        t0 = time.monotonic()
        proc.send_signal(subprocess.signal.SIGTERM)
        rc = proc.wait(timeout=120)
        graced = time.monotonic() - t0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    _, err = proc.communicate()
    from gamesmanmpi_tpu.resilience.preempt import GRACE_EXIT_CODE

    assert rc == GRACE_EXIT_CODE, err[-2000:]
    assert graced < 60, "drain blew the grace deadline"
    assert "preempted" in err
    out = tmp_path / "resumed.npz"
    resumed = _run_cli(
        ["tictactoe", "--checkpoint-dir", str(ck),
         "--table-out", str(out)]
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    _assert_tables_equal(out, ttt_clean_table)


@pytest.mark.slow
def test_chaos_sigterm_multiprocess_grace_both_ranks(tmp_path):
    """SIGTERM to BOTH ranks mid-level: the rank-coordinated boundary
    round makes every rank drain at the same program point — each exits
    75 (or 124 if wedged past the deadline), never a hang, never a torn
    tree — and a restart resumes to parity."""
    from tools.launch_multihost import start_world

    from gamesmanmpi_tpu.resilience.preempt import GRACE_EXIT_CODE

    ck = tmp_path / "ck"
    delay = "sharded.backward:delay=0.7:always"
    env = dict(os.environ)
    env.update({
        "GAMESMAN_PLATFORM": "cpu",
        "GAMESMAN_BARRIER_SECS": "20",
        "GAMESMAN_PREEMPT_GRACE_SECS": "90",
        "GAMESMAN_FAULTS_RANK_0": delay,
        "GAMESMAN_FAULTS_RANK_1": delay,
    })
    env.pop("GAMESMAN_FAULTS", None)
    world = start_world(
        [_C3, "--devices", "4", "--checkpoint-dir", str(ck)],
        processes=2, log_dir=str(tmp_path), env=env,
    )
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        if list(ck.glob("level_*.shard_*.npz")):
            break
        time.sleep(0.1)
    world.send_signal(subprocess.signal.SIGTERM)
    ranks = world.wait(120)
    _skip_unless_world_spawned(ranks)
    for r in ranks:
        assert r.returncode in (GRACE_EXIT_CODE, 124), (
            r.rank, r.returncode, r.stderr[-2000:]
        )
    # At least one rank drained through the grace path proper.
    assert any(r.returncode == GRACE_EXIT_CODE for r in ranks), [
        r.returncode for r in ranks
    ]
    ck_obj = LevelCheckpointer(ck)
    for k in ck_obj.completed_levels():
        ck_obj.load_level(k)
    ranks2 = _launch_world(
        [_C3, "--devices", "4", "--checkpoint-dir", str(ck)], tmp_path
    )
    for r in ranks2:
        assert r.returncode == 0, (r.rank, r.stderr[-2000:])
        assert "value: TIE" in r.stdout


@pytest.mark.slow
def test_chaos_kill_sweep_every_level_boundary(tmp_path):
    """ISSUE 12 satellite: resume-under-kill at EVERY level boundary of
    a small sharded solve — not sampled points. A clean checkpointed
    run counts the level-seal visits; then each visit index in turn is
    a kill schedule, and every resume depth must reach byte-parity."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    ck0 = tmp_path / "count_ck"
    seq = []
    real_fire = faults.fire

    def recording_fire(point, **kw):
        seq.append(point)
        return real_fire(point, **kw)

    faults.fire = recording_fire
    try:
        clean = ShardedSolver(
            get_game(_C3), num_shards=2,
            checkpointer=LevelCheckpointer(ck0),
        ).solve()
    finally:
        faults.fire = real_fire
    golden = tmp_path / "golden.npz"
    save_result_npz(golden, clean)
    boundaries = seq.count("ckpt.save_level")
    assert boundaries >= 5, seq  # every solved level seals once
    for visit in range(1, boundaries + 1):
        ck = tmp_path / f"ck_{visit:02d}"
        killed = _run_cli(
            [_C3, "--devices", "2", "--checkpoint-dir", str(ck)],
            {"GAMESMAN_FAULTS": f"ckpt.save_level:kill:{visit}"},
        )
        assert killed.returncode == faults.KILL_EXIT_CODE, (
            f"visit {visit}: rc={killed.returncode}\n"
            + killed.stderr[-2000:]
        )
        out = tmp_path / f"resumed_{visit:02d}.npz"
        resumed = _run_cli(
            [_C3, "--devices", "2", "--checkpoint-dir", str(ck),
             "--table-out", str(out)]
        )
        assert resumed.returncode == 0, (
            f"visit {visit}:\n" + resumed.stderr[-2000:]
        )
        _assert_tables_equal(out, golden)


# ------------------------------------------------- serving fleet chaos


def _fleet_db(tmp_path):
    from gamesmanmpi_tpu.db import export_result

    spec = "subtract:total=10,moves=1-2"
    db = tmp_path / "db"
    export_result(Solver(get_game(spec)).solve(), db, spec)
    return db


def _fleet_proc(db, tmp_path, extra_env):
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_SERVE_RESTART_BASE_SECS"] = "0.05"
    env.pop("GAMESMAN_FAULTS", None)
    env.update(extra_env)
    return subprocess.Popen(
        _CLI + ["serve", str(db), "--port", "0", "--workers", "2",
                "--control-port", "0"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=str(REPO),
    )


def _fleet_ports(proc):
    banner = proc.stdout.readline()
    assert "serving fleet" in banner, banner
    return (int(banner.split("http://127.0.0.1:")[1].split(" ")[0]),
            int(banner.split("http://127.0.0.1:")[2].split(" ")[0]))


def test_chaos_worker_spawn_crashloop_opens_storm_breaker(tmp_path):
    """serve.worker_spawn chaos: a worker whose every spawn dies at the
    fault point (a rotted replica shape — the same failure recurs on
    each restart) trips the slot's restart-storm breaker; the healthy
    worker keeps the fleet answering, degraded."""
    db = _fleet_db(tmp_path)
    proc = _fleet_proc(db, tmp_path, {
        "GAMESMAN_FAULTS_WORKER_0": "serve.worker_spawn:fatal:always",
        "GAMESMAN_SERVE_STORM_RESTARTS": "2",
        "GAMESMAN_SERVE_STORM_SECS": "600",
    })
    try:
        port, cport = _fleet_ports(proc)
        control = f"http://127.0.0.1:{cport}"
        deadline = time.monotonic() + 120
        st = {}
        while time.monotonic() < deadline:
            st = _get(control + "/healthz")[1]
            if st["workers"]["0"]["breaker"] == "open" \
                    and st["workers"]["1"]["state"] == "ready":
                break
            time.sleep(0.2)
        assert st["workers"]["0"]["breaker"] == "open", st
        assert st["workers"]["0"]["state"] == "broken"
        assert st["workers"]["0"]["restarts"] >= 2
        # The injected warm-start refusal is attributed on the slot.
        assert "rc=3" in st["workers"]["0"]["last_error"]
        assert st["status"] == "degraded"
        # The surviving worker still answers through the shared socket.
        status, body = _post(f"http://127.0.0.1:{port}/query",
                             {"positions": [10]})
        assert status == 200
        assert body["results"][0]["found"]
        proc.send_signal(subprocess.signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_chaos_heartbeat_stall_is_killed_and_restarted(tmp_path):
    """serve.heartbeat chaos: a delay injected on the beat path stalls
    the worker's liveness signal; the supervisor's beat deadline turns
    the silent hang into SIGKILL + backoff restart while the sibling
    keeps serving."""
    db = _fleet_db(tmp_path)
    proc = _fleet_proc(db, tmp_path, {
        # The 2nd beat of worker 0 sleeps far past the beat deadline.
        "GAMESMAN_FAULTS_WORKER_0": "serve.heartbeat:delay=60:2",
        "GAMESMAN_SERVE_HEARTBEAT_SECS": "0.1",
        "GAMESMAN_SERVE_HEARTBEAT_TIMEOUT": "1.0",
    })
    try:
        port, cport = _fleet_ports(proc)
        control = f"http://127.0.0.1:{cport}"
        deadline = time.monotonic() + 120
        st = {}
        while time.monotonic() < deadline:
            st = _get(control + "/healthz")[1]
            if st["workers"]["0"]["restarts"] >= 1 \
                    and st["workers"]["1"]["state"] == "ready":
                break
            time.sleep(0.2)
        assert st["workers"]["0"]["restarts"] >= 1, st
        status, body = _post(f"http://127.0.0.1:{port}/query",
                             {"positions": [10]})
        assert status == 200
        assert body["results"][0]["found"]
    finally:
        proc.kill()
        proc.wait()


def test_chaos_reload_fault_fails_closed_fleet_untouched(tmp_path):
    """serve.reload chaos: a fault at the top of a rolling reload fails
    the RELOAD, not the fleet — no worker is drained, the error is
    reported on /healthz state, and the next (clean) reload rolls."""
    from gamesmanmpi_tpu.serve import ServeSupervisor, single_db_entries

    from helpers import fake_fleet_spawn

    db = _fleet_db(tmp_path)
    faults.configure("serve.reload:fatal:1")
    sup = ServeSupervisor(
        single_db_entries(db), workers=2, control_port=None,
        heartbeat_secs=0.05, heartbeat_timeout=5.0, restart_base=0.01,
        spawn=fake_fleet_spawn(lambda i: "ok"),
    ).start()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sup.status()["status"] == "ok":
                break
            time.sleep(0.05)
        gen0_pids = {w["pid"] for w in sup.status()["workers"].values()}
        sup.request_reload()
        st = {}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = sup.status()
            if st["last_reload_error"]:
                break
            time.sleep(0.05)
        assert "FatalFault" in (st["last_reload_error"] or ""), st
        assert st["gen"] == 0
        assert st["reloads_done"] == 0
        assert st["status"] == "ok"
        # No worker was drained by the failed reload.
        assert {w["pid"] for w in st["workers"].values()} == gen0_pids
        # The fault was one-shot (visit 1): the next reload rolls clean.
        sup.request_reload()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = sup.status()
            if st["reloads_done"] == 1 and st["status"] == "ok":
                break
            time.sleep(0.05)
        assert st["reloads_done"] == 1, st
        assert st["gen"] == 1
        assert st["last_reload_error"] is None
    finally:
        sup.stop()


# --------------------------------------------------- registry chaos matrix
#
# DB distribution (ISSUE 19) fault points, every shape the pull/publish/
# solve-on-demand paths claim to survive, run through real subprocesses:
#
#   registry.fetch:torn      torn download mid-range -> resumed pull
#   registry.install:kill    SIGKILL before rename-install -> no install
#   registry.publish:kill    death after payload, before catalog seal
#   jobs.claim:kill          runner SIGKILL after the fsync'd claim
#
# The invariant in all four: the fleet-visible state (catalog, installed
# epoch, job ledger) either did not change or converges on retry —
# never a half-landed epoch a worker could serve from.


@pytest.fixture(scope="module")
def registry_db(tmp_path_factory):
    """Tiny subtract DB: the registry chaos tests' published artifact."""
    from gamesmanmpi_tpu.db import export_result

    spec = "subtract:total=10,moves=1-2"
    d = tmp_path_factory.mktemp("regdb") / "sub"
    export_result(Solver(get_game(spec)).solve(), d, spec)
    return d


def _registry_env(**extra):
    env = dict(os.environ, GAMESMAN_PLATFORM="cpu")
    env.pop("GAMESMAN_FAULTS", None)
    env.update(extra)
    return env


def test_chaos_torn_download_resumes_to_verified_install(
        registry_db, tmp_path):
    """registry.fetch chaos: a download torn mid-range (file truncated,
    process killed) leaves only quarantined staging bytes; re-running
    the pull resumes from the verified prefix and installs a DB that
    passes the full integrity gate."""
    from gamesmanmpi_tpu.db.check import check_db
    from gamesmanmpi_tpu.registry.server import RegistryServer, publish_db

    root = tmp_path / "registry"
    publish_db(root, "sub", registry_db)
    srv = RegistryServer(root)
    srv.start()
    try:
        dest = tmp_path / "replica"
        cmd = [sys.executable, str(REPO / "tools" / "pull_db.py"),
               srv.url, "sub", "--dest", str(dest), "--json"]
        torn = subprocess.run(
            cmd, env=_registry_env(GAMESMAN_FAULTS="registry.fetch:torn:2"),
            capture_output=True, text=True, cwd=str(REPO), timeout=120,
        )
        assert torn.returncode == faults.TORN_EXIT_CODE, torn.stderr[-2000:]
        # Nothing installed; the partial bytes live only in staging.
        assert not [d for d in dest.iterdir()
                    if not d.name.startswith(".")]
        assert list((dest / ".registry_tmp").rglob("*"))
        clean = subprocess.run(
            cmd, env=_registry_env(), capture_output=True, text=True,
            cwd=str(REPO), timeout=120,
        )
        assert clean.returncode == 0, clean.stderr[-2000:]
        rec = json.loads(clean.stdout)["pulled"][0]
        assert rec["installed"]
        # The fully-verified file from before the tear was NOT refetched.
        assert rec["resumed_files"] >= 1
        assert check_db(rec["db"]) == []
    finally:
        srv.stop()


def test_chaos_kill_before_install_keeps_replica_clean(
        registry_db, tmp_path):
    """registry.install chaos: SIGKILL after every staged file verified
    but before the atomic rename leaves NO installed epoch (a fleet
    manifest can never name it); the re-run reuses every verified
    staged file and installs."""
    from gamesmanmpi_tpu.registry.server import RegistryServer, publish_db

    root = tmp_path / "registry"
    rec = publish_db(root, "sub", registry_db)
    srv = RegistryServer(root)
    srv.start()
    try:
        dest = tmp_path / "replica"
        cmd = [sys.executable, str(REPO / "tools" / "pull_db.py"),
               srv.url, "sub", "--dest", str(dest), "--json"]
        killed = subprocess.run(
            cmd,
            env=_registry_env(GAMESMAN_FAULTS="registry.install:kill:1"),
            capture_output=True, text=True, cwd=str(REPO), timeout=120,
        )
        assert killed.returncode == faults.KILL_EXIT_CODE, \
            killed.stderr[-2000:]
        assert not [d for d in dest.iterdir()
                    if not d.name.startswith(".")]
        rerun = subprocess.run(
            cmd, env=_registry_env(), capture_output=True, text=True,
            cwd=str(REPO), timeout=120,
        )
        assert rerun.returncode == 0, rerun.stderr[-2000:]
        out = json.loads(rerun.stdout)["pulled"][0]
        assert out["installed"]
        # Every file was already staged + verified: zero refetches.
        assert out["resumed_files"] == len(rec["files"])
        assert out["refetched_files"] == 0
    finally:
        srv.stop()


def test_chaos_publish_kill_keeps_old_catalog_authoritative(
        registry_db, tmp_path):
    """registry.publish chaos: the publisher dying AFTER the payload
    directory lands but BEFORE the catalog seal must leave the old
    catalog authoritative (replicas keep pulling the old epoch); a
    re-publish of the same DB converges to a sealed catalog."""
    from gamesmanmpi_tpu.registry.server import catalog_seal, load_catalog

    root = tmp_path / "registry"
    cmd = _CLI + ["registry", "publish", str(registry_db),
                  "--root", str(root), "--name", "sub"]
    killed = subprocess.run(
        cmd, env=_registry_env(GAMESMAN_FAULTS="registry.publish:kill:1"),
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert killed.returncode == faults.KILL_EXIT_CODE, killed.stderr[-2000:]
    # Payload landed, but the catalog never sealed the new epoch.
    assert list((root / "dbs" / "sub").iterdir())
    cat = load_catalog(root)
    assert cat["dbs"] == {}
    assert cat["seal"] == catalog_seal({})
    republish = subprocess.run(
        cmd, env=_registry_env(), capture_output=True, text=True,
        cwd=str(REPO), timeout=120,
    )
    assert republish.returncode == 0, republish.stderr[-2000:]
    cat = load_catalog(root)
    assert set(cat["dbs"]) == {"sub"}
    assert cat["seal"] == catalog_seal(cat["dbs"])


def test_chaos_runner_sigkill_at_claim_resumes_to_published_db(tmp_path):
    """jobs.claim chaos: the solve-on-demand runner SIGKILLed right
    after its claim record is fsync'd leaves a running job with a dead
    pid; the next runner's classify-and-resume reclaims it and drives
    the job all the way to a published catalog epoch."""
    from gamesmanmpi_tpu.registry.jobs import JobQueue
    from gamesmanmpi_tpu.registry.server import load_catalog

    root = tmp_path / "registry"
    queue = JobQueue(root / "jobs.jsonl")
    job = queue.enqueue("subtract:total=5,moves=1-2", name="sub5")
    cmd = _CLI + ["registry", "run-jobs", "--root", str(root), "--once"]
    killed = subprocess.run(
        cmd, env=_registry_env(GAMESMAN_FAULTS="jobs.claim:kill:1"),
        capture_output=True, text=True, cwd=str(REPO), timeout=180,
    )
    assert killed.returncode == faults.KILL_EXIT_CODE, killed.stderr[-2000:]
    # The claim is durable: the ledger shows a running job whose pid is
    # dead — exactly what the reclaim classifier looks for.
    state = queue.jobs()[job["id"]]
    assert state["state"] == "running"
    resumed = subprocess.run(
        cmd, env=_registry_env(), capture_output=True, text=True,
        cwd=str(REPO), timeout=600,
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    out = json.loads(resumed.stdout)
    assert out["results"][0]["ok"], out
    assert queue.jobs()[job["id"]]["state"] == "done"
    assert "sub5" in load_catalog(root)["dbs"]
