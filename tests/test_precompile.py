"""solve/precompile.py: background compile scheduling semantics.

These run with stub "jit functions" (no real XLA compiles), so they cover
the scheduler's contract — idempotence, eviction, heavy-slot routing,
transient-only retry — on the CPU test mesh where the engine keeps the
precompiler off.
"""

import threading
import time

import pytest

from gamesmanmpi_tpu.solve.precompile import Precompiler


class _StubLowered:
    def __init__(self, result, fail=None):
        self._result = result
        self._fail = fail

    def compile(self):
        if self._fail is not None:
            raise self._fail
        return self._result


class _StubFn:
    """Stands in for a jax.jit function: lower(*avals).compile()."""

    def __init__(self, result="exe", fail_first=None, delay=0.0,
                 fail_always=None):
        self.result = result
        self.fail_first = fail_first
        self.fail_always = fail_always
        self.delay = delay
        self.calls = 0
        self.lock = threading.Lock()

    def lower(self, *avals):
        with self.lock:
            self.calls += 1
            calls = self.calls
        if self.delay:
            time.sleep(self.delay)
        if self.fail_always is not None:
            return _StubLowered(None, fail=self.fail_always)
        if self.fail_first is not None and calls == 1:
            return _StubLowered(None, fail=self.fail_first)
        return _StubLowered(self.result)


@pytest.fixture
def make_pre():
    """Construct Precompilers that are CLOSED at test end — each instance
    spawns a worker pool, and un-closed test instances leaked 30+ idle
    threads into the rest of the suite."""
    made = []

    def factory():
        pre = Precompiler()
        made.append(pre)
        return pre

    yield factory
    for pre in made:
        pre.close()


def test_schedule_is_idempotent_and_get_evicts(make_pre):
    pre = make_pre()
    fn = _StubFn(result="exe1")
    pre.schedule("k", fn, ())
    pre.schedule("k", fn, ())  # duplicate: must not enqueue twice
    assert pre.get("k", block=True) == "exe1"
    assert fn.calls == 1
    # Consumed futures are evicted: the caller's kernel cache owns the
    # executable now, and a re-schedule is possible.
    assert not pre.scheduled("k")
    assert pre.get("k") is None


def test_unscheduled_key_returns_none(make_pre):
    pre = make_pre()
    assert pre.get("missing") is None
    assert not pre.scheduled("missing")


def test_transient_failure_retries_once(monkeypatch, make_pre):
    # Patch the backoff so the test doesn't sleep 8 s.
    import gamesmanmpi_tpu.solve.precompile as pc

    monkeypatch.setattr(pc.time, "sleep", lambda s: None)
    pre = make_pre()
    fn = _StubFn(result="exe", fail_first=RuntimeError("HTTP 500: boom"))
    pre.schedule("k", fn, ())
    assert pre.get("k", block=True) == "exe"
    assert fn.calls == 2  # failed once, retried once


def test_deterministic_failure_does_not_retry(monkeypatch, make_pre):
    import gamesmanmpi_tpu.solve.precompile as pc

    monkeypatch.setattr(pc.time, "sleep", lambda s: None)
    pre = make_pre()
    fn = _StubFn(fail_always=ValueError("bad shape"))
    pre.schedule("k", fn, ())
    # Failure is swallowed (caller falls back to inline jit) and evicted
    # so a later retry is possible.
    assert pre.get("k", block=True) is None
    assert fn.calls == 1
    assert not pre.scheduled("k")


def test_heavy_jobs_do_not_starve_light_jobs(monkeypatch, make_pre):
    """With every heavy slot busy, queued heavy work must be requeued so
    light compiles keep flowing through the pool."""
    import gamesmanmpi_tpu.solve.precompile as pc

    monkeypatch.setenv("GAMESMAN_COMPILE_WORKERS", "2")
    monkeypatch.setenv("GAMESMAN_HEAVY_COMPILES", "1")
    pre = make_pre()
    slow_heavy = _StubFn(result="h1", delay=1.0)
    pre.schedule("h1", slow_heavy, (), heavy=True)
    pre.schedule("h2", _StubFn(result="h2", delay=1.0), (), heavy=True)
    pre.schedule("light", _StubFn(result="l"), ())
    t0 = time.time()
    assert pre.get("light", block=True) == "l"
    # The light job must complete while h1 still holds the only heavy
    # slot (h2 requeued) — i.e. well under the 2 s of serialized heavy
    # work.
    assert time.time() - t0 < 1.0
    assert pre.get("h1", block=True) == "h1"
    assert pre.get("h2", block=True) == "h2"


def test_sds_shape_dtype():
    import numpy as np

    from gamesmanmpi_tpu.solve.precompile import sds

    s = sds((4,), np.uint32)
    assert s.shape == (4,) and s.dtype == np.uint32
