"""DB registry: signed catalog, crash-safe pull, solve-on-demand.

Acceptance axes (ISSUE 19):

* publish/catalog — a published DB becomes an immutable epoch in a
  sha256-sealed catalog; tampering with the catalog fails the pull
  client's seal check; re-publishing an unchanged DB is a no-op;
* verified pull — every file is staged in quarantine, checksummed
  (crc32 + sha256) BEFORE the atomic rename-install, and admitted
  through verify_for_serving; rot is quarantined (`.corrupt`), never
  installed; interrupted pulls resume from verified bytes;
* fleet integration — a fork-mode CLI fleet serving epoch A keeps
  answering with ZERO failed requests while epoch B is pulled,
  verified, installed and rolled in (sync_fleet -> POST /reload); a
  rotted epoch is quarantined with the fleet untouched;
* solve-on-demand — a query for an unregistered game becomes a durable
  deduped job (fsync'd append-only ledger) that a runner drives through
  campaign -> export -> publish; admission control bounds queue depth;
  the ledger survives torn tails and dead claims (classify-and-resume;
  the SIGKILL shapes live in tests/test_resilience.py).

Satellites: fleet-manifest half-landed-DB rejection, db_equal_fast
digest screen + check_db --same-as/--deep, load_gen soak progress.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from gamesmanmpi_tpu.db import export_result
from gamesmanmpi_tpu.db.check import check_db, db_equal, db_equal_fast
from gamesmanmpi_tpu.db.format import MANIFEST_NAME, file_sha256
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.registry.jobs import JobQueue, QueueRefused
from gamesmanmpi_tpu.registry.pull import (
    PullError,
    ensure_db,
    fetch_catalog,
    pull_db,
    sync_fleet,
)
from gamesmanmpi_tpu.registry.server import (
    RegistryServer,
    catalog_seal,
    load_catalog,
    publish_db,
)
from gamesmanmpi_tpu.serve.manifest import load_fleet_manifest
from gamesmanmpi_tpu.solve import Solver

from helpers import REPO, load_module

_CLI = [sys.executable, "-m", "gamesmanmpi_tpu.cli"]
_SPEC = "subtract:total=10,moves=1-2"


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _wait_for(pred, timeout=60.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def sub_result():
    """One solve, shared by every export in this module."""
    return Solver(get_game(_SPEC)).solve()


@pytest.fixture(scope="module")
def sub_db(sub_result, tmp_path_factory):
    """Epoch A: the plain (v1) subtract DB."""
    d = tmp_path_factory.mktemp("regdbA") / "sub"
    export_result(sub_result, d, _SPEC)
    return d


@pytest.fixture(scope="module")
def sub_db_v2(sub_result, tmp_path_factory):
    """Epoch B: the SAME solved content, block-compressed — different
    stored bytes (different epoch), identical answers."""
    d = tmp_path_factory.mktemp("regdbB") / "sub"
    export_result(sub_result, d, _SPEC, compress=True)
    return d


@pytest.fixture()
def registry(tmp_path):
    srv = RegistryServer(tmp_path / "registry")
    srv.start()
    yield srv
    srv.stop()


# ------------------------------------------------------ publish / catalog


def test_publish_seals_catalog_and_is_idempotent(tmp_path, sub_db):
    root = tmp_path / "reg"
    rec = publish_db(root, "sub", sub_db)
    assert rec["epoch"] == file_sha256(sub_db / MANIFEST_NAME)
    assert {f["name"] for f in rec["files"]} \
        >= {MANIFEST_NAME, "level_0000.keys.npy"}
    cat = load_catalog(root)
    assert cat["seal"] == catalog_seal(cat["dbs"])
    assert cat["dbs"]["sub"]["epoch"] == rec["epoch"]
    # Published payload is a copy: a valid DB in its own right. The
    # record's path is root-relative (the catalog must survive the
    # registry root moving).
    assert check_db(root / rec["path"]) == []
    # Same DB again: no new epoch, no catalog churn.
    again = publish_db(root, "sub", sub_db)
    assert again["epoch"] == rec["epoch"]
    assert load_catalog(root) == cat


def test_catalog_http_and_tamper_detection(registry, tmp_path, sub_db):
    publish_db(registry.root, "sub", sub_db)
    doc = fetch_catalog(registry.url)
    assert set(doc["dbs"]) == {"sub"}
    status, man = _get(f"{registry.url}/db/sub/manifest")
    assert status == 200 and man["name"] == "sub" and man["files"]
    # Unknown DB: 404 that tells the client solve-on-demand exists.
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{registry.url}/db/nope/manifest")
    assert e.value.code == 404
    # A tampered catalog (rotted disk, MITM, truncated write) fails the
    # pull client's seal check — no silent wrong-epoch pull.
    cat_path = registry.root / "catalog.json"
    doc = json.loads(cat_path.read_text())
    doc["dbs"]["sub"]["epoch"] = "0" * 64
    cat_path.write_text(json.dumps(doc))
    with pytest.raises(PullError, match="seal"):
        fetch_catalog(registry.url)


# ------------------------------------------------------------------- pull


def test_pull_installs_verified_and_reruns_noop(registry, tmp_path, sub_db):
    publish_db(registry.root, "sub", sub_db)
    dest = tmp_path / "replica"
    rec = pull_db(registry.url, "sub", dest)
    assert rec["installed"]
    assert rec["epoch"] == file_sha256(sub_db / MANIFEST_NAME)
    assert check_db(rec["db"]) == []
    # Identical content to the source, proven by digest alone.
    assert db_equal_fast(sub_db, rec["db"]) == ("same", [])
    again = pull_db(registry.url, "sub", dest)
    assert not again["installed"]
    assert again["db"] == rec["db"]


def test_pull_refetches_rotted_staging_bytes(registry, tmp_path, sub_db):
    """Garbage pre-staged in quarantine (a torn earlier pull, cosmic
    rays, a liar of a filesystem) must be detected by checksum and
    refetched — never installed."""
    publish_db(registry.root, "sub", sub_db)
    epoch12 = file_sha256(sub_db / MANIFEST_NAME)[:12]
    dest = tmp_path / "replica"
    stage = dest / ".registry_tmp" / f"sub@{epoch12}"
    stage.mkdir(parents=True)
    # Same size as the real file, wrong bytes: the resume fast path
    # can't skip it, the checksum catches it, trial 2 refetches clean.
    real = (sub_db / "level_0000.keys.npy").read_bytes()
    (stage / "level_0000.keys.npy").write_bytes(b"\xff" * len(real))
    rec = pull_db(registry.url, "sub", dest)
    assert rec["installed"]
    assert rec["refetched_files"] >= 1
    assert check_db(rec["db"]) == []
    # The quarantined garbage did not survive into the install.
    import pathlib

    assert not list(pathlib.Path(rec["db"]).glob("*.corrupt"))


def test_pull_quarantines_epoch_that_fails_admission(
        registry, tmp_path, sub_db):
    """A DB whose files all match their published checksums but whose
    CONTENT fails the serving gate (the publisher sealed rot) must end
    quarantined, not installed — the last line of defense."""
    import numpy as np

    rotted = tmp_path / "rotted"
    import shutil

    shutil.copytree(sub_db, rotted)
    # Rot the payload (zeroed cells decode to UNDECIDED — a solver-bug
    # shape), then re-seal its digest in the manifest so every
    # transport-level checksum passes and only verify_for_serving can
    # object.
    cells_file = rotted / "level_0003.cells.npy"
    np.save(cells_file.with_suffix(""),
            np.zeros_like(np.load(cells_file)))
    man = json.loads((rotted / MANIFEST_NAME).read_text())
    man["levels"]["3"]["cells_sha256"] = file_sha256(cells_file)
    (rotted / MANIFEST_NAME).write_text(json.dumps(man))
    publish_db(registry.root, "sub", rotted)
    dest = tmp_path / "replica"
    with pytest.raises(PullError, match="quarantin"):
        pull_db(registry.url, "sub", dest)
    installs = [d for d in dest.iterdir() if not d.name.startswith(".")]
    assert all(d.name.endswith(".corrupt") for d in installs), installs


# ------------------------------------------------- satellites: validation


def test_fleet_manifest_rejects_half_landed_db(tmp_path):
    """A manifest entry pointing at a directory with no DB manifest (a
    half-landed pull) must fail validation NAMING the entry, before any
    worker is touched."""
    empty = tmp_path / "dbs" / "sub"
    empty.mkdir(parents=True)
    manifest = tmp_path / "fleet.json"
    manifest.write_text(json.dumps({
        "version": 1, "games": [{"name": "sub", "db": "dbs/sub"}],
    }))
    with pytest.raises(ValueError) as e:
        load_fleet_manifest(manifest)
    msg = str(e.value)
    assert "games[0]" in msg and "sub" in msg and MANIFEST_NAME in msg


def test_db_equal_fast_verdicts(tmp_path, sub_db, sub_db_v2):
    # Identical bytes: digest screen alone proves equality.
    twin = tmp_path / "twin"
    import shutil

    shutil.copytree(sub_db, twin)
    assert db_equal_fast(sub_db, twin) == ("same", [])
    # Same content, different storage: inconclusive by design — and the
    # deep compare it defers to says "identical".
    verdict, diffs = db_equal_fast(sub_db, sub_db_v2)
    assert verdict == "unknown"
    assert diffs
    assert db_equal(sub_db, sub_db_v2) == []
    # Different game: the manifests alone settle it.
    other = tmp_path / "other"
    export_result(
        Solver(get_game("subtract:total=6,moves=1-2")).solve(), other,
        "subtract:total=6,moves=1-2",
    )
    verdict, diffs = db_equal_fast(sub_db, other)
    assert verdict == "different"
    assert diffs


def test_check_db_cli_same_as_fast_then_deep(sub_db, sub_db_v2, capsys):
    check_db_cli = load_module(REPO / "tools" / "check_db.py")
    # Identical twin: fast path decides, no decode.
    assert check_db_cli.main(
        [str(sub_db), "--same-as", str(sub_db), "--quiet"]) == 0
    # v1 vs v2 twin: screen is inconclusive, deep compare passes.
    assert check_db_cli.main(
        [str(sub_db), "--same-as", str(sub_db_v2), "--quiet"]) == 0
    # --deep forces the streamed compare outright.
    assert check_db_cli.main(
        [str(sub_db), "--same-as", str(sub_db_v2), "--deep",
         "--quiet"]) == 0
    capsys.readouterr()


def test_load_gen_soak_emits_progress(tmp_path):
    """Soak mode: periodic cumulative snapshots while the load runs —
    pointed at a dead port so every request classifies as dropped and
    the test needs no server."""
    load_gen = load_module(REPO / "tools" / "load_gen.py")
    snaps = []
    rec = load_gen.run_load(
        "http://127.0.0.1:9", [1, 2, 3], duration=1.0, concurrency=2,
        timeout=0.2, progress_secs=0.25, progress=snaps.append,
    )
    assert rec["dropped"] > 0 and rec["ok"] == 0
    assert len(snaps) >= 2
    assert {"t_secs", "requests", "qps", "p99_ms", "errors", "dropped",
            "mismatches"} <= set(snaps[0])
    assert snaps[-1]["requests"] >= snaps[0]["requests"]


def test_load_gen_honors_retry_after_on_503(tmp_path):
    """A 503 carrying Retry-After is its own outcome class
    (shed_retried) and the thread actually sleeps the advertised delay
    before its next request; a malformed header degrades to a plain
    shed with no sleep."""
    import http.server

    load_gen = load_module(REPO / "tools" / "load_gen.py")

    class _Shedding(http.server.BaseHTTPRequestHandler):
        retry_after = "10"  # capped to 5 s — longer than the run

        def log_message(self, fmt, *args):
            pass

        def _shed(self):
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                self.rfile.read(length)
            body = b'{"error": "draining"}'
            self.send_response(503)
            if self.retry_after is not None:
                self.send_header("Retry-After", self.retry_after)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        do_GET = _shed
        do_POST = _shed

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Shedding)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_port}"
    try:
        rec = load_gen.run_load(
            base, [1, 2, 3], duration=0.5, concurrency=2, timeout=10,
        )
        assert rec["shed_retried"] >= 1 and rec["shed"] == 0
        assert rec["errors"] == 0 and rec["mismatches"] == 0
        # The honored (capped 5 s > duration) sleep parks each thread
        # after its first shed instead of hammering the draining server.
        assert rec["shed_retried"] <= 2 * 2
        assert rec["requests"] == rec["shed_retried"] + rec["dropped"]
        # Malformed header: classification falls back to plain shed.
        _Shedding.retry_after = "later"
        rec2 = load_gen.run_load(
            base, [1, 2, 3], duration=0.3, concurrency=2, timeout=10,
        )
        assert rec2["shed"] >= 1 and rec2["shed_retried"] == 0
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------------------- solve-on-demand


def test_job_queue_durable_dedup_admission(tmp_path, monkeypatch):
    q = JobQueue(tmp_path / "jobs.jsonl")
    job = q.enqueue("subtract:total=6,moves=1-2", name="sub6")
    assert job["state"] == "pending"
    # Dedup: same (name, spec) is the same job, not a second solve.
    assert q.enqueue("subtract:total=6,moves=1-2",
                     name="sub6")["id"] == job["id"]
    assert q.depth() == 1
    # State is ledger replay: a fresh handle sees the same queue.
    assert JobQueue(tmp_path / "jobs.jsonl").depth() == 1
    # A torn tail line (death mid-append) is skipped, earlier state kept.
    with open(tmp_path / "jobs.jsonl", "a") as fh:
        fh.write('{"op": "enqueue", "job": "tornton')
    assert q.depth() == 1
    # Admission: depth cap refuses new work, dedup still answers.
    monkeypatch.setenv("GAMESMAN_JOBS_MAX_DEPTH", "1")
    with pytest.raises(QueueRefused):
        q.enqueue("subtract:total=7,moves=1-2", name="sub7")
    assert q.enqueue("subtract:total=6,moves=1-2",
                     name="sub6")["id"] == job["id"]
    monkeypatch.setenv("GAMESMAN_JOBS_DISK_FLOOR_MB", "1e9")
    monkeypatch.setenv("GAMESMAN_JOBS_MAX_DEPTH", "64")
    with pytest.raises(QueueRefused, match="disk"):
        q.enqueue("subtract:total=8,moves=1-2", name="sub8")


def test_job_queue_reclaims_dead_claims_and_caps_attempts(
        tmp_path, monkeypatch):
    monkeypatch.setenv("GAMESMAN_JOBS_MAX_ATTEMPTS", "2")
    q = JobQueue(tmp_path / "jobs.jsonl")
    job = q.enqueue("subtract:total=6,moves=1-2")
    # A pid that is provably dead by claim time.
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait()
    got = q.claim(pid=dead.pid)
    assert got["id"] == job["id"] and got["attempts"] == 1
    # The claim's pid is dead: the next claim reclaims the SAME job.
    got2 = q.claim(pid=dead.pid)
    assert got2["id"] == job["id"] and got2["attempts"] == 2
    # Attempts exhausted: the job fails terminally instead of looping.
    assert q.claim(pid=dead.pid) is None
    assert q.jobs()[job["id"]]["state"] == "failed"
    # release() puts a live claim back to pending for a later runner.
    job2 = q.enqueue("subtract:total=7,moves=1-2")
    live = q.claim()
    assert live["id"] == job2["id"]
    q.release(job2["id"], error="step blew up")
    assert q.jobs()[job2["id"]]["state"] == "pending"


def test_registry_solve_endpoint_queues_and_bounds(tmp_path, monkeypatch):
    monkeypatch.setenv("GAMESMAN_JOBS_MAX_DEPTH", "1")
    root = tmp_path / "reg"
    srv = RegistryServer(root, queue=JobQueue(root / "jobs.jsonl"))
    srv.start()
    try:
        # ensure_db: manifest 404 + a spec in hand -> queued job.
        out = ensure_db(srv.url, "sub6", spec="subtract:total=6,moves=1-2")
        assert out["status"] == "pending" and out["id"]
        # Same spec again: the SAME job (dedup), not a 429.
        again = ensure_db(srv.url, "sub6",
                          spec="subtract:total=6,moves=1-2")
        assert again["id"] == out["id"]
        status, jobs = _get(f"{srv.url}/jobs")
        assert status == 200 and jobs["depth"] == 1
        # Queue full: 429, the thundering herd degrades politely — and
        # carries Retry-After, the header the class's wire contract
        # (`# wire: 429-retry-after`, GM1004) promises on every shed.
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{srv.url}/solve",
                  {"name": "sub7", "spec": "subtract:total=7,moves=1-2"})
        assert e.value.code == 429
        assert float(e.value.headers.get("Retry-After")) > 0
    finally:
        srv.stop()


# ------------------------------------- the fleet epoch-flip acceptance


def test_fleet_serves_old_epoch_while_pulling_new_under_load(
        tmp_path, sub_db, sub_db_v2):
    """THE ISSUE 19 gate: a fork-mode CLI fleet on epoch A answers a
    query hammer with zero failures while epoch B is pulled, verified,
    installed and rolled in; the served epoch (ETag) flips exactly once;
    a rotted epoch C is then quarantined with the fleet untouched."""
    load_gen = load_module(REPO / "tools" / "load_gen.py")
    root = tmp_path / "registry"
    publish_db(root, "sub", sub_db)
    srv = RegistryServer(root)
    srv.start()
    dest = tmp_path / "dbs"
    pulled_a = pull_db(srv.url, "sub", dest)
    manifest = tmp_path / "fleet.json"
    manifest.write_text(json.dumps({
        "version": 1, "games": [{"name": "sub", "db": pulled_a["db"]}],
    }))
    env = dict(os.environ, GAMESMAN_PLATFORM="cpu")
    env.pop("GAMESMAN_FAULTS", None)
    proc = subprocess.Popen(
        _CLI + ["serve", "--fleet-manifest", str(manifest), "--port", "0",
                "--workers", "2", "--control-port", "0"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=str(REPO),
    )
    try:
        banner = proc.stdout.readline()
        assert "serving fleet" in banner, banner
        port = int(banner.split("http://127.0.0.1:")[1].split(" ")[0])
        cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
        base = f"http://127.0.0.1:{port}"
        control = f"http://127.0.0.1:{cport}"
        _wait_for(
            lambda: _get(control + "/healthz")[1]["status"] == "ok",
            timeout=120, what="fleet ready",
        )

        # One-game fleet: the single route is also the default route, so
        # the bare /query endpoints (load_gen's shape) hit game "sub".
        def _etag():
            with urllib.request.urlopen(
                    f"{base}/query?p=0xa", timeout=10) as resp:
                return resp.headers.get("ETag")

        etag_a = _etag()
        assert etag_a

        # Epoch B appears upstream while the hammer runs.
        publish_db(root, "sub", sub_db_v2)
        stop = threading.Event()
        result = {}

        def _hammer():
            result.update(load_gen.run_load(
                base, list(range(11)), duration=60,
                concurrency=4, chunk_size=4, timeout=10, stop_event=stop,
            ))

        t = threading.Thread(target=_hammer)
        t.start()
        try:
            time.sleep(0.5)
            sync = sync_fleet(srv.url, ["sub"], manifest, dest,
                              control_url=control)
            assert sync["status"] == "rolled", sync
            _wait_for(
                lambda: (s := _get(control + "/healthz")[1])
                ["reloads_done"] == 1 and s["status"] == "ok",
                timeout=120, what="rolling reload onto epoch B",
            )
            time.sleep(0.5)
        finally:
            stop.set()
            t.join(timeout=60)
        # Zero failed requests across the pull + verify + install + roll.
        assert result["ok"] > 0
        assert result["errors"] == 0
        assert result["dropped"] == 0
        assert result["mismatches"] == 0

        # The served epoch flipped exactly once: A -> B.
        st = _get(control + "/healthz")[1]
        assert st["reloads_done"] == 1
        etag_b = _etag()
        assert etag_b and etag_b != etag_a
        assert file_sha256(sub_db_v2 / MANIFEST_NAME)[:12] in \
            json.loads(manifest.read_text())["games"][0]["db"]
        # The supervisor recorded the sync (control POST /registry-sync).
        assert st["registry_sync"]["status"] == "rolled"
        assert "sub" in st["registry_sync"]["epochs"]

        # Rotted epoch C: checksums pass, admission fails -> quarantine,
        # fleet stays healthy on B.
        import shutil

        import numpy as np

        rotted = tmp_path / "rotted"
        shutil.copytree(sub_db, rotted)
        cells_file = rotted / "level_0002.cells.npy"
        np.save(cells_file.with_suffix(""),
                np.zeros_like(np.load(cells_file)))
        man = json.loads((rotted / MANIFEST_NAME).read_text())
        man["levels"]["2"]["cells_sha256"] = file_sha256(cells_file)
        (rotted / MANIFEST_NAME).write_text(json.dumps(man))
        publish_db(root, "sub", rotted)
        sync = sync_fleet(srv.url, ["sub"], manifest, dest,
                          control_url=control)
        assert sync["status"] == "nothing_pulled", sync
        assert sync["failed"] and \
            "admission gate" in sync["failed"][0]["error"], sync
        st = _get(control + "/healthz")[1]
        assert st["status"] == "ok"
        assert st["reloads_done"] == 1  # no second flip
        assert _etag() == etag_b
        assert any(d.name.endswith(".corrupt") for d in dest.iterdir())

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        srv.stop()
