"""compat/: unmodified reference-style modules through both paths."""

import pytest

from gamesmanmpi_tpu.compat import TensorizedModule, load_game_module, solve_module
from gamesmanmpi_tpu.core.values import TIE, WIN
from gamesmanmpi_tpu.solve import Solver

from helpers import REF_GAMES, assert_table_parity


def test_solve_module_tictactoe():
    module = load_game_module(REF_GAMES / "tictactoe.py")
    value, remoteness, table = solve_module(module)
    assert value == TIE and remoteness == 9
    assert len(table) == 5478


def test_solve_module_accepts_generate_moves_spelling(tmp_path):
    src = (REF_GAMES / "ten_to_zero.py").read_text()
    src = src.replace("def gen_moves", "def generate_moves")
    p = tmp_path / "alt_spelling.py"
    p.write_text(src)
    module = load_game_module(p)
    value, _, _ = solve_module(module)
    assert value == WIN


def test_load_game_module_validates(tmp_path):
    p = tmp_path / "bad_game.py"
    p.write_text("initial_position = 0\n")
    with pytest.raises(AttributeError):
        load_game_module(p)


def test_tensorized_module_through_jit_engine():
    """The boundary proof: an unmodified scalar module driven by the same
    jitted level-synchronous engine, full-table parity vs the host oracle."""
    module = load_game_module(REF_GAMES / "ten_to_zero.py")
    game = TensorizedModule(
        module,
        max_moves=2,
        level_fn=lambda pos: module.initial_position - pos,
        max_level_jump=2,
        num_levels=11,
    )
    result = Solver(game, paranoid=True).solve()
    _, _, oracle_table = solve_module(module)
    assert result.value == WIN
    assert_table_parity(result, oracle_table)


def test_auto_max_moves_probe():
    """A module with no max_moves gets it derived by the BFS probe."""
    module = load_game_module(REF_GAMES / "ten_to_zero.py")
    game = TensorizedModule(
        module,
        level_fn=lambda pos: module.initial_position - pos,
        max_level_jump=2,
        num_levels=11,
    )
    assert game.max_moves == 2  # 10-to-0 is fully explored by the probe
    result = Solver(game, paranoid=True).solve()
    assert result.value == WIN


def _branchy_module():
    """Branching explodes past the probe sample: 0->1->...->6, then six
    moves from 6; primitive at >= 7."""
    import types

    m = types.ModuleType("branchy")
    m.initial_position = 0
    m.gen_moves = lambda pos: [1] if pos < 6 else list(range(1, 7))
    m.do_move = lambda pos, mv: pos + mv
    m.primitive = lambda pos: "LOSE" if pos >= 7 else "UNDECIDED"
    m.level_of = lambda pos: pos
    m.max_level_jump = 6
    m.num_levels = 14
    return m


def test_auto_max_moves_grow_and_retry(monkeypatch):
    """When the probe under-samples, solve_module_jitted must grow max_moves
    and re-solve instead of failing (BASELINE "runs unmodified")."""
    import gamesmanmpi_tpu.compat.shim as shim

    module = _branchy_module()
    monkeypatch.setattr(shim, "_PROBE_LIMIT", 4)
    # The under-sized wrapper really is under-sized (retry must fire).
    assert TensorizedModule(module).max_moves == 1
    result = shim.solve_module_jitted(module)
    assert result.value == WIN  # position 6 moves straight to a LOSE
    assert result.remoteness == 7
    assert result.num_positions == 13  # 0..12


def test_tensorized_module_sharded_multidevice():
    """Host callbacks under shard_map/all_to_all with devices>1: the
    unmodified-module path through the ShardedSolver, table parity vs the
    host oracle."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 (fake) devices")
    from gamesmanmpi_tpu.parallel import ShardedSolver

    module = load_game_module(REF_GAMES / "ten_to_zero.py")
    game = TensorizedModule(
        module,
        level_fn=lambda pos: module.initial_position - pos,
        max_level_jump=2,
        num_levels=11,
    )
    result = ShardedSolver(game, num_shards=2, paranoid=True).solve()
    _, _, oracle_table = solve_module(module)
    assert result.value == WIN
    assert_table_parity(result, oracle_table)


def test_tensorized_module_tictactoe():
    module = load_game_module(REF_GAMES / "tictactoe.py")
    game = TensorizedModule(
        module,
        max_moves=9,
        level_fn=lambda pos: bin(pos).count("1"),
        num_levels=10,
    )
    result = Solver(game, paranoid=True).solve()
    assert result.value == TIE and result.remoteness == 9
    assert result.num_positions == 5478


def test_tensorized_module_sharded_8_with_spill_retry():
    """The advertised `--devices 8` compat path at full width, with the
    route-capacity retry forced (VERDICT r2 weak #7 / item 8): an
    unmodified scalar module through ShardedSolver at 8 shards, on a game
    big enough to have real routing load, must survive an undersized first
    routing capacity (spill_retries > 0) and keep full-table parity with
    the host oracle."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (fake) devices")
    from gamesmanmpi_tpu.parallel import ShardedSolver

    module = load_game_module(REF_GAMES / "tictactoe.py")
    game = TensorizedModule(
        module,
        max_moves=9,
        level_fn=lambda pos: bin(pos).count("1"),
        num_levels=10,
    )
    solver = ShardedSolver(game, num_shards=8, paranoid=True)
    # Undersized first attempt on every route: forces the overflow retry
    # loop through the host-callback kernels too.
    solver._initial_route_cap = lambda cap: 1
    result = solver.solve()
    assert solver.spill_retries > 0
    _, _, oracle_table = solve_module(module)
    assert result.value == TIE
    assert_table_parity(result, oracle_table)
