"""compat/: unmodified reference-style modules through both paths."""

import numpy as np
import pytest

from gamesmanmpi_tpu.compat import TensorizedModule, load_game_module, solve_module
from gamesmanmpi_tpu.core.values import TIE, WIN
from gamesmanmpi_tpu.solve import Solver

from helpers import REF_GAMES, assert_table_parity


def test_solve_module_tictactoe():
    module = load_game_module(REF_GAMES / "tictactoe.py")
    value, remoteness, table = solve_module(module)
    assert value == TIE and remoteness == 9
    assert len(table) == 5478


def test_solve_module_accepts_generate_moves_spelling(tmp_path):
    src = (REF_GAMES / "ten_to_zero.py").read_text()
    src = src.replace("def gen_moves", "def generate_moves")
    p = tmp_path / "alt_spelling.py"
    p.write_text(src)
    module = load_game_module(p)
    value, _, _ = solve_module(module)
    assert value == WIN


def test_load_game_module_validates(tmp_path):
    p = tmp_path / "bad_game.py"
    p.write_text("initial_position = 0\n")
    with pytest.raises(AttributeError):
        load_game_module(p)


def test_tensorized_module_through_jit_engine():
    """The boundary proof: an unmodified scalar module driven by the same
    jitted level-synchronous engine, full-table parity vs the host oracle."""
    module = load_game_module(REF_GAMES / "ten_to_zero.py")
    game = TensorizedModule(
        module,
        max_moves=2,
        level_fn=lambda pos: module.initial_position - pos,
        max_level_jump=2,
        num_levels=11,
    )
    result = Solver(game, paranoid=True).solve()
    _, _, oracle_table = solve_module(module)
    assert result.value == WIN
    assert_table_parity(result, oracle_table)


def test_tensorized_module_tictactoe():
    module = load_game_module(REF_GAMES / "tictactoe.py")
    game = TensorizedModule(
        module,
        max_moves=9,
        level_fn=lambda pos: bin(pos).count("1"),
        num_levels=10,
    )
    result = Solver(game, paranoid=True).solve()
    assert result.value == TIE and result.remoteness == 9
    assert result.num_positions == 5478
