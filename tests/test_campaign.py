"""Self-healing solve campaigns (ISSUE 12): auto-resume supervision,
no-progress breaker, disk-budget degradation, append-only ledger.

Acceptance axes:

* entrypoint smoke (tier-1) — ``tools/run_campaign.py --help`` exits 0
  and a 1-attempt trivial campaign (ttt, no faults) completes with a
  well-formed ledger, so the campaign CLI can never silently rot;
* chaos campaign — a sharded solve killed at distinct points (forward,
  backward, mid-write-behind) is driven to byte-parity completion with
  zero operator input, every attempt on the ledger;
* breaker — attempts that seal nothing trip the no-progress breaker
  into a clean abort with a diagnosis bundle;
* disk budget — an ``enospc``-classified death triggers retention GC
  and a retry; the hard floor aborts cleanly, prefix intact.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.resilience.campaign import (
    DISK_FLOOR_EXIT_CODE,
    NO_PROGRESS_EXIT_CODE,
    Campaign,
    checkpoint_progress,
    progress_score,
)
from gamesmanmpi_tpu.resilience.faults import KILL_EXIT_CODE
from gamesmanmpi_tpu.utils.checkpoint import (
    LevelCheckpointer,
    _loadz,
    save_result_npz,
)

from helpers import REPO, full_table

_CAMPAIGN = [sys.executable, os.path.join(REPO, "tools", "run_campaign.py")]
_C3 = "connect4:w=3,h=3,connect=3"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _run_campaign(args, extra_env=None, timeout=900):
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env.pop("GAMESMAN_FAULTS", None)
    # Fast inter-attempt backoff: the tests assert policy, not patience.
    env.setdefault("GAMESMAN_CAMPAIGN_BACKOFF_BASE_SECS", "0.05")
    env.update(extra_env or {})
    return subprocess.run(
        _CAMPAIGN + list(args), capture_output=True, text=True,
        timeout=timeout, env=env, cwd=str(REPO),
    )


def _ledger(path):
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def _phases(records):
    return [r.get("phase") for r in records]


def _assert_tables_equal(a, b):
    with _loadz(a) as za, _loadz(b) as zb:
        assert sorted(za.files) == sorted(zb.files)
        for f in za.files:
            assert np.array_equal(za[f], zb[f]), f


# ----------------------------------------------------------- tier-1 smoke


def test_run_campaign_help_exits_zero():
    """The entrypoint can never silently rot: --help must exit 0 (and
    without importing jax — the supervisor stays instant)."""
    out = subprocess.run(
        _CAMPAIGN + ["--help"], capture_output=True, text=True,
        timeout=60, cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr
    assert "--checkpoint-dir" in out.stdout
    assert "--chaos" in out.stdout


def test_run_campaign_usage_errors():
    out = subprocess.run(
        _CAMPAIGN + ["tictactoe"], capture_output=True, text=True,
        timeout=60, cwd=str(REPO),
    )
    assert out.returncode == 2  # --checkpoint-dir is required
    out = _run_campaign(
        ["tictactoe", "--checkpoint-dir", "/tmp/x", "--processes", "0"]
    )
    assert out.returncode == 2
    out = _run_campaign(
        ["tictactoe", "--checkpoint-dir", "/tmp/x", "--",
         "--checkpoint-dir", "/tmp/y"]
    )
    assert out.returncode == 2  # the campaign owns the checkpoint flag


def test_trivial_ttt_campaign_completes_with_well_formed_ledger(tmp_path):
    """The tier-1 acceptance smoke: one clean attempt, rc 0, every
    ledger record shaped as documented."""
    ck = tmp_path / "ck"
    out = _run_campaign(
        ["tictactoe", "--checkpoint-dir", str(ck), "--max-attempts", "1"]
    )
    assert out.returncode == 0, out.stderr[-2000:]
    records = _ledger(ck / "campaign.jsonl")
    assert _phases(records) == [
        "campaign_start", "campaign_attempt", "campaign_done"
    ]
    start, attempt, done = records
    assert start["solver_args"][0] == "tictactoe"
    assert attempt["attempt"] == 1
    assert attempt["cause"] == "complete"
    assert attempt["rcs"] == {"0": 0}
    assert attempt["progressed"] is True
    assert attempt["wall_secs"] > 0
    assert done["attempts"] == 1
    assert all("wall_time" in r for r in records)
    # The checkpoint really solved: the manifest seals levels.
    progress = checkpoint_progress(ck)
    assert progress["solved_levels"] and progress["frontiers_complete"]


def test_disk_floor_aborts_cleanly_before_burning_attempts(tmp_path):
    """Hard floor: free space below an absurd floor (and nothing to GC)
    aborts with exit 4 + diagnosis bundle, without launching a solve."""
    ck = tmp_path / "ck"
    out = _run_campaign(
        ["tictactoe", "--checkpoint-dir", str(ck),
         "--disk-floor-mb", str(10 ** 9)],
    )
    assert out.returncode == DISK_FLOOR_EXIT_CODE, out.stderr[-2000:]
    records = _ledger(ck / "campaign.jsonl")
    assert "campaign_attempt" not in _phases(records)
    abort = records[-1]
    assert abort["phase"] == "campaign_abort"
    assert abort["code"] == DISK_FLOOR_EXIT_CODE
    assert (ck / "campaign_diagnosis.json").exists()


# ---------------------------------------------------- progress + classify


def test_progress_score_monotone_across_consolidation():
    """The forward->backward seam: consolidating the frontier snapshot
    DELETES the per-level forward seals it supersedes — the score must
    still strictly increase (lexicographic by phase)."""
    forward_mid = {"solved_levels": [], "forward_levels": 5,
                   "frontiers_complete": False, "dense_levels": 0}
    forward_more = dict(forward_mid, forward_levels=7)
    consolidated = {"solved_levels": [], "forward_levels": 0,
                    "frontiers_complete": True, "dense_levels": 0}
    backward_mid = dict(consolidated, solved_levels=[9, 8])
    assert progress_score(forward_more) > progress_score(forward_mid)
    assert progress_score(consolidated) > progress_score(forward_more)
    assert progress_score(backward_mid) > progress_score(consolidated)
    # Quarantine (a solved level unsealed) reads as regression.
    quarantined = dict(backward_mid, solved_levels=[9])
    assert progress_score(quarantined) < progress_score(backward_mid)


def test_checkpoint_progress_tolerates_missing_and_torn_manifest(tmp_path):
    p = checkpoint_progress(tmp_path / "nope")
    assert p["solved_levels"] == [] and p["deepest_solved"] is None
    d = tmp_path / "torn"
    d.mkdir()
    (d / "manifest.json").write_text('{"levels": [1, 2')
    assert checkpoint_progress(d)["solved_levels"] == []


def test_classify_causes():
    c = Campaign.classify
    assert c({0: 0, 1: 0}, {}) == "complete"
    assert c({0: 77, 1: 124}, {}) == "killed"
    assert c({0: 86}, {}) == "torn_kill"
    assert c({0: 75, 1: 124}, {}) == "preempted"
    assert c({0: 124, 1: 124}, {}) == "deadline_abort"
    assert c({0: 1}, {"a": "OSError: [Errno 28] No space left on device"}) \
        == "enospc"
    assert c({0: None}, {}) == "timeout"
    assert c({0: -9}, {}) == "signal"
    assert c({0: 1}, {"a": "traceback"}) == "crash"


def test_classify_oom_markers():
    """Every oom shape the stack can die with classifies as `oom`: the
    injected fault kind, the host-memory guard, the CLI's clean
    diagnostics, XLA's allocator, a bare MemoryError, and glibc/errno
    spellings. A SIGKILL with an empty tail stays `signal` (the kernel
    OOM-killer leaves nothing to read — the guard exists for that)."""
    c = Campaign.classify
    for tail in (
        "MemoryError: injected oom (RESOURCE_EXHAUSTED: out of memory)",
        "HostMemoryExceeded: host RSS 900 MiB exceeds",
        "out of memory: host RSS 900 MiB exceeds ... progress: {}",
        "jaxlib.xla_extension.XlaRuntimeError: RESOURCE_EXHAUSTED: "
        "Out of memory allocating 1073741824 bytes",
        "MemoryError",
        "OSError: [Errno 12] Cannot allocate memory",
    ):
        assert c({0: 1}, {"a": tail}) == "oom", tail
    assert c({0: -9}, {}) == "signal"


# ------------------------------------------ adaptive geometry (tier-1)


def _policy_campaign(tmp_path, solver_args, **cfg_kw):
    from gamesmanmpi_tpu.resilience.campaign import CampaignConfig

    cfg = CampaignConfig(
        solver_args=solver_args,
        checkpoint_dir=str(tmp_path / "ck"),
        **cfg_kw,
    )
    return Campaign(cfg, echo=lambda m: None), cfg


def test_oom_policy_escalates_shards_and_shrinks_cache(tmp_path):
    """oom -> S doubles (under the cap) and the store cache halves (to
    the floor); the rewritten --devices, the env override, and the
    ledger records all agree."""
    camp, cfg = _policy_campaign(
        tmp_path, [_C3, "--devices", "2"],
        max_shards=8, cache_floor_mb=32,
    )
    assert camp._parse_shards(["x", "--devices=4"]) == 4
    assert camp._parse_shards(["x"]) is None
    camp._apply_policy("oom", 1)
    assert camp._shards == 4
    args = camp._solver_args()
    assert args[args.index("--devices") + 1] == "4"
    env = camp._attempt_env(2)
    assert env["GAMESMAN_FAKE_DEVICES"] == "4"
    assert int(env["GAMESMAN_STORE_CACHE_MB"]) < 256
    camp._apply_policy("oom", 2)
    assert camp._shards == 8
    camp._apply_policy("oom", 3)  # at the cap: only the cache can move
    assert camp._shards == 8
    assert camp._cache_mb == 32  # floored
    records = _ledger(cfg.ledger_path)
    assert all(r["phase"] == "campaign_reshard" for r in records)
    assert records[0]["from_shards"] == 2
    assert records[0]["to_shards"] == 4
    assert records[0]["to_cache_mb"] < records[0]["from_cache_mb"]


def test_oom_policy_respects_opt_out_and_missing_devices(tmp_path):
    camp, cfg = _policy_campaign(
        tmp_path, [_C3, "--devices", "2"], oom_escalate=False,
    )
    camp._apply_policy("oom", 1)
    assert camp._shards == 2 and camp._cache_mb is None
    assert not os.path.exists(cfg.ledger_path)
    # No --devices: only the cache shrinks (a single-device engine
    # cannot be resharded into existence).
    camp2, cfg2 = _policy_campaign(tmp_path, ["tictactoe"])
    camp2._apply_policy("oom", 1)
    assert camp2._shards is None
    assert camp2._cache_mb is not None
    records = _ledger(cfg2.ledger_path)
    assert records[0]["from_shards"] is None


def test_lost_rank_policy_is_opt_in_and_steps_world_down(tmp_path):
    camp, cfg = _policy_campaign(
        tmp_path, [_C3, "--devices", "4"],
        processes=3, local_devices=2, elastic_ranks=True,
    )
    camp._apply_policy("killed", 1)
    assert camp._processes == 2
    assert camp._local_devices == 2  # ceil(4/2)
    camp._apply_policy("deadline_abort", 2)
    assert camp._processes == 1
    assert camp._local_devices == 4  # ceil(4/1)
    camp._apply_policy("signal", 3)
    assert camp._processes == 1  # floor
    env = camp._attempt_env(4)
    assert "GAMESMAN_NUM_PROCESSES" not in env  # stale wiring dropped
    records = _ledger(cfg.ledger_path)
    assert [r["kind"] for r in records] == ["lost_rank", "lost_rank"]
    assert records[0]["from_processes"] == 3
    # default: off
    camp2, _ = _policy_campaign(tmp_path, [_C3], processes=2)
    camp2._apply_policy("killed", 1)
    assert camp2._processes == 2


def test_infeasible_escalation_reverts_shards(tmp_path):
    """An escalated attempt dying at mesh construction ('requested N
    shards but only M devices' — real hardware, where fake devices
    cannot be conjured) steps the shard count back down instead of
    crash-looping the impossible mesh into the breaker; the shrunken
    cache stays (always legal), and the original request is the
    floor."""
    camp, cfg = _policy_campaign(tmp_path, [_C3, "--devices", "2"])
    camp._apply_policy("oom", 1)
    camp._apply_policy("oom", 2)
    assert camp._shards == 8
    tail = ("ValueError: requested 8 shards but only 4 devices")
    camp._maybe_revert_shards("crash", tail, 3)
    assert camp._shards == 4
    assert camp._cache_mb is not None  # the cache shrink is kept
    camp._maybe_revert_shards("crash", tail, 4)
    assert camp._shards == 2  # floor: the original request
    camp._maybe_revert_shards("crash", tail, 5)
    assert camp._shards == 2
    # Unrelated crashes / unescalated campaigns never revert.
    camp2, _ = _policy_campaign(tmp_path, [_C3, "--devices", "2"])
    camp2._maybe_revert_shards("crash", tail, 1)
    assert camp2._shards == 2
    camp._apply_policy("oom", 6)
    before = camp._shards
    camp._maybe_revert_shards("crash", "unrelated traceback", 7)
    assert camp._shards == before
    records = _ledger(cfg.ledger_path)
    reverts = [r for r in records if r.get("cause") == "infeasible"]
    assert [r["from_shards"] for r in reverts] == [8, 4]
    assert [r["to_shards"] for r in reverts] == [4, 2]


def test_checkpoint_progress_reports_sealed_geometry(tmp_path):
    """checkpoint_progress carries the sealed geometry the ledger's
    per-attempt sealed_shards field reads (jax-free manifest walk)."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    assert checkpoint_progress(tmp_path / "nope")["shards"] is None
    ck = LevelCheckpointer(tmp_path / "ck")
    faults.configure("sharded.backward:fatal:2")
    with pytest.raises(faults.FatalFault):
        ShardedSolver(get_game(_C3), num_shards=2,
                      checkpointer=ck).solve()
    faults.clear()
    p = checkpoint_progress(tmp_path / "ck")
    assert p["shards"] == 2 and p["shard_counts"] == [2]
    assert p["num_processes"] == 1


# ------------------------------------------------- retention GC (tier-1)


def test_gc_superseded_consumed_edges_and_strays_resume_parity(tmp_path):
    """A partially-backward sharded checkpoint: GC reclaims the solved
    levels' consumed edge shards (+ planted corrupt/tmp/stray files),
    keeps unsolved levels' edges, and the resumed solve still reaches
    parity (the per-level lookup fallback covers GC'd edges even if a
    level re-quarantines)."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    clean = ShardedSolver(get_game(_C3), num_shards=2).solve()
    ck_dir = tmp_path / "ck"
    ck = LevelCheckpointer(ck_dir)
    faults.configure("sharded.backward:fatal:3")
    with pytest.raises(faults.FatalFault):
        ShardedSolver(get_game(_C3), num_shards=2, checkpointer=ck).solve()
    faults.clear()
    manifest = ck.load_manifest()
    solved = {int(k) for k in manifest.get("sharded_levels", {})}
    edges = {int(k) for k in manifest.get("edge_levels", {})}
    consumed = solved & edges
    assert consumed, "fixture: no solved level still holds edges"
    assert edges - solved, "fixture: no unsolved level holds edges"
    # Plant every superseded class the GC claims to reclaim.
    (ck_dir / "level_0099.npz.corrupt").write_bytes(b"x" * 64)
    (ck_dir / "frontier_0042.shard_0000.npz").write_bytes(b"y" * 64)
    (ck_dir / f"level_0001.{os.getpid()}.tmp.npz").write_bytes(b"z")
    freed = ck.gc_superseded()
    assert freed["files"] >= len(consumed) * 2 + 3
    assert freed["bytes"] > 0
    assert set(freed["kinds"]) >= {"edges", "corrupt", "frontier", "tmp"}
    after = ck.load_manifest()
    assert not (solved & {int(k) for k in after.get("edge_levels", {})})
    # Unsolved levels keep their sealed edges (still needed).
    assert {int(k) for k in after.get("edge_levels", {})} == edges - solved
    assert not list(ck_dir.glob("*.corrupt"))
    assert not list(ck_dir.glob("*.tmp.npz"))
    for k in consumed:
        assert not list(ck_dir.glob(f"edges_{k:04d}.*"))
    resumed = ShardedSolver(
        get_game(_C3), num_shards=2, checkpointer=LevelCheckpointer(ck_dir)
    ).solve()
    assert full_table(resumed) == full_table(clean)


def test_disk_usage_kinds_and_gauges(tmp_path):
    from gamesmanmpi_tpu.obs import MetricsRegistry
    from gamesmanmpi_tpu.parallel import ShardedSolver

    ck_dir = tmp_path / "ck"
    ck = LevelCheckpointer(ck_dir)
    ShardedSolver(get_game(_C3), num_shards=2, checkpointer=ck).solve()
    reg = MetricsRegistry()
    usage = ck.disk_usage(registry=reg)
    assert usage["level"] > 0
    assert usage["manifest"] > 0
    assert usage["corrupt"] == 0
    snap = reg.snapshot()
    kinds = {
        row["labels"]["kind"]: row["value"]
        for row in snap["gamesman_ckpt_bytes"]["values"]
    }
    assert kinds["level"] == usage["level"]
    assert kinds["tmp"] == 0.0  # every kind always set, GC'd kinds read 0


def test_artifact_kind_classification():
    k = LevelCheckpointer.artifact_kind
    assert k("manifest.json") == "manifest"
    assert k("level_0004.npz") == "level"
    assert k("level_0004.shard_0001.npz") == "level"
    assert k("frontier_0003.npz") == "frontier"
    assert k("frontiers.shard_0000.npz") == "frontier"
    assert k("edges_0002.shard_0000.npz") == "edges"
    assert k("dense_0001.npz") == "dense"
    assert k("level_0004.npz.corrupt") == "corrupt"
    assert k("level_0004.12345.tmp.npz") == "tmp"
    assert k("campaign.jsonl") == "other"


# ----------------------------------------------------- chaos (slow, subproc)


@pytest.mark.slow
def test_campaign_kill_chaos_driven_to_byte_parity(tmp_path):
    """The acceptance core, at test scale: a sharded solve SIGKILLed at
    three distinct points — forward, backward, mid-write-behind — is
    driven to byte-parity completion with zero operator input, the
    ledger recording every attempt."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    golden = tmp_path / "golden.npz"
    save_result_npz(
        golden, ShardedSolver(get_game(_C3), num_shards=2).solve()
    )
    ck = tmp_path / "ck"
    out_table = tmp_path / "resumed.npz"
    out = _run_campaign([
        _C3, "--checkpoint-dir", str(ck),
        "--chaos", "sharded.forward:kill:3",
        "--chaos", "sharded.backward:kill:2",
        "--chaos", "store.writebehind:kill:1",
        "--", "--devices", "2", "--table-out", str(out_table),
    ])
    assert out.returncode == 0, out.stderr[-3000:]
    records = _ledger(ck / "campaign.jsonl")
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    assert len(attempts) == 4  # 3 injected deaths + the clean finisher
    assert [a["cause"] for a in attempts[:3]] == ["killed"] * 3
    assert attempts[3]["cause"] == "complete"
    assert all(a["rcs"] == {"0": KILL_EXIT_CODE} for a in attempts[:3])
    assert records[-1]["phase"] == "campaign_done"
    _assert_tables_equal(out_table, golden)


@pytest.mark.slow
def test_campaign_no_progress_breaker_writes_diagnosis(tmp_path):
    """K consecutive attempts dying without sealing anything new abort
    the campaign (exit 3) with the diagnosis bundle: last progress,
    quarantine inventory, log tails."""
    ck = tmp_path / "ck"
    out = _run_campaign([
        "tictactoe", "--checkpoint-dir", str(ck),
        "--no-progress", "2", "--max-attempts", "8",
        "--chaos", "engine.forward:kill:1",
        "--chaos", "engine.forward:kill:1",
        "--chaos", "engine.forward:kill:1",
        "--chaos", "engine.forward:kill:1",
    ])
    assert out.returncode == NO_PROGRESS_EXIT_CODE, out.stderr[-2000:]
    records = _ledger(ck / "campaign.jsonl")
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    # Attempt 1 seals the seed frontier level (progress); 2 and 3 die at
    # the same point with nothing new -> breaker at K=2.
    assert len(attempts) <= 3
    assert records[-1]["phase"] == "campaign_abort"
    assert records[-1]["code"] == NO_PROGRESS_EXIT_CODE
    bundle = json.loads((ck / "campaign_diagnosis.json").read_text())
    assert bundle["attempts"] == len(attempts)
    assert "progress" in bundle and "quarantine" in bundle
    assert any(bundle["log_tails"].values())


@pytest.mark.slow
def test_campaign_enospc_triggers_gc_and_retry(tmp_path):
    """An ENOSPC-classified death (injected `enospc` fault) pauses into
    retention GC — which reclaims the planted superseded artifacts —
    and the retry completes. The acceptance shape: pause -> GC -> retry,
    never a torn write."""
    ck = tmp_path / "ck"
    ck.mkdir()
    # Superseded artifacts for the GC to find: a quarantined level and
    # an unreferenced stray shard.
    (ck / "level_0099.npz.corrupt").write_bytes(b"x" * 1024)
    (ck / "edges_0042.shard_0000.npz").write_bytes(b"y" * 1024)
    out = _run_campaign([
        "tictactoe", "--checkpoint-dir", str(ck),
        "--chaos", "ckpt.save_frontier:enospc:3",
    ])
    assert out.returncode == 0, out.stderr[-3000:]
    records = _ledger(ck / "campaign.jsonl")
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    assert attempts[0]["cause"] == "enospc"
    assert attempts[-1]["cause"] == "complete"
    gcs = [r for r in records if r["phase"] == "campaign_gc"]
    assert gcs and gcs[0]["reason"] == "enospc"
    assert gcs[0]["freed_files"] >= 2
    assert gcs[0]["freed_bytes"] >= 2048
    # The GC's quarantine snapshot preserved the evidence on the ledger.
    assert any(q["file"] == "level_0099.npz.corrupt"
               for q in gcs[0]["quarantined"])
    assert not (ck / "level_0099.npz.corrupt").exists()
    assert not (ck / "edges_0042.shard_0000.npz").exists()


_NO_BACKEND = "Multiprocess computations aren't implemented"


@pytest.mark.slow
def test_campaign_multiprocess_kill_resumes_to_completion(tmp_path):
    """A 2-process world per attempt: rank 0 SIGKILLed mid-forward on
    attempt 1 (rank 1 exits through the coordinated abort), attempt 2
    resumes the world to completion — zero operator input."""
    ck = tmp_path / "ck"
    out = _run_campaign(
        [_C3, "--checkpoint-dir", str(ck), "--processes", "2",
         "--chaos", "sharded.forward:kill:3",
         "--", "--devices", "4"],
        extra_env={"GAMESMAN_BARRIER_SECS": "10",
                   "GAMESMAN_COLLECTIVE_TIMEOUT": "60"},
    )
    logs = " ".join(
        p.read_text(errors="replace")
        for p in (ck / "logs").rglob("rank*.err")
    )
    if _NO_BACKEND in logs:
        pytest.skip("backend cannot run multiprocess collectives")
    assert out.returncode == 0, out.stderr[-3000:]
    records = _ledger(ck / "campaign.jsonl")
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    assert len(attempts) == 2
    assert attempts[0]["cause"] == "killed"
    assert attempts[0]["rcs"]["0"] == KILL_EXIT_CODE
    assert attempts[0]["rcs"]["1"] == 124  # coordinated abort, in time
    assert attempts[1]["cause"] == "complete"
    assert attempts[1]["rcs"] == {"0": 0, "1": 0}


@pytest.mark.slow
def test_campaign_sigterm_preempts_and_is_resumable(tmp_path):
    """SIGTERM to the CAMPAIGN forwards to the attempt (which drains to
    exit 75) and the campaign exits 75; rerunning the same command
    continues from the sealed prefix to byte-parity."""
    from gamesmanmpi_tpu.resilience.preempt import GRACE_EXIT_CODE
    from gamesmanmpi_tpu.solve import Solver

    golden = tmp_path / "golden.npz"
    save_result_npz(golden, Solver(get_game("tictactoe")).solve())
    ck = tmp_path / "ck"
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env.pop("GAMESMAN_FAULTS", None)  # the campaign arms chaos itself
    proc = subprocess.Popen(
        # --chaos stretches attempt 1's backward so the SIGTERM lands
        # mid-solve deterministically (the campaign pops a plain
        # GAMESMAN_FAULTS from attempt envs by design).
        _CAMPAIGN + ["tictactoe", "--checkpoint-dir", str(ck),
                     "--chaos", "engine.backward:delay=0.7:always"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(REPO),
    )
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if list(ck.glob("level_*.npz")):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("attempt never sealed a level")
        proc.send_signal(subprocess.signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert rc == GRACE_EXIT_CODE, proc.stderr.read()[-2000:]
    records = _ledger(ck / "campaign.jsonl")
    assert records[-1]["phase"] == "campaign_preempted"
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    assert attempts and attempts[-1]["cause"] == "preempted"
    # Rerun the same command: resumes to parity.
    out_table = tmp_path / "resumed.npz"
    out = _run_campaign([
        "tictactoe", "--checkpoint-dir", str(ck),
        "--", "--table-out", str(out_table),
    ])
    assert out.returncode == 0, out.stderr[-2000:]
    _assert_tables_equal(out_table, golden)


# ------------------------------------------- elastic campaigns (slow)


@pytest.mark.slow
def test_campaign_oom_escalates_geometry_to_completion(tmp_path):
    """The oom acceptance shape: attempt 1 (S=2) dies on an injected
    oom, the policy escalates to S=4 with a halved store cache, attempt
    2 adopts the S=2 tree by reshard-on-resume and completes — table
    byte-parity with an uninterrupted solve, every geometry change on
    the ledger, zero operator input."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    golden = tmp_path / "golden.npz"
    save_result_npz(
        golden, ShardedSolver(get_game(_C3), num_shards=2).solve()
    )
    ck = tmp_path / "ck"
    out_table = tmp_path / "resumed.npz"
    out = _run_campaign([
        _C3, "--checkpoint-dir", str(ck),
        "--chaos", "sharded.backward:oom:2",
        "--", "--devices", "2", "--table-out", str(out_table),
    ])
    assert out.returncode == 0, out.stderr[-3000:]
    records = _ledger(ck / "campaign.jsonl")
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    assert [a["cause"] for a in attempts] == ["oom", "complete"]
    assert attempts[0]["shards"] == 2
    assert attempts[1]["shards"] == 4
    assert attempts[1]["sealed_shards"] == 2  # reshard adoption, on ledger
    assert attempts[1]["cache_mb"] == 128
    reshards = [r for r in records if r["phase"] == "campaign_reshard"]
    assert len(reshards) == 1
    assert reshards[0]["from_shards"] == 2
    assert reshards[0]["to_shards"] == 4
    _assert_tables_equal(out_table, golden)


@pytest.mark.slow
def test_campaign_adopts_foreign_shard_count(tmp_path):
    """A tree sealed by a DIFFERENT geometry's run (S=4, SIGKILLed
    mid-backward) is driven to completion by a campaign at S=2: the
    first attempt IS a reshard adoption (sealed_shards=4 on the
    ledger), table byte-parity."""
    from gamesmanmpi_tpu.parallel import ShardedSolver

    golden = tmp_path / "golden.npz"
    save_result_npz(
        golden, ShardedSolver(get_game(_C3), num_shards=2).solve()
    )
    ck = tmp_path / "ck"
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_FAULTS"] = "sharded.backward:kill:2"
    killed = subprocess.run(
        [sys.executable, "-m", "gamesmanmpi_tpu.cli", _C3,
         "--devices", "4", "--checkpoint-dir", str(ck)],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(REPO),
    )
    assert killed.returncode == KILL_EXIT_CODE, killed.stderr[-2000:]
    out_table = tmp_path / "resumed.npz"
    out = _run_campaign([
        _C3, "--checkpoint-dir", str(ck),
        "--", "--devices", "2", "--table-out", str(out_table),
    ])
    assert out.returncode == 0, out.stderr[-3000:]
    records = _ledger(ck / "campaign.jsonl")
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    assert attempts[0]["sealed_shards"] == 4
    assert attempts[0]["shards"] == 2
    assert attempts[-1]["cause"] == "complete"
    _assert_tables_equal(out_table, golden)


@pytest.mark.slow
def test_campaign_elastic_ranks_world_shrinks_to_one(tmp_path):
    """--elastic-ranks: a 2-process world killed on rank 0 retries as a
    SINGLE process (W-1), which adopts the world's tree and completes."""
    ck = tmp_path / "ck"
    out = _run_campaign(
        [_C3, "--checkpoint-dir", str(ck), "--processes", "2",
         "--elastic-ranks",
         "--chaos", "sharded.forward:kill:3",
         "--", "--devices", "4"],
        extra_env={"GAMESMAN_BARRIER_SECS": "10",
                   "GAMESMAN_COLLECTIVE_TIMEOUT": "60"},
    )
    logs = " ".join(
        p.read_text(errors="replace")
        for p in (ck / "logs").rglob("rank*.err")
    )
    if _NO_BACKEND in logs:
        pytest.skip("backend cannot run multiprocess collectives")
    assert out.returncode == 0, out.stderr[-3000:]
    records = _ledger(ck / "campaign.jsonl")
    attempts = [r for r in records if r["phase"] == "campaign_attempt"]
    assert attempts[0]["cause"] == "killed"
    assert attempts[0]["processes"] == 2
    degrades = [r for r in records if r["phase"] == "campaign_degrade"]
    assert degrades and degrades[0]["kind"] == "lost_rank"
    assert degrades[0]["to_processes"] == 1
    assert attempts[-1]["cause"] == "complete"
    assert attempts[-1]["processes"] == 1
