"""games/: tensorized kernels vs scalar reference-style modules.

Per-move parity (SURVEY.md §4.2): for random reachable positions, the batched
expand/primitive must agree exactly with the scalar module of identical
packing in examples/ref_games/.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve.oracle import normalize_value

from helpers import REF_GAMES, load_module

# Smoke tier: fast, compile-light, single-process-safe (see pyproject).
pytestmark = pytest.mark.smoke


def _random_walk_positions(module, rng, n_walks=60):
    """Sample reachable positions by random playouts of the scalar module."""
    seen = {module.initial_position}
    for _ in range(n_walks):
        pos = module.initial_position
        while True:
            if normalize_value(module.primitive(pos)) != 0:
                break
            moves = list(
                getattr(module, "gen_moves", getattr(module, "generate_moves", None))(
                    pos
                )
            )
            if not moves:
                break
            pos = module.do_move(pos, moves[rng.integers(len(moves))])
            seen.add(pos)
    return sorted(seen)


CASES = [
    ("tictactoe", "tictactoe.py"),
    ("subtract:total=10,moves=1-2", "ten_to_zero.py"),
    ("nim:heaps=3-4-5", "nim_345.py"),
    ("connect4:w=4,h=4", "connect4_4x4.py"),
    ("chomp:w=3,h=3", "chomp_33.py"),
]


@pytest.mark.parametrize("spec,ref_file", CASES)
def test_expand_primitive_parity(spec, ref_file):
    game = get_game(spec)
    module = load_module(REF_GAMES / ref_file)
    rng = np.random.default_rng(42)
    positions = _random_walk_positions(module, rng)
    states = jnp.asarray(np.array(positions, dtype=np.uint64))

    children, mask = game.expand(states)
    prim = game.primitive(states)
    children = np.asarray(children)
    mask = np.asarray(mask)
    prim = np.asarray(prim)

    gen = getattr(module, "gen_moves", None) or module.generate_moves
    for i, pos in enumerate(positions):
        expected_prim = normalize_value(module.primitive(pos))
        assert prim[i] == expected_prim, f"primitive mismatch at {pos:#x}"
        expected_children = sorted(module.do_move(pos, m) for m in gen(pos))
        got = sorted(int(c) for c, ok in zip(children[i], mask[i]) if ok)
        assert got == expected_children, f"expand mismatch at {pos:#x}"


@pytest.mark.parametrize("spec,ref_file", CASES)
def test_initial_state_matches(spec, ref_file):
    game = get_game(spec)
    module = load_module(REF_GAMES / ref_file)
    assert int(game.initial_state()) == int(module.initial_position)


@pytest.mark.parametrize("spec,ref_file", CASES)
def test_level_function_is_topological(spec, ref_file):
    """Every move strictly raises level_of by at most max_level_jump."""
    game = get_game(spec)
    module = load_module(REF_GAMES / ref_file)
    rng = np.random.default_rng(7)
    positions = _random_walk_positions(module, rng, n_walks=30)
    states = jnp.asarray(np.array(positions, dtype=np.uint64))
    levels = np.asarray(game.level_of(states))
    children, mask = game.expand(states)
    child_levels = np.asarray(game.level_of(children.reshape(-1))).reshape(mask.shape)
    mask = np.asarray(mask)
    prim = np.asarray(game.primitive(states))
    for i in range(len(positions)):
        if prim[i] != 0:
            continue
        for j in range(mask.shape[1]):
            if mask[i, j]:
                jump = child_levels[i, j] - levels[i]
                assert 1 <= jump <= game.max_level_jump


def test_connect4_describe_and_moves():
    game = get_game("connect4:w=4,h=4")
    s = game.initial_state()
    states = jnp.asarray(np.array([s], dtype=np.uint64))
    children, mask = game.expand(states)
    assert np.asarray(mask).all()  # all 4 columns open
    # One move fills one cell.
    levels = np.asarray(game.level_of(children[0]))
    assert (levels == 1).all()


def test_mnk_444_forward_smoke():
    """BASELINE config #2 (4x4 tictactoe / mnk(4,4,4)): the vmapped move-gen
    kernel compiles and expands correctly on the bigger board (full solve is
    exercised on TPU via the bench ladder, not in CI)."""
    game = get_game("tictactoe:m=4,n=4,k=4")
    s = game.initial_state()
    states = jnp.asarray(np.array([s], dtype=np.uint64))
    children, mask = game.expand(states)
    assert int(np.asarray(mask).sum()) == 16  # 16 opening moves
    levels = np.asarray(game.level_of(children[0]))
    assert (levels == 1).all()
    prim = np.asarray(game.primitive(children[0]))
    assert (prim == 0).all()  # no opening move ends the game
