"""serve/: HTTP query serving over a solved-position DB.

Acceptance axis: concurrent batched POST /query traffic answers with
oracle-exact value/remoteness, /healthz is live, and /metrics proves the
micro-batching actually coalesced (mean batch size > 1 under concurrent
load) and the LRU cache hit on repeats.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gamesmanmpi_tpu.core.values import value_name
from gamesmanmpi_tpu.db import DbReader, export_result
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.serve import Batcher, QueryServer
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.oracle import oracle_solve

from helpers import REF_GAMES, load_module


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture(scope="module")
def nim_db(tmp_path_factory):
    spec = "nim:heaps=3-4-5"
    d = tmp_path_factory.mktemp("nimdb")
    export_result(Solver(get_game(spec)).solve(), d, spec)
    _, _, oracle = oracle_solve(load_module(REF_GAMES / "nim_345.py"))
    with DbReader(d) as reader:
        yield reader, oracle


@pytest.fixture(scope="module")
def ttt_db(tmp_path_factory):
    d = tmp_path_factory.mktemp("tttdb")
    export_result(Solver(get_game("tictactoe")).solve(), d, "tictactoe")
    _, _, oracle = oracle_solve(load_module(REF_GAMES / "tictactoe.py"))
    with DbReader(d) as reader:
        yield reader, oracle


def _fire_concurrent(server, chunks):
    """POST each chunk from its own thread, barrier-synchronized so they
    land inside one coalescing window. Returns the per-chunk bodies."""
    url = f"http://127.0.0.1:{server.port}/query"
    barrier = threading.Barrier(len(chunks))
    out = [None] * len(chunks)
    errors = []

    def worker(i, chunk):
        try:
            barrier.wait()
            status, body = _post(url, {"positions": chunk})
            assert status == 200
            out[i] = body
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(i, c))
        for i, c in enumerate(chunks)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    return out


def test_concurrent_queries_match_oracle_and_batch(nim_db):
    """Every reachable nim_345 position served concurrently matches the
    oracle; /metrics shows real coalescing and cache hits on repeats."""
    reader, oracle = nim_db
    positions = sorted(oracle)
    with QueryServer(reader, window=0.05) as server:
        base = f"http://127.0.0.1:{server.port}"
        status, health = _get(base + "/healthz")
        assert status == 200
        assert health["status"] == "ok"
        assert health["game"] == reader.game.name
        assert health["positions"] == reader.num_positions

        n_threads = 6
        chunks = [
            [hex(p) for p in positions[i::n_threads]]
            for i in range(n_threads)
        ]
        bodies = _fire_concurrent(server, chunks)
        for chunk, body in zip(chunks, bodies):
            assert len(body["results"]) == len(chunk)
            for q, rec in zip(chunk, body["results"]):
                v, r = oracle[int(q, 0)]
                assert rec["found"], q
                assert rec["value"] == value_name(v), q
                assert rec["remoteness"] == r, q

        # Repeat the same traffic: answers now come from the LRU cache.
        _fire_concurrent(server, chunks)

        # JSON counters moved to /metrics.json (Prometheus text owns
        # /metrics; negotiation is covered in test_obs.py).
        # http_requests counts on request COMPLETION (the finally in
        # do_POST), so the last handler threads may not have counted
        # themselves by the time their clients have the response — give
        # the counter a moment to settle before asserting.
        deadline = time.monotonic() + 5.0
        while True:
            status, metrics = _get(base + "/metrics.json")
            assert status == 200
            if (metrics["http_requests"] >= 2 * n_threads
                    or time.monotonic() > deadline):
                break
            time.sleep(0.02)
        assert metrics["batches"] >= 1
        assert metrics["mean_batch_size"] > 1  # coalescing happened
        assert metrics["cache_hits"] >= len(positions)
        assert metrics["http_requests"] >= 2 * n_threads
        assert metrics["latency_mean_ms"] > 0


def test_serve_full_tictactoe_oracle(ttt_db):
    """The acceptance game: all 5478 tictactoe positions, served in
    concurrent chunks, match the oracle exactly."""
    reader, oracle = ttt_db
    positions = sorted(oracle)
    with QueryServer(reader, window=0.02) as server:
        chunks = [[hex(p) for p in positions[i::8]] for i in range(8)]
        bodies = _fire_concurrent(server, chunks)
        for chunk, body in zip(chunks, bodies):
            for q, rec in zip(chunk, body["results"]):
                v, r = oracle[int(q, 0)]
                assert (rec["found"], rec["value"], rec["remoteness"]) == (
                    True, value_name(v), r,
                ), q


def test_best_move_chain_reaches_terminal(nim_db):
    """Following served best moves from the root plays a full optimal
    game: remoteness decreases by exactly 1 per ply to 0."""
    reader, oracle = nim_db
    with QueryServer(reader) as server:
        url = f"http://127.0.0.1:{server.port}/query"
        pos = int(reader.game.initial_state())
        _, body = _post(url, {"positions": [pos]})
        rec = body["results"][0]
        seen = 0
        while rec["best"] is not None:
            nxt = int(rec["best"], 0)
            _, body = _post(url, {"positions": [nxt]})
            nrec = body["results"][0]
            assert nrec["remoteness"] == rec["remoteness"] - 1
            rec = nrec
            seen += 1
        assert rec["remoteness"] == 0
        assert seen > 0


def test_http_error_paths(nim_db):
    reader, _ = nim_db
    with QueryServer(reader) as server:
        base = f"http://127.0.0.1:{server.port}"
        status, body = _post(
            base + "/query", {"positions": [1, "zz", -3, 4.2, True]}
        )
        assert status == 200
        ok, bad, neg, flt, boolean = body["results"]
        assert "error" in bad and "error" in neg
        # Non-integer numbers and booleans are refused, never truncated to
        # a neighboring position's answer.
        assert "error" in flt and "error" in boolean
        assert "found" in ok
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base + "/query", {"wrong": []})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(base + "/nope")
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base + "/nope", {})
        assert e.value.code == 404
        # Rejects are visible in the counters: every POST lands in
        # http_requests, errors in http_errors.
        _, metrics = _get(base + "/metrics.json")
        assert metrics["http_errors"] >= 2
        assert metrics["http_requests"] >= 3


def test_batcher_coalesces_and_caches(nim_db):
    """Batcher unit semantics without HTTP: concurrent submits coalesce
    into fewer lookup_best calls; repeats hit the LRU."""
    reader, oracle = nim_db
    positions = sorted(oracle)[:30]
    batcher = Batcher(reader, window=0.05, cache_size=1024)
    try:
        barrier = threading.Barrier(5)
        outs = [None] * 5

        def worker(i):
            barrier.wait()
            outs[i] = batcher.submit(positions[i::5])

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(5):
            for pos, (v, r, found, _) in zip(positions[i::5], outs[i]):
                assert found and (v, r) == oracle[pos]
        m = batcher.metrics()
        assert m["batches"] < m["requests"]  # coalescing, not per-request
        assert m["mean_batch_size"] > 1
        assert m["cache_hits"] == 0
        again = batcher.submit(positions)
        for pos, (v, r, found, _) in zip(positions, again):
            assert found and (v, r) == oracle[pos]
        assert batcher.metrics()["cache_hits"] == len(positions)
    finally:
        batcher.close()
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit([0])


def test_batcher_close_rejects_parked_submitters(nim_db):
    """Submitters parked in the coalescing window when close() lands must
    receive BatcherClosed — not hang forever on an event nobody sets."""
    from gamesmanmpi_tpu.serve import BatcherClosed

    reader, oracle = nim_db
    batcher = Batcher(reader, window=60.0, cache_size=0)  # park "forever"
    errors = []

    def worker():
        try:
            batcher.submit(sorted(oracle)[:3])
            errors.append("answered")  # should NOT be flushed
        except BatcherClosed:
            errors.append("closed")
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    t = threading.Thread(target=worker)
    t.start()
    deadline = threading.Event()
    for _ in range(500):  # wait until the request is parked
        if batcher.metrics()["cache_misses"] >= 3 and not deadline.wait(0.01):
            break
    batcher.close()
    t.join(timeout=10)
    assert not t.is_alive(), "parked submitter hung across close()"
    assert errors == ["closed"]


def test_batcher_burst_splits_across_probes(nim_db):
    """A synchronized burst larger than max_batch must split into
    multiple probes with every request answered (none starved behind an
    oversized batch)."""
    reader, oracle = nim_db
    positions = sorted(oracle)[:24]
    batcher = Batcher(reader, window=0.05, cache_size=0, max_batch=8)
    try:
        barrier = threading.Barrier(6)
        outs = [None] * 6

        def worker(i):
            barrier.wait()
            outs[i] = batcher.submit(positions[i * 4:(i + 1) * 4])

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(6):
            assert outs[i] is not None
            for pos, (v, r, found, _) in zip(
                positions[i * 4:(i + 1) * 4], outs[i]
            ):
                assert found and (v, r) == oracle[pos]
        m = batcher.metrics()
        # 24 positions with an 8-position flush threshold: >= 3 probes,
        # none above the threshold.
        assert m["batches"] >= 3
        assert m["max_batch_size"] <= 8
    finally:
        batcher.close()


def test_batcher_sheds_when_queue_full(nim_db):
    """max_queue requests parked -> further submits answer
    BatcherOverloaded immediately instead of deepening the pile."""
    from gamesmanmpi_tpu.serve import BatcherOverloaded

    reader, oracle = nim_db
    positions = sorted(oracle)
    batcher = Batcher(reader, window=60.0, cache_size=0, max_queue=1)

    def _park():
        with pytest.raises(RuntimeError):  # BatcherClosed at teardown
            batcher.submit(positions[:2], timeout=15)

    try:
        parked = threading.Thread(target=_park)
        parked.start()
        for _ in range(500):
            if batcher.metrics()["cache_misses"] >= 2:
                break
            time.sleep(0.01)
        with pytest.raises(BatcherOverloaded):
            batcher.submit(positions[2:4])
        assert batcher.metrics()["shed"] >= 1
    finally:
        batcher.close()  # parked request gets BatcherClosed
        parked.join(timeout=10)
        assert not parked.is_alive()


def test_client_abort_is_counted_not_crashed(nim_db):
    """A client that hangs up mid-response increments http_client_aborts
    instead of dumping a handler-thread traceback."""
    import socket

    reader, oracle = nim_db
    with QueryServer(reader, window=0.001) as server:
        # Large response (many positions) so the server's write
        # overflows the socket buffer and hits the closed peer. 6000
        # repeats of one position keep the probe kernel at a modest
        # capacity bucket while the response stays a few hundred KB.
        positions = [sorted(oracle)[0]] * 6000
        body = json.dumps({"positions": positions}).encode()
        req = (
            b"POST /query HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n"
        ) + body
        s = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        # RST on close so the server's write fails loudly and promptly.
        s.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER,
            __import__("struct").pack("ii", 1, 0),
        )
        s.sendall(req)
        s.close()
        deadline = time.monotonic() + 10
        aborts = 0
        while time.monotonic() < deadline:
            aborts = server.metrics()["http_client_aborts"]
            if aborts:
                break
            time.sleep(0.05)
        assert aborts >= 1


def test_serve_jsonl_metrics(nim_db, tmp_path):
    """Per-batch serving records land in the shared JSONL stream."""
    from gamesmanmpi_tpu.utils.metrics import JsonlLogger

    reader, oracle = nim_db
    path = tmp_path / "serve.jsonl"
    with JsonlLogger(str(path)) as logger:
        with QueryServer(reader, logger=logger) as server:
            _post(
                f"http://127.0.0.1:{server.port}/query",
                {"positions": sorted(oracle)[:5]},
            )
    records = [json.loads(line) for line in path.read_text().splitlines()]
    batch = [r for r in records if r["phase"] == "serve_batch"]
    assert batch and batch[0]["batch_size"] == 5


@pytest.mark.slow
def test_serve_sustained_load(ttt_db):
    """Sustained mixed load (repeats + misses) stays oracle-exact; marked
    slow: many serial HTTP rounds."""
    reader, oracle = ttt_db
    rng = np.random.default_rng(11)
    positions = sorted(oracle)
    # Zipf-ish traffic: most queries land in a small hot set (openings),
    # the rest spread over the whole table.
    hot = positions[:256]
    with QueryServer(reader, window=0.005) as server:
        url = f"http://127.0.0.1:{server.port}/query"
        for _ in range(40):
            chunk = [
                hex(hot[i]) for i in rng.choice(len(hot), size=48)
            ] + [
                hex(positions[i]) for i in rng.choice(len(positions), size=16)
            ]
            _, body = _post(url, {"positions": chunk})
            for q, rec in zip(chunk, body["results"]):
                v, r = oracle[int(q, 0)]
                assert (rec["value"], rec["remoteness"]) == (
                    value_name(v), r,
                )
        metrics = server.metrics()
        assert metrics["cache_hit_rate"] > 0.5  # Zipf-ish repeats hit
