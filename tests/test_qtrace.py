"""obs/qtrace + obs/slo: query-path tracing and SLO burn rates.

Acceptance axis (ISSUE 17): a traceparent survives the whole query path
(HTTP ingress -> batcher -> reader probe -> response header), the tail
sampler keeps exactly the traces an operator wants (errors, sheds,
slow, 1-in-N baseline) under concurrent offers with bounded memory, the
SLO engine's fast-window burn rate rises past its threshold during a
bad minute AND recovers after it, exemplars join metrics to traces
without disturbing the default exposition, and the reporting tools
(load_gen --out-jsonl, obs_report, bench_compare) speak the new record
shapes.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from gamesmanmpi_tpu.db import DbReader, export_result
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.obs import MetricsRegistry
from gamesmanmpi_tpu.obs.qtrace import (
    QueryTrace,
    TraceRing,
    activate,
    active_traces,
    format_traceparent,
    mint_trace_ids,
    parse_traceparent,
    qspan,
)
from gamesmanmpi_tpu.obs.slo import (
    SLO_FAST_BURN_TRIPS,
    SloEngine,
)
from gamesmanmpi_tpu.solve import Solver

from helpers import REPO, load_module


# ------------------------------------------------------ traceparent wire


def test_traceparent_mint_format_parse_roundtrip():
    tid, sid = mint_trace_ids()
    assert len(tid) == 32 and len(sid) == 16
    header = format_traceparent(tid, sid)
    assert header == f"00-{tid}-{sid}-01"
    assert parse_traceparent(header) == (tid, sid)
    # Case-insensitive per W3C: an uppercase header still parses.
    assert parse_traceparent(header.upper()) == (tid, sid)


@pytest.mark.parametrize("header", [
    None,
    "",
    "garbage",
    "00-abc-def-01",  # wrong field widths
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",  # non-hex
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
    "00-" + "1" * 32 + "-" + "2" * 16,  # missing flags
])
def test_traceparent_malformed_is_rejected_not_fatal(header):
    assert parse_traceparent(header) is None
    # A server handed a malformed header mints a fresh root instead of
    # failing the request.
    trace = QueryTrace(traceparent=header)
    assert len(trace.trace_id) == 32 and trace.parent_id is None


def test_query_trace_adopts_client_context():
    tid, sid = mint_trace_ids()
    trace = QueryTrace(traceparent=format_traceparent(tid, sid),
                       route="nim", worker=3)
    assert trace.trace_id == tid
    assert trace.parent_id == sid
    assert trace.duration_ms is None  # not finished yet
    trace.add_span("queue_wait", 0.001, 0.002, batch=4)
    secs = trace.finish(status="ok", code=200)
    # finish is idempotent: a second call must not restart the clock.
    assert trace.finish(status="ok", code=200) == secs
    rec = trace.to_dict()
    assert rec["trace_id"] == tid and rec["parent_id"] == sid
    assert rec["route"] == "nim" and rec["worker"] == 3
    assert rec["dur_ms"] == pytest.approx(secs * 1e3, rel=1e-6, abs=1e-3)
    (span,) = rec["spans"]
    assert span["name"] == "queue_wait"
    assert span["start_ms"] == 1.0 and span["dur_ms"] == 2.0
    assert span["batch"] == 4


def test_query_trace_span_fields_are_json_safe():
    trace = QueryTrace()
    trace.add_span("store_read", 0.0, 0.0, path="hit", level=2,
                   weird=object())
    span = trace.to_dict()["spans"][0]
    assert span["path"] == "hit" and span["level"] == 2
    assert isinstance(span["weird"], str)  # coerced, not a crash
    json.dumps(trace.to_dict())  # the whole record must serialize


# ------------------------------------------------- activation and qspan


def test_qspan_attributes_to_every_active_trace():
    a, b = QueryTrace(), QueryTrace()
    assert active_traces() == ()
    with activate([a, None, b]):  # None entries (untraced peers) skipped
        assert active_traces() == (a, b)
        with qspan("block_decode", level=1, block=7) as extra:
            extra["path"] = "sync"
    assert active_traces() == ()
    for tr in (a, b):
        (span,) = tr.to_dict()["spans"]
        assert span["name"] == "block_decode"
        assert span["level"] == 1 and span["block"] == 7
        assert span["path"] == "sync"  # extra fields merged at exit


def test_qspan_without_active_trace_is_a_noop():
    with qspan("canonicalize", queries=5) as handle:
        assert handle is None  # fast path: no clock, no span dict


def test_activation_is_thread_local():
    trace = QueryTrace()
    seen = []

    def other():
        seen.append(active_traces())

    with activate([trace]):
        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert seen == [()]  # the other thread never saw our binding


# --------------------------------------------------- tail-based sampling


def _finished(status="ok", dur_ms=1.0, code=200):
    """A trace finished with an exact duration via injected clocks."""
    trace = QueryTrace(clock=lambda: 0.0)
    trace.finish(status=status, code=code, clock=lambda: dur_ms / 1e3)
    return trace


def test_tail_sampler_keeps_errors_sheds_and_slow():
    ring = TraceRing(capacity=16, slow_ms=50.0, head_n=1000,
                     enabled=True, registry=MetricsRegistry())
    assert ring.offer(_finished(status="error", code=500)) == "error"
    assert ring.offer(_finished(status="shed", code=503)) == "shed"
    assert ring.offer(_finished(status="tripped", code=503)) == "tripped"
    assert ring.offer(_finished(dur_ms=120.0)) == "slow"
    # Fast + ok + not the head slot -> dropped.
    assert ring.offer(_finished(dur_ms=1.0)) is None
    snap = ring.snapshot()
    assert snap["seen"] == 5
    assert snap["kept"] == 4 and snap["dropped"] == 1
    assert snap["seen"] == snap["kept"] + snap["dropped"]
    reasons = [t["keep"] for t in snap["traces"]]
    assert reasons == ["error", "shed", "tripped", "slow"]


def test_tail_sampler_head_keeps_one_in_n():
    ring = TraceRing(capacity=64, slow_ms=1e9, head_n=10,
                     enabled=True, registry=MetricsRegistry())
    reasons = [ring.offer(_finished(dur_ms=1.0)) for _ in range(30)]
    assert [r for r in reasons if r] == ["head"] * 3  # offers 1, 11, 21
    assert reasons[0] == "head" and reasons[10] == "head"


def test_tail_sampler_disabled_drops_everything():
    ring = TraceRing(capacity=16, slow_ms=0.0, head_n=1,
                     enabled=False, registry=MetricsRegistry())
    assert ring.offer(_finished(status="error", code=500)) is None
    snap = ring.snapshot()
    assert not snap["enabled"]
    assert snap["seen"] == 0 and snap["traces"] == []


def test_trace_ring_find_and_snapshot_limit():
    ring = TraceRing(capacity=8, slow_ms=0.0, head_n=1,
                     enabled=True, registry=MetricsRegistry())
    traces = [_finished(dur_ms=5.0) for _ in range(5)]
    for tr in traces:
        ring.offer(tr)
    assert ring.find(traces[2].trace_id)["trace_id"] == traces[2].trace_id
    assert ring.find("f" * 32) is None
    limited = ring.snapshot(limit=2)["traces"]
    assert [t["trace_id"] for t in limited] == \
        [traces[-2].trace_id, traces[-1].trace_id]  # newest-last


def test_trace_ring_concurrent_hammer_bounded_and_race_free():
    """Many threads offering finished traces while one drains the
    outbox: counters stay consistent (seen == kept + dropped), the ring
    never exceeds capacity, and drained traces never re-ship."""
    ring = TraceRing(capacity=32, slow_ms=50.0, head_n=7,
                     enabled=True, registry=MetricsRegistry())
    threads, offered = 8, 200
    statuses = ["ok", "ok", "ok", "error", "shed", "ok", "tripped", "ok"]
    drained: list = []
    stop = threading.Event()

    def offerer(i):
        for j in range(offered):
            dur = 120.0 if (i + j) % 5 == 0 else 1.0
            ring.offer(_finished(status=statuses[(i + j) % len(statuses)],
                                 dur_ms=dur))

    def drainer():
        while not stop.is_set():
            drained.extend(ring.drain_outbox(8))
        drained.extend(ring.drain_outbox(10**6))  # final sweep

    workers = [threading.Thread(target=offerer, args=(i,))
               for i in range(threads)]
    d = threading.Thread(target=drainer)
    d.start()
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    stop.set()
    d.join()
    snap = ring.snapshot()
    assert snap["seen"] == threads * offered
    assert snap["seen"] == snap["kept"] + snap["dropped"]
    assert len(snap["traces"]) <= 32  # ring bounded by capacity
    assert snap["kept"] >= len(snap["traces"])
    # Every drained record was kept exactly once (no re-shipping), and
    # the outbox never exceeds its own bound between drains.
    assert len(drained) <= snap["kept"]
    ids = [id(rec) for rec in drained]
    assert len(ids) == len(set(ids))
    assert ring.drain_outbox() == []  # fully drained stays drained


# ------------------------------------------------------ SLO burn engine


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_slo_burn_rises_trips_and_recovers():
    clock = _FakeClock()
    reg = MetricsRegistry()
    eng = SloEngine(p99_ms=100.0, avail_target=0.9, latency_target=0.9,
                    fast_window=5.0, slow_window=10.0, fast_burn=2.0,
                    min_requests=10, registry=reg, clock=clock)
    # A healthy second: fast requests, 200s -> burn 0, no trip.
    for _ in range(10):
        eng.observe("default", 0.001, 200)
    snap = eng.snapshot()
    avail = snap["routes"]["default"]["availability"]
    assert avail["burn_fast"] == 0.0 and not avail["fast_burn"]
    assert not snap["fast_burn"]
    # The bad second: every request errors AND blows the latency
    # objective. bad_frac 20/30 over budget 0.1 -> burn ~6.7 > 2.0.
    clock.t += 1.0
    for _ in range(20):
        eng.observe("default", 0.5, 500)
    snap = eng.snapshot()
    avail = snap["routes"]["default"]["availability"]
    lat = snap["routes"]["default"]["latency"]
    assert avail["burn_fast"] > 2.0 and avail["fast_burn"]
    assert lat["burn_fast"] > 2.0 and lat["fast_burn"]
    assert snap["fast_burn"] and eng.fast_burning()
    # Edge-triggered trips: a second snapshot while still burning must
    # not count a second crossing.
    eng.snapshot()
    trips = sum(row["value"]
                for row in reg.snapshot()[SLO_FAST_BURN_TRIPS]["values"])
    assert trips == 2  # availability + latency, once each
    # The bad minute ends: advance past the fast window and the burn
    # rate recovers without any new traffic.
    clock.t += 20.0
    snap = eng.snapshot()
    assert not snap["fast_burn"]
    assert snap["routes"]["default"]["availability"]["burn_fast"] == 0.0
    # A fresh bad burst after recovery IS a new crossing.
    for _ in range(20):
        eng.observe("default", 0.5, 500)
    eng.snapshot()
    trips = sum(row["value"]
                for row in reg.snapshot()[SLO_FAST_BURN_TRIPS]["values"])
    assert trips == 4


def test_slo_volume_gate_blocks_meaningless_trips():
    clock = _FakeClock()
    eng = SloEngine(p99_ms=100.0, avail_target=0.999,
                    fast_window=5.0, slow_window=10.0, fast_burn=2.0,
                    min_requests=100, registry=MetricsRegistry(),
                    clock=clock)
    # One bad request among five: burn rate is astronomically over
    # threshold but the window holds far fewer than min_requests.
    eng.observe("default", 0.001, 500)
    for _ in range(4):
        eng.observe("default", 0.001, 200)
    snap = eng.snapshot()
    avail = snap["routes"]["default"]["availability"]
    assert avail["burn_fast"] > 2.0  # reported honestly...
    assert not avail["fast_burn"]  # ...but not tripped
    assert not snap["fast_burn"]


def test_slo_shed_counts_against_availability():
    clock = _FakeClock()
    eng = SloEngine(p99_ms=1e9, avail_target=0.9, fast_window=5.0,
                    slow_window=10.0, fast_burn=1.0, min_requests=1,
                    registry=MetricsRegistry(), clock=clock)
    eng.observe("default", 0.001, 503, shed=True)
    snap = eng.snapshot()
    assert snap["routes"]["default"]["availability"]["burn_fast"] > 1.0
    # An intentional 503 is still perfectly fast.
    assert snap["routes"]["default"]["latency"]["burn_fast"] == 0.0


# ---------------------------------------------------- metrics exemplars


def test_exemplar_rides_openmetrics_not_the_default_exposition():
    def build(with_exemplar):
        reg = MetricsRegistry()
        h = reg.histogram("gamesman_http_request_seconds",
                          "wall seconds per POST request")
        h.observe(0.3, exemplar={"trace_id": "ab" * 16}
                  if with_exemplar else None)
        return reg

    plain, tagged = build(False), build(True)
    # The v0.0.4 exposition every existing scraper parses is
    # byte-identical whether or not an exemplar was attached.
    assert plain.render_prometheus() == tagged.render_prometheus()
    om = tagged.render_openmetrics()
    assert '# {trace_id="' + "ab" * 16 + '"}' in om
    assert om.rstrip().endswith("# EOF")
    assert '# {' not in plain.render_openmetrics()
    # The snapshot carries it too (the /metrics.json join path).
    rows = tagged.snapshot()["gamesman_http_request_seconds"]["values"]
    assert rows[0]["exemplar"]["labels"] == {"trace_id": "ab" * 16}


# ----------------------------------------------- end-to-end over HTTP


@pytest.fixture(scope="module")
def sub_reader(tmp_path_factory):
    spec = "subtract:total=15,moves=1-2"
    d = tmp_path_factory.mktemp("qtracedb")
    export_result(Solver(get_game(spec)).solve(), d, spec)
    with DbReader(d) as reader:
        yield reader


def _post_with_headers(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _get(url, headers=None, timeout=30):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read().decode()


def test_server_traces_query_end_to_end(sub_reader, monkeypatch):
    """POST with a client traceparent -> the response echoes the trace
    id, GET /traces holds the sampled trace with probe spans, /healthz
    carries the SLO snapshot, and /metrics negotiates OpenMetrics."""
    from gamesmanmpi_tpu.serve import QueryServer

    monkeypatch.setenv("GAMESMAN_TRACE_HEAD_N", "1")  # keep everything
    pos = int(sub_reader.game.initial_state())
    with QueryServer(sub_reader, window=0.001,
                     registry=MetricsRegistry()) as server:
        base = f"http://127.0.0.1:{server.port}"
        tid, sid = mint_trace_ids()
        status, headers, body = _post_with_headers(
            base + "/query", {"positions": [pos]},
            headers={"traceparent": format_traceparent(tid, sid)},
        )
        assert status == 200 and body["results"][0]["found"]
        # The response joins client to server: same trace id, a server
        # span id (never an echo of the client's).
        echoed = parse_traceparent(headers.get("traceparent"))
        assert echoed is not None
        assert echoed[0] == tid and echoed[1] != sid
        # The sampled trace is queryable by the client's id.
        _, _, raw = _get(base + "/traces")
        snap = json.loads(raw)
        assert snap["kind"] == "qtrace_ring" and snap["enabled"]
        rec = next(t for t in snap["traces"] if t["trace_id"] == tid)
        assert rec["parent_id"] == sid
        assert rec["status"] == "ok" and rec["code"] == 200
        names = {s["name"] for s in rec["spans"]}
        assert {"queue_wait", "canonicalize", "searchsorted"} <= names
        # Span timing is consistent: every span fits inside the trace.
        for s in rec["spans"]:
            assert s["start_ms"] + s["dur_ms"] <= rec["dur_ms"] + 1.0
        # /healthz carries the SLO burn snapshot.
        health = json.loads(_get(base + "/healthz")[2])
        assert health["status"] == "ok"
        assert "latency" in health["slo"]["routes"]["default"]
        # Content negotiation: OpenMetrics on request, v0.0.4 default.
        _, h, om = _get(base + "/metrics",
                        headers={"Accept": "application/openmetrics-text"})
        assert "openmetrics-text" in h.get("Content-Type", "")
        assert om.rstrip().endswith("# EOF")
        _, h, _ = _get(base + "/metrics")
        assert "openmetrics" not in h.get("Content-Type", "")


def test_server_no_trace_disables_ring_and_header(sub_reader,
                                                  monkeypatch):
    from gamesmanmpi_tpu.serve import QueryServer

    monkeypatch.setenv("GAMESMAN_TRACE", "0")
    pos = int(sub_reader.game.initial_state())
    with QueryServer(sub_reader, window=0.001,
                     registry=MetricsRegistry()) as server:
        base = f"http://127.0.0.1:{server.port}"
        status, headers, body = _post_with_headers(
            base + "/query", {"positions": [pos]},
        )
        assert status == 200 and body["results"][0]["found"]
        assert headers.get("traceparent") is None
        snap = json.loads(_get(base + "/traces")[2])
        assert not snap["enabled"] and snap["traces"] == []


def test_serve_stats_record_and_obs_report_folding(sub_reader,
                                                   monkeypatch):
    """QueryServer.serve_stats() emits the per-route quantile + SLO
    record obs_report folds into its serving table."""
    from gamesmanmpi_tpu.serve import QueryServer

    monkeypatch.setenv("GAMESMAN_TRACE_HEAD_N", "1")
    pos = int(sub_reader.game.initial_state())
    with QueryServer(sub_reader, window=0.001,
                     registry=MetricsRegistry()) as server:
        base = f"http://127.0.0.1:{server.port}"
        for _ in range(8):
            _post_with_headers(base + "/query", {"positions": [pos]})
        # note_request lands in the handler's finally, which can run a
        # hair after the last response hit the wire — poll briefly.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            rec = server.serve_stats()
            if rec["routes"].get("default", {}).get("count", 0) >= 8:
                break
            time.sleep(0.01)
    assert rec["phase"] == "serve_stats"
    route = rec["routes"]["default"]
    assert route["count"] >= 8
    assert any(k in route for k in ("p50_ms", "p95_ms", "p99_ms"))
    assert rec["slo"]["fast_burn"] is False
    assert "availability" in rec["slo"]["routes"]["default"]
    json.dumps(rec)  # must be JSONL-safe

    obs_report = load_module(REPO / "tools" / "obs_report.py")
    records = [
        {"phase": "serve_batch", "worker": 0, "requests": 8,
         "batch_size": 8, "secs": 0.01},
        dict(rec, worker=0),
    ]
    rows = obs_report.serving_summary(records)
    assert rows[0]["routes"]["default"]["count"] >= 8
    assert rows[0]["slo"]["p99_ms"] == rec["slo"]["p99_ms"]
    lines = obs_report.summarize_serving(records)
    assert any("route[default]:" in ln and "p99_ms=" in ln
               for ln in lines)
    assert any("slo: fast_burn=ok" in ln for ln in lines)


# ----------------------------------------------------- reporting tools


def test_load_gen_out_jsonl_records_join_by_trace_id(sub_reader,
                                                     tmp_path,
                                                     monkeypatch):
    from gamesmanmpi_tpu.serve import QueryServer

    monkeypatch.setenv("GAMESMAN_TRACE_HEAD_N", "1")
    load_gen = load_module(REPO / "tools" / "load_gen.py")
    pos = int(sub_reader.game.initial_state())
    out = tmp_path / "requests.jsonl"
    with QueryServer(sub_reader, window=0.001,
                     registry=MetricsRegistry()) as server:
        base = f"http://127.0.0.1:{server.port}"
        stats = load_gen.run_load(
            base, [pos], duration=0.5, concurrency=2,
            chunk_size=1, out_jsonl=str(out),
        )
        snap = json.loads(_get(base + "/traces")[2])
    assert stats["requests"] > 0
    records = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(records) == stats["requests"]
    server_ids = {t["trace_id"] for t in snap["traces"]}
    joined = 0
    for rec in records:
        assert set(rec) == {"trace_id", "kind", "code", "latency_ms",
                            "mismatch"}
        assert len(rec["trace_id"]) == 32
        assert rec["kind"] == "ok" and rec["mismatch"] is False
        assert rec["latency_ms"] > 0
        joined += rec["trace_id"] in server_ids
    # head_n=1 keeps every trace, so client records join server traces
    # by id (modulo ring-capacity eviction under longer runs).
    assert joined > 0


def test_bench_compare_gates_trace_ab():
    bench_compare = load_module(REPO / "tools" / "bench_compare.py")
    ok, lines = bench_compare.check_trace_ab({"metric": "x"})
    assert ok and lines == []  # no arm -> nothing to gate
    ok, lines = bench_compare.check_trace_ab(
        {"serve": {"trace_ab": {"ok": True, "delta_pct": 1.2,
                                "max_delta_pct": 5.0}}})
    assert ok and "trace_ab" in lines[0]
    ok, lines = bench_compare.check_trace_ab(
        {"serve": {"trace_ab": {"ok": False, "delta_pct": 9.9,
                                "max_delta_pct": 5.0}}})
    assert not ok
    assert any("TRACING OVERHEAD REGRESSION" in ln for ln in lines)
    ok, lines = bench_compare.check_trace_ab(
        {"trace_ab": {"error": "fleet never became healthy"}})
    assert not ok and any("TRACE A/B BROKEN" in ln for ln in lines)
    # The full gate: a record passing the ratio check still fails on a
    # busted A/B arm.
    new = {"metric": "m", "device": "cpu", "value": 100.0,
           "serve": {"trace_ab": {"ok": False, "delta_pct": 9.9,
                                  "max_delta_pct": 5.0}}}
    ref = {"metric": "m", "device": "cpu", "value": 100.0}
    ok, lines = bench_compare.compare(new, [("BENCH_ref.json", ref)],
                                      0.6)
    assert not ok
