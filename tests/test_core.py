"""core/: value algebra, codec, hashing, bit ops."""

import pytest
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core import (
    WIN,
    LOSE,
    TIE,
    UNDECIDED,
    negate,
    pack_cells,
    unpack_cells,
    owner_shard,
    splitmix64,
    popcount,
    msb_index,
    SENTINEL64,
)
from gamesmanmpi_tpu.core.hashing import owner_shard_np
from gamesmanmpi_tpu.core.values import MAX_REMOTENESS

# Smoke tier: fast, compile-light, single-process-safe (see pyproject).
pytestmark = pytest.mark.smoke


def test_negate_involution():
    vals = jnp.arange(4, dtype=jnp.uint8)
    assert (negate(negate(vals)) == vals).all()
    assert int(negate(jnp.uint8(WIN))) == LOSE
    assert int(negate(jnp.uint8(LOSE))) == WIN
    assert int(negate(jnp.uint8(TIE))) == TIE
    assert int(negate(jnp.uint8(UNDECIDED))) == UNDECIDED


def test_codec_roundtrip():
    rng = np.random.default_rng(0)
    values = jnp.asarray(rng.integers(0, 4, 1000), jnp.uint8)
    rem = jnp.asarray(rng.integers(0, MAX_REMOTENESS + 1, 1000), jnp.int32)
    v, r = unpack_cells(pack_cells(values, rem))
    assert (v == values).all()
    assert (r == rem).all()


def test_splitmix64_bijective_sample():
    rng = np.random.default_rng(1)
    xs = jnp.asarray(rng.integers(0, 2**63, 4096, dtype=np.uint64))
    hs = np.asarray(splitmix64(xs))
    assert len(np.unique(hs)) == len(np.unique(np.asarray(xs)))


def test_owner_shard_total_and_deterministic():
    # Hash-partition totality (SURVEY.md §4.2 axis 3): every position owned by
    # exactly one shard, stable across calls, and consistent host vs device.
    rng = np.random.default_rng(2)
    xs = rng.integers(0, 2**63, 10000, dtype=np.uint64)
    for n in (1, 2, 8):
        owners = np.asarray(owner_shard(jnp.asarray(xs), n))
        assert owners.min() >= 0 and owners.max() < n
        assert (owners == np.asarray(owner_shard(jnp.asarray(xs), n))).all()
        assert (owners == owner_shard_np(xs, n)).all()
    # Reasonable balance over 8 shards.
    counts = np.bincount(owner_shard_np(xs, 8), minlength=8)
    assert counts.min() > 0.8 * len(xs) / 8


def test_bitops():
    xs = jnp.asarray(np.array([1, 2, 3, 2**40, SENTINEL64], dtype=np.uint64))
    assert list(np.asarray(popcount(xs))) == [1, 1, 2, 1, 64]
    assert list(np.asarray(msb_index(xs))) == [0, 1, 1, 40, 63]
