"""Hybrid dense/BFS engine: bit-identical to both component engines.

The hybrid is pure implementation strategy (SURVEY.md §1's contract is
value+remoteness of every reachable position); these tests pin it to the
classic solver's full tables across cutover placements, including the
degenerate ends where one engine does almost all the work.
"""

import pytest

from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.hybrid import HybridSolver, default_cutover


def _full_parity(spec: str, cutovers):
    g = get_game(spec)
    ref = Solver(g).solve()
    for K in cutovers:
        hy = HybridSolver(get_game(spec), cutover=K).solve()
        assert (hy.value, hy.remoteness) == (ref.value, ref.remoteness), K
        # Reachable count must match the BFS discovery exactly (the dense
        # sweep and the BFS frontier are cross-checked inside solve too).
        assert hy.num_positions == ref.num_positions, K
        for level, table in ref.levels.items():
            for i in range(table.states.shape[0]):
                s = int(table.states[i])
                assert hy.lookup(s) == (
                    int(table.values[i]), int(table.remoteness[i])
                ), (K, level, hex(s))


def test_hybrid_full_parity_3x3c3():
    # Cutovers spanning the whole range: K=0 (dense solves only the empty
    # board), the default, and K=ncells-1 (BFS solves only the full level).
    _full_parity("connect4:w=3,h=3,connect=3", (0, 3, default_cutover(9), 8))


@pytest.mark.slow  # ~64 s CPU full-board parity; 3x3c3 covers the seam fast
def test_hybrid_full_parity_4x3():
    _full_parity("connect4:w=4,h=3", (5, 8))


def test_hybrid_validates_args():
    g4 = get_game("connect4:w=3,h=3,connect=3")
    with pytest.raises(ValueError, match="cutover"):
        HybridSolver(g4, cutover=9)  # == ncells: no BFS region
    with pytest.raises(ValueError, match="cutover"):
        HybridSolver(g4, cutover=-1)
    with pytest.raises(TypeError):
        HybridSolver(get_game("tictactoe"))


def test_hybrid_sym_parity_3x3c3():
    """sym=1 (VERDICT r4 #4): the BFS region keeps the mirror reduction,
    the dense region indexes the full space through a sym-free twin, and
    the seam canonicalizes both directions. Root must match the classic
    sym solve; every reachable position — BOTH members of each mirror
    class, ground truth from the full non-sym solve — must answer."""
    spec = "connect4:w=3,h=3,connect=3"
    ref = Solver(get_game(spec + ",sym=1")).solve()
    plain = Solver(get_game(spec)).solve()
    for K in (0, 3, default_cutover(9), 8):
        hy = HybridSolver(get_game(spec + ",sym=1"), cutover=K).solve()
        assert (hy.value, hy.remoteness) == (ref.value, ref.remoteness), K
        # Region accounting: dense counts the FULL reachable set (its
        # indexing cannot skip mirror duplicates), BFS representatives.
        assert hy.stats["positions_dense_region"] == sum(
            plain.levels[L].states.shape[0]
            for L in plain.levels if L <= K
        ), K
        assert hy.stats["positions_bfs_region"] == sum(
            ref.levels[L].states.shape[0] for L in ref.levels if L > K
        ), K
        for level, table in plain.levels.items():
            for i in range(table.states.shape[0]):
                s = int(table.states[i])
                assert hy.lookup(s) == (
                    int(table.values[i]), int(table.remoteness[i])
                ), (K, level, hex(s))


def test_hybrid_sym_sharded_bfs():
    """sym=1 with devices>1: the mirror-reduced BFS region rides the
    owner-routed ShardedSolver — the exact composition the v4-16 6x6
    plan costs out (sym on the sharded BFS side)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    ref = Solver(get_game("connect4:w=3,h=3,connect=3,sym=1")).solve()
    hy = HybridSolver(get_game("connect4:w=3,h=3,connect=3,sym=1"),
                      cutover=4, devices=4).solve()
    assert (hy.value, hy.remoteness) == (ref.value, ref.remoteness)
    for level, table in ref.levels.items():
        for i in range(table.states.shape[0]):
            s = int(table.states[i])
            assert hy.lookup(s) == (
                int(table.values[i]), int(table.remoteness[i])
            ), (level, hex(s))


def test_hybrid_env_cutover(monkeypatch):
    monkeypatch.setenv("GAMESMAN_HYBRID_CUTOVER", "4")
    hy = HybridSolver(get_game("connect4:w=3,h=3,connect=3"))
    assert hy.cutover == 4


def test_hybrid_no_tables_root_only():
    g = get_game("connect4:w=3,h=3,connect=3")
    ref = Solver(g).solve()
    hy = HybridSolver(g, store_tables=False, cutover=5).solve()
    assert (hy.value, hy.remoteness, hy.num_positions) == (
        ref.value, ref.remoteness, ref.num_positions
    )
    with pytest.raises(KeyError):
        hy.lookup(int(g.initial_state()))


def test_hybrid_garbage_lookup_refused():
    """Dense-side lookup refuses the fabricated mover-already-won class,
    exactly like DenseSolveResult.lookup."""
    g = get_game("connect4:w=3,h=3,connect=3")
    hy = HybridSolver(g, cutover=6).solve()
    # Level 6, heights (3,2,1): the player to move (p1, 3 stones) owns all
    # of column 0 — a completed vertical line of their own, so this cell
    # is a fabricated terminal, never a position.
    h1 = 4
    guards = (1 << 3) | (1 << (h1 + 2)) | (1 << (2 * h1 + 1))
    current = 0b111  # the mover's own completed line in column 0
    with pytest.raises(KeyError, match="line"):
        hy.lookup(guards | current)


def test_cli_engine_hybrid(capsys):
    from gamesmanmpi_tpu.cli import main as cli_main

    rc = cli_main(["connect4:w=3,h=3,connect=3", "--engine", "hybrid",
                   "--hybrid-cutover", "5"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "positions: 694" in out
    assert "value: TIE" in out

    # Eligibility errors mirror the dense engine's.
    rc = cli_main(["tictactoe", "--engine", "hybrid"])
    assert rc == 2


def test_cli_engine_hybrid_sym(capsys):
    """sym=1 is hybrid-eligible at the CLI (r5): the mirror-reduced BFS
    region rides behind the same flag surface."""
    from gamesmanmpi_tpu.cli import main as cli_main

    rc = cli_main(["connect4:w=3,h=3,connect=3,sym=1", "--engine",
                   "hybrid", "--hybrid-cutover", "5"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "value: TIE" in out
    assert "remoteness: 9" in out
    # 453 = full-space dense levels 0..5 + mirror representatives 6..9:
    # the mixed count unique to THIS composition (non-sym hybrid/classic
    # print 694), so a CLI regression silently dropping sym, or an
    # engine fallback that still exits 0, cannot pass on TIE/r9 alone.
    assert "positions: 453" in out


def test_cli_hybrid_bad_cutover_exits_cleanly(capsys, monkeypatch):
    from gamesmanmpi_tpu.cli import main as cli_main

    rc = cli_main(["connect4:w=3,h=3,connect=3", "--engine", "hybrid",
                   "--hybrid-cutover", "99"])
    assert rc == 2
    assert "cutover" in capsys.readouterr().err

    monkeypatch.setenv("GAMESMAN_HYBRID_CUTOVER", "24k")
    rc = cli_main(["connect4:w=3,h=3,connect=3", "--engine", "hybrid"])
    assert rc == 2
    assert "not an integer" in capsys.readouterr().err


def test_hybrid_sharded_bfs_parity():
    """devices>1 routes the BFS region through the owner-routed
    ShardedSolver on the fake mesh; the result must be bit-identical to
    the single-device hybrid and the classic solver."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    g = get_game("connect4:w=3,h=3,connect=3")
    ref = Solver(g).solve()
    hy = HybridSolver(g, cutover=4, devices=4).solve()
    assert (hy.value, hy.remoteness) == (ref.value, ref.remoteness)
    assert hy.num_positions == ref.num_positions
    for level, table in ref.levels.items():
        for i in range(table.states.shape[0]):
            s = int(table.states[i])
            assert hy.lookup(s) == (
                int(table.values[i]), int(table.remoteness[i])
            ), (level, hex(s))


def test_hybrid_sharded_no_tables():
    """Big-run sharded hybrid: only the boundary table materializes (the
    seam needs it); the result still answers root + counts exactly."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 fake devices")
    g = get_game("connect4:w=3,h=3,connect=3")
    hy = HybridSolver(g, cutover=4, devices=4, store_tables=False).solve()
    assert (hy.value, hy.remoteness, hy.num_positions) == (3, 9, 694)
    with pytest.raises(KeyError):
        hy.lookup(int(g.initial_state()))


def test_hybrid_streamed_boundary_parity(monkeypatch):
    """Forcing the boundary table out of residency must stream it through
    the join in blocks with bit-identical results (the mechanism that
    decouples the seam's HBM need from reachable(B) on giant boards)."""
    g = get_game("connect4:w=4,h=3")
    ref = Solver(g).solve()
    monkeypatch.setenv("GAMESMAN_HYBRID_RESIDENT_MB", "0")
    monkeypatch.setenv("GAMESMAN_HYBRID_WBLOCK", "256")
    hy_solver = HybridSolver(get_game("connect4:w=4,h=3"), cutover=6)
    hy = hy_solver.solve()
    assert hy_solver.boundary_stream_blocks > 1  # streaming really engaged
    assert (hy.value, hy.remoteness) == (ref.value, ref.remoteness)
    assert hy.num_positions == ref.num_positions
    for level, table in ref.levels.items():
        for i in range(table.states.shape[0]):
            s = int(table.states[i])
            assert hy.lookup(s) == (
                int(table.values[i]), int(table.remoteness[i])
            ), (level, hex(s))


def test_hybrid_bad_capacity_knobs_fail_fast(monkeypatch):
    """Boundary-join capacity typos must fail at construction with a
    clear message, not hours later when the join finally reads them."""
    monkeypatch.setenv("GAMESMAN_HYBRID_RESIDENT_MB", "2g")
    with pytest.raises(ValueError, match="not an integer"):
        HybridSolver(get_game("connect4:w=3,h=3,connect=3"), cutover=4)
    monkeypatch.delenv("GAMESMAN_HYBRID_RESIDENT_MB")
    monkeypatch.setenv("GAMESMAN_HYBRID_WBLOCK", "4M")
    with pytest.raises(ValueError, match="not an integer"):
        HybridSolver(get_game("connect4:w=3,h=3,connect=3"), cutover=4)
