"""ISSUE 15 mission control: live /status, flight recorder, roofline gate.

Four surfaces under test:

* the live status endpoint (obs/status.py) — tracker/ETA model units,
  the HTTP server, env gating, fleet merge with straggler flagging, and
  a REAL in-process sharded solve polled live (monotone positions
  solved, phase transitions, a finite converging ETA);
* the flight recorder (obs/flightrec.py) — ring bounds, in-flight span
  tracking, atomic dumps, and the abnormal exit paths: injected fatal
  fault (the CLI crash handler), watchdog abort, SIGTERM preemption;
* the coordinator address book (announce/peers) the fleet scraper uses;
* tools/bench_compare.py's regression gate exit codes.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from gamesmanmpi_tpu.obs import Span, flightrec
from gamesmanmpi_tpu.obs import status as obs_status
from gamesmanmpi_tpu.obs.registry import (
    MetricsRegistry,
    estimate_quantiles,
)
from helpers import REPO, load_module

_CLI = [sys.executable, "-m", "gamesmanmpi_tpu.cli"]


def _get_json(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


# ------------------------------------------------------ registry quantiles


def test_estimate_quantiles_interpolates_within_buckets():
    bounds = (1.0, 2.0, 4.0, float("inf"))
    counts = [1, 2, 3, 1]  # 7 samples
    q = estimate_quantiles(bounds, counts, (0.5, 0.95, 0.99))
    # p50: target 3.5 lands in the (2, 4] bucket (cum 3 before it):
    # 2 + 2 * (3.5 - 3) / 3.
    assert abs(q[0.5] - (2 + 2 * 0.5 / 3)) < 1e-9
    # p99: target 6.93 lands in the +Inf bucket -> saturates at the
    # last finite bound, never an invented value.
    assert q[0.99] == 4.0


def test_estimate_quantiles_empty_histogram_is_none():
    q = estimate_quantiles((1.0, float("inf")), [0, 0])
    assert q[0.5] is None and q[0.99] is None


def test_histogram_snapshot_carries_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("gamesman_q_seconds", "x", buckets=[1, 2, 4])
    for v in (0.5, 1.5, 3.0, 3.0):
        h.observe(v)
    row = reg.snapshot()["gamesman_q_seconds"]["values"][0]
    assert set(row["quantiles"]) == {"p50", "p95", "p99"}
    assert 1.0 < row["quantiles"]["p50"] <= 4.0
    # Unobserved histograms snapshot with null quantiles, not a crash.
    reg.histogram("gamesman_q2_seconds", "x", buckets=[1])
    row2 = reg.snapshot()["gamesman_q2_seconds"]["values"][0]
    assert row2["quantiles"]["p99"] is None


# ------------------------------------------------------------ ETA tracker


def test_tracker_eta_converges_with_level_schedule():
    t = obs_status.SolveStatusTracker()
    assert t.eta_secs() is None  # no schedule yet
    t.set_schedule({0: 100, 1: 100, 2: 100})
    assert t.eta_secs() is None  # nothing resolved yet
    t.backward_level(2, 100, 1.0)
    assert t.eta_secs() == pytest.approx(2.0)  # 200 left at 100 pps
    t.backward_level(1, 100, 1.0)
    assert t.eta_secs() == pytest.approx(1.0)
    t.backward_level(0, 100, 1.0)
    assert t.eta_secs() == 0.0
    snap = t.snapshot({"phase": "backward", "level": 0})
    assert snap["positions_solved"] == 300
    assert snap["levels_solved"] == 3 and snap["levels_total"] == 3


def test_tracker_resumed_levels_do_not_poison_eta():
    """A checkpoint-resumed level replays millions of positions in
    milliseconds; the ETA's throughput EWMA must skip it or a restarted
    run claims hours of work finish in seconds."""
    t = obs_status.SolveStatusTracker()
    t.set_schedule({0: 1000, 1: 1000, 2: 1000})
    t.backward_level(2, 1000, 0.001, resumed=True)  # replayed from disk
    assert t.eta_secs() is None  # no real throughput observed yet
    t.backward_level(1, 1000, 10.0)  # real compute: 100 pps
    assert t.eta_secs() == pytest.approx(10.0)
    # A later resumed level still shrinks the remaining work but not
    # the rate model.
    t.backward_level(0, 1000, 0.001, resumed=True)
    assert t.eta_secs() == 0.0
    assert t.snapshot()["positions_solved"] == 3000
    assert t.snapshot()["throughput_pps"] == pytest.approx(100.0)


def test_status_request_counter_label_is_bounded():
    """Probed junk paths must not mint unbounded registry series."""
    reg = MetricsRegistry()
    srv = obs_status.StatusServer(
        lambda: {}, port=0, registry=reg
    ).start()
    try:
        for path in ("/admin", "/etc/passwd", "/x" * 3):
            with pytest.raises(urllib.error.HTTPError):
                _get_json(f"http://{srv.address}{path}")
        _get_json(f"http://{srv.address}/status")
    finally:
        srv.stop()
    rows = reg.snapshot()["gamesman_status_requests_total"]["values"]
    paths = {r["labels"]["path"] for r in rows}
    assert paths <= {"/status", "/metrics", "other"}
    other = next(r for r in rows if r["labels"]["path"] == "other")
    assert other["value"] == 3


def test_tracker_positions_solved_is_monotone_under_updates():
    t = obs_status.SolveStatusTracker()
    seen = []
    for lvl in (5, 4, 3):
        t.backward_level(lvl, 10, 0.1)
        seen.append(t.snapshot()["positions_solved"])
    assert seen == sorted(seen)


# ----------------------------------------------------------- HTTP server


def test_status_server_serves_status_metrics_and_404(tmp_path):
    reg = MetricsRegistry()
    reg.counter("gamesman_fixture_total", "x").inc(3)
    addr_file = tmp_path / "addr"
    srv = obs_status.StatusServer(
        lambda: {"phase": "forward", "level": 7},
        port=0, registry=reg, addr_file=str(addr_file),
    ).start()
    try:
        assert addr_file.read_text() == srv.address
        got = _get_json(f"http://{srv.address}/status")
        assert got == {"phase": "forward", "level": 7}
        with urllib.request.urlopen(
            f"http://{srv.address}/metrics", timeout=5
        ) as resp:
            text = resp.read().decode()
        assert "gamesman_fixture_total 3" in text
        assert "gamesman_status_requests_total" in text
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(f"http://{srv.address}/nope")
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_status_server_provider_error_is_500_not_death():
    srv = obs_status.StatusServer(
        lambda: 1 / 0, port=0, registry=MetricsRegistry()
    ).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(f"http://{srv.address}/status")
        assert ei.value.code == 500
    finally:
        srv.stop()


def test_maybe_status_server_env_gating(monkeypatch):
    monkeypatch.delenv("GAMESMAN_STATUS_PORT", raising=False)
    assert obs_status.maybe_status_server(lambda: {}) is None
    monkeypatch.setenv("GAMESMAN_STATUS_PORT", "junk")
    assert obs_status.maybe_status_server(lambda: {}) is None
    monkeypatch.setenv("GAMESMAN_STATUS_PORT", "0")
    srv = obs_status.maybe_status_server(lambda: {"ok": True})
    assert srv is not None
    try:
        assert _get_json(f"http://{srv.address}/status") == {"ok": True}
    finally:
        srv.stop()


# ------------------------------------------------------------ fleet merge


def _snap(levels, eta=None, phase="backward", solved=0):
    return {
        "phase": phase, "level": min(levels) if levels else None,
        "positions_solved": solved, "eta_secs": eta,
        "levels": {
            str(k): {"n": 10, "fwd_secs": f, "bwd_secs": b}
            for k, (f, b) in levels.items()
        },
    }


def test_merge_fleet_max_walls_and_straggler_flagging():
    snaps = {
        0: _snap({3: (1.0, 1.0), 4: (1.0, 0.0)}, eta=5.0, solved=100),
        1: _snap({3: (1.0, 1.1), 4: (1.0, 0.0)}, eta=6.0, solved=100),
        2: _snap({3: (1.0, 9.0), 4: (1.0, 0.0)}, eta=30.0, solved=60),
    }
    fleet = obs_status.merge_fleet(snaps, world=3, factor=1.5)
    assert fleet["world"] == 3
    assert fleet["ranks_reporting"] == [0, 1, 2]
    # Per-level wall is max across ranks (the level ran once,
    # collectively), not a sum.
    assert fleet["levels"]["3"]["wall_secs"] == pytest.approx(10.0)
    # Rank 2's level-3 wall (10.0) is far past 1.5x the median (2.1):
    # flagged, with the evidence attached.
    assert [s["rank"] for s in fleet["stragglers"]] == [2]
    assert fleet["stragglers"][0]["level"] == 3
    assert fleet["stragglers"][0]["lag"] > 1.5
    # Fleet ETA is the slowest rank's (the fleet finishes when the
    # last rank does).
    assert fleet["eta_secs"] == pytest.approx(30.0)


def test_merge_fleet_without_divergence_flags_nobody():
    snaps = {
        0: _snap({3: (1.0, 1.0)}),
        1: _snap({3: (1.0, 1.05)}),
    }
    fleet = obs_status.merge_fleet(snaps, world=2, factor=1.5)
    assert fleet["stragglers"] == []


def test_fetch_status_dead_peer_degrades_to_none():
    assert obs_status.fetch_status("127.0.0.1:1", timeout=0.2) is None


# ------------------------------------------------- coordinator address book


def test_coordinator_announce_and_peers():
    from gamesmanmpi_tpu.resilience.coordination import (
        CoordinatorServer,
        EpochBarrier,
    )

    server = CoordinatorServer(world=2, deadline=5.0)
    try:
        c0 = EpochBarrier(server.address, 0, deadline=5.0)
        c1 = EpochBarrier(server.address, 1, deadline=5.0)
        c0.announce("127.0.0.1:1111")
        c1.announce("127.0.0.1:2222")
        assert c0.peers() == {0: "127.0.0.1:1111", 1: "127.0.0.1:2222"}
        # Re-announce overwrites (a restarted rank rebinds a new port).
        c1.announce("127.0.0.1:3333")
        assert c0.peers()[1] == "127.0.0.1:3333"
    finally:
        server.close()


# -------------------------------------------------- live solve end-to-end


def test_live_status_during_real_sharded_solve(monkeypatch, tmp_path):
    """The acceptance shape, in-process: a real 2-shard solve serves
    /status while running; polls observe monotone positions_solved,
    the forward->backward phase transition, and a finite ETA."""
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.parallel import ShardedSolver
    from gamesmanmpi_tpu.resilience import faults

    addr_file = tmp_path / "addr"
    monkeypatch.setenv("GAMESMAN_STATUS_PORT", "0")
    monkeypatch.setenv("GAMESMAN_STATUS_ADDR_FILE", str(addr_file))
    # Stretch each forward level so the poller observes mid-flight
    # state deterministically (delays are absorbed, never fatal).
    faults.configure("sharded.forward:delay=0.04:always")
    solver = ShardedSolver(get_game("tictactoe"), num_shards=2)
    done = {}

    def run():
        done["result"] = solver.solve()

    t = threading.Thread(target=run)
    t.start()
    samples = []
    addr = None
    try:
        while t.is_alive():
            if addr is None:
                try:
                    addr = addr_file.read_text().strip()
                except OSError:
                    time.sleep(0.01)
                    continue
            try:
                samples.append(
                    _get_json(f"http://{addr}/status", timeout=2)
                )
            except Exception:
                pass
            time.sleep(0.01)
        t.join()
    finally:
        faults.clear()
    assert done["result"].value is not None
    assert len(samples) >= 3, "poller never observed the live solve"
    solved = [s["positions_solved"] for s in samples]
    assert solved == sorted(solved), "positions_solved regressed"
    phases = {s.get("phase") for s in samples}
    assert "forward" in phases and "backward" in phases
    etas = [s["eta_secs"] for s in samples
            if s.get("eta_secs") is not None]
    assert etas, "no finite ETA observed during backward"
    assert all(e < 3600 for e in etas)
    # The identity + io fields ride every snapshot.
    assert samples[-1]["engine"] == "sharded"
    assert samples[-1]["shards"] == 2
    assert "io" in samples[-1]


# --------------------------------------------------------- flight recorder


def test_flightrec_ring_bound_and_dropped_accounting():
    rec = flightrec.FlightRecorder(capacity=16)
    for i in range(40):
        rec.record("x", i=i)
    snap = rec.snapshot()
    assert len(snap["events"]) == 16
    assert snap["dropped"] == 24
    assert snap["events"][-1]["i"] == 39  # newest survive


def test_flightrec_tracks_inflight_spans():
    base = flightrec.default_recorder().snapshot()
    n0 = len(base["inflight_spans"])
    sp = Span("forward", level=9)
    mid = flightrec.default_recorder().snapshot()
    names = [s["span"] for s in mid["inflight_spans"]]
    assert names.count("forward") == n0_forward(base) + 1
    sp.end()
    after = flightrec.default_recorder().snapshot()
    assert len(after["inflight_spans"]) == n0
    assert any(
        e["kind"] == "span" and e.get("span") == "forward"
        and e.get("level") == 9
        for e in after["events"]
    )


def n0_forward(snap):
    return sum(
        1 for s in snap["inflight_spans"] if s["span"] == "forward"
    )


def test_flightrec_dump_is_atomic_and_named(tmp_path):
    rec = flightrec.FlightRecorder(capacity=32)
    rec.level_complete("forward", 5)
    rec.record("retry", point="engine.forward")
    path = rec.dump("unit_test", directory=str(tmp_path), rank="7")
    assert path == str(tmp_path / "flightrec_7.json")
    body = json.loads((tmp_path / "flightrec_7.json").read_text())
    assert body["reason"] == "unit_test"
    assert body["last_completed"] == {"forward": 5}
    assert any(e["kind"] == "retry" for e in body["events"])
    assert not list(tmp_path.glob("*.tmp*"))  # tmp+replace left no turd


def test_flightrec_boundary_dump_gated_on_env(tmp_path, monkeypatch):
    monkeypatch.delenv("GAMESMAN_FLIGHTREC_DIR", raising=False)
    flightrec.boundary("forward", 1)  # env unset: notes, never writes
    assert not list(tmp_path.glob("flightrec_*.json"))
    monkeypatch.setenv("GAMESMAN_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.delenv("GAMESMAN_PROCESS_ID", raising=False)
    flightrec.boundary("forward", 2)
    body = json.loads((tmp_path / "flightrec_0.json").read_text())
    assert body["reason"] == "boundary"
    assert body["last_completed"]["forward"] == 2


def test_watchdog_abort_dumps_flightrec(tmp_path, monkeypatch):
    from gamesmanmpi_tpu.resilience.supervisor import Watchdog

    monkeypatch.setenv("GAMESMAN_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.delenv("GAMESMAN_PROCESS_ID", raising=False)
    fired = threading.Event()
    wd = Watchdog(
        lambda: {"phase": "backward", "level": 3},
        min_secs=0.05, poll=0.01, action=fired.set,
        registry=MetricsRegistry(),
    ).start()
    try:
        assert fired.wait(timeout=10)
    finally:
        wd.stop()
    body = json.loads((tmp_path / "flightrec_0.json").read_text())
    assert body["reason"] == "watchdog_abort"
    assert any(e["kind"] == "watchdog_abort" for e in body["events"])


def test_cli_fatal_fault_leaves_crash_flightrec(tmp_path):
    """Injected fatal fault mid-backward: the CLI's crash handler dumps
    flightrec_0.json (into the checkpoint dir by default) naming the
    last completed forward level and the events leading to the death."""
    ck = tmp_path / "ck"
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_FAULTS"] = "engine.backward:fatal"
    env.pop("GAMESMAN_FLIGHTREC_DIR", None)
    proc = subprocess.run(
        _CLI + ["tictactoe", "--checkpoint-dir", str(ck)],
        capture_output=True, text=True, env=env, cwd=str(REPO),
        timeout=300,
    )
    assert proc.returncode != 0
    body = json.loads((ck / "flightrec_0.json").read_text())
    assert body["reason"] == "crash"
    assert body["last_completed"]["forward"] >= 0
    assert any(e["kind"] == "fault" for e in body["events"])
    assert any(
        e.get("span") == "forward" for e in body["events"]
        if e["kind"] == "span"
    )


def test_cli_sigterm_preemption_leaves_flightrec(tmp_path):
    """SIGTERM grace drain (exit 75) also leaves the post-mortem."""
    ck = tmp_path / "ck"
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    # Stretch forward levels so the signal lands mid-solve.
    env["GAMESMAN_FAULTS"] = "engine.forward:delay=0.2:always"
    env.pop("GAMESMAN_FLIGHTREC_DIR", None)
    proc = subprocess.Popen(
        _CLI + ["connect4:w=4,h=4", "--checkpoint-dir", str(ck)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(REPO),
    )
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if (ck / "manifest.json").exists():
                break
            if proc.poll() is not None:
                pytest.fail(f"solve died early: {proc.stderr.read()}")
            time.sleep(0.1)
        time.sleep(0.5)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert rc == 75, proc.stderr.read()
    body = json.loads((ck / "flightrec_0.json").read_text())
    assert body["reason"] == "preempted"
    assert "forward" in body["last_completed"]


# ---------------------------------------------------------- campaign proxy


def test_campaign_status_payload_proxies_child(tmp_path):
    from gamesmanmpi_tpu.resilience.campaign import (
        Campaign,
        CampaignConfig,
    )

    cfg = CampaignConfig(
        solver_args=["tictactoe"],
        checkpoint_dir=str(tmp_path / "ck"),
        max_attempts=2, no_progress_limit=2,
    )
    camp = Campaign(cfg)
    camp._attempt = 3
    camp._last_cause = "killed"
    camp._no_progress = 1
    child = obs_status.StatusServer(
        lambda: {"phase": "backward", "level": 4,
                 "positions_solved": 123},
        port=0, registry=MetricsRegistry(),
        addr_file=str(camp._solve_addr_file),
    ).start()
    try:
        payload = camp._status_payload()
    finally:
        child.stop()
    assert payload["kind"] == "campaign"
    assert payload["attempt"] == 3
    assert payload["last_cause"] == "killed"
    assert payload["breaker"] == "closed"
    assert payload["solve"]["positions_solved"] == 123
    assert "progress" in payload  # jax-free checkpoint progress


def test_campaign_death_classifier_dumps_flightrec(tmp_path):
    """One fatally-wounded attempt: the campaign's classifier leaves
    flightrec_campaign.json next to the attempt logs, and the attempt
    itself (boundary dumps armed by the campaign env) leaves
    flightrec_0.json."""
    from gamesmanmpi_tpu.resilience.campaign import (
        Campaign,
        CampaignConfig,
    )

    ck = tmp_path / "ck"
    cfg = CampaignConfig(
        solver_args=["tictactoe"],
        checkpoint_dir=str(ck),
        max_attempts=2, no_progress_limit=2,
        backoff_base_secs=0.01, backoff_max_secs=0.01,
        chaos=["engine.backward:fatal"],
    )
    rc = Campaign(cfg).run()
    # Attempt 1 crashes (injected fatal), attempt 2 resumes clean.
    assert rc == 0
    log_dir = tmp_path / "ck" / "logs"
    camp_body = json.loads(
        (log_dir / "flightrec_campaign.json").read_text()
    )
    assert camp_body["rank"] == "campaign"
    assert any(
        e["kind"] == "campaign_attempt" and e.get("cause") == "crash"
        for e in camp_body["events"]
    )
    # The attempt's own dumps (GAMESMAN_FLIGHTREC_DIR armed by the
    # campaign env) name its last completed level. (Attempt 2 resumed
    # from complete frontiers, so its final boundary is a backward one.)
    child_body = json.loads((log_dir / "flightrec_0.json").read_text())
    assert child_body["last_completed"], "no level boundary recorded"


def test_campaign_sigkilled_attempt_leaves_flightrec(tmp_path):
    """The acceptance shape: an attempt SIGKILLed mid-solve (kill fault
    — no in-process exit path at all) still leaves flightrec_0.json,
    because the campaign arms GAMESMAN_FLIGHTREC_DIR and the engines
    checkpoint the ring at every level boundary."""
    from gamesmanmpi_tpu.resilience.campaign import (
        Campaign,
        CampaignConfig,
    )

    ck = tmp_path / "ck"
    cfg = CampaignConfig(
        solver_args=["tictactoe"],
        checkpoint_dir=str(ck),
        max_attempts=3, no_progress_limit=3,
        backoff_base_secs=0.01, backoff_max_secs=0.01,
        chaos=["ckpt.save_level:kill:2"],
    )
    rc = Campaign(cfg).run()
    assert rc == 0
    log_dir = ck / "logs"
    camp_body = json.loads(
        (log_dir / "flightrec_campaign.json").read_text()
    )
    assert any(
        e["kind"] == "campaign_attempt" and e.get("cause") == "killed"
        for e in camp_body["events"]
    )
    # The SIGKILLed attempt's boundary dump (or the clean retry's final
    # one — latest writer wins) names a completed level and carries the
    # in-flight span table.
    child_body = json.loads((log_dir / "flightrec_0.json").read_text())
    assert child_body["last_completed"]
    assert "inflight_spans" in child_body
    assert any(e["kind"] == "level" for e in child_body["events"])


# ---------------------------------------------------------- bench_compare


def _bench_record(value, metric="fixture_pps", device="cpu", **extra):
    return {
        "metric": metric, "value": value, "device": device,
        "roofline": {"operand_gbps": 0.1, "pps_per_chip": value,
                     "dispatch_overhead_frac": 0.01},
        "dispatches": {"total": 10, "per_level": 2.0},
        **extra,
    }


def test_bench_compare_gates_regression(tmp_path):
    bench_compare = load_module(REPO / "tools" / "bench_compare.py")
    ref = tmp_path / "BENCH_ref.json"
    ref.write_text(json.dumps(_bench_record(1000.0)))
    traj = str(tmp_path / "BENCH_*.json")
    ok = tmp_path / "new_ok.json"
    ok.write_text(json.dumps(_bench_record(950.0)))
    assert bench_compare.main(
        [str(ok), "--trajectory", traj]
    ) == 0
    # A synthetic 2x slowdown gates non-zero at the default threshold.
    slow = tmp_path / "new_slow.json"
    slow.write_text(json.dumps(_bench_record(500.0)))
    assert bench_compare.main(
        [str(slow), "--trajectory", traj]
    ) == 1
    # --min-ratio overrides the default.
    assert bench_compare.main(
        [str(slow), "--trajectory", traj, "--min-ratio", "0.4"]
    ) == 0


def test_bench_compare_no_reference_passes_with_note(tmp_path, capsys):
    bench_compare = load_module(REPO / "tools" / "bench_compare.py")
    new = tmp_path / "new.json"
    new.write_text(json.dumps(_bench_record(1.0, metric="novel_pps")))
    assert bench_compare.main(
        [str(new), "--trajectory", str(tmp_path / "BENCH_*.json")]
    ) == 0
    assert "no comparable reference" in capsys.readouterr().out


def test_bench_compare_usage_errors_exit_2(tmp_path):
    bench_compare = load_module(REPO / "tools" / "bench_compare.py")
    assert bench_compare.main([str(tmp_path / "missing.json")]) == 2
    junk = tmp_path / "junk.json"
    junk.write_text("not a record")
    assert bench_compare.main([str(junk)]) == 2


def test_bench_compare_passes_committed_trajectory():
    """The acceptance gate: the newest committed record passes the
    committed trajectory with the default threshold."""
    bench_compare = load_module(REPO / "tools" / "bench_compare.py")
    assert bench_compare.main([str(REPO / "BENCH_fused_r14.json")]) == 0


# ------------------------------------------------------------ solve stats


def test_solver_stats_carry_roofline_rollup(monkeypatch):
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.solve import Solver

    monkeypatch.setenv("GAMESMAN_DISPATCH_COST_SECS", "0.0001")
    stats = Solver(get_game("subtract:total=12,moves=1-2")).solve().stats
    rf = stats["roofline"]
    assert set(rf) == {"operand_gbps", "pps_per_chip",
                       "dispatch_overhead_frac"}
    assert rf["pps_per_chip"] > 0
    assert 0 < rf["dispatch_overhead_frac"] <= 1.0
    assert stats["bytes_host"] >= 0
