"""gamesman-lint coverage: every checker id proven on a known-bad
fixture (exact id + line), known-good fixtures proven clean, the
suppression/baseline escape hatches round-tripped, and — the tier-1
gate — the real repository linting clean.

Fixture projects are miniature repos built in tmp_path with the same
conventions the runner discovers in the real one: a `pkg/` package,
`docs/CONFIG.md` / `docs/OBSERVABILITY.md` registry docs, and a
`tests/test_resilience.py` chaos matrix. Expected lines are located by
`# MARK` comments rather than hand-counted line numbers, so editing a
fixture cannot silently shift an assertion.
"""

import json
import os
import shutil
import subprocess
import textwrap
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gamesmanmpi_tpu.analysis.cli import main as lint_main
from gamesmanmpi_tpu.analysis.diagnostics import (
    Diagnostic,
    fingerprint,
    suppressed_ids,
    write_baseline,
)
from gamesmanmpi_tpu.analysis.runner import run_project

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG_HEADER = "| Env var | Default | Meaning |\n|---|---|---|\n"


def build_project(tmp_path, files, config_md="", observability_md="",
                  chaos=""):
    """Write a miniature project; `files` maps pkg-relative names to
    source text (dedented)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, text in files.items():
        p = pkg / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "CONFIG.md").write_text(config_md)
    (docs / "OBSERVABILITY.md").write_text(observability_md)
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    (tdir / "test_resilience.py").write_text(chaos)
    return tmp_path


def mark_line(tmp_path, rel, mark="MARK"):
    """1-based line of the `# <mark>` comment in a fixture file."""
    text = (tmp_path / rel).read_text()
    for i, line in enumerate(text.splitlines(), 1):
        if f"# {mark}" in line:
            return i
    raise AssertionError(f"no # {mark} in {rel}")


def findings(tmp_path, **kw):
    res = run_project(tmp_path, **kw)
    return res, [(d.id, d.path, d.line) for d in res.new]


# --------------------------------------------------------------- GM1xx: jax


def test_gm101_clock_under_jit(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def kernel(x):
            t = time.time()  # MARK
            return x + t
    """})
    _, got = findings(tmp_path)
    assert got == [("GM101", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm102_python_rng_under_jit(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import random
        import jax

        @jax.jit
        def kernel(x):
            r = random.random()  # MARK
            return x * r
    """})
    _, got = findings(tmp_path)
    assert got == [("GM102", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm103_host_sync_of_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def kernel(x):
            y = float(x)  # MARK
            return y
    """})
    _, got = findings(tmp_path)
    assert got == [("GM103", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm103_item_on_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def kernel(x):
            y = x.sum()
            return y.item()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM103", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm104_branch_on_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def kernel(x):
            if x > 0:  # MARK
                return x
            return -x
    """})
    _, got = findings(tmp_path)
    assert got == [("GM104", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm105_numpy_on_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return np.cumsum(x)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM105", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm106_unhashable_static_default(tmp_path):
    build_project(tmp_path, {"mod.py": """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnums=(1,))
        def kernel(x, opts=[]):  # MARK
            return x
    """})
    _, got = findings(tmp_path)
    assert got == [("GM106", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_jax_taint_propagates_through_local_calls(tmp_path):
    """Impurity inside a helper the jitted function calls is found."""
    build_project(tmp_path, {"mod.py": """
        import jax

        def helper(v):
            return int(v)  # MARK

        @jax.jit
        def kernel(x):
            return helper(x + 1)
    """})
    _, got = findings(tmp_path)
    assert got == [("GM103", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_jax_clean_kernel_passes(tmp_path):
    """Shape reads, jnp math, static-arg branching: all legitimate."""
    build_project(tmp_path, {"mod.py": """
        from functools import partial
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("mode",))
        def kernel(x, mode="fast"):
            n = x.shape[0]
            if mode == "fast":
                return jnp.where(x > 0, x, -x) + n
            return jnp.cumsum(x)
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_jax_ignores_plain_host_functions(tmp_path):
    """The same impurity OUTSIDE any traced root is not a finding."""
    build_project(tmp_path, {"mod.py": """
        import time

        def host_side(x):
            t0 = time.time()
            if x > 0:
                return float(x) + t0
            return -x
    """})
    _, got = findings(tmp_path)
    assert got == []


# -------------------------------------------------------------- GM2xx: locks


def test_gm201_guarded_field_without_lock(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def good(self):
                with self._lock:
                    return len(self._items)

            def bad(self):
                return len(self._items)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM201", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm202_reacquire_nonreentrant(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    with self._lock:  # MARK
                        pass
    """})
    _, got = findings(tmp_path)
    assert got == [("GM202", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm202_deadlock_through_method_call(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def locked_op(self):
                with self._lock:
                    pass

            def bad(self):
                with self._lock:
                    self.locked_op()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM202", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm202_rlock_reacquire_is_fine(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.RLock()

            def fine(self):
                with self._lock:
                    with self._lock:
                        pass
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_gm203_blocking_call_with_lock_held(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM203", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm203_queue_get_with_lock_held(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import queue
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bad(self):
                with self._lock:
                    return self._q.get()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM203", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm204_requires_lock_called_without(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lock

            # requires-lock: _lock
            def bump(self):
                self._n += 1

            def good(self):
                with self._lock:
                    self.bump()

            def bad(self):
                self.bump()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM204", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm205_signal_handler_reaches_lock(tmp_path):
    """A handler registered via signal.signal that (transitively)
    acquires a lock is the PR 7 self-deadlock class — flagged at the
    registration site, naming the lock."""
    build_project(tmp_path, {"mod.py": """
        import signal
        import threading

        class Sup:
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = False

            def request_stop(self):
                with self._lock:
                    self._stop = True

            def _on_term(self, signum, frame):
                self.request_stop()

            def install(self):
                signal.signal(signal.SIGTERM, self._on_term)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM205", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]
    res, _ = findings(tmp_path)
    (d,) = res.new
    assert "_lock" in d.message and "_on_term" in d.message


def test_gm205_cross_module_reach(tmp_path):
    """Reach is whole-program: the lock acquisition may live in another
    module entirely (the handler calls an imported helper)."""
    build_project(tmp_path, {
        "locks.py": """
            import threading

            _lock = threading.Lock()

            def note_stop():
                with _lock:
                    pass
        """,
        "mod.py": """
            import signal

            from pkg.locks import note_stop

            def _on_term(signum, frame):
                note_stop()

            def install():
                signal.signal(signal.SIGTERM, _on_term)  # MARK
        """,
    })
    _, got = findings(tmp_path)
    assert got == [("GM205", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm205_lock_free_handler_and_thread_target_pass(tmp_path):
    """The clean twins: a flag-setting handler, and a handler that only
    SPAWNS a locking function on a Thread/Timer (another thread's
    program order — cannot deadlock the interrupted main thread)."""
    build_project(tmp_path, {"mod.py": """
        import signal
        import threading

        class Sup:
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = False

            def _locked_teardown(self):
                with self._lock:
                    pass

            def _on_term(self, signum, frame):
                self._stop = True  # lock-free: a plain flag store
                threading.Thread(
                    target=self._locked_teardown, daemon=True
                ).start()
                self._timer = threading.Timer(1.0, self._locked_teardown)
                self._timer.start()

            def install(self):
                signal.signal(signal.SIGTERM, self._on_term)
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_condition_aliases_its_lock(tmp_path):
    """Holding a Condition built over the lock counts as holding it."""
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._items = []  # guarded-by: _lock

            def fine(self):
                with self._cond:
                    self._items.append(1)
                    self._cond.wait(0.01)
    """})
    _, got = findings(tmp_path)
    assert got == []


# ---------------------------------------------------------- GM3xx: env vars


def test_gm301_raw_environ_read(tmp_path):
    build_project(
        tmp_path,
        {"mod.py": """
            import os

            def knob():
                return os.environ.get("GAMESMAN_FIXTURE_KNOB", "1")  # MARK
        """},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_KNOB` | 1 | knob |\n",
    )
    _, got = findings(tmp_path)
    assert got == [("GM301", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm302_undocumented_var(tmp_path):
    build_project(tmp_path, {"mod.py": """
        from gamesmanmpi_tpu.utils.env import env_int

        def knob():
            return env_int("GAMESMAN_FIXTURE_SECRET", 3)  # MARK
    """}, config_md=CONFIG_HEADER)
    _, got = findings(tmp_path)
    assert got == [("GM302", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm303_stale_doc_row(tmp_path):
    build_project(
        tmp_path, {"mod.py": "x = 1\n"},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_GHOST` | — | gone |\n",
    )
    _, got = findings(tmp_path)
    assert got == [("GM303", "docs/CONFIG.md", 3)]


def test_gm302_prefix_of_documented_var_still_flagged(tmp_path):
    """Substring matching must not fail open: a var whose name is a
    prefix of a documented one is still undocumented."""
    build_project(
        tmp_path,
        {"mod.py": """
            from gamesmanmpi_tpu.utils.env import env_str

            def knob():
                return env_str("GAMESMAN_FIXTURE", "x")  # MARK
        """},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_ROW` | — | other |\n",
    )
    _, got = findings(tmp_path)
    assert ("GM302", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py")) in got


def test_env_helpers_documented_pass(tmp_path):
    build_project(
        tmp_path,
        {"mod.py": """
            from gamesmanmpi_tpu.utils.env import env_int

            def knob():
                return env_int("GAMESMAN_FIXTURE_KNOB", 1)
        """},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_KNOB` | 1 | knob |\n",
    )
    _, got = findings(tmp_path)
    assert got == []


# ---------------------------------------------------------- GM4xx: metrics


def test_gm401_metric_naming(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.counter("gamesman_things_total").inc()
            reg.counter("gamesman_things")  # MARK
    """}, observability_md="`gamesman_things_total` `gamesman_things`")
    _, got = findings(tmp_path)
    assert got == [("GM401", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm401_prefix_rule(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.gauge("queueDepth")  # MARK
    """}, observability_md="`queueDepth`")
    _, got = findings(tmp_path)
    assert got == [("GM401", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm402_undocumented_metric(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.gauge("gamesman_fixture_depth")  # MARK
    """}, observability_md="nothing here")
    _, got = findings(tmp_path)
    assert got == [("GM402", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm402_prefix_of_documented_metric_still_flagged(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.gauge("gamesman_retries")  # MARK
    """}, observability_md="only `gamesman_retries_total` is documented")
    _, got = findings(tmp_path)
    assert got == [("GM402", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


_SPAN_REGISTRY_DOC = """
### Span name registry

| Span | Emitted by | One per |
|---|---|---|
| `forward` | engine | level |
"""


def test_gm405_unregistered_span(tmp_path):
    build_project(tmp_path, {"mod.py": """
        from obs import Span, trace_span

        def work(logger):
            sp = Span("forward", logger=logger)
            with trace_span("mystery_phase"):  # MARK
                pass
            sp.end()
    """}, observability_md=_SPAN_REGISTRY_DOC)
    _, got = findings(tmp_path)
    assert got == [("GM405", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm405_stale_registry_row(tmp_path):
    doc = _SPAN_REGISTRY_DOC + "| `ghost_phase` | nobody | nothing |\n"
    build_project(tmp_path, {"mod.py": """
        from obs import Span

        def work():
            Span("forward").end()
    """}, observability_md=doc)
    _, got = findings(tmp_path)
    assert len(got) == 1
    assert got[0][0] == "GM405"
    assert got[0][1] == "docs/OBSERVABILITY.md"
    # The finding points at the ghost row's exact doc line.
    doc_line = next(
        i for i, line in enumerate(doc.splitlines(), 1)
        if "ghost_phase" in line
    )
    assert got[0][2] == doc_line


def test_gm405_conditional_span_resolves_both_branches(tmp_path):
    """The sharded backward's IfExp name registers BOTH branches; one
    branch missing from the registry is still a finding."""
    doc = _SPAN_REGISTRY_DOC + "| `backward` | engine | level |\n"
    build_project(tmp_path, {"mod.py": """
        from obs import Span

        def work(edges):
            Span("backward_edges" if edges else "backward").end()  # MARK
            Span("forward").end()
    """}, observability_md=doc)
    _, got = findings(tmp_path)
    assert got == [("GM405", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]
    # Registering the other branch clears it.
    build_project(tmp_path, {"mod.py": """
        from obs import Span

        def work(edges):
            Span("backward_edges" if edges else "backward").end()
            Span("forward").end()
    """}, observability_md=doc + "| `backward_edges` | engine | level |\n")
    _, got = findings(tmp_path)
    assert got == []


def test_gm405_dynamic_span_name(tmp_path):
    build_project(tmp_path, {"mod.py": """
        from obs import Span

        def work(name):
            Span(name).end()  # MARK
            Span("forward").end()
    """}, observability_md=_SPAN_REGISTRY_DOC)
    _, got = findings(tmp_path)
    assert got == [("GM405", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm405_skipped_without_registry_section(tmp_path):
    """A project whose OBSERVABILITY.md has no span registry opts the
    family out entirely (same shape as the exit-code registry)."""
    build_project(tmp_path, {"mod.py": """
        from obs import Span

        def work():
            Span("anything_at_all").end()
    """}, observability_md="no registry section here")
    _, got = findings(tmp_path)
    assert got == []


def test_gm403_dynamic_metric_name(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg, which):
            reg.counter(which)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM403", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_module_constant_metric_name_resolves(tmp_path):
    build_project(tmp_path, {"mod.py": """
        DEPTH = "gamesman_fixture_depth"

        def emit(reg):
            reg.gauge(DEPTH)
    """}, observability_md="`gamesman_fixture_depth` is documented")
    _, got = findings(tmp_path)
    assert got == []


# ------------------------------------------------------ GM5xx: fault points


def _faults_registry(points="\"lvl.fwd\": \"forward\","):
    return f"""
        KNOWN_POINTS = {{
            {points}
        }}
    """


def test_gm501_unregistered_fire(tmp_path):
    build_project(tmp_path, {
        "reg.py": _faults_registry(),
        "mod.py": """
            from pkg.reg import fire

            def step():
                fire("lvl.fwd")
                fire("lvl.nope")  # MARK
        """,
    }, chaos="lvl.fwd lvl.nope")
    _, got = findings(tmp_path)
    assert got == [("GM501", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm502_never_woven_point(tmp_path):
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",
                "lvl.ghost": "never fired",  # MARK
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
        """,
    }, chaos="lvl.fwd lvl.ghost")
    _, got = findings(tmp_path)
    assert got == [("GM502", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_gm503_duplicate_point(tmp_path):
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",
                "lvl.fwd": "again",  # MARK
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
        """,
    }, chaos="lvl.fwd")
    _, got = findings(tmp_path)
    assert got == [("GM503", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_gm504_no_chaos_coverage(tmp_path):
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",  # MARK
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
        """,
    }, chaos="")
    _, got = findings(tmp_path)
    assert got == [("GM504", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_gm505_dynamic_fire_point(tmp_path):
    build_project(tmp_path, {
        "reg.py": _faults_registry(),
        "mod.py": """
            def step(faults, which):
                faults.fire("lvl.fwd")
                faults.fire(which)  # MARK
        """,
    }, chaos="lvl.fwd")
    _, got = findings(tmp_path)
    assert got == [("GM505", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


# -------------------------------------- GM506/GM507: exit-code parity


def _campaign_module(extra="", registry=None):
    """A minimal campaign module: attempt-death constants, a classify
    that names them, and the CAMPAIGN_EXIT_CODES registry."""
    registry = registry if registry is not None else """
        CAMPAIGN_EXIT_CODES = {
            0: "solved",
            2: "usage",
            BREAKER_EXIT_CODE: "breaker",
        }
    """
    return """
        KILL_EXIT_CODE = 77
        BREAKER_EXIT_CODE = 3
    """ + extra + registry + """

        class Campaign:
            @staticmethod
            def classify(rcs):
                if KILL_EXIT_CODE in set(rcs.values()):
                    return "killed"
                return "crash"
    """


def test_gm506_unclassified_exit_code(tmp_path):
    """A new *_EXIT_CODE constant the classifier never learned and the
    registry never named: a death that silently classifies as crash."""
    build_project(tmp_path, {
        "campaign.py": _campaign_module(),
        "newfail.py": """
            ROT_EXIT_CODE = 99  # MARK
        """,
    })
    _, got = findings(tmp_path)
    assert got == [
        ("GM506", "pkg/newfail.py", mark_line(tmp_path, "pkg/newfail.py"))
    ]


def test_gm506_clean_when_classified_or_registered(tmp_path):
    """Constants referenced by classify() OR registered (by name or by
    literal value) in CAMPAIGN_EXIT_CODES are covered."""
    build_project(tmp_path, {
        "campaign.py": _campaign_module(extra="""
        USAGE_EXIT_CODE = 2
    """)})
    _, got = findings(tmp_path)
    assert got == []


def test_gm507_documented_exit_codes_two_way(tmp_path):
    """A script's "Exit codes:" docstring list must match the registry
    both ways: a phantom documented code AND a registry value the doc
    omits each flag."""
    build_project(tmp_path, {
        "campaign.py": _campaign_module(),
        "run.py": '''
            """Driver.

            Exit codes: 0 solved, 9 mystery.
            """

            if __name__ == "__main__":
                pass
        ''',
    })
    _, got = findings(tmp_path)
    ids = sorted((d[0], d[1]) for d in got)
    # 9 documented-but-unregistered (on the script), 2 and 3
    # registered-but-undocumented (on the registry).
    assert ids == [
        ("GM507", "pkg/campaign.py"),
        ("GM507", "pkg/campaign.py"),
        ("GM507", "pkg/run.py"),
    ]


def test_gm507_clean_script_and_library_docstring_exempt(tmp_path):
    """A matching script list passes; a LIBRARY module describing
    return codes (no __main__ guard) never participates."""
    build_project(tmp_path, {
        "campaign.py": _campaign_module(),
        "run.py": '''
            """Driver.

            Exit codes: 0 solved, 2 usage, 3 breaker budget.
            """

            if __name__ == "__main__":
                pass
        ''',
        "lib.py": '''
            """Library helper.

            Exit codes: 0 solved, 9 library-only lore.
            """

            def f():
                return 0
        ''',
    })
    _, got = findings(tmp_path)
    assert got == []


def test_gm506_skips_projects_without_registry(tmp_path):
    build_project(tmp_path, {"mod.py": """
        SOME_EXIT_CODE = 5
    """})
    _, got = findings(tmp_path)
    assert got == []


# ------------------------------------------------- GM6xx: SPMD safety


def test_gm601_collective_in_one_rank_arm(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        def step(x):
            if jax.process_index() == 0:
                y = jax.lax.psum(x, "i")  # MARK
                return y
            return x
    """})
    _, got = findings(tmp_path)
    assert got == [("GM601", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm601_early_return_under_rank_test(tmp_path):
    """`if rank != 0: return` then a collective: only rank 0 reaches it."""
    build_project(tmp_path, {"mod.py": """
        import jax

        def step(x):
            rank = jax.process_index()
            if rank != 0:
                return x
            return jax.lax.all_to_all(x, "i", 0, 0)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM601", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm601_through_call_graph(tmp_path):
    """A collective two calls deep under the rank branch is still found."""
    build_project(tmp_path, {"mod.py": """
        import jax

        def _deep(x):
            return jax.lax.psum(x, "i")

        def _helper(x):
            return _deep(x)

        def step(x, rank):
            if rank == 0:
                return _helper(x)  # MARK
            return x
    """})
    _, got = findings(tmp_path)
    assert got == [("GM601", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm601_rank_uniform_branches_pass(tmp_path):
    """Same collective sequence in both arms, rank-0-only manifest
    writes, raise-terminated arms, and process_count() tests are all
    legitimate."""
    build_project(tmp_path, {"mod.py": """
        import jax

        def seal(manifest):
            return manifest

        def step(x, manifest):
            if jax.process_index() == 0:
                seal(manifest)  # no collective: fine
            if jax.process_index() == 0:
                y = jax.lax.psum(x, "i")
            else:
                y = jax.lax.psum(x, "i")
            if jax.process_count() > 1:
                y = jax.lax.psum(y, "i")
            if jax.process_index() > 8:
                raise ValueError("abort path is exempt")
            return y
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_gm602_collective_order_divergence(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        def step(x, rank):
            if rank == 0:  # MARK
                a = jax.lax.psum(x, "i")
                b = jax.lax.all_to_all(x, "i", 0, 0)
            else:
                b = jax.lax.all_to_all(x, "i", 0, 0)
                a = jax.lax.psum(x, "i")
            return a, b
    """})
    _, got = findings(tmp_path)
    assert got == [("GM602", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm603_unrouted_dispatch(tmp_path):
    """In a module with _retry_collective, fetching+invoking a built
    collective kernel outside a retry thunk is flagged; the routed twin
    passes."""
    build_project(tmp_path, {"mod.py": """
        import jax

        def shard_map(f):
            return f

        def get_kernel(key, build):
            return build()

        class Eng:
            def _retry(self, point, fn):
                return self._retry_collective(point, fn)

            def _retry_collective(self, point, fn):
                return fn()

            def _kernel_fn(self):
                def build():
                    def body(x):
                        return jax.lax.all_to_all(x, "i", 0, 0)
                    return shard_map(body)
                return get_kernel("k", build)

            def good(self, x):
                def _step():
                    return self._kernel_fn()(x)
                return self._retry("p", _step)

            def bad(self, x):
                return self._kernel_fn()(x)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM603", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm604_collective_under_lock(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading
        import jax

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self, x):
                with self._lock:
                    return jax.lax.psum(x, "i")  # MARK

            def good(self, x):
                with self._lock:
                    y = x + 1
                return jax.lax.psum(y, "i")
    """})
    _, got = findings(tmp_path)
    assert got == [("GM604", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm604_barrier_on_coord_handle_under_lock(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self, coord):
                self._lock = threading.Lock()
                self.coord = coord

            def bad(self):
                with self._lock:
                    self.coord.barrier("resume")  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM604", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


# -------------------------------------------- GM7xx: resource lifecycle


def test_gm701_unguarded_open(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def leak(path):
            f = open(path)  # MARK
            data = f.read()
            f.close()
            return data
    """})
    _, got = findings(tmp_path)
    assert got == [("GM701", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm701_popen_discarded(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import subprocess

        def leak(cmd):
            proc = subprocess.Popen(cmd)  # MARK
            return proc.pid
    """})
    _, got = findings(tmp_path)
    assert got == [("GM701", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm701_self_field_never_released(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import subprocess

        class Held:
            def __init__(self, cmd):
                self.proc = subprocess.Popen(cmd)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM701", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm701_clean_patterns_pass(tmp_path):
    """with, try/finally, ownership transfer (return / argument /
    container / tracked self field), and daemon threads are all fine."""
    build_project(tmp_path, {"mod.py": """
        import subprocess
        import threading

        def ok_with(path):
            with open(path) as f:
                return f.read()

        def ok_finally(path):
            f = open(path)
            try:
                return f.read()
            finally:
                f.close()

        def ok_return(path):
            return open(path)

        def ok_transfer(cmd, registry):
            proc = subprocess.Popen(cmd)
            registry.track(proc)

        def ok_daemon():
            t = threading.Thread(target=print, daemon=True)
            t.start()

        class Tracked:
            def __init__(self, cmd):
                self.proc = subprocess.Popen(cmd)

            def stop(self):
                self.proc.kill()
                self.proc.wait()
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_gm701_awaited_acquisition_does_not_crash(tmp_path):
    """An acquisition under `await` unwraps to its binding instead of
    crashing the scan (regression: NameError in _context_of)."""
    build_project(tmp_path, {"mod.py": """
        import os

        async def ok(fd, registry):
            f = await os.fdopen(fd)
            registry.track(f)
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_gm701_from_import_popen_still_flagged(tmp_path):
    """`from subprocess import Popen` must not blind the checker."""
    build_project(tmp_path, {"mod.py": """
        from subprocess import Popen

        def leak(cmd):
            proc = Popen(cmd)  # MARK
            return proc.pid
    """})
    _, got = findings(tmp_path)
    assert got == [("GM701", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm702_from_import_lock_before_fork(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import os
        from threading import Lock

        def bad_spawn():
            lk = Lock()  # MARK
            pid = os.fork()
            return pid, lk
    """})
    _, got = findings(tmp_path)
    assert got == [("GM702", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm702_thread_and_lock_before_fork(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import os
        import threading

        def bad_spawn():
            t = threading.Thread(target=print, daemon=True)  # MARK
            t.start()
            pid = os.fork()
            return pid

        def ok_spawn():
            pid = os.fork()
            if pid == 0:
                t = threading.Thread(target=print, daemon=True)
                t.start()
            return pid
    """})
    _, got = findings(tmp_path)
    assert got == [("GM702", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


# ---------------------------------------- GM8xx: atomic-write discipline


def test_gm801_direct_write_bypasses_discipline(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import json
        import os

        import numpy as np

        def good(path, manifest):
            tmp = f"{path}.tmp"
            with open(tmp, "w") as fh:
                json.dump(manifest, fh)
            os.replace(tmp, path)

        def bad(path, arr):
            np.savez(path, data=arr)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM801", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm801_sealed_write_annotation_exempts(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import numpy as np

        # sealed-write: payload sealed by the caller's manifest
        def payload_helper(path, arr):
            np.save(path, arr)
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_gm801_non_participating_module_exempt(tmp_path):
    """A report tool that never practices atomicity is out of scope."""
    build_project(tmp_path, {"mod.py": """
        import json

        def write_report(path, rows):
            with open(path, "w") as fh:
                json.dump(rows, fh)
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_gm803_direct_payload_read_flagged(tmp_path):
    """np.load / os.pread / open-rb of a checkpoint/DB payload outside
    store/ bypasses the sealed-read door + shared cache (ISSUE 11)."""
    build_project(tmp_path, {"mod.py": """
        import os

        import numpy as np

        def resume(d, fd):
            z = np.load(d / "level_0001.shard_0000.npz")  # MARK
            blob = os.pread(fd, 10, 0)  # MARK2: level_0002.gmb stream
            with open(d / "frontier_0003.npz", "rb") as fh:  # MARK3
                fh.read()
            with open(d / "edges_0004.npz", mode="rb") as fh:  # MARK4
                fh.read()
            return z, blob

        def user_artifact(path):
            # A generic npy read names no payload: out of scope.
            return np.load(path)
    """})
    _, got = findings(tmp_path)
    assert got == [
        ("GM803", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py")),
        ("GM803", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py", "MARK2")),
        ("GM803", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py", "MARK3")),
        ("GM803", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py", "MARK4")),
    ]


def test_gm803_store_modules_and_annotated_escapes_exempt(tmp_path):
    build_project(tmp_path, {
        "store/__init__.py": "",
        "store/sealed.py": """
            import numpy as np

            def loadz(path):
                return np.load(path)  # the one door: level_0001.npz etc.
        """,
        "gate.py": """
            import numpy as np

            def audit(d, rec):
                # store-io: integrity gate reads raw bytes on purpose
                keys = np.load(d / rec["keys"], mmap_mode="r")
                return keys
        """,
    })
    _, got = findings(tmp_path)
    assert got == []


def test_gm802_payload_after_seal(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import os

        import numpy as np

        def bad(ckpt, path, arr):
            ckpt.seal_level(3)
            np.save(path + ".tmp", arr)  # MARK
            os.replace(path + ".tmp", path)

        def good(ckpt, path, arr):
            np.save(path + ".tmp", arr)
            os.replace(path + ".tmp", path)
            ckpt.seal_level(3)
    """})
    _, got = findings(tmp_path)
    assert got == [("GM802", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


# ------------------------------------------------ lockdep (runtime witness)


def test_lockdep_witnesses_cycle(tmp_path):
    import threading

    from gamesmanmpi_tpu.analysis import lockdep

    with lockdep.witness(watch=(str(tmp_path),), check=False) as ld:
        # construction sites must be inside the watched path
        src = tmp_path / "locks_fixture.py"
        src.write_text(
            "import threading\n"
            "a = threading.Lock()\n"
            "b = threading.Lock()\n"
        )
        ns: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns)
        a, b = ns["a"], ns["b"]
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert len(ld.edges()) == 2
        assert ld.cycles()
        with pytest.raises(lockdep.LockOrderError):
            ld.assert_acyclic()
    # uninstalled afterwards: plain locks again
    assert type(threading.Lock()).__name__ != "_LockProxy"


def test_lockdep_consistent_order_is_acyclic(tmp_path):
    from gamesmanmpi_tpu.analysis import lockdep

    with lockdep.witness(watch=(str(tmp_path),), check=False) as ld:
        src = tmp_path / "ok_fixture.py"
        src.write_text(
            "import threading\n"
            "a = threading.Lock()\n"
            "b = threading.Lock()\n"
        )
        ns: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns)
        a, b = ns["a"], ns["b"]
        for _ in range(3):
            with a:
                with b:
                    pass
        assert ld.edges() and ld.cycles() == []
        ld.assert_acyclic()


def test_lockdep_rlock_reentry_records_no_self_edge(tmp_path):
    from gamesmanmpi_tpu.analysis import lockdep

    with lockdep.witness(watch=(str(tmp_path),), check=False) as ld:
        src = tmp_path / "rlock_fixture.py"
        src.write_text("import threading\nr = threading.RLock()\n")
        ns: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns)
        r = ns["r"]
        with r:
            with r:
                pass
        assert ld.edges() == []
        ld.assert_acyclic()


def test_lockdep_condition_wait_releases_held_state(tmp_path):
    """Condition.wait over an instrumented lock must drop the held
    entry (no phantom edges from the waiting thread)."""
    import threading

    from gamesmanmpi_tpu.analysis import lockdep

    with lockdep.witness(watch=(str(tmp_path),), check=False) as ld:
        src = tmp_path / "cond_fixture.py"
        src.write_text(
            "import threading\n"
            "lk = threading.Lock()\n"
            "other = threading.Lock()\n"
        )
        ns: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns)
        lk, other = ns["lk"], ns["other"]
        cond = threading.Condition(lk)
        done = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                done.append(1)
            with other:  # held state clean: no lk->other edge pending
                pass

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        with cond:
            cond.notify()
        t.join(timeout=5)
        assert done == [1]
        assert all(a != b for a, b in ld.edges())
        ld.assert_acyclic()


def test_lockdep_same_site_locks_keep_distinct_nodes(tmp_path):
    """Two locks born at the same line (a loop) must stay distinct
    graph nodes — an inversion BETWEEN them is a real deadlock and must
    still be witnessed."""
    from gamesmanmpi_tpu.analysis import lockdep

    with lockdep.witness(watch=(str(tmp_path),), check=False) as ld:
        src = tmp_path / "same_site_fixture.py"
        src.write_text(
            "import threading\n"
            "locks = [threading.Lock() for _ in range(2)]\n"
        )
        ns: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns)
        a, b = ns["locks"]
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert len(ld.instrumented()) == 2  # distinct names per instance
        assert ld.cycles(), ld.edges()


def test_lockdep_condition_wait_over_reentrant_rlock(tmp_path):
    """wait() on a Condition over an RLock held at depth 2 must restore
    the proxy's depth on wake-up: the edges recorded AFTER the wait
    prove the lock still counts as held."""
    import threading

    from gamesmanmpi_tpu.analysis import lockdep

    with lockdep.witness(watch=(str(tmp_path),), check=False) as ld:
        src = tmp_path / "rlock_cond_fixture.py"
        src.write_text(
            "import threading\n"
            "r = threading.RLock()\n"
            "other = threading.Lock()\n"
        )
        ns: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns)
        r, other = ns["r"], ns["other"]
        cond = threading.Condition(r)
        done = []

        def waiter():
            with r:          # depth 1
                with cond:   # depth 2 (condition aliases r)
                    cond.wait(timeout=5)
                    done.append(1)
                with other:  # r still held: edge r -> other
                    pass

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        with cond:
            cond.notify()
        t.join(timeout=5)
        assert done == [1]
        assert any("rlock_cond_fixture.py:2" in a
                   and "rlock_cond_fixture.py:3" in b
                   for a, b in ld.edges()), ld.edges()
        ld.assert_acyclic()


def test_lockdep_witness_restores_outer_install(tmp_path):
    """A scoped witness over a session-wide install (GAMESMAN_LOCKDEP=1
    via conftest) must restore the outer watch list, edge graph, and
    instrumentation on exit — not blind the rest of the session."""
    import threading

    from gamesmanmpi_tpu.analysis import lockdep

    lockdep.install(watch=(str(tmp_path),))
    try:
        src = tmp_path / "outer_fixture.py"
        src.write_text(
            "import threading\n"
            "a = threading.Lock()\n"
            "b = threading.Lock()\n"
        )
        ns: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns)
        with ns["a"]:
            with ns["b"]:
                pass
        outer_edges = lockdep.edges()
        assert len(outer_edges) == 1

        with lockdep.witness(watch=("/nonexistent/",), check=False) as ld:
            assert ld.edges() == []  # clean slate inside

        # outer install intact: edges restored, still instrumenting
        assert lockdep.edges() == outer_edges
        ns2: dict = {}
        exec(compile(src.read_text(), str(src), "exec"), ns2)
        assert type(ns2["a"]).__name__ == "_LockProxy"
    finally:
        lockdep.uninstall()
        lockdep.reset()


def test_lockdep_instruments_real_subsystems():
    """The ISSUE-10 acceptance wiring: under a witness, constructing the
    real obs/serve/resilience lock users records their construction
    sites, exercising them records any acquisition edges, and the
    session-level acyclicity assertion passes."""
    from gamesmanmpi_tpu.analysis import lockdep

    with lockdep.witness() as ld:
        from gamesmanmpi_tpu.obs.registry import MetricsRegistry
        from gamesmanmpi_tpu.resilience.coordination import (
            CoordinatorServer,
            EpochBarrier,
        )
        from gamesmanmpi_tpu.serve.batcher import Batcher

        reg = MetricsRegistry()
        reg.counter("gamesman_lockdep_test_total", "d").inc()
        reg.histogram("gamesman_lockdep_test_seconds", "d").observe(0.1)
        reg.snapshot()

        class _StubReader:
            def lookup_best(self, positions):
                return [None] * len(positions)

        batcher = Batcher(_StubReader(), window=0.01, cache_size=8)
        batcher.close()

        srv = CoordinatorServer(1, deadline=5.0)
        try:
            bar = EpochBarrier(srv.address, 0, deadline=5.0)
            assert bar.propose("lockdep", "ok") == "ok"
        finally:
            srv.close()

        sites = ld.instrumented()
        assert any("obs/registry" in s for s in sites), sites
        assert any("serve/batcher" in s for s in sites), sites
        assert any("resilience/coordination" in s for s in sites), sites
        ld.assert_acyclic()


# --------------------------------------------------------- --changed-only


def _git(cwd, *argv):
    return subprocess.run(
        ["git", "-C", str(cwd), "-c", "user.email=l@l", "-c",
         "user.name=lint", *argv],
        capture_output=True, text=True, check=True,
    )


def test_changed_only_scopes_reporting_not_scanning(tmp_path, capsys):
    """--changed-only: a finding in an UNchanged file is not reported,
    a finding in a changed file fails the run with the same exit
    semantics, and whole-project registry parity (GM303 needs every
    reader) keeps working because the scan stays global."""
    if shutil.which("git") is None:
        pytest.skip("git not available")
    build_project(
        tmp_path,
        {
            "stale.py": """
                import os
                X = os.environ.get("PATH")
            """,
            "fresh.py": "x = 1\n",
        },
        config_md=CONFIG_HEADER,
    )
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")

    # Nothing changed: exit 0 even though stale.py holds a finding.
    rc = lint_main(["--root", str(tmp_path), "--changed-only"])
    assert rc == 0
    assert "no lint targets changed" in capsys.readouterr().err

    # Change ONLY fresh.py, introducing a new finding there.
    (tmp_path / "pkg" / "fresh.py").write_text(
        "import os\nY = os.environ.get(\"HOME\")\n"
    )
    rc = lint_main(["--root", str(tmp_path), "--changed-only"])
    out = capsys.readouterr()
    assert rc == 1
    assert "pkg/fresh.py" in out.out
    assert "pkg/stale.py" not in out.out  # unchanged: not reported

    # Baseline semantics unchanged: a baselined finding in the changed
    # file demotes to exit 0.
    from gamesmanmpi_tpu.analysis.runner import run_project

    res = run_project(tmp_path)
    write_baseline(tmp_path / "lint_baseline.json", res.fingerprints)
    assert lint_main(["--root", str(tmp_path), "--changed-only"]) == 0
    capsys.readouterr()

    # The full run still sees both findings (scan scope never shrank).
    assert lint_main(["--root", str(tmp_path), "--no-baseline"]) == 1
    full = capsys.readouterr().out
    assert "pkg/stale.py" in full and "pkg/fresh.py" in full

    # Refuses to combine with --update-baseline or explicit paths.
    assert lint_main(["--root", str(tmp_path), "--changed-only",
                      "--update-baseline"]) == 2
    assert lint_main(["--root", str(tmp_path), "--changed-only",
                      "pkg/fresh.py"]) == 2
    capsys.readouterr()

    # A junk base ref is a usage error, not a traceback.
    assert lint_main(["--root", str(tmp_path), "--changed-only",
                      "--base-ref", "no_such_ref"]) == 2
    capsys.readouterr()


# --------------------------------------------- suppressions + baseline


def test_inline_suppression(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import os

        def knob():
            # deliberate: fixture  # lint: disable=GM301
            return os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert got == []
    assert [d.id for d in res.suppressed] == ["GM301"]


def test_file_level_suppression(tmp_path):
    build_project(tmp_path, {"mod.py": """
        # lint: disable-file=GM301
        import os

        def a():
            return os.environ.get("PATH")

        def b():
            return os.environ.get("HOME")
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert got == []
    assert len(res.suppressed) == 2


def test_suppressed_ids_parsing():
    lines = [
        "# deliberate  # lint: disable=GM301, GM401",
        "x = 1",
    ]
    assert suppressed_ids(lines, 1) == {"GM301", "GM401"}
    # comment-only line above applies to the statement below it
    assert suppressed_ids(lines, 2) == {"GM301", "GM401"}


def test_trailing_suppression_does_not_bleed_to_next_line(tmp_path):
    """A justified disable on line N must not silence a genuinely new
    violation on line N+1."""
    build_project(tmp_path, {"mod.py": """
        import os

        A = os.environ.get("PATH")  # why: fixture  # lint: disable=GM301
        B = os.environ.get("HOME")  # MARK
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert got == [("GM301", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]
    assert [d.id for d in res.suppressed] == ["GM301"]


def test_baseline_round_trip(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import os

        def knob():
            return os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert [g[0] for g in got] == ["GM301"]

    baseline = tmp_path / "lint_baseline.json"
    write_baseline(baseline, res.fingerprints)
    res2, got2 = findings(tmp_path, baseline_path=str(baseline))
    assert got2 == []
    assert [d.id for d in res2.baselined] == ["GM301"]

    # Line-shifting edits must not churn the baseline: fingerprints key
    # on source text, not line numbers.
    mod = tmp_path / "pkg" / "mod.py"
    mod.write_text("# a new leading comment\n" + mod.read_text())
    res3, got3 = findings(tmp_path, baseline_path=str(baseline))
    assert got3 == []
    assert [d.id for d in res3.baselined] == ["GM301"]

    # A genuinely NEW finding still fails against the old baseline.
    mod.write_text(
        mod.read_text()
        + "\ndef knob2():\n    return os.environ.get(\"HOME\")\n"
    )
    _, got4 = findings(tmp_path, baseline_path=str(baseline))
    assert [g[0] for g in got4] == ["GM301"]


def test_fingerprint_ignores_message_wording(tmp_path):
    lines = ["value = os.environ.get('X')"]
    a = Diagnostic("p.py", 1, "GM301", "old wording")
    b = Diagnostic("p.py", 1, "GM301", "new improved wording")
    assert fingerprint(a, lines) == fingerprint(b, lines)


# ------------------------------------------------------------------- runner


def test_gm001_syntax_error(tmp_path):
    build_project(tmp_path, {"mod.py": "def broken(:\n"})
    _, got = findings(tmp_path)
    assert got[0][0] == "GM001" and got[0][1] == "pkg/mod.py"


def test_cli_json_format_and_exit_codes(tmp_path, capsys):
    build_project(tmp_path, {"mod.py": """
        import os
        X = os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    rc = lint_main(["--root", str(tmp_path), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [d["id"] for d in out["new"]] == ["GM301"]

    # --update-baseline accepts the findings; the next run is clean.
    assert lint_main(["--root", str(tmp_path), "--update-baseline"]) == 0
    assert lint_main(["--root", str(tmp_path)]) == 0
    # --no-baseline sees them again.
    assert lint_main(["--root", str(tmp_path), "--no-baseline"]) == 1


def test_missing_target_is_usage_error(tmp_path, capsys):
    build_project(tmp_path, {"mod.py": "x = 1\n"})
    rc = lint_main(["--root", str(tmp_path), "pkg/no_such_file.py"])
    assert rc == 2
    assert "lint target not found" in capsys.readouterr().err


def test_target_outside_root_is_usage_error(tmp_path, capsys):
    build_project(tmp_path, {"mod.py": "x = 1\n"})
    outside = tmp_path.parent / "outside_target.py"
    outside.write_text("x = 1\n")
    rc = lint_main(["--root", str(tmp_path), str(outside)])
    assert rc == 2
    assert "outside --root" in capsys.readouterr().err


def test_update_baseline_refuses_partial_runs(tmp_path, capsys):
    """A pathed run sees a subset of findings; writing that subset back
    would drop every accepted entry outside the scanned paths."""
    build_project(tmp_path, {"mod.py": "x = 1\n"})
    rc = lint_main(["--root", str(tmp_path), "pkg", "--update-baseline"])
    assert rc == 2
    assert "whole-project" in capsys.readouterr().err


def test_gm504_prefix_point_is_not_coverage(tmp_path):
    """'engine.fwd' appearing only inside 'engine.fwd_edges' in the
    chaos matrix is NOT coverage for 'engine.fwd'."""
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",  # MARK
                "lvl.fwd_edges": "edge variant",
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
                faults.fire("lvl.fwd_edges")
        """,
    }, chaos="exercises lvl.fwd_edges only")
    _, got = findings(tmp_path)
    assert got == [("GM504", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_update_baseline_anchors_at_root(tmp_path, monkeypatch):
    """--no-baseline --update-baseline must write <root>/lint_baseline
    .json, not a file in whatever directory the command ran from."""
    build_project(tmp_path, {"mod.py": """
        import os
        X = os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    elsewhere = tmp_path / "elsewhere"
    elsewhere.mkdir()
    monkeypatch.chdir(elsewhere)
    assert lint_main(
        ["--root", str(tmp_path), "--no-baseline", "--update-baseline"]
    ) == 0
    assert (tmp_path / "lint_baseline.json").exists()
    assert not (elsewhere / "lint_baseline.json").exists()


def test_explicit_paths_restrict_lint_scope(tmp_path):
    build_project(tmp_path, {
        "clean.py": "x = 1\n",
        "dirty.py": """
            import os
            X = os.environ.get("PATH")
        """,
    }, config_md=CONFIG_HEADER)
    _, got = findings(tmp_path, paths=["pkg/clean.py"])
    assert got == []
    _, got = findings(tmp_path, paths=["pkg/dirty.py"])
    assert [g[0] for g in got] == ["GM301"]


# ------------------------------------------------------------- tier-1 gate


def test_repository_lints_clean():
    """THE gate: the real repo must hold zero new findings (baseline
    empty or justified), and the whole run must stay fast enough to sit
    in tier-1 forever."""
    t0 = time.perf_counter()
    res = run_project(
        REPO, baseline_path=os.path.join(REPO, "lint_baseline.json")
    )
    elapsed = time.perf_counter() - t0
    assert res.new == [], "new lint findings:\n" + "\n".join(
        d.format() for d in res.new
    )
    # Suppressions must stay rare and deliberate (each carries its "why"
    # inline); a creeping count means the lint is being routed around.
    assert len(res.suppressed) <= 8, [d.format() for d in res.suppressed]
    assert len(res.project.files) > 50  # discovery actually found the repo
    assert elapsed < 60, f"lint took {elapsed:.1f}s — too slow for tier-1"
    # The cross-module call graph (ISSUE 10) is the expensive index; the
    # 60 s budget above holds because every checker shares ONE build.
    assert res.project.callgraph_builds == 1


def test_repository_passes_ruff():
    """The generic-linter floor ([tool.ruff] in pyproject.toml): runs
    wherever a ruff binary exists; skipped (not failed) on containers
    that don't ship one — gamesman-lint above is the always-on gate."""
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff binary not installed in this container")
    proc = subprocess.run(
        [ruff, "check", "."], cwd=REPO, capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ------------------------------------------------ ISSUE 14: fused sites


def test_gm603_fused_callback_kernel_routing(tmp_path):
    """The fused-dedup kernel bodies (pure_callback inside a shard_map
    body, collectives around it) change nothing about GM603: the body is
    traced-via-get_kernel (exempt), the dispatch site is what's checked —
    routed through _retry_collective passes, unrouted is flagged."""
    build_project(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        def shard_map(f):
            return f

        def get_kernel(key, build):
            return build()

        def _np_unique(flat):
            return np.unique(flat)

        class Eng:
            def _retry(self, point, fn):
                return self._retry_collective(point, fn)

            def _retry_collective(self, point, fn):
                return fn()

            def _fused_fn(self):
                def build():
                    def body(x):
                        y = jax.pure_callback(_np_unique, x, x)
                        return jax.lax.all_to_all(y, "i", 0, 0)
                    return shard_map(body)
                return get_kernel("k", build)

            def good(self, x):
                def _step():
                    return self._fused_fn()(x)
                return self._retry("p", _step)

            def bad(self, x):
                return self._fused_fn()(x)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM603", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm1xx_pure_callback_body_is_host_code(tmp_path):
    """GM1xx trace-safety of the fused megakernel shape: a module-level
    numpy function passed BY NAME into jax.pure_callback from traced code
    runs on the HOST with concrete arrays — its np.* calls and host syncs
    must NOT be flagged. The same function reached through a non-callback
    combinator still is (the callback rule's reason to exist)."""
    build_project(tmp_path, {"clean.py": """
        import jax
        import numpy as np

        def _np_dedup(flat, n):
            u = np.unique(flat[:int(n)])
            out = np.full(flat.shape[0], 0, dtype=flat.dtype)
            out[:len(u)] = u
            return out

        @jax.jit
        def fused_kernel(flat, n):
            return jax.pure_callback(_np_dedup, flat, flat, n)
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1xx_non_callback_callee_still_traced(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        def _body(carry, x):
            return carry, np.cumsum(x)  # MARK

        @jax.jit
        def kernel(xs):
            return jax.lax.scan(_body, 0, xs)
    """})
    _, got = findings(tmp_path)
    assert got == [("GM105", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


# ------------------------------------------- ISSUE 20: GM10xx wire contracts

# A miniature fleet handler in the repo's serve/server.py idiom: a
# `_send_json` helper (forwarding a computed code does NOT open the
# class's code set) and string-compare route dispatch in do_GET.
_WIRE_SRV = """
    import json
    from http.server import BaseHTTPRequestHandler

    class _FixtureHandler(BaseHTTPRequestHandler):
        def _send_json(self, code, payload, headers=None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._send_json(200, {"status": "ok"})
            else:
                self._send_json(404, {"error": "no route"})
"""


def test_gm1001_client_route_no_server_defines(tmp_path):
    build_project(tmp_path, {
        "srv.py": _WIRE_SRV,
        "cli.py": """
            from urllib.request import urlopen

            BASE = "http://127.0.0.1:9"

            def probe():
                with urlopen(BASE + "/nope", timeout=2) as r:  # MARK
                    return r.status
        """,
    })
    _, got = findings(tmp_path)
    assert got == [("GM1001", "pkg/cli.py", mark_line(tmp_path, "pkg/cli.py"))]


def test_gm1001_clean_when_route_exists(tmp_path):
    build_project(tmp_path, {
        "srv.py": _WIRE_SRV,
        "cli.py": """
            from urllib.request import urlopen

            BASE = "http://127.0.0.1:9"

            def probe():
                with urlopen(BASE + "/healthz", timeout=2) as r:
                    return r.status
        """,
    })
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1001_unknown_coordination_op(tmp_path):
    """The op vocabulary direction: a dict-literal `op` sent from a
    module that opens sockets must be one some coordination server
    compares against (the job ledger's {"op": ...} records, in modules
    with no sockets, are exempt by design)."""
    build_project(tmp_path, {
        "coord_srv.py": """
            def serve_one(req):
                if req.get("op") == "ping":
                    return {"ok": True}
                return {"ok": False}
        """,
        "coord_cli.py": """
            import json
            import socket

            def call(addr):
                conn = socket.create_connection(addr, timeout=2)
                try:
                    conn.sendall(json.dumps({"op": "pingg"}).encode())  # MARK
                finally:
                    conn.close()
        """,
    })
    _, got = findings(tmp_path)
    assert got == [
        ("GM1001", "pkg/coord_cli.py", mark_line(tmp_path, "pkg/coord_cli.py"))
    ]
    # The fixed spelling is clean.
    build_project(tmp_path, {"coord_cli.py": """
        import json
        import socket

        def call(addr):
            conn = socket.create_connection(addr, timeout=2)
            try:
                conn.sendall(json.dumps({"op": "ping"}).encode())
            finally:
                conn.close()
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1002_client_branch_on_unemitted_code(tmp_path):
    build_project(tmp_path, {
        "srv.py": _WIRE_SRV,
        "cli.py": """
            import urllib.error
            from urllib.request import urlopen

            def probe(base):
                try:
                    with urlopen(base + "/healthz", timeout=2) as r:
                        return r.status
                except urllib.error.HTTPError as e:
                    if e.code == 418:  # MARK
                        return -1
                    raise
        """,
    })
    _, got = findings(tmp_path)
    assert got == [("GM1002", "pkg/cli.py", mark_line(tmp_path, "pkg/cli.py"))]


def test_gm1002_server_shed_code_unhandled(tmp_path):
    """The other direction: a server that sheds with 503 while no
    client anywhere branches on it — the backpressure path would
    surface as a generic unhandled error."""
    build_project(tmp_path, {
        "srv.py": """
            import json
            from http.server import BaseHTTPRequestHandler

            class _FixtureHandler(BaseHTTPRequestHandler):
                def _send_json(self, code, payload, headers=None):
                    body = json.dumps(payload).encode()
                    self.send_response(code)
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):
                    if self.path == "/healthz":
                        self._send_json(200, {"status": "ok"})
                    elif self.path == "/busy":
                        self._send_json(503, {"error": "busy"})  # MARK
                    else:
                        self._send_json(404, {"error": "no route"})
        """,
        "cli.py": """
            import urllib.error
            from urllib.request import urlopen

            def probe(base):
                try:
                    with urlopen(base + "/healthz", timeout=2) as r:
                        return r.status
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        return None
                    raise
        """,
    })
    _, got = findings(tmp_path)
    assert got == [("GM1002", "pkg/srv.py", mark_line(tmp_path, "pkg/srv.py"))]
    # A client handling the shed code (the `in (404, 503)` membership
    # shape) closes the gap.
    build_project(tmp_path, {"cli.py": """
        import urllib.error
        from urllib.request import urlopen

        def probe(base):
            try:
                with urlopen(base + "/healthz", timeout=2) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                if e.code in (404, 503):
                    return None
                raise
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1003_outbound_call_without_timeout(tmp_path):
    """Both the missing-argument and the explicit timeout=None shapes
    hang forever on a dead peer."""
    build_project(tmp_path, {"mod.py": """
        from urllib.request import urlopen

        def probe():
            return urlopen("http://db-registry:8940/catalog")  # MARK

        def probe_none(url):
            return urlopen(url, timeout=None)  # MARK2
    """})
    _, got = findings(tmp_path)
    assert got == [
        ("GM1003", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py")),
        ("GM1003", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py", "MARK2")),
    ]


def test_gm1003_clean_with_finite_timeout(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import socket
        from http.client import HTTPConnection
        from urllib.request import urlopen

        def probe(url, addr):
            with urlopen(url, timeout=5) as r:
                r.read()
            with socket.create_connection(addr, 2) as conn:
                conn.sendall(b"ping")
            return HTTPConnection("peer", 80, 3)
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1004_shed_without_retry_after(tmp_path):
    build_project(tmp_path, {"srv.py": """
        import json
        from http.server import BaseHTTPRequestHandler

        # wire: 503-retry-after
        class _Shedding(BaseHTTPRequestHandler):
            def _send_json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/work":
                    self._send_json(503, {"error": "busy"})  # MARK
                else:
                    self._send_json(200, {"status": "ok"})
    """})
    _, got = findings(tmp_path)
    assert got == [("GM1004", "pkg/srv.py", mark_line(tmp_path, "pkg/srv.py"))]
    # Attaching the promised header satisfies the declared contract.
    build_project(tmp_path, {"srv.py": """
        import json
        from http.server import BaseHTTPRequestHandler

        # wire: 503-retry-after
        class _Shedding(BaseHTTPRequestHandler):
            def _send_json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/work":
                    self._send_json(503, {"error": "busy"},
                                    headers={"Retry-After": "2"})
                else:
                    self._send_json(200, {"status": "ok"})
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1004_etag_dict_without_cache_control(tmp_path):
    build_project(tmp_path, {"srv.py": """
        import json
        from http.server import BaseHTTPRequestHandler

        # wire: etag-cache-control
        class _Caching(BaseHTTPRequestHandler):
            def _headers(self, tag):
                return {"ETag": tag, "Vary": "Accept"}  # MARK

            def do_GET(self):
                self.send_response(200)
                self.end_headers()
    """})
    _, got = findings(tmp_path)
    assert got == [("GM1004", "pkg/srv.py", mark_line(tmp_path, "pkg/srv.py"))]
    build_project(tmp_path, {"srv.py": """
        import json
        from http.server import BaseHTTPRequestHandler

        # wire: etag-cache-control
        class _Caching(BaseHTTPRequestHandler):
            def _headers(self, tag):
                return {"ETag": tag, "Cache-Control": "max-age=30"}

            def do_GET(self):
                self.send_response(200)
                self.end_headers()
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1004_echo_traceparent_never_sent(tmp_path):
    build_project(tmp_path, {"srv.py": """
        from http.server import BaseHTTPRequestHandler

        # wire: echo-traceparent
        class _Tracing(BaseHTTPRequestHandler):  # MARK
            def do_GET(self):
                self.send_response(200)
                self.end_headers()
    """})
    _, got = findings(tmp_path)
    assert got == [("GM1004", "pkg/srv.py", mark_line(tmp_path, "pkg/srv.py"))]
    build_project(tmp_path, {"srv.py": """
        from http.server import BaseHTTPRequestHandler

        # wire: echo-traceparent
        class _Tracing(BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                tp = self.headers.get("traceparent")
                if tp:
                    self.send_header("traceparent", tp)
                self.end_headers()
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1004_unknown_wire_token(tmp_path):
    build_project(tmp_path, {"mod.py": """
        # wire: bogus
        def helper():  # MARK
            return 1
    """})
    _, got = findings(tmp_path)
    assert got == [("GM1004", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm1005_consumed_key_never_produced(tmp_path):
    build_project(tmp_path, {
        "srv.py": """
            # wire: producer
            def reply():
                return {"status": "ok", "epoch": 3}
        """,
        "cli.py": """
            import json
            from urllib.request import urlopen

            def fetch_status(base):
                with urlopen(base + "/status", timeout=2) as r:
                    payload = json.loads(r.read())
                return payload["generation"]  # MARK
        """,
    })
    _, got = findings(tmp_path)
    assert got == [("GM1005", "pkg/cli.py", mark_line(tmp_path, "pkg/cli.py"))]
    # Keys the producer actually writes are clean, subscript or .get.
    build_project(tmp_path, {"cli.py": """
        import json
        from urllib.request import urlopen

        def fetch_status(base):
            with urlopen(base + "/status", timeout=2) as r:
                payload = json.loads(r.read())
            return payload["epoch"], payload.get("status")
    """})
    _, got = findings(tmp_path)
    assert got == [], got


def test_gm1005_consumer_annotation_seeds_parameters(tmp_path):
    """The supervisor's `_on_msg(slot, msg, now)` shape: json.loads
    happens one frame up, so the `# wire: consumer` annotation makes
    the function's parameters wire payloads."""
    build_project(tmp_path, {
        "srv.py": """
            # wire: producer
            def reply():
                return {"beat": 1}
        """,
        "sup.py": """
            # wire: consumer
            def on_msg(slot, msg):
                return msg["missing"]  # MARK
        """,
    })
    _, got = findings(tmp_path)
    assert got == [("GM1005", "pkg/sup.py", mark_line(tmp_path, "pkg/sup.py"))]


_OBS_TABLE = (
    "## Status endpoints\n\n"
    "| Method | Path | Codes |\n"
    "|---|---|---|\n"
    "| GET | `/healthz` | 200 |\n"
)


def test_gm1006_route_missing_from_endpoint_tables(tmp_path):
    build_project(tmp_path, {"srv.py": """
        import json
        from http.server import BaseHTTPRequestHandler

        class _FixtureHandler(BaseHTTPRequestHandler):
            def _send_json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send_json(200, {"status": "ok"})
                elif self.path == "/extra":  # MARK
                    self._send_json(200, {"extra": 1})
                else:
                    self._send_json(404, {"error": "no route"})
    """}, observability_md=_OBS_TABLE)
    _, got = findings(tmp_path)
    assert got == [("GM1006", "pkg/srv.py", mark_line(tmp_path, "pkg/srv.py"))]


def test_gm1006_documented_endpoint_no_server_defines(tmp_path):
    md = _OBS_TABLE + "| GET | `/gone` | 200 |\n"
    build_project(tmp_path, {"srv.py": _WIRE_SRV}, observability_md=md)
    _, got = findings(tmp_path)
    line = md.splitlines().index("| GET | `/gone` | 200 |") + 1
    assert got == [("GM1006", "docs/OBSERVABILITY.md", line)]


def test_wire_clean_fleet_fixture(tmp_path):
    """A consistent miniature fleet — routes (exact and `<name>`-prefix)
    documented, codes handled both ways, keys produced before consumed —
    lints clean across the whole GM10xx family."""
    md = (
        "| Method | Path | Codes |\n"
        "|---|---|---|\n"
        "| GET | `/healthz` | 200 404 |\n"
        "| GET | `/db/<name>` | 200 404 |\n"
    )
    build_project(tmp_path, {
        "srv.py": """
            import json
            from http.server import BaseHTTPRequestHandler

            class _FixtureHandler(BaseHTTPRequestHandler):
                def _send_json(self, code, payload, headers=None):
                    body = json.dumps(payload).encode()
                    self.send_response(code)
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):
                    if self.path == "/healthz":
                        self._send_json(200, {"status": "ok", "epoch": 2})
                    elif self.path.startswith("/db/"):
                        self._send_json(200, {"blob": "x"})
                    else:
                        self._send_json(404, {"error": "no route"})
        """,
        "cli.py": """
            import json
            import urllib.error
            from urllib.request import urlopen

            def fetch_status(base):
                try:
                    with urlopen(base + "/healthz", timeout=2) as r:
                        payload = json.loads(r.read())
                    return payload["epoch"]
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        return None
                    raise
        """,
    }, observability_md=md)
    _, got = findings(tmp_path)
    assert got == [], got


# ----------------------------------- wirecheck: the runtime wire witness


def test_wirecheck_contracts_cover_fleet_handlers():
    """The witness's statically loaded contracts reach every fleet
    handler class, with the repo's declared header rules intact."""
    from gamesmanmpi_tpu.analysis import wirecheck

    contracts = wirecheck.load_repo_contracts()
    assert {"_Handler", "_ControlHandler", "_RegistryHandler",
            "_StatusHandler"} <= set(contracts)
    h = contracts["_Handler"]
    assert h.codes is not None and 503 in h.codes and 304 in h.codes
    assert {"503-retry-after", "etag-cache-control",
            "echo-traceparent"} <= h.rules
    assert "429-retry-after" in contracts["_RegistryHandler"].rules


def test_wirecheck_witness_records_violations():
    """A live handler shedding 503 without Retry-After and emitting an
    uncontracted code is caught by the runtime witness; the scoped
    `witness` raises at exit when asked to check."""
    from gamesmanmpi_tpu.analysis import wirecheck

    class Naughty(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            body = b"{}"
            self.send_response(503)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    was_installed = wirecheck._Installed.active
    contracts = {"Naughty": wirecheck.Contract({200}, {"503-retry-after"})}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), Naughty)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_port}"

    def drive():
        try:
            urllib.request.urlopen(base + "/x", timeout=10)
        except urllib.error.HTTPError as e:
            assert e.code == 503

    try:
        with wirecheck.witness(contracts=contracts, check=False) as wc:
            drive()
            vio = wc.violations()
            assert any("outside the statically extracted set" in v
                       for v in vio), vio
            assert any("Retry-After" in v for v in vio), vio
            assert wc.checked_classes() == ["Naughty"]
            with pytest.raises(wirecheck.WireConformanceError):
                wc.assert_conformant()
        # check=True (the default) turns the violation into a failure
        # at scope exit — the shape conftest uses at session teardown.
        with pytest.raises(wirecheck.WireConformanceError):
            with wirecheck.witness(contracts=contracts):
                drive()
    finally:
        srv.shutdown()
        srv.server_close()
    # The scoped witness restored the prior installation state (it may
    # be nested inside a session-wide GAMESMAN_WIRECHECK=1 install).
    assert wirecheck._Installed.active == was_installed
    assert (BaseHTTPRequestHandler.end_headers
            is wirecheck._end_headers) == was_installed


def test_wirecheck_real_registry_server_conforms(tmp_path):
    """The repo-extracted contracts hold against a live fleet server:
    a real RegistryServer answers 200 and 404 under the witness with
    zero violations — and the class is proven CHECKED, so the clean
    result is coverage, not silence."""
    from gamesmanmpi_tpu.analysis import wirecheck
    from gamesmanmpi_tpu.registry.server import RegistryServer

    srv = RegistryServer(tmp_path / "registry")
    srv.start()
    try:
        with wirecheck.witness() as wc:
            with urllib.request.urlopen(
                    srv.url + "/healthz", timeout=30) as r:
                assert json.loads(r.read())["status"] == "ok"
            try:
                urllib.request.urlopen(srv.url + "/nope", timeout=30)
            except urllib.error.HTTPError as e:
                assert e.code == 404
            assert wc.violations() == []
            assert "_RegistryHandler" in wc.checked_classes()
    finally:
        srv.stop()


# ------------------------------------------------------- SARIF output


def test_cli_sarif_format_round_trip(tmp_path, capsys):
    """--format=sarif mirrors the json findings (id, path, line) in
    SARIF 2.1.0 with unchanged exit semantics."""
    build_project(tmp_path, {"mod.py": """
        import os
        X = os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    rc = lint_main(["--root", str(tmp_path), "--format", "sarif"])
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "gamesman-lint"
    rc2 = lint_main(["--root", str(tmp_path), "--format", "json"])
    plain = json.loads(capsys.readouterr().out)["new"]
    assert rc2 == 1 and plain
    assert [
        (r["ruleId"],
         r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"],
         r["locations"][0]["physicalLocation"]["region"]["startLine"])
        for r in run["results"]
    ] == [(d["id"], d["path"], d["line"]) for d in plain]
    assert all(r["level"] == "error" and r["message"]["text"]
               for r in run["results"])
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} \
        == {r["ruleId"] for r in run["results"]}
    # Accepting the findings into the baseline empties the SARIF log
    # without changing the exit contract.
    assert lint_main(["--root", str(tmp_path), "--update-baseline"]) == 0
    rc3 = lint_main(["--root", str(tmp_path), "--format", "sarif"])
    out3 = json.loads(capsys.readouterr().out)
    assert rc3 == 0 and out3["runs"][0]["results"] == []
