"""gamesman-lint coverage: every checker id proven on a known-bad
fixture (exact id + line), known-good fixtures proven clean, the
suppression/baseline escape hatches round-tripped, and — the tier-1
gate — the real repository linting clean.

Fixture projects are miniature repos built in tmp_path with the same
conventions the runner discovers in the real one: a `pkg/` package,
`docs/CONFIG.md` / `docs/OBSERVABILITY.md` registry docs, and a
`tests/test_resilience.py` chaos matrix. Expected lines are located by
`# MARK` comments rather than hand-counted line numbers, so editing a
fixture cannot silently shift an assertion.
"""

import json
import os
import shutil
import subprocess
import textwrap
import time

import pytest

from gamesmanmpi_tpu.analysis.cli import main as lint_main
from gamesmanmpi_tpu.analysis.diagnostics import (
    Diagnostic,
    fingerprint,
    suppressed_ids,
    write_baseline,
)
from gamesmanmpi_tpu.analysis.runner import run_project

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG_HEADER = "| Env var | Default | Meaning |\n|---|---|---|\n"


def build_project(tmp_path, files, config_md="", observability_md="",
                  chaos=""):
    """Write a miniature project; `files` maps pkg-relative names to
    source text (dedented)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, text in files.items():
        p = pkg / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "CONFIG.md").write_text(config_md)
    (docs / "OBSERVABILITY.md").write_text(observability_md)
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    (tdir / "test_resilience.py").write_text(chaos)
    return tmp_path


def mark_line(tmp_path, rel, mark="MARK"):
    """1-based line of the `# <mark>` comment in a fixture file."""
    text = (tmp_path / rel).read_text()
    for i, line in enumerate(text.splitlines(), 1):
        if f"# {mark}" in line:
            return i
    raise AssertionError(f"no # {mark} in {rel}")


def findings(tmp_path, **kw):
    res = run_project(tmp_path, **kw)
    return res, [(d.id, d.path, d.line) for d in res.new]


# --------------------------------------------------------------- GM1xx: jax


def test_gm101_clock_under_jit(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import time
        import jax

        @jax.jit
        def kernel(x):
            t = time.time()  # MARK
            return x + t
    """})
    _, got = findings(tmp_path)
    assert got == [("GM101", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm102_python_rng_under_jit(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import random
        import jax

        @jax.jit
        def kernel(x):
            r = random.random()  # MARK
            return x * r
    """})
    _, got = findings(tmp_path)
    assert got == [("GM102", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm103_host_sync_of_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def kernel(x):
            y = float(x)  # MARK
            return y
    """})
    _, got = findings(tmp_path)
    assert got == [("GM103", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm103_item_on_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def kernel(x):
            y = x.sum()
            return y.item()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM103", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm104_branch_on_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def kernel(x):
            if x > 0:  # MARK
                return x
            return -x
    """})
    _, got = findings(tmp_path)
    assert got == [("GM104", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm105_numpy_on_tracer(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return np.cumsum(x)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM105", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm106_unhashable_static_default(tmp_path):
    build_project(tmp_path, {"mod.py": """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnums=(1,))
        def kernel(x, opts=[]):  # MARK
            return x
    """})
    _, got = findings(tmp_path)
    assert got == [("GM106", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_jax_taint_propagates_through_local_calls(tmp_path):
    """Impurity inside a helper the jitted function calls is found."""
    build_project(tmp_path, {"mod.py": """
        import jax

        def helper(v):
            return int(v)  # MARK

        @jax.jit
        def kernel(x):
            return helper(x + 1)
    """})
    _, got = findings(tmp_path)
    assert got == [("GM103", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_jax_clean_kernel_passes(tmp_path):
    """Shape reads, jnp math, static-arg branching: all legitimate."""
    build_project(tmp_path, {"mod.py": """
        from functools import partial
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("mode",))
        def kernel(x, mode="fast"):
            n = x.shape[0]
            if mode == "fast":
                return jnp.where(x > 0, x, -x) + n
            return jnp.cumsum(x)
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_jax_ignores_plain_host_functions(tmp_path):
    """The same impurity OUTSIDE any traced root is not a finding."""
    build_project(tmp_path, {"mod.py": """
        import time

        def host_side(x):
            t0 = time.time()
            if x > 0:
                return float(x) + t0
            return -x
    """})
    _, got = findings(tmp_path)
    assert got == []


# -------------------------------------------------------------- GM2xx: locks


def test_gm201_guarded_field_without_lock(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def good(self):
                with self._lock:
                    return len(self._items)

            def bad(self):
                return len(self._items)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM201", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm202_reacquire_nonreentrant(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    with self._lock:  # MARK
                        pass
    """})
    _, got = findings(tmp_path)
    assert got == [("GM202", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm202_deadlock_through_method_call(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def locked_op(self):
                with self._lock:
                    pass

            def bad(self):
                with self._lock:
                    self.locked_op()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM202", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm202_rlock_reacquire_is_fine(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.RLock()

            def fine(self):
                with self._lock:
                    with self._lock:
                        pass
    """})
    _, got = findings(tmp_path)
    assert got == []


def test_gm203_blocking_call_with_lock_held(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM203", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm203_queue_get_with_lock_held(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import queue
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bad(self):
                with self._lock:
                    return self._q.get()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM203", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm204_requires_lock_called_without(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lock

            # requires-lock: _lock
            def bump(self):
                self._n += 1

            def good(self):
                with self._lock:
                    self.bump()

            def bad(self):
                self.bump()  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM204", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_condition_aliases_its_lock(tmp_path):
    """Holding a Condition built over the lock counts as holding it."""
    build_project(tmp_path, {"mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._items = []  # guarded-by: _lock

            def fine(self):
                with self._cond:
                    self._items.append(1)
                    self._cond.wait(0.01)
    """})
    _, got = findings(tmp_path)
    assert got == []


# ---------------------------------------------------------- GM3xx: env vars


def test_gm301_raw_environ_read(tmp_path):
    build_project(
        tmp_path,
        {"mod.py": """
            import os

            def knob():
                return os.environ.get("GAMESMAN_FIXTURE_KNOB", "1")  # MARK
        """},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_KNOB` | 1 | knob |\n",
    )
    _, got = findings(tmp_path)
    assert got == [("GM301", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm302_undocumented_var(tmp_path):
    build_project(tmp_path, {"mod.py": """
        from gamesmanmpi_tpu.utils.env import env_int

        def knob():
            return env_int("GAMESMAN_FIXTURE_SECRET", 3)  # MARK
    """}, config_md=CONFIG_HEADER)
    _, got = findings(tmp_path)
    assert got == [("GM302", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm303_stale_doc_row(tmp_path):
    build_project(
        tmp_path, {"mod.py": "x = 1\n"},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_GHOST` | — | gone |\n",
    )
    _, got = findings(tmp_path)
    assert got == [("GM303", "docs/CONFIG.md", 3)]


def test_gm302_prefix_of_documented_var_still_flagged(tmp_path):
    """Substring matching must not fail open: a var whose name is a
    prefix of a documented one is still undocumented."""
    build_project(
        tmp_path,
        {"mod.py": """
            from gamesmanmpi_tpu.utils.env import env_str

            def knob():
                return env_str("GAMESMAN_FIXTURE", "x")  # MARK
        """},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_ROW` | — | other |\n",
    )
    _, got = findings(tmp_path)
    assert ("GM302", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py")) in got


def test_env_helpers_documented_pass(tmp_path):
    build_project(
        tmp_path,
        {"mod.py": """
            from gamesmanmpi_tpu.utils.env import env_int

            def knob():
                return env_int("GAMESMAN_FIXTURE_KNOB", 1)
        """},
        config_md=CONFIG_HEADER + "| `GAMESMAN_FIXTURE_KNOB` | 1 | knob |\n",
    )
    _, got = findings(tmp_path)
    assert got == []


# ---------------------------------------------------------- GM4xx: metrics


def test_gm401_metric_naming(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.counter("gamesman_things_total").inc()
            reg.counter("gamesman_things")  # MARK
    """}, observability_md="`gamesman_things_total` `gamesman_things`")
    _, got = findings(tmp_path)
    assert got == [("GM401", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm401_prefix_rule(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.gauge("queueDepth")  # MARK
    """}, observability_md="`queueDepth`")
    _, got = findings(tmp_path)
    assert got == [("GM401", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm402_undocumented_metric(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.gauge("gamesman_fixture_depth")  # MARK
    """}, observability_md="nothing here")
    _, got = findings(tmp_path)
    assert got == [("GM402", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm402_prefix_of_documented_metric_still_flagged(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg):
            reg.gauge("gamesman_retries")  # MARK
    """}, observability_md="only `gamesman_retries_total` is documented")
    _, got = findings(tmp_path)
    assert got == [("GM402", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm403_dynamic_metric_name(tmp_path):
    build_project(tmp_path, {"mod.py": """
        def emit(reg, which):
            reg.counter(which)  # MARK
    """})
    _, got = findings(tmp_path)
    assert got == [("GM403", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_module_constant_metric_name_resolves(tmp_path):
    build_project(tmp_path, {"mod.py": """
        DEPTH = "gamesman_fixture_depth"

        def emit(reg):
            reg.gauge(DEPTH)
    """}, observability_md="`gamesman_fixture_depth` is documented")
    _, got = findings(tmp_path)
    assert got == []


# ------------------------------------------------------ GM5xx: fault points


def _faults_registry(points="\"lvl.fwd\": \"forward\","):
    return f"""
        KNOWN_POINTS = {{
            {points}
        }}
    """


def test_gm501_unregistered_fire(tmp_path):
    build_project(tmp_path, {
        "reg.py": _faults_registry(),
        "mod.py": """
            from pkg.reg import fire

            def step():
                fire("lvl.fwd")
                fire("lvl.nope")  # MARK
        """,
    }, chaos="lvl.fwd lvl.nope")
    _, got = findings(tmp_path)
    assert got == [("GM501", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


def test_gm502_never_woven_point(tmp_path):
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",
                "lvl.ghost": "never fired",  # MARK
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
        """,
    }, chaos="lvl.fwd lvl.ghost")
    _, got = findings(tmp_path)
    assert got == [("GM502", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_gm503_duplicate_point(tmp_path):
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",
                "lvl.fwd": "again",  # MARK
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
        """,
    }, chaos="lvl.fwd")
    _, got = findings(tmp_path)
    assert got == [("GM503", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_gm504_no_chaos_coverage(tmp_path):
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",  # MARK
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
        """,
    }, chaos="")
    _, got = findings(tmp_path)
    assert got == [("GM504", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_gm505_dynamic_fire_point(tmp_path):
    build_project(tmp_path, {
        "reg.py": _faults_registry(),
        "mod.py": """
            def step(faults, which):
                faults.fire("lvl.fwd")
                faults.fire(which)  # MARK
        """,
    }, chaos="lvl.fwd")
    _, got = findings(tmp_path)
    assert got == [("GM505", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]


# --------------------------------------------- suppressions + baseline


def test_inline_suppression(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import os

        def knob():
            # deliberate: fixture  # lint: disable=GM301
            return os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert got == []
    assert [d.id for d in res.suppressed] == ["GM301"]


def test_file_level_suppression(tmp_path):
    build_project(tmp_path, {"mod.py": """
        # lint: disable-file=GM301
        import os

        def a():
            return os.environ.get("PATH")

        def b():
            return os.environ.get("HOME")
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert got == []
    assert len(res.suppressed) == 2


def test_suppressed_ids_parsing():
    lines = [
        "# deliberate  # lint: disable=GM301, GM401",
        "x = 1",
    ]
    assert suppressed_ids(lines, 1) == {"GM301", "GM401"}
    # comment-only line above applies to the statement below it
    assert suppressed_ids(lines, 2) == {"GM301", "GM401"}


def test_trailing_suppression_does_not_bleed_to_next_line(tmp_path):
    """A justified disable on line N must not silence a genuinely new
    violation on line N+1."""
    build_project(tmp_path, {"mod.py": """
        import os

        A = os.environ.get("PATH")  # why: fixture  # lint: disable=GM301
        B = os.environ.get("HOME")  # MARK
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert got == [("GM301", "pkg/mod.py", mark_line(tmp_path, "pkg/mod.py"))]
    assert [d.id for d in res.suppressed] == ["GM301"]


def test_baseline_round_trip(tmp_path):
    build_project(tmp_path, {"mod.py": """
        import os

        def knob():
            return os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    res, got = findings(tmp_path)
    assert [g[0] for g in got] == ["GM301"]

    baseline = tmp_path / "lint_baseline.json"
    write_baseline(baseline, res.fingerprints)
    res2, got2 = findings(tmp_path, baseline_path=str(baseline))
    assert got2 == []
    assert [d.id for d in res2.baselined] == ["GM301"]

    # Line-shifting edits must not churn the baseline: fingerprints key
    # on source text, not line numbers.
    mod = tmp_path / "pkg" / "mod.py"
    mod.write_text("# a new leading comment\n" + mod.read_text())
    res3, got3 = findings(tmp_path, baseline_path=str(baseline))
    assert got3 == []
    assert [d.id for d in res3.baselined] == ["GM301"]

    # A genuinely NEW finding still fails against the old baseline.
    mod.write_text(
        mod.read_text()
        + "\ndef knob2():\n    return os.environ.get(\"HOME\")\n"
    )
    _, got4 = findings(tmp_path, baseline_path=str(baseline))
    assert [g[0] for g in got4] == ["GM301"]


def test_fingerprint_ignores_message_wording(tmp_path):
    lines = ["value = os.environ.get('X')"]
    a = Diagnostic("p.py", 1, "GM301", "old wording")
    b = Diagnostic("p.py", 1, "GM301", "new improved wording")
    assert fingerprint(a, lines) == fingerprint(b, lines)


# ------------------------------------------------------------------- runner


def test_gm001_syntax_error(tmp_path):
    build_project(tmp_path, {"mod.py": "def broken(:\n"})
    _, got = findings(tmp_path)
    assert got[0][0] == "GM001" and got[0][1] == "pkg/mod.py"


def test_cli_json_format_and_exit_codes(tmp_path, capsys):
    build_project(tmp_path, {"mod.py": """
        import os
        X = os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    rc = lint_main(["--root", str(tmp_path), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [d["id"] for d in out["new"]] == ["GM301"]

    # --update-baseline accepts the findings; the next run is clean.
    assert lint_main(["--root", str(tmp_path), "--update-baseline"]) == 0
    assert lint_main(["--root", str(tmp_path)]) == 0
    # --no-baseline sees them again.
    assert lint_main(["--root", str(tmp_path), "--no-baseline"]) == 1


def test_missing_target_is_usage_error(tmp_path, capsys):
    build_project(tmp_path, {"mod.py": "x = 1\n"})
    rc = lint_main(["--root", str(tmp_path), "pkg/no_such_file.py"])
    assert rc == 2
    assert "lint target not found" in capsys.readouterr().err


def test_target_outside_root_is_usage_error(tmp_path, capsys):
    build_project(tmp_path, {"mod.py": "x = 1\n"})
    outside = tmp_path.parent / "outside_target.py"
    outside.write_text("x = 1\n")
    rc = lint_main(["--root", str(tmp_path), str(outside)])
    assert rc == 2
    assert "outside --root" in capsys.readouterr().err


def test_update_baseline_refuses_partial_runs(tmp_path, capsys):
    """A pathed run sees a subset of findings; writing that subset back
    would drop every accepted entry outside the scanned paths."""
    build_project(tmp_path, {"mod.py": "x = 1\n"})
    rc = lint_main(["--root", str(tmp_path), "pkg", "--update-baseline"])
    assert rc == 2
    assert "whole-project" in capsys.readouterr().err


def test_gm504_prefix_point_is_not_coverage(tmp_path):
    """'engine.fwd' appearing only inside 'engine.fwd_edges' in the
    chaos matrix is NOT coverage for 'engine.fwd'."""
    build_project(tmp_path, {
        "reg.py": """
            KNOWN_POINTS = {
                "lvl.fwd": "forward",  # MARK
                "lvl.fwd_edges": "edge variant",
            }
        """,
        "mod.py": """
            def step(faults):
                faults.fire("lvl.fwd")
                faults.fire("lvl.fwd_edges")
        """,
    }, chaos="exercises lvl.fwd_edges only")
    _, got = findings(tmp_path)
    assert got == [("GM504", "pkg/reg.py", mark_line(tmp_path, "pkg/reg.py"))]


def test_update_baseline_anchors_at_root(tmp_path, monkeypatch):
    """--no-baseline --update-baseline must write <root>/lint_baseline
    .json, not a file in whatever directory the command ran from."""
    build_project(tmp_path, {"mod.py": """
        import os
        X = os.environ.get("PATH")
    """}, config_md=CONFIG_HEADER)
    elsewhere = tmp_path / "elsewhere"
    elsewhere.mkdir()
    monkeypatch.chdir(elsewhere)
    assert lint_main(
        ["--root", str(tmp_path), "--no-baseline", "--update-baseline"]
    ) == 0
    assert (tmp_path / "lint_baseline.json").exists()
    assert not (elsewhere / "lint_baseline.json").exists()


def test_explicit_paths_restrict_lint_scope(tmp_path):
    build_project(tmp_path, {
        "clean.py": "x = 1\n",
        "dirty.py": """
            import os
            X = os.environ.get("PATH")
        """,
    }, config_md=CONFIG_HEADER)
    _, got = findings(tmp_path, paths=["pkg/clean.py"])
    assert got == []
    _, got = findings(tmp_path, paths=["pkg/dirty.py"])
    assert [g[0] for g in got] == ["GM301"]


# ------------------------------------------------------------- tier-1 gate


def test_repository_lints_clean():
    """THE gate: the real repo must hold zero new findings (baseline
    empty or justified), and the whole run must stay fast enough to sit
    in tier-1 forever."""
    t0 = time.perf_counter()
    res = run_project(
        REPO, baseline_path=os.path.join(REPO, "lint_baseline.json")
    )
    elapsed = time.perf_counter() - t0
    assert res.new == [], "new lint findings:\n" + "\n".join(
        d.format() for d in res.new
    )
    # Suppressions must stay rare and deliberate (each carries its "why"
    # inline); a creeping count means the lint is being routed around.
    assert len(res.suppressed) <= 8, [d.format() for d in res.suppressed]
    assert len(res.project.files) > 50  # discovery actually found the repo
    assert elapsed < 60, f"lint took {elapsed:.1f}s — too slow for tier-1"


def test_repository_passes_ruff():
    """The generic-linter floor ([tool.ruff] in pyproject.toml): runs
    wherever a ruff binary exists; skipped (not failed) on containers
    that don't ship one — gamesman-lint above is the always-on gate."""
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff binary not installed in this container")
    proc = subprocess.run(
        [ruff, "check", "."], cwd=REPO, capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
