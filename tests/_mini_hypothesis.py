"""Drop-in mini property-test runner for boxes without `hypothesis`.

The suite's property tests (test_properties.py, test_pallas_gather.py)
declare laws with hypothesis' @given/@settings/strategies API. The
dependency is in pyproject, but this container image does not ship it
and cannot pip install — which left the two modules as tier-1
COLLECTION ERRORS (the import died before pytest could even skip).

This shim implements the small strategy subset those tests use —
integers / sampled_from / booleans / lists / tuples / data — with
deterministic per-test seeding (crc32 of the test's qualname), so:

* the laws still RUN (50 deterministic examples beats 0 skipped tests),
* runs are reproducible (no flaky seeds in CI),
* when real hypothesis is present it is preferred — the test modules
  fall back here only on ModuleNotFoundError, so richer shrinking and
  example databases return the moment the dependency exists.

Deliberately NOT implemented: shrinking, @example, assume, profiles.
A failing example raises with the drawn arguments in the message —
enough to reproduce (the seed is fixed) without a shrinker.
"""

from __future__ import annotations

import functools
import inspect
import random
import zlib


class _Strategy:
    """A draw rule: example(rng) -> one value."""

    def __init__(self, draw_fn, describe: str):
        self._draw = draw_fn
        self._describe = describe

    def example(self, rng: random.Random):
        return self._draw(rng)

    def __repr__(self):
        return self._describe


class _DataObject:
    """The st.data() handle: mid-test draws from the same rng stream."""

    def __init__(self, rng: random.Random):
        self._rng = rng

    def draw(self, strategy: _Strategy):
        return strategy.example(self._rng)


class strategies:
    """Namespace mirroring `hypothesis.strategies` (the used subset)."""

    @staticmethod
    def integers(min_value=None, max_value=None) -> _Strategy:
        lo = -(2**63) if min_value is None else int(min_value)
        hi = 2**63 - 1 if max_value is None else int(max_value)

        def draw(rng):
            # Bias toward boundaries: hypothesis finds edge bugs by
            # shrinking; without a shrinker, sample the edges outright.
            pick = rng.random()
            if pick < 0.1:
                return lo
            if pick < 0.2:
                return hi
            return rng.randint(lo, hi)

        return _Strategy(draw, f"integers({lo}, {hi})")

    @staticmethod
    def sampled_from(elements) -> _Strategy:
        pool = list(elements)
        return _Strategy(
            lambda rng: pool[rng.randrange(len(pool))],
            f"sampled_from(<{len(pool)}>)",
        )

    @staticmethod
    def booleans() -> _Strategy:
        return _Strategy(lambda rng: rng.random() < 0.5, "booleans()")

    @staticmethod
    def lists(elements: _Strategy, min_size: int = 0,
              max_size: int = 10) -> _Strategy:
        def draw(rng):
            size = rng.randint(min_size, max_size)
            return [elements.example(rng) for _ in range(size)]

        return _Strategy(draw, f"lists({elements!r})")

    @staticmethod
    def tuples(*parts: _Strategy) -> _Strategy:
        return _Strategy(
            lambda rng: tuple(p.example(rng) for p in parts),
            f"tuples(<{len(parts)}>)",
        )

    @staticmethod
    def data() -> _Strategy:
        # example() is handed the rng by the runner; the DataObject
        # draws from the SAME stream so a test's whole example sequence
        # replays from one seed.
        return _Strategy(lambda rng: _DataObject(rng), "data()")


st = strategies


def settings(**config):
    """Records max_examples etc. on the function; order-agnostic with
    @given (hypothesis allows either stacking order)."""

    def deco(fn):
        fn._mini_settings = dict(config)
        return fn

    return deco


def given(**named_strategies):
    """Run the test once per generated example (max_examples, default
    50), deterministically seeded per test so failures reproduce."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            conf = (
                getattr(wrapper, "_mini_settings", None)
                or getattr(fn, "_mini_settings", None)
                or {}
            )
            examples = int(conf.get("max_examples", 50))
            rng = random.Random(
                zlib.crc32(fn.__qualname__.encode()) & 0xFFFFFFFF
            )
            for i in range(examples):
                drawn = {
                    name: strat.example(rng)
                    for name, strat in named_strategies.items()
                }
                try:
                    fn(*args, **drawn, **kwargs)
                except Exception as e:
                    raise AssertionError(
                        f"falsifying example #{i + 1} "
                        f"(mini-hypothesis, seeded): "
                        + ", ".join(
                            f"{k}={v!r}" for k, v in drawn.items()
                            if not isinstance(v, _DataObject)
                        )
                    ) from e

        # Hide the generated parameters from pytest's fixture
        # resolution: functools.wraps leaves __wrapped__ pointing at the
        # original function, whose (states, shards, ...) parameters
        # pytest would otherwise demand as fixtures. The surviving
        # signature is whatever @given did NOT fill (real fixtures keep
        # working in mixed tests).
        del wrapper.__wrapped__
        original = inspect.signature(fn)
        wrapper.__signature__ = original.replace(parameters=[
            p for name, p in original.parameters.items()
            if name not in named_strategies
        ])
        return wrapper

    return deco
