"""parallel/: shard-count invariance on a faked 8-device CPU mesh.

The TPU analog of the reference's `mpirun -np 1` vs `-np 8` runs
(SURVEY.md §4.2 axis 2): identical full tables regardless of shard count.
"""

import numpy as np
import pytest

import jax

from gamesmanmpi_tpu.core.values import TIE
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.parallel import ShardedSolver
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.solve.engine import SolverError

from helpers import full_table

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (fake) devices"
)


@pytest.mark.parametrize(
    "spec",
    [
        "tictactoe",
        "subtract:total=21,moves=1-2-3",
        "nim:heaps=3-4-5",
        "connect4:w=4,h=4",
        # chomp: the widest-max_moves generic-path game (max_moves=w*h-1) —
        # the routing-capacity stress case (VERDICT r2 weak #4).
        "chomp:w=3,h=3",
    ],
)
def test_shard_count_invariance(spec):
    single = Solver(get_game(spec), paranoid=True).solve()
    for S in (2, 8):
        sharded = ShardedSolver(
            get_game(spec), num_shards=S, paranoid=True
        ).solve()
        assert sharded.value == single.value
        assert sharded.remoteness == single.remoteness
        assert sharded.num_positions == single.num_positions
        assert full_table(sharded) == full_table(single)


def test_sharded_tictactoe_answer():
    result = ShardedSolver(get_game("tictactoe"), num_shards=8).solve()
    assert result.value == TIE
    assert result.remoteness == 9
    assert result.num_positions == 5478


@pytest.mark.parametrize("spec", ["tictactoe", "nim:heaps=2-3-4"])
def test_route_capacity_spill_path(spec):
    """A deterministically-undersized route capacity must take the overflow
    retry loop (SURVEY.md §5.8 "capacity counters + host-side spill loop")
    and still produce the right tables — covering both the fast (tictactoe)
    and generic (nim) paths, forward and backward."""
    single = Solver(get_game(spec), paranoid=True).solve()
    solver = ShardedSolver(get_game(spec), num_shards=8, paranoid=True)
    # Force every first routing attempt to overflow: capacity 1 is below any
    # real per-destination load past the first level.
    solver._initial_route_cap = lambda cap: 1
    result = solver.solve()
    # The retry loop must actually have fired — if the spill path is deleted,
    # this assertion (not just correctness) fails.
    assert solver.spill_retries > 0
    assert result.value == single.value
    assert result.remoteness == single.remoteness
    assert full_table(result) == full_table(single)


def test_route_headroom_knob(monkeypatch):
    """GAMESMAN_ROUTE_HEADROOM scales the first-try route capacity (the
    peak-memory lever on fake meshes — the r5 8-shard 5x6 witness was
    OOM-killed under the 2x default); tight headroom must still solve
    exactly, leaning on the exact overflow retry."""
    single = Solver(get_game("tictactoe")).solve()
    # The knob's whole point is to be exported in memory-constrained
    # shells; don't let an ambient setting fail the default assertion.
    monkeypatch.delenv("GAMESMAN_ROUTE_HEADROOM", raising=False)
    default = ShardedSolver(get_game("tictactoe"), num_shards=4)
    assert default.route_headroom == 2.0
    monkeypatch.setenv("GAMESMAN_ROUTE_HEADROOM", "1.0")
    lean = ShardedSolver(get_game("tictactoe"), num_shards=4)
    assert lean.route_headroom == 1.0
    assert (lean._initial_route_cap(4096)
            <= default._initial_route_cap(4096) // 2)
    r = lean.solve()
    assert (r.value, r.remoteness) == (single.value, single.remoteness)
    monkeypatch.setenv("GAMESMAN_ROUTE_HEADROOM", "zero")
    with pytest.raises(SolverError, match="ROUTE_HEADROOM"):
        ShardedSolver(get_game("tictactoe"), num_shards=4)
    monkeypatch.setenv("GAMESMAN_ROUTE_HEADROOM", "-1")
    with pytest.raises(SolverError, match="ROUTE_HEADROOM"):
        ShardedSolver(get_game("tictactoe"), num_shards=4)
    monkeypatch.setenv("GAMESMAN_ROUTE_HEADROOM", "nan")
    with pytest.raises(SolverError, match="finite"):
        ShardedSolver(get_game("tictactoe"), num_shards=4)


@pytest.mark.parametrize("mode", ["edges", "lookup"])
def test_sharded_blocked_backward_parity(mode, monkeypatch):
    """Column-blocked owner-routed backward: same tables, bounded
    temporaries. Parametrized over GAMESMAN_BACKWARD so the lookup join's
    blocking keeps coverage now that edges is the default (the edges
    resolve is gather-only and ignores the resolve-side blocking)."""
    monkeypatch.setenv("GAMESMAN_BACKWARD", mode)
    single = Solver(get_game("tictactoe")).solve()
    solver = ShardedSolver(get_game("tictactoe"), num_shards=8, paranoid=True)
    solver.backward_block = 256
    result = solver.solve()
    assert full_table(result) == full_table(single)


def test_sharded_store_tables_false():
    """Big-run mode: nothing leaves the devices except the psum-replicated
    root answer and the per-shard counters (multi-host safe)."""
    full = ShardedSolver(get_game("tictactoe"), num_shards=4).solve()
    lean = ShardedSolver(
        get_game("tictactoe"), num_shards=4, store_tables=False
    ).solve()
    assert (lean.value, lean.remoteness) == (full.value, full.remoteness)
    assert lean.num_positions == full.num_positions
    assert len(lean.levels) == 0  # no host tables at all


def test_sharded_root_answer_via_kernel_matches_table():
    """The replicated root-lookup kernel and the materialized root table
    must agree (store_tables=True computes both)."""
    result = ShardedSolver(get_game("nim:heaps=2-3-4"), num_shards=4).solve()
    root_level = min(result.levels)
    table = result.levels[root_level]
    import numpy as np

    i = int(np.searchsorted(table.states, result.game.initial_state()))
    assert (result.value, result.remoteness) == (
        int(table.values[i]),
        int(table.remoteness[i]),
    )


@pytest.mark.parametrize("spec", ["tictactoe", "nim:heaps=3-4-5"])
def test_sharded_window_streaming_parity(spec):
    """Window levels wider than window_block must spill to host and stream
    back through HBM in blocks (the 7x6 capacity mechanism) — with
    identical tables and the streaming path demonstrably taken, on both
    the fast (tictactoe) and generic multi-jump (nim) paths."""
    single = Solver(get_game(spec), paranoid=True).solve()
    solver = ShardedSolver(get_game(spec), num_shards=8, paranoid=True)
    # Below even the smallest bucket (min_bucket=256): every window spills
    # and streams in >=2 blocks.
    solver.window_block = 128
    result = solver.solve()
    assert solver.window_stream_blocks > 0
    assert result.value == single.value
    assert result.remoteness == single.remoteness
    assert full_table(result) == full_table(single)


@pytest.mark.parametrize("mode", ["edges", "lookup"])
def test_sharded_window_streaming_composes_with_blocked_backward(
        mode, monkeypatch):
    """Both blockings at once: resolving side in column blocks AND window
    side streamed — the full 7x6 memory shape, in both backward modes
    (edges streams only the window cells; lookup also blocks the
    resolving side)."""
    monkeypatch.setenv("GAMESMAN_BACKWARD", mode)
    single = Solver(get_game("tictactoe")).solve()
    solver = ShardedSolver(get_game("tictactoe"), num_shards=8, paranoid=True)
    solver.window_block = 128
    solver.backward_block = 256
    result = solver.solve()
    assert solver.window_stream_blocks > 0
    assert full_table(result) == full_table(single)


def test_multihost_host_spill_snapshot_owner_writes(monkeypatch):
    """Host-resident level under multi-process execution (ISSUE 6): every
    rank holds the full copy (gather collective), so write-ownership
    follows the mesh — the rank owning the shard's device writes its
    file, every other rank defers. Previously this path refused outright;
    now one writer per shard, no racy duplicate snapshot files."""
    import numpy as np

    from gamesmanmpi_tpu.parallel.sharded import _SLevel

    solver = ShardedSolver(get_game("nim:heaps=2-3"), num_shards=2)
    rec = _SLevel(
        np.array([1, 0], dtype=np.int64),
        None,
        [np.array([3], dtype=np.uint32), np.empty(0, dtype=np.uint32)],
    )
    solver.num_processes = 2
    # This single-host mesh owns every shard (process_index 0 on all
    # devices): the owning rank writes the rows...
    assert solver._shard_ranks() == [0, 0]
    assert solver.rank == 0
    assert list(solver._shard_rows(rec, 0)) == [3]
    # ...and a non-owning rank defers instead of writing a duplicate.
    solver.rank = 1
    assert solver._shard_rows(rec, 0) is None


def test_multihost_manifest_seal_gated_to_process_zero(monkeypatch, tmp_path):
    """Non-zero processes write their shard files but must not seal the
    manifest; the barrier must run before sealing either way."""
    import numpy as np
    import jax

    from gamesmanmpi_tpu.parallel.sharded import _SLevel, _pad_shards
    from gamesmanmpi_tpu.parallel import sharded as sh
    from gamesmanmpi_tpu.utils import LevelCheckpointer

    solver = ShardedSolver(
        get_game("nim:heaps=2-3"), num_shards=2,
        checkpointer=LevelCheckpointer(str(tmp_path / "d")),
    )
    shards = [np.array([3], dtype=np.uint32), np.empty(0, dtype=np.uint32)]
    rec = _SLevel(
        np.array([1, 0], dtype=np.int64),
        jax.device_put(_pad_shards(shards, 256), solver._sharding),
        None,
    )
    barriers = []
    monkeypatch.setattr(
        type(solver), "_sync_processes",
        staticmethod(lambda tag: barriers.append(tag)),
    )
    monkeypatch.setattr(sh.jax, "process_index", lambda: 1)
    solver._checkpoint_frontier_shards({0: rec})
    assert barriers  # barrier ran before the (skipped) seal
    assert solver.checkpointer.load_manifest().get("frontier_shards") is None

    monkeypatch.setattr(sh.jax, "process_index", lambda: 0)
    solver._checkpoint_frontier_shards({0: rec})
    assert solver.checkpointer.load_manifest().get("frontier_shards") == 2
