"""obs/: metrics registry, span tracing, heartbeat, CLI artifacts.

Acceptance axes (ISSUE 2): concurrent registry increments are exact,
histogram boundaries are inclusive, Prometheus rendering survives a
strict parser (golden file for the exact text), span nesting/timing is
deterministic under a fake clock, and a real tictactoe solve driven
through the CLI with --metrics-out + --trace-events + --checkpoint-dir
produces artifacts that parse and whose span names cover the forward /
dedup / backward / checkpoint phases while the per-level JSONL stays
bench-compatible.
"""

import json
import threading

import pytest

from gamesmanmpi_tpu.obs import (
    Heartbeat,
    MetricsRegistry,
    Span,
    TraceEventSink,
    set_trace_sink,
    trace_span,
)
from gamesmanmpi_tpu.obs.heartbeat import rss_bytes

from helpers import REPO, load_module, parse_prometheus_text


# ------------------------------------------------------------- registry


def test_counter_concurrent_increments_exact():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "concurrent counter")
    n_threads, per_thread = 8, 2000

    def worker():
        for _ in range(per_thread):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread


def test_histogram_concurrent_observes_exact():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "concurrent histogram", buckets=(1, 10))

    def worker(v):
        for _ in range(1000):
            h.observe(v)

    threads = [
        threading.Thread(target=worker, args=(v,)) for v in (0.5, 5, 50)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 3000
    assert h.sum == pytest.approx(0.5 * 1000 + 5 * 1000 + 50 * 1000)
    snap = reg.snapshot()["h_seconds"]["values"][0]
    assert snap["buckets"] == {"1": 1000, "10": 1000, "+Inf": 1000}


def test_histogram_bucket_boundaries_inclusive():
    """le is INCLUSIVE: a sample equal to a boundary lands in that
    bucket (the Prometheus contract)."""
    reg = MetricsRegistry()
    h = reg.histogram("b_seconds", "", buckets=(0.1, 1.0))
    for v in (0.1, 1.0, 1.0000001):
        h.observe(v)
    snap = reg.snapshot()["b_seconds"]["values"][0]
    assert snap["buckets"] == {"0.1": 1, "1": 1, "+Inf": 1}
    # Rendered cumulatively.
    fams = parse_prometheus_text(reg.render_prometheus())
    samples = {
        (n, lb.get("le")): v for n, lb, v in fams["b_seconds"]["samples"]
    }
    assert samples[("b_seconds_bucket", "0.1")] == 1
    assert samples[("b_seconds_bucket", "1")] == 2
    assert samples[("b_seconds_bucket", "+Inf")] == 3
    assert samples[("b_seconds_count", None)] == 3


def test_registry_kind_conflicts_and_validation():
    reg = MetricsRegistry()
    reg.counter("x_total", "")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total", "")
    with pytest.raises(ValueError, match="only go up"):
        reg.counter("x_total", "").inc(-1)
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("9bad", "")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad-name", "")


def test_prometheus_rendering_golden():
    """Exact text, byte for byte: the exposition format is a wire
    protocol, and an accidental reordering or escape change is a break
    even when a lenient parser still accepts it."""
    reg = MetricsRegistry()
    reg.counter("req_total", 'requests with "quotes" and \\slash',
                method="post", code="200").inc(3)
    reg.counter("req_total", "", method="get", code="200").inc()
    reg.gauge("temp_celsius", "ambient\nmultiline").set(21.5)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.25, 1))
    h.observe(0.1)
    h.observe(3)
    golden = (
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.25"} 1\n'
        'lat_seconds_bucket{le="1"} 1\n'
        'lat_seconds_bucket{le="+Inf"} 2\n'
        "lat_seconds_sum 3.1\n"
        "lat_seconds_count 2\n"
        # HELP escapes only backslash and newline (label VALUES also
        # escape quotes; help text does not — the v0.0.4 rule).
        '# HELP req_total requests with "quotes" and \\\\slash\n'
        "# TYPE req_total counter\n"
        'req_total{code="200",method="get"} 1\n'
        'req_total{code="200",method="post"} 3\n'
        "# HELP temp_celsius ambient\\nmultiline\n"
        "# TYPE temp_celsius gauge\n"
        "temp_celsius 21.5\n"
    )
    assert reg.render_prometheus() == golden
    # And it round-trips through the strict parser.
    fams = parse_prometheus_text(golden)
    assert fams["req_total"]["type"] == "counter"
    assert fams["lat_seconds"]["type"] == "histogram"


def test_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("c_total", "help", a="1").inc(2)
    reg.gauge("g", "").set(7)
    snap = reg.snapshot()
    assert snap["c_total"]["type"] == "counter"
    assert snap["c_total"]["values"] == [{"labels": {"a": "1"}, "value": 2.0}]
    assert snap["g"]["values"][0]["value"] == 7.0
    json.dumps(snap)  # JSON-serializable as-is (--metrics-out contract)


def test_registered_instruments_export_zero_before_first_write():
    """A scrape between registration and first write must show 0, not
    'no data' — an error-rate alert cannot tell an unseeded counter from
    a counter reset."""
    reg = MetricsRegistry()
    reg.counter("errs_total", "never incremented")
    reg.histogram("lat_seconds", "never observed", buckets=(1,))
    snap = reg.snapshot()
    assert snap["errs_total"]["values"] == [{"labels": {}, "value": 0.0}]
    assert snap["lat_seconds"]["values"][0]["count"] == 0
    fams = parse_prometheus_text(reg.render_prometheus())
    assert ("errs_total", {}, 0.0) in fams["errs_total"]["samples"]
    assert ("lat_seconds_count", {}, 0.0) in fams["lat_seconds"]["samples"]


def test_parser_rejects_sample_without_type_line():
    with pytest.raises(ValueError, match="TYPE"):
        parse_prometheus_text("# HELP x help only\nx 1\n")
    with pytest.raises(ValueError, match="TYPE"):
        parse_prometheus_text("y 1\n")


# ----------------------------------------------------------------- spans


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class _ListLogger:
    def __init__(self):
        self.records = []

    def log(self, rec):
        self.records.append(rec)


def test_span_timing_and_jsonl_record_fake_clock():
    clock = _FakeClock()
    reg = MetricsRegistry()
    logger = _ListLogger()
    sp = Span("forward", logger=logger, registry=reg, clock=clock, level=3)
    clock.t += 2.5
    sp.end(frontier=10, children=40, bytes_sorted=320)
    assert sp.secs == pytest.approx(2.5)
    assert logger.records == [
        {"phase": "forward", "level": 3, "frontier": 10, "children": 40,
         "bytes_sorted": 320, "secs": pytest.approx(2.5)}
    ]
    # Idempotent end: a with-block exit after explicit end is a no-op.
    clock.t += 50
    assert sp.end() == pytest.approx(2.5)
    assert len(logger.records) == 1
    # Wall time landed in the registry histogram; integer payloads in the
    # payload counters (level excluded — it is a coordinate, not a size).
    snap = reg.snapshot()
    spanrow = snap["gamesman_span_seconds"]["values"][0]
    assert spanrow["labels"] == {"span": "forward"}
    assert spanrow["sum"] == pytest.approx(2.5)
    payloads = {
        tuple(sorted(v["labels"].items())): v["value"]
        for v in snap["gamesman_span_payload_total"]["values"]
    }
    assert payloads[(("key", "children"), ("span", "forward"))] == 40
    assert (("key", "level"), ("span", "forward")) not in payloads


def test_span_nesting_trace_events_fake_clock():
    clock = _FakeClock()
    reg = MetricsRegistry()
    sink = TraceEventSink()
    prev = set_trace_sink(sink)
    try:
        with trace_span("outer", registry=reg, clock=clock, level=1):
            clock.t += 1.0
            with trace_span("inner", registry=reg, clock=clock):
                clock.t += 0.25
            clock.t += 1.0
    finally:
        set_trace_sink(prev)
    events = {e["name"]: e for e in sink.to_dict()["traceEvents"]}
    assert events.keys() == {"outer", "inner"}
    outer, inner = events["outer"], events["inner"]
    assert outer["dur"] == pytest.approx(2.25e6)
    assert inner["dur"] == pytest.approx(0.25e6)
    # The inner span nests strictly inside the outer one on the
    # timeline — what makes the Chrome/Perfetto flame view truthful.
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert outer["ph"] == inner["ph"] == "X"
    assert outer["tid"] == inner["tid"]
    assert outer["args"]["level"] == 1


def test_span_records_time_on_exception():
    clock = _FakeClock()
    reg = MetricsRegistry()
    with pytest.raises(RuntimeError):
        with trace_span("doomed", registry=reg, clock=clock):
            clock.t += 4.0
            raise RuntimeError("mid-phase death")
    row = reg.snapshot()["gamesman_span_seconds"]["values"][0]
    assert row["labels"] == {"span": "doomed"}
    assert row["sum"] == pytest.approx(4.0)


def test_trace_sink_dump_is_valid_json(tmp_path):
    sink = TraceEventSink()
    sink.add_complete("phase", 1.0, 0.5, 7, {"n": 3, "obj": object()})
    out = tmp_path / "trace.json"
    sink.dump(out)
    data = json.loads(out.read_text())
    (ev,) = data["traceEvents"]
    assert ev["name"] == "phase" and ev["dur"] == 0.5e6
    assert isinstance(ev["args"]["obj"], str)  # exotic values stringified


# ------------------------------------------------------------- heartbeat


def test_heartbeat_beats_and_stops():
    reg = MetricsRegistry()
    logger = _ListLogger()
    seen = []

    def progress():
        seen.append(1)
        return {"phase": "forward", "level": 4}

    hb = Heartbeat(0.01, progress=progress, logger=logger, registry=reg)
    with hb:
        while hb.beats < 3:
            threading.Event().wait(0.005)
    assert not hb._thread  # joined
    recs = logger.records
    assert len(recs) >= 3
    # Progress nests: its own "phase" key must not let a heartbeat
    # masquerade as a per-level record in the shared stream.
    assert recs[0]["phase"] == "heartbeat"
    assert recs[0]["progress"] == {"phase": "forward", "level": 4}
    assert recs[0]["rss_bytes"] > 0
    assert recs[0]["uptime_secs"] >= 0
    snap = reg.snapshot()
    assert snap["gamesman_heartbeat_beats_total"]["values"][0]["value"] >= 3
    assert snap["gamesman_rss_bytes"]["values"][0]["value"] > 0


def test_heartbeat_survives_broken_progress():
    logger = _ListLogger()
    hb = Heartbeat(
        1, progress=lambda: 1 / 0, logger=logger, registry=MetricsRegistry()
    )
    rec = hb.beat()  # direct beat: no thread needed
    assert rec["phase"] == "heartbeat"  # ZeroDivisionError swallowed


def test_heartbeat_rejects_nonpositive_interval():
    with pytest.raises(ValueError):
        Heartbeat(0)


def test_rss_bytes_reports_something():
    assert rss_bytes() > 1 << 20  # a Python + jax process is > 1 MB


def test_heartbeat_rss_unavailable_emits_null(monkeypatch):
    """A /proc-less (or masked-/proc) host degrades the rss field to
    null — one beat, one null, no traceback, gauge untouched (the
    ISSUE 15 heartbeat-degradation satellite)."""
    import builtins
    import resource

    real_open = builtins.open

    def fake_open(path, *a, **kw):
        if str(path) == "/proc/self/statm":
            raise OSError("masked /proc")
        return real_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", fake_open)
    monkeypatch.setattr(
        resource, "getrusage",
        lambda *_: (_ for _ in ()).throw(OSError("no rusage")),
    )
    assert rss_bytes() is None
    reg = MetricsRegistry()
    logger = _ListLogger()
    hb = Heartbeat(1, logger=logger, registry=reg)
    rec = hb.beat()  # must not raise
    assert rec["rss_bytes"] is None
    assert json.loads(json.dumps(rec))["rss_bytes"] is None  # JSON null
    assert "gamesman_rss_bytes" not in reg.snapshot()
    # The beat still counted and still logged.
    snap = reg.snapshot()
    assert snap["gamesman_heartbeat_beats_total"]["values"][0]["value"] == 1
    assert logger.records[0]["rss_bytes"] is None


def test_solver_heartbeat_integration():
    """Solver(heartbeat_secs=...) emits heartbeat records carrying the
    solver's live progress into the shared JSONL stream."""
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.solve import Solver

    logger = _ListLogger()
    Solver(
        get_game("tictactoe"), logger=logger, heartbeat_secs=0.01
    ).solve()
    beats = [r for r in logger.records if r["phase"] == "heartbeat"]
    assert beats, "no heartbeat records in a multi-interval solve"
    assert any("level" in b.get("progress", {}) for b in beats)
    assert all(b["rss_bytes"] > 0 for b in beats)
    # The per-level stream is intact alongside the heartbeats.
    phases = {r["phase"] for r in logger.records}
    assert {"forward", "backward", "done"} <= phases


# ------------------------------------------------------------ JsonlLogger


def test_jsonl_logger_close_is_durable_and_reentrant(tmp_path):
    from gamesmanmpi_tpu.utils.metrics import JsonlLogger, TeeLogger

    path = tmp_path / "m.jsonl"
    logger = JsonlLogger(str(path))
    logger.log({"phase": "forward", "level": 0})
    logger.close()
    logger.close()  # double-close tolerated
    # TeeLogger teardown after an explicit close (the abort path where
    # both the finally and the context manager fire) is also safe.
    tee = TeeLogger(JsonlLogger(str(path)))
    tee.log({"phase": "backward", "level": 0})
    tee.close()
    tee.close()
    records = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["phase"] for r in records] == ["forward", "backward"]


# ----------------------------------------------- CLI artifacts (smoke tier)


@pytest.mark.smoke
def test_cli_solve_artifacts_parse_and_cover_phases(tmp_path, capsys):
    """The acceptance run: a tictactoe solve with --metrics-out +
    --trace-events (+ --jsonl + --checkpoint-dir) must leave three
    parseable artifacts; the trace's span names must cover the forward,
    dedup, backward, and checkpoint phases; the JSONL must still carry
    the per-level schema bench.py and obs_report consume."""
    from gamesmanmpi_tpu.cli import main as cli_main

    trace = tmp_path / "trace.json"
    metrics = tmp_path / "metrics.json"
    jsonl = tmp_path / "m.jsonl"
    rc = cli_main([
        "tictactoe",
        "--trace-events", str(trace),
        "--metrics-out", str(metrics),
        "--jsonl", str(jsonl),
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert rc == 0
    assert "value: TIE" in capsys.readouterr().out

    # 1. Chrome trace: valid JSON, complete events, phase coverage.
    data = json.loads(trace.read_text())
    events = data["traceEvents"]
    assert events and all(e["ph"] == "X" for e in events)
    names = {e["name"] for e in events}
    assert {"forward", "dedup", "backward", "checkpoint"} <= names
    assert all(e["dur"] >= 0 and "ts" in e for e in events)

    # 2. Registry snapshot: valid JSON with the span histograms.
    snap = json.loads(metrics.read_text())
    spans = snap["gamesman_span_seconds"]
    assert spans["type"] == "histogram"
    span_labels = {v["labels"]["span"] for v in spans["values"]}
    assert {"forward", "dedup", "backward", "checkpoint"} <= span_labels
    assert "gamesman_solve_positions_total" in snap

    # 3. Per-level JSONL: unchanged schema.
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    fwd = [r for r in records if r["phase"] == "forward"]
    bwd = [r for r in records if r["phase"] == "backward"]
    done = [r for r in records if r["phase"] == "done"]
    assert fwd and bwd and len(done) == 1
    assert {"level", "frontier", "children", "bytes_sorted", "secs"} <= set(
        fwd[0]
    )
    assert {"level", "n", "resumed", "bytes_sorted", "secs"} <= set(bwd[0])
    assert done[0]["positions"] == 5478

    # 4. obs_report folds the stream into a per-level table.
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    table = obs_report.report(records)
    assert "TOTAL" in table and "5478" in table
    rows = obs_report.summarize_levels(records)
    assert sum(r["positions"] for r in rows) == 5478
    assert all(r["bwd_secs"] > 0 for r in rows)


@pytest.mark.smoke
def test_obs_report_cli(tmp_path, capsys):
    jsonl = tmp_path / "m.jsonl"
    jsonl.write_text(
        json.dumps({"phase": "forward", "level": 0, "frontier": 1,
                    "children": 9, "bytes_sorted": 72, "secs": 0.5}) + "\n"
        + json.dumps({"phase": "backward", "level": 0, "n": 1,
                      "resumed": False, "bytes_sorted": 0,
                      "bytes_gathered": 8, "secs": 0.25}) + "\n"
        + "{torn line\n"
        + json.dumps({"phase": "done", "game": "x", "positions": 10,
                      "positions_per_sec": 13.3}) + "\n"
    )
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    assert obs_report.main([str(jsonl)]) == 0
    out = capsys.readouterr().out
    assert "TOTAL" in out
    assert "done: game=x positions=10" in out
    assert obs_report.main([str(tmp_path / "missing.jsonl")]) == 2


def test_obs_report_json_output(tmp_path, capsys):
    """--json: the machine-readable report (per-level table, totals,
    campaign summary) bench_compare/CI consume without screen-scraping
    (the ISSUE 15 satellite)."""
    jsonl = tmp_path / "m.jsonl"
    jsonl.write_text(
        json.dumps({"phase": "forward", "level": 0, "frontier": 4,
                    "children": 9, "bytes_sorted": 72, "secs": 0.5}) + "\n"
        + json.dumps({"phase": "backward", "level": 0, "n": 4,
                      "bytes_sorted": 0, "bytes_gathered": 8,
                      "secs": 0.25}) + "\n"
        + json.dumps({"phase": "campaign_attempt", "attempt": 1,
                      "cause": "killed", "wall_secs": 2.0,
                      "resume_level": None}) + "\n"
        + json.dumps({"phase": "campaign_attempt", "attempt": 2,
                      "cause": "complete", "wall_secs": 1.0,
                      "resume_level": 3}) + "\n"
        + json.dumps({"phase": "campaign_done", "attempts": 2,
                      "wall_secs": 3.5}) + "\n"
        + json.dumps({"phase": "serve_batch", "worker": 0,
                      "requests": 2, "batch_size": 3,
                      "secs": 0.01}) + "\n"
        + json.dumps({"phase": "done", "game": "x", "positions": 4,
                      "positions_per_sec": 8.0}) + "\n"
    )
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    assert obs_report.main([str(jsonl), "--json"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got["levels"][0]["level"] == 0
    assert got["levels"][0]["positions"] == 4
    assert got["totals"]["positions"] == 4
    assert got["totals"]["bytes_sorted"] == 72
    assert got["done"][0]["game"] == "x"
    assert got["campaign"]["attempts"] == 2
    assert got["campaign"]["ending"]["state"] == "solved"
    assert got["campaign"]["causes"] == {"killed": 1, "complete": 1}
    assert got["campaign"]["time_lost_restarts_secs"] == 2.0
    assert got["serving"][0]["worker"] == 0
    assert got["serving"][0]["queries"] == 3
    # The text report over the same records is unchanged in spirit.
    assert obs_report.main([str(jsonl)]) == 0
    assert "campaign: attempts=2" in capsys.readouterr().out


def test_obs_report_compression_and_cache_columns(tmp_path, capsys):
    """ISSUE 9 satellites: a compressed export's per-level
    raw/stored_bytes fold into one whole-DB ratio line, and serve_batch
    records carrying db_cache_* counters grow per-worker hit-rate
    columns (cumulative counters: the largest total wins, so
    interleaved streams cannot double-count)."""
    jsonl = tmp_path / "m.jsonl"
    jsonl.write_text("\n".join(json.dumps(r) for r in [
        {"phase": "export_db", "level": 0, "n": 10,
         "raw_bytes": 1200, "stored_bytes": 300},
        {"phase": "export_db", "level": 1, "n": 20,
         "raw_bytes": 2400, "stored_bytes": 600},
        {"phase": "serve_batch", "batch_size": 8, "requests": 2,
         "secs": 0.01, "worker": 0, "db_cache_hits": 5,
         "db_cache_misses": 5},
        {"phase": "serve_batch", "batch_size": 8, "requests": 2,
         "secs": 0.01, "worker": 0, "db_cache_hits": 70,
         "db_cache_misses": 30},
        {"phase": "serve_batch", "batch_size": 4, "requests": 1,
         "secs": 0.01, "worker": 1},
        # Worker 2 serves TWO compressed routes: each keeps its own
        # cache figures (the cold route must not vanish behind the
        # busy one).
        {"phase": "serve_batch", "batch_size": 8, "requests": 2,
         "secs": 0.01, "worker": 2, "db": "busy",
         "db_cache_hits": 900, "db_cache_misses": 100},
        {"phase": "serve_batch", "batch_size": 8, "requests": 2,
         "secs": 0.01, "worker": 2, "db": "cold",
         "db_cache_hits": 1, "db_cache_misses": 9},
    ]) + "\n")
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    assert obs_report.main([str(jsonl)]) == 0
    out = capsys.readouterr().out
    assert "export_db: levels=2" in out
    assert "ratio=4.00x" in out
    # Worker 0: final cumulative counters, not a sum over records.
    assert "db_cache_hits=70 db_cache_misses=30" in out
    assert "db_cache_hit_rate=0.700" in out
    # Worker 1 (v1 route, no cache): line present, no cache columns.
    assert "serve[worker 1]: batches=1" in out
    w1_line = next(l for l in out.splitlines() if "worker 1" in l)
    assert "db_cache" not in w1_line
    # Worker 2 (two compressed routes): per-route qualified columns.
    w2_line = next(l for l in out.splitlines() if "worker 2" in l)
    assert "db_cache_hit_rate[busy]=0.900" in w2_line
    assert "db_cache_hit_rate[cold]=0.100" in w2_line


def test_obs_report_campaign_summary(tmp_path, capsys):
    """ISSUE 12 satellite: a campaign.jsonl ledger folds into one
    campaign line — attempts, causes, resume levels, wall-clock lost to
    restarts (failed attempts only) + backoff, GC reclamation — and the
    ledger records stay out of the aux 'other records' noise."""
    jsonl = tmp_path / "campaign.jsonl"
    jsonl.write_text("\n".join(json.dumps(r) for r in [
        {"phase": "campaign_start", "solver_args": ["ttt"],
         "processes": 1, "max_attempts": 8},
        {"phase": "campaign_attempt", "attempt": 1, "cause": "killed",
         "rcs": {"0": 77}, "wall_secs": 4.0, "resume_level": None,
         "progressed": True},
        {"phase": "campaign_backoff", "secs": 0.5},
        {"phase": "campaign_gc", "reason": "enospc", "freed_files": 3,
         "freed_bytes": 2_000_000, "kinds": {"edges": 2_000_000}},
        {"phase": "campaign_attempt", "attempt": 2, "cause": "enospc",
         "rcs": {"0": 1}, "wall_secs": 2.0, "resume_level": 7,
         "progressed": False},
        {"phase": "campaign_backoff", "secs": 1.0},
        {"phase": "campaign_attempt", "attempt": 3, "cause": "complete",
         "rcs": {"0": 0}, "wall_secs": 9.0, "resume_level": 5,
         "progressed": True},
        {"phase": "campaign_done", "attempts": 3, "wall_secs": 17.5},
    ]) + "\n")
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    assert obs_report.main([str(jsonl)]) == 0
    out = capsys.readouterr().out
    line = next(l for l in out.splitlines() if l.startswith("campaign:"))
    assert "attempts=3" in line
    assert "solved in 17.5s" in line
    assert "complete:1" in line and "enospc:1" in line \
        and "killed:1" in line
    assert "resume_levels=[None, 7, 5]" in line
    assert "time_lost_restarts=6.0s" in line  # failed attempts only
    assert "backoff=1.5s" in line
    assert "gc_reclaimed_MB=2.0" in line
    assert "campaign_attempt" not in out.replace(line, "")
    # An aborted ledger reports the abort, not 'in flight'.
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    records = records[:-2] + [{"phase": "campaign_abort",
                               "reason": "breaker", "code": 3}]
    lines = obs_report.summarize_campaign(records)
    assert lines and "ABORTED (breaker)" in lines[0]
    # Geometry-free ledgers (PR 12 vintage) stay one line.
    assert len(lines) == 1


def test_obs_report_campaign_geometry_columns(tmp_path):
    """ISSUE 13 satellite: attempt geometry (shards/ranks/cache-MB),
    reshard count, and degrade causes from the ledger — with `!`
    marking a reshard adoption (sealed_shards != shards going in)."""
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    records = [
        {"phase": "campaign_start", "processes": 1},
        {"phase": "campaign_attempt", "attempt": 1, "cause": "oom",
         "rcs": {"0": 1}, "wall_secs": 3.0, "resume_level": None,
         "progressed": True, "shards": 2, "processes": 1,
         "cache_mb": None, "sealed_shards": None},
        {"phase": "campaign_reshard", "attempt": 1, "cause": "oom",
         "from_shards": 2, "to_shards": 4, "from_cache_mb": 256,
         "to_cache_mb": 128, "processes": 1},
        {"phase": "campaign_degrade", "attempt": 2, "kind": "lost_rank",
         "cause": "killed", "from_processes": 2, "to_processes": 1},
        {"phase": "campaign_attempt", "attempt": 2, "cause": "complete",
         "rcs": {"0": 0}, "wall_secs": 5.0, "resume_level": 7,
         "progressed": True, "shards": 4, "processes": 1,
         "cache_mb": 128, "sealed_shards": 2},
        {"phase": "campaign_done", "attempts": 2, "wall_secs": 9.0},
    ]
    lines = obs_report.summarize_campaign(records)
    assert len(lines) == 2
    geom = lines[1]
    assert geom.startswith("campaign geometry:")
    assert "a1:S=2/W=1" in geom
    assert "a2:S=4!/W=1/cache=128MB" in geom  # ! = reshard adoption
    assert "reshards=1" in geom
    assert "degrades=lost_rank:1,oom:1" in geom
    # The new ledger phases stay out of the aux noise.
    report = obs_report.report(records)
    assert "campaign_reshard" not in report
    assert "campaign_degrade" not in report


@pytest.mark.smoke
def test_obs_report_merges_rank_streams_without_double_counting(
        tmp_path, capsys):
    """Per-rank JSONL merge (ISSUE 6): both ranks of a 2-process run
    time the SAME wall-clock level, so the merged table must take the
    slowest rank, not the sum — and a retry that one rank logged first
    still shows the consensus count (ranks agree by construction)."""
    def rec(rank, **kw):
        return json.dumps({"rank": rank, **kw}) + "\n"

    r0 = tmp_path / "m.rank0.jsonl"
    r1 = tmp_path / "m.rank1.jsonl"
    r0.write_text(
        rec(0, phase="forward", level=0, frontier=100, bytes_sorted=10,
            secs=1.0)
        + rec(0, phase="retry", level=0, point="sharded.forward")
        + rec(0, phase="backward", level=0, n=100, bytes_sorted=0,
              bytes_gathered=4, secs=0.5)
        + rec(0, phase="done", game="x", positions=100)
    )
    r1.write_text(
        rec(1, phase="forward", level=0, frontier=100, bytes_sorted=10,
            secs=1.25)  # the slowest rank defines the level's wall-clock
        + rec(1, phase="retry", level=0, point="sharded.forward")
        + rec(1, phase="backward", level=0, n=100, bytes_sorted=0,
              bytes_gathered=4, secs=0.25)
        + rec(1, phase="done", game="x", positions=100)
    )
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    records = (obs_report.load_records(str(r0))
               + obs_report.load_records(str(r1)))
    rows = obs_report.summarize_levels(records)
    assert len(rows) == 1
    row = rows[0]
    assert row["positions"] == 100  # not 200: one level, two observers
    assert row["fwd_secs"] == 1.25  # max across ranks, not 2.25
    assert row["bwd_secs"] == 0.5
    assert row["retries"] == 1      # the consensus count, not 2
    assert row["bytes_gathered"] == 4
    # Single-stream behavior unchanged: within one rank seconds still
    # accumulate (a re-logged level really did run twice there).
    alone = obs_report.summarize_levels(
        obs_report.load_records(str(r0)))
    assert alone[0]["fwd_secs"] == 1.0
    # CLI accepts the whole per-rank set; done lines stay attributable.
    assert obs_report.main([str(r0), str(r1)]) == 0
    out = capsys.readouterr().out
    assert "done[rank 0]: game=x" in out
    assert "done[rank 1]: game=x" in out


# ------------------------------------------------- server exposition (HTTP)


def test_server_metrics_prometheus_and_negotiation(tmp_path):
    """curl /metrics returns valid Prometheus text exposition (strict
    parser), Accept: application/json returns the JSON dict, and
    /metrics.json always does."""
    import urllib.request

    from gamesmanmpi_tpu.db import DbReader, export_result
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.serve import QueryServer
    from gamesmanmpi_tpu.solve import Solver

    spec = "subtract:total=10,moves=1-2"
    d = tmp_path / "db"
    export_result(Solver(get_game(spec)).solve(), d, spec)
    with DbReader(d) as reader, QueryServer(reader) as server:
        base = f"http://127.0.0.1:{server.port}"
        req = urllib.request.Request(
            base + "/query",
            data=json.dumps({"positions": [9, 3]}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 200
            json.loads(resp.read())

        with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
            ctype = resp.headers["Content-Type"]
            text = resp.read().decode()
        assert ctype == "text/plain; version=0.0.4; charset=utf-8"
        fams = parse_prometheus_text(text)  # raises on malformed output
        assert fams["gamesman_http_requests_total"]["type"] == "counter"
        assert fams["gamesman_server_start_time_seconds"]["type"] == "gauge"
        (start_sample,) = fams["gamesman_server_start_time_seconds"]["samples"]
        assert start_sample[2] > 1e9  # unix seconds: uptime is derivable
        assert fams["gamesman_batch_seconds"]["type"] == "histogram"
        assert fams["gamesman_db_probe_seconds"]["type"] == "histogram"
        # The db reader's probe/page counters moved with real traffic.
        (q,) = fams["gamesman_db_probe_queries_total"]["samples"]
        assert q[2] >= 2
        (pages,) = fams["gamesman_db_mmap_page_touches_total"]["samples"]
        assert pages[2] > 0

        # Content negotiation: JSON on request; /metrics.json always.
        for path, hdrs in (
            ("/metrics", {"Accept": "application/json"}),
            ("/metrics.json", {}),
        ):
            req = urllib.request.Request(base + path, headers=hdrs)
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = json.loads(resp.read())
            assert body["http_requests"] >= 1
            assert body["server_start_time"] > 1e9
            assert body["uptime_secs"] >= 0


@pytest.mark.smoke
def test_obs_report_merges_per_worker_serving_streams(tmp_path, capsys):
    """Fleet JSONL (ISSUE 7): serve_batch records from N worker-stamped
    streams summarize per worker (workers are independent processes —
    their figures accumulate separately, never merged by max the way
    per-rank solve times are)."""
    recs = [
        {"worker": 0, "phase": "serve_batch", "requests": 2,
         "batch_size": 5, "secs": 0.01},
        {"worker": 0, "phase": "serve_batch", "requests": 1,
         "batch_size": 3, "secs": 0.02},
        {"worker": 1, "phase": "serve_batch", "requests": 4,
         "batch_size": 8, "secs": 0.04},
        # A legacy single-process stream has no worker tag.
        {"phase": "serve_batch", "requests": 1, "batch_size": 1,
         "secs": 0.005},
    ]
    obs_report = load_module(REPO / "tools" / "obs_report.py")
    lines = obs_report.summarize_serving(recs)
    assert lines == [
        "serve[worker 0]: batches=2 requests=3 queries=8 "
        "mean_batch=4.0 secs=0.030",
        "serve[worker 1]: batches=1 requests=4 queries=8 "
        "mean_batch=8.0 secs=0.040",
        "serve: batches=1 requests=1 queries=1 mean_batch=1.0 "
        "secs=0.005",
    ]
    # And through the CLI: worker-stamped streams fold into one report
    # (serve_batch stays out of the aux record counts).
    for i, rec in enumerate(recs):
        path = tmp_path / f"serve.worker{i}.jsonl"
        path.write_text(json.dumps(rec) + "\n")
    assert obs_report.main(
        [str(tmp_path / f"serve.worker{i}.jsonl") for i in range(4)]
    ) == 0
    out = capsys.readouterr().out
    assert "serve[worker 0]" in out
    assert "serve_batch" not in out
