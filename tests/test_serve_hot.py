"""Serving hot path (ISSUE 18): shared block cache, book, edge GETs.

Acceptance axes:

* cross-worker shared block cache — a ``ShmBlockCache`` segment
  hammered by forked writer/reader processes never returns a torn or
  foreign payload (a stale slot is a MISS, never a wrong answer); a
  late attacher (the killed-and-restarted worker) reads blocks its
  siblings decoded without decoding them itself; an epoch bump (the
  rolling-reload signature) invalidates every slot at once; memory is
  bounded by construction (collisions evict, the segment never grows);
* resident opening book — ``build_book`` seals a table whose every
  answer byte-matches ``DbReader.lookup_best``; the sealed file is
  tamper-evident (sha over content, deep re-probe via check_db);
* edge-cacheable GETs — ``GET /query?p=`` carries the epoch-prefixed
  ETag + Cache-Control contract, answers If-None-Match revalidation
  with 304 and NO lookup work, and a rolling reload onto a different
  DB flips the ETag so a stale cached body can never be confirmed.
"""

import json
import multiprocessing
import os
import shutil
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gamesmanmpi_tpu.db import DbReader, export_result
from gamesmanmpi_tpu.db.book import OpeningBook, build_book, verify_book
from gamesmanmpi_tpu.db.format import DbFormatError, read_manifest
from gamesmanmpi_tpu.games import get_game
from gamesmanmpi_tpu.serve import QueryServer
from gamesmanmpi_tpu.solve import Solver
from gamesmanmpi_tpu.store.shm import ShmBlockCache

from helpers import REPO

_CLI = [sys.executable, "-m", "gamesmanmpi_tpu.cli"]


def _get_raw(url, headers=None, timeout=30):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _wait_for(pred, timeout=60.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def book_db(tmp_path_factory):
    """Subtract DB with a sealed 3-ply opening book."""
    spec = "subtract:total=10,moves=1-2"
    d = tmp_path_factory.mktemp("hotdb") / "sub"
    export_result(Solver(get_game(spec)).solve(), d, spec)
    rec = build_book(d, 3)
    return d, rec


# ------------------------------------------------- shared block cache


def _payload_for(key: tuple, salt: int = 0):
    """Deterministic (keys, cells) pair derived from the block key —
    the hammer's torn-read oracle: any hit must reproduce it exactly."""
    dev, ino, block = key
    base = (dev * 1000003 + ino * 101 + block * 7 + salt) % (1 << 31)
    keys = (np.arange(16, dtype=np.uint64) + np.uint64(base))
    cells = (np.arange(16, dtype=np.uint32) * np.uint32(3)
             + np.uint32(base % 97))
    return keys, cells


def test_shm_roundtrip_epoch_and_eviction():
    cache = ShmBlockCache.create(
        f"gmtest-{os.getpid()}-rt", slot_bytes=4096, budget_bytes=1 << 20,
    )
    try:
        key = (5, 42, 7)
        keys, cells = _payload_for(key)
        assert cache.get(key, "epochA") is None  # cold
        assert cache.put(key, "epochA", keys, cells) is True
        hit = cache.get(key, "epochA")
        assert hit is not None
        np.testing.assert_array_equal(hit[0], keys)
        np.testing.assert_array_equal(hit[1], cells)
        # Same block re-published under the same epoch: a no-op (a
        # sibling already paid the decode).
        assert cache.put(key, "epochA", keys, cells) is False
        # Epoch mismatch — the rolling-reload signature — is a miss,
        # and the slot is recyclable under the new epoch.
        assert cache.get(key, "epochB") is None
        assert cache.put(key, "epochB", keys, cells) is True
        assert cache.get(key, "epochA") is None
        # Oversized payloads are refused, not truncated.
        big = np.zeros(4096, dtype=np.uint64)
        assert cache.put((1, 1, 1), "epochA", big, big) is False
        st = cache.stats()
        assert st["stores"] == 2 and st["hits"] == 1
        assert st["evictions"] >= 1  # the epochB overwrite
    finally:
        cache.unlink()


def test_shm_attach_rejects_foreign_segment():
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(
        name=f"gmtest-{os.getpid()}-junk", create=True, size=8192,
    )
    try:
        shm.buf[:8] = b"NOTGMSHM"
        with pytest.raises(ValueError):
            ShmBlockCache(shm, owner=True)
    finally:
        shm.close()
        shm.unlink()


def test_shm_budget_too_small_raises():
    with pytest.raises(ValueError):
        ShmBlockCache.create("gmtest-tiny", slot_bytes=1 << 20,
                             budget_bytes=4096)


def _hammer_child(name: str, epoch: str, nkeys: int, rounds: int,
                  seed: int, q) -> None:
    """get/put storm over a shared key set; any hit must byte-match the
    deterministic payload (a torn or foreign read is a test failure)."""
    try:
        cache = ShmBlockCache.attach(name)
        rng = np.random.default_rng(seed)
        hits = 0
        for _ in range(rounds):
            k = int(rng.integers(nkeys))
            key = (1, 2, k)
            keys, cells = _payload_for(key)
            got = cache.get(key, epoch)
            if got is not None:
                hits += 1
                np.testing.assert_array_equal(got[0], keys)
                np.testing.assert_array_equal(got[1], cells)
            else:
                cache.put(key, epoch, keys, cells)
        cache.close()
        q.put(("ok", hits))
    except BaseException as e:  # noqa: BLE001 - shipped to the parent
        q.put(("fail", f"{type(e).__name__}: {e}"))


def test_shm_multiprocess_hammer_and_restart_reattach():
    """Forked workers hammer one segment: no torn/foreign payload ever
    surfaces; a worker attaching AFTER the storm (the restart path)
    reads sibling-decoded blocks without decoding; an epoch bump then
    invalidates everything; memory stays bounded (nslots < nkeys forces
    evictions rather than growth)."""
    ctx = multiprocessing.get_context("fork")
    nkeys, nprocs, rounds = 48, 4, 300
    sup = ShmBlockCache.create(
        f"gmtest-{os.getpid()}-hammer", slot_bytes=1024,
        budget_bytes=4096 + 32 * (1024 + 128),  # ~32 slots < 48 keys
    )
    try:
        q = ctx.Queue()
        procs = [
            ctx.Process(target=_hammer_child,
                        args=(sup.name, "epochA", nkeys, rounds, i, q))
            for i in range(nprocs)
        ]
        for p in procs:
            p.start()
        outs = [q.get(timeout=120) for _ in procs]
        for p in procs:
            p.join(timeout=60)
        failures = [detail for status, detail in outs if status != "ok"]
        assert not failures, failures
        assert sum(hits for _, hits in outs) > 0, "storm never hit"

        # Restart path: a FRESH attacher (new pid) inherits the warm
        # set — sibling-decoded blocks are hits, not re-decodes.
        q2 = ctx.Queue()
        late = ctx.Process(target=_hammer_child,
                           args=(sup.name, "epochA", nkeys, rounds, 99, q2))
        late.start()
        status, hits = q2.get(timeout=120)
        late.join(timeout=60)
        assert status == "ok", hits
        assert hits > 0, "restarted worker re-decoded everything"

        # Epoch bump (rolling reload): every surviving slot is stale
        # at once — all misses, no wrong answers, no touch needed.
        assert all(
            sup.get((1, 2, k), "epochB") is None for k in range(nkeys)
        )
        st = sup.stats()
        assert st["nslots"] < nkeys  # collisions were real
    finally:
        sup.unlink()


# ------------------------------------------------------- opening book


def test_book_build_lookup_parity(book_db):
    db, rec = book_db
    assert rec["plies"] == 3 and rec["count"] == len(
        OpeningBook.load(db)
    ) > 0
    manifest = read_manifest(db)
    assert manifest["book"]["sha256"] == rec["sha256"]
    book = OpeningBook.load(db)
    with DbReader(db) as reader:
        # The reader attached the book itself (GAMESMAN_SERVE_BOOK
        # defaults on) and its epoch covers the sealed manifest.
        assert reader.book is not None
        assert len(reader.book) == rec["count"]
        probe = np.concatenate([
            book.positions,
            np.asarray([10 ** 6 + 7], dtype=book.positions.dtype),
        ])
        bv, br, bf, bb = book.lookup(probe)
        rv, rr, rf, rb = reader.lookup_best(probe)
        assert bool(bf[-1]) is False  # alien position: a miss
        np.testing.assert_array_equal(bf[:-1],
                                      np.ones(len(book), dtype=bool))
        np.testing.assert_array_equal(bv[bf], rv[bf])
        np.testing.assert_array_equal(br[bf], rr[bf])
        np.testing.assert_array_equal(bb[bf], rb[bf])
    assert verify_book(db) == []


def test_book_env_gate(book_db, monkeypatch):
    db, _ = book_db
    monkeypatch.setenv("GAMESMAN_SERVE_BOOK", "0")
    with DbReader(db) as reader:
        assert reader.book is None


def test_book_tamper_is_caught(book_db, tmp_path):
    """A flipped byte in the sealed book fails the load-time sha; if an
    attacker ALSO reseals the manifest, the deep re-probe (check_db's
    book gate) still catches the wrong answer."""
    db, _ = book_db
    rotted = tmp_path / "rot"
    shutil.copytree(db, rotted)
    path = rotted / "book.gmb"
    blob = bytearray(path.read_bytes())
    blob[-1] ^= 0x01
    path.write_bytes(bytes(blob))
    with pytest.raises(DbFormatError):
        OpeningBook.load(rotted)
    # Reseal: structural checks now pass, the deep probe must not.
    import hashlib

    manifest = read_manifest(rotted)
    manifest["book"]["sha256"] = hashlib.sha256(
        path.read_bytes()
    ).hexdigest()
    from gamesmanmpi_tpu.db.format import write_manifest

    write_manifest(rotted, manifest)
    assert OpeningBook.load(rotted) is not None  # seal matches again
    problems = verify_book(rotted)
    assert problems and "book" in problems[0]


# ------------------------------------------- edge GETs (ETag contract)


def test_get_query_etag_304_and_book_counter(book_db):
    db, _ = book_db
    with DbReader(db) as reader:
        epoch16 = reader.epoch[:16]
        with QueryServer(reader) as server:
            base = f"http://127.0.0.1:{server.port}"
            # Full answer with the edge-cache contract.
            code, headers, body = _get_raw(base + "/query?p=10")
            assert code == 200
            etag = headers["ETag"]
            assert etag == f'"{epoch16}-a"'  # 10 == 0xa
            assert "public" in headers["Cache-Control"]
            assert "max-age=" in headers["Cache-Control"]
            rec = json.loads(body)["results"][0]
            assert rec["found"] is True
            # Hex and decimal spellings of one position share the ETag.
            code2, headers2, _ = _get_raw(base + "/query?p=0xa")
            assert code2 == 200 and headers2["ETag"] == etag
            # Revalidation: 304, empty body, contract headers intact.
            code, headers, body = _get_raw(
                base + "/query?p=10", headers={"If-None-Match": etag},
            )
            assert (code, body) == (304, b"")
            assert headers["ETag"] == etag
            code, _, _ = _get_raw(
                base + "/query?p=10", headers={"If-None-Match": "*"},
            )
            assert code == 304
            # A different position is a different resource.
            code, headers, _ = _get_raw(
                base + "/query?p=9", headers={"If-None-Match": etag},
            )
            assert code == 200 and headers["ETag"] != etag
            # Malformed/missing p: client errors, never a 500.
            assert _get_raw(base + "/query?p=zzz")[0] == 400
            assert _get_raw(base + "/query")[0] == 400
            assert _get_raw(base + "/query/nope?p=1")[0] == 404
            # The book answered at least one of those GETs from RAM.
            code, _, text = _get_raw(base + "/metrics")
            assert code == 200
            line = next(
                ln for ln in text.decode().splitlines()
                if ln.startswith("gamesman_book_hits_total")
            )
            assert float(line.rsplit(" ", 1)[1]) > 0


def test_batcher_inflight_dedup_counter(book_db):
    db, _ = book_db
    os.environ.pop("GAMESMAN_FAULTS", None)
    with DbReader(db) as reader:
        with QueryServer(reader) as server:
            base = f"http://127.0.0.1:{server.port}"
            # 8 copies of a fresh NON-book position in one request: one
            # flush, one probed row, 7 coalesced away. (A book position
            # would never reach the batcher; a cached one never flushes.
            # 3 is 4 plies from the initial 10 — past the 3-ply book.)
            req = urllib.request.Request(
                base + "/query",
                data=json.dumps({"positions": [3] * 8}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = json.loads(resp.read())
            assert all(r["found"] for r in body["results"])
            assert len({json.dumps(r) for r in body["results"]}) == 1
            counters = server.batcher.counters
            assert counters["dup_hits"] >= 7


# ------------------------------------- rolling reload flips the epoch


def test_fleet_reload_flips_etag_and_book(book_db, tmp_path):
    """E2E freshness gate: a fork-mode fleet serves epoch-stamped GETs;
    a rolling reload onto a DIFFERENT DB (different rules => different
    answers) flips the ETag, so a cache holding the old body gets a
    full 200 + new ETag instead of a confirming 304 — the stale book
    and blocks can never be served across the reload."""
    db1, _ = book_db
    spec2 = "subtract:total=10,moves=1-3"
    db2 = tmp_path / "sub2"
    export_result(Solver(get_game(spec2)).solve(), db2, spec2)
    build_book(db2, 2)
    with DbReader(db2) as r2:
        want = r2.lookup_best(
            np.asarray([10], dtype=r2.game.state_dtype)
        )
        want_rem = int(want[1][0])

    manifest = tmp_path / "fleet.json"
    manifest.write_text(json.dumps({
        "version": 1, "games": [{"name": "sub", "db": str(db1)}],
    }))
    env = dict(os.environ)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_SERVE_RESTART_BASE_SECS"] = "0.1"
    env.pop("GAMESMAN_FAULTS", None)
    proc = subprocess.Popen(
        _CLI + ["serve", "--fleet-manifest", str(manifest), "--port", "0",
                "--workers", "2", "--control-port", "0"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=str(REPO),
    )
    try:
        banner = proc.stdout.readline()
        assert "serving fleet" in banner, banner
        port = int(banner.split("http://127.0.0.1:")[1].split(" ")[0])
        cport = int(banner.split("http://127.0.0.1:")[2].split(" ")[0])
        base, control = (f"http://127.0.0.1:{port}",
                         f"http://127.0.0.1:{cport}")
        _wait_for(
            lambda: _get_raw(control + "/healthz")[0] == 200
            and json.loads(_get_raw(control + "/healthz")[2])
            ["status"] == "ok",
            timeout=120, what="fleet ready",
        )
        code, headers, body = _get_raw(base + "/query/sub?p=10")
        assert code == 200
        etag1 = headers["ETag"]
        rem1 = json.loads(body)["results"][0]["remoteness"]
        # Both workers answer 304 for the current epoch (the shared
        # accept queue spreads these across the fleet).
        for _ in range(8):
            code, _, _ = _get_raw(
                base + "/query/sub?p=10",
                headers={"If-None-Match": etag1},
            )
            assert code == 304

        manifest.write_text(json.dumps({
            "version": 1, "games": [{"name": "sub", "db": str(db2)}],
        }))
        urllib.request.urlopen(urllib.request.Request(
            control + "/reload", method="POST", data=b""), timeout=10)
        _wait_for(
            lambda: json.loads(_get_raw(control + "/healthz")[2])
            .get("reloads_done", 0) >= 1
            and json.loads(_get_raw(control + "/healthz")[2])
            ["status"] == "ok",
            timeout=120, what="rolling reload done",
        )
        # The old ETag is NEVER confirmed post-reload: full 200, new
        # ETag, and the answer is the NEW rules' answer on every worker.
        for _ in range(8):
            code, headers, body = _get_raw(
                base + "/query/sub?p=10",
                headers={"If-None-Match": etag1},
            )
            assert code == 200
            assert headers["ETag"] != etag1
            rec = json.loads(body)["results"][0]
            assert rec["remoteness"] == want_rem
        assert rem1 != want_rem  # the rules change was observable
        proc.send_signal(__import__("signal").SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
