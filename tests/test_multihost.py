"""REAL multi-process execution over jax.distributed (SURVEY.md §5.8).

Until round 4 the multi-host control plane was mock-tested only (the CLI's
--coordinator flags drove a fake jax.distributed.initialize). XLA's CPU
collectives (Gloo) support genuine multi-controller execution on this
container, so these tests launch TWO OS processes that join one
coordinator, build a 4-device global mesh (2 local devices each), and run
the owner-routed sharded solve across it — cross-process all_to_all,
psum-replicated control plane, non-addressable shards and all. Both
processes must print identical, known-correct answers.

This is the closest analog this environment allows to the reference's
`mpirun -np 2` integration run.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env() -> dict:
    env = dict(os.environ)
    # The suite's own fake-device flag must NOT leak: each child gets
    # exactly 2 local CPU devices so the 4-device mesh spans processes.
    env.pop("XLA_FLAGS", None)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_FAKE_DEVICES"] = "2"
    return env


def _run_two_process_solve(spec: str, extra_args=(), tmp_dir="/tmp"):
    port = _free_port()
    procs, files = [], []
    for pid in range(2):
        # File-backed stdio, not PIPEs: the two children are coupled by
        # cross-process collectives, so blocking on one's unread pipe can
        # stall the other — converting any verbose failure into a bare
        # timeout with the diagnostics lost.
        out_f = open(os.path.join(tmp_dir, f"mh_{port}_{pid}.out"), "w+")
        err_f = open(os.path.join(tmp_dir, f"mh_{port}_{pid}.err"), "w+")
        files.append((out_f, err_f))
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "solve_launcher.py"), spec,
             "--devices", "4", "--no-tables",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             *extra_args],
            cwd=REPO, env=_child_env(), stdout=out_f, stderr=err_f,
        ))
    outs = []
    for p, (out_f, err_f) in zip(procs, files):
        try:
            rc = p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host solve timed out")
        out_f.seek(0)
        err_f.seek(0)
        outs.append((rc, out_f.read(), err_f.read()))
        out_f.close()
        err_f.close()
    for rc, out, err in outs:
        if rc != 0 and "Multiprocess computations aren't implemented" in err:
            # This jaxlib's CPU collectives cannot span OS processes
            # (no Gloo backend): the capability under test does not
            # exist here. Skip — not a regression — so tier-1 is green
            # by construction; real multi-host containers still run it.
            pytest.skip(
                "backend cannot run multiprocess collectives on this "
                "jaxlib (CPU: multiprocess computations not implemented)"
            )
        assert rc == 0, f"process failed rc={rc}\n{err[-2000:]}"
    return outs


def test_multihost_generic_path_nim(tmp_path):
    """Generic (multi-jump) engine across 2 processes: nim 2-3-4 is WIN
    remoteness 7 with 60 positions — both processes must agree."""
    outs = _run_two_process_solve("nim:heaps=2-3-4", tmp_dir=str(tmp_path))
    for _, out, _ in outs:
        assert "positions: 60" in out
        assert "value: WIN" in out
        assert "remoteness: 7" in out


def test_multihost_fast_path_connect3(tmp_path):
    """Device-resident fast path across 2 processes: 3x3 connect-3 is a
    TIE at remoteness 9 with 694 reachable positions."""
    outs = _run_two_process_solve("connect4:w=3,h=3,connect=3",
                                  tmp_dir=str(tmp_path))
    for _, out, _ in outs:
        assert "positions: 694" in out
        assert "value: TIE" in out
        assert "remoteness: 9" in out


def test_multihost_checkpoint_and_resume(tmp_path):
    """Per-shard checkpoint write discipline under REAL multi-process
    execution: each process writes only the shards its devices own into a
    shared directory, process 0 seals the manifest after the
    sync_global_devices barrier, and a second two-process run resumes
    from the files. Previously this was covered only by mocking
    jax.process_index/process_count."""
    ck = str(tmp_path / "ck")
    outs = _run_two_process_solve(
        "connect4:w=3,h=3,connect=3",
        extra_args=("--checkpoint-dir", ck),
        tmp_dir=str(tmp_path),
    )
    for _, out, _ in outs:
        assert "value: TIE" in out and "remoteness: 9" in out

    import json
    import pathlib

    files = {p.name for p in pathlib.Path(ck).iterdir()}
    # Per-(level, shard) cells and per-shard frontier snapshots for ALL 4
    # shards — i.e. both processes' writes landed — and a sealed manifest.
    for s in range(4):
        assert any(
            f.endswith(f".shard_{s:04d}.npz") and f.startswith("level_")
            for f in files
        ), (s, sorted(files))
        assert f"frontiers.shard_{s:04d}.npz" in files
    manifest = json.loads((pathlib.Path(ck) / "manifest.json").read_text())
    assert manifest.get("frontier_shards") == 4
    assert manifest.get("sharded_levels")

    # Resume: a fresh two-process run against the same directory loads
    # shard-to-shard and must answer identically.
    outs2 = _run_two_process_solve(
        "connect4:w=3,h=3,connect=3",
        extra_args=("--checkpoint-dir", ck),
        tmp_dir=str(tmp_path),
    )
    for _, out, _ in outs2:
        assert "value: TIE" in out and "remoteness: 9" in out
