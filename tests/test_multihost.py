"""REAL multi-process execution over jax.distributed (ISSUE 6).

These tests drive ``tools/launch_multihost.py`` — the project's
``mpirun -np N`` analog: N OS processes, each a stock solve CLI run,
joined into one PJRT world via env-configured
``jax.distributed.initialize`` with CPU Gloo collectives
(``parallel/mesh.enable_cpu_collectives``) so the 4-device global mesh
genuinely spans 2 processes — cross-process all_to_all, psum-replicated
control plane, non-addressable shards and all.

Until ISSUE 6 this file had to skip on this container ("Multiprocess
computations aren't implemented on the CPU backend"): the Gloo knob was
never flipped. Now the skip remains ONLY for environments where the
harness itself cannot run a cross-process collective (old jaxlib, no
Gloo); anything else is a real failure. The capability probe doubles as
the tier-1 solve test so the budget pays for one 2-process bring-up.
"""

import json
import pathlib

import numpy as np
import pytest

from helpers import REPO

# A real import, not helpers.load_module: the harness defines a
# dataclass, whose field-type resolution needs the module registered in
# sys.modules (repo root is on sys.path when pytest runs from it).
from tools import launch_multihost

#: The tier-1 board: 3x3 connect-3 — 694 reachable positions, TIE at
#: remoteness 9, uniform level jump (device-resident fast path).
_C3 = "connect4:w=3,h=3,connect=3"
#: Gloo cannot run multiprocess collectives on this jaxlib -> skip.
_NO_BACKEND = "Multiprocess computations aren't implemented"


def _launch(args, tmp_dir, **kw):
    kw.setdefault("processes", 2)
    kw.setdefault("timeout", 240)
    return launch_multihost.launch(list(args), log_dir=str(tmp_dir), **kw)


def _assert_world_ok(ranks):
    """Every rank exited 0 — or the backend lacks the capability, which
    is the one remaining skip (the harness can't spawn a real world)."""
    for r in ranks:
        if r.returncode != 0 and _NO_BACKEND in r.stderr:
            pytest.skip(
                "backend cannot run multiprocess collectives on this "
                "jaxlib (no CPU Gloo) — the harness cannot spawn a world"
            )
    for r in ranks:
        assert r.returncode == 0, (
            f"rank {r.rank} failed rc={r.returncode}\n{r.stderr[-2000:]}"
        )


def _table_arrays(path):
    with np.load(path) as z:
        return {f: z[f].copy() for f in z.files}


@pytest.fixture(scope="module")
def two_process_solve(tmp_path_factory):
    """The capability probe AND the shared 2-process artifact set: one
    real 2-process solve of the tier-1 board, with per-rank tables and
    JSONL streams for the assertions below."""
    d = tmp_path_factory.mktemp("mh")
    ranks = _launch(
        [_C3, "--devices", "4", "--no-tables",
         "--table-out", str(d / "table.npz"),
         "--jsonl", str(d / "m.jsonl")],
        d,
    )
    _assert_world_ok(ranks)
    return d, ranks


def test_two_process_solve_for_real(two_process_solve):
    """num_processes>1 for REAL: both ranks print the known-correct
    answer, and the rank-qualified artifacts prove each child saw
    jax.process_count() == 2 (single-process runs write the bare path)."""
    d, ranks = two_process_solve
    assert len(ranks) == 2
    for r in ranks:
        assert "positions: 694" in r.stdout
        assert "value: TIE" in r.stdout
        assert "remoteness: 9" in r.stdout
    # Rank-qualified artifact names happen only under process_count > 1.
    for rank in range(2):
        assert (d / f"table.rank{rank}.npz").exists()
        assert (d / f"m.rank{rank}.jsonl").exists()
    assert not (d / "table.npz").exists()


def test_two_process_ranks_agree_byte_for_byte(two_process_solve):
    """Both ranks materialize the SAME global table (the gather
    collective replicates every shard's rows to every rank)."""
    d, _ = two_process_solve
    a = _table_arrays(d / "table.rank0.npz")
    b = _table_arrays(d / "table.rank1.npz")
    assert sorted(a) == sorted(b)
    for f in a:
        assert np.array_equal(a[f], b[f]), f


def test_two_process_output_matches_single_process(two_process_solve,
                                                   tmp_path):
    """The acceptance bar: a 2-process 4-shard solve is byte-identical
    to the single-process 4-shard sharded engine."""
    import os
    import subprocess
    import sys

    d, _ = two_process_solve
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("GAMESMAN_FAULTS", None)
    env["GAMESMAN_PLATFORM"] = "cpu"
    env["GAMESMAN_FAKE_DEVICES"] = "4"
    single = tmp_path / "single.npz"
    proc = subprocess.run(
        [sys.executable, str(REPO / "solve_launcher.py"), _C3,
         "--devices", "4", "--no-tables", "--table-out", str(single)],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    a = _table_arrays(d / "table.rank0.npz")
    b = _table_arrays(single)
    assert sorted(a) == sorted(b)
    for f in a:
        assert np.array_equal(a[f], b[f]), f


def test_jsonl_records_carry_rank(two_process_solve):
    """Every per-level record in a multi-process stream is rank-stamped
    (utils/metrics.RankLogger): without the label the merged streams
    are rank-ambiguous (tools/obs_report.py relies on it)."""
    d, _ = two_process_solve
    for rank in range(2):
        records = [
            json.loads(line)
            for line in (d / f"m.rank{rank}.jsonl").read_text().splitlines()
            if line.strip()
        ]
        assert records, f"rank {rank} wrote no records"
        assert all(r.get("rank") == rank for r in records), records[:3]


def _free_port_pair():
    """Two consecutive free ports (base for rank 0, base+1 for rank 1 —
    the status server's rank-offset convention). Best-effort: bind both
    to prove the pair, retrying a few candidates."""
    import socket

    for _ in range(16):
        s0 = socket.socket()
        try:
            s0.bind(("127.0.0.1", 0))
            base = s0.getsockname()[1]
            s1 = socket.socket()
            try:
                s1.bind(("127.0.0.1", base + 1))
            except OSError:
                continue
            finally:
                s1.close()
            return base
        finally:
            s0.close()
    pytest.skip("could not find two consecutive free ports")


def test_two_process_live_status_fleet_merge(tmp_path):
    """ISSUE 15 acceptance: a REAL 2-process sharded solve serves
    /status on rank 0 with the fleet-merged per-rank view (peer
    addresses via the coordinator address book), monotone
    positions_solved, and a finite ETA."""
    import time
    import urllib.request

    base = _free_port_pair()
    env = dict(__import__("os").environ)
    # Stretch levels so the poller observes the run mid-flight; the
    # collective structure means a rank-0 delay paces both ranks.
    env["GAMESMAN_FAULTS_RANK_0"] = (
        "sharded.forward:delay=0.1:always,"
        "sharded.backward:delay=0.05:always"
    )
    env["GAMESMAN_STATUS_PORT"] = str(base)
    world = launch_multihost.start_world(
        [_C3, "--devices", "4", "--no-tables"],
        processes=2, log_dir=str(tmp_path), env=env,
    )
    samples = []
    try:
        while any(p.poll() is None for p in world._procs):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{base}/status", timeout=2
                ) as resp:
                    samples.append(json.loads(resp.read().decode()))
            except Exception:
                pass
            time.sleep(0.02)
    finally:
        ranks = world.wait(timeout=240)
    _assert_world_ok(ranks)
    assert samples, "poller never reached rank 0's /status"
    solved = [s["positions_solved"] for s in samples]
    assert solved == sorted(solved), "positions_solved regressed"
    fleet_samples = [s["fleet"] for s in samples if "fleet" in s]
    assert fleet_samples, "rank 0 never served the fleet view"
    assert fleet_samples[-1]["world"] == 2
    # The peer announced itself through the coordinator address book
    # and was scraped into the merged view at least once.
    assert any(
        "1" in f["ranks"] for f in fleet_samples
    ), "rank 1 never appeared in the fleet-merged view"
    merged_levels = [f for f in fleet_samples if f["levels"]]
    assert merged_levels, "no per-level fleet walls merged"
    etas = [s["eta_secs"] for s in samples
            if s.get("eta_secs") is not None]
    assert etas and all(e < 3600 for e in etas), etas


@pytest.mark.slow
def test_multihost_generic_path_nim(tmp_path):
    """Generic (multi-jump) engine across 2 processes: nim 2-3-4 is WIN
    remoteness 7 with 60 positions — both processes must agree."""
    ranks = _launch(["nim:heaps=2-3-4", "--devices", "4"], tmp_path)
    _assert_world_ok(ranks)
    for r in ranks:
        assert "positions: 60" in r.stdout
        assert "value: WIN" in r.stdout
        assert "remoteness: 7" in r.stdout


@pytest.mark.slow
def test_multihost_checkpoint_and_resume(tmp_path):
    """Per-shard checkpoint write discipline under REAL multi-process
    execution: each process writes only the shards its devices own into
    a shared directory, process 0 seals the manifest (rank-set + epoch
    stamped) after the barrier, and a second two-process run passes the
    rank-consistent resume barrier and answers identically."""
    ck = tmp_path / "ck"
    ranks = _launch(
        [_C3, "--devices", "4", "--checkpoint-dir", str(ck)], tmp_path
    )
    _assert_world_ok(ranks)
    for r in ranks:
        assert "value: TIE" in r.stdout and "remoteness: 9" in r.stdout

    files = {p.name for p in pathlib.Path(ck).iterdir()}
    # Per-(level, shard) cells and per-shard frontier snapshots for ALL 4
    # shards — i.e. both processes' writes landed — and a sealed manifest.
    for s in range(4):
        assert any(
            f.endswith(f".shard_{s:04d}.npz") and f.startswith("level_")
            for f in files
        ), (s, sorted(files))
        assert f"frontiers.shard_{s:04d}.npz" in files
    manifest = json.loads((pathlib.Path(ck) / "manifest.json").read_text())
    assert manifest.get("frontier_shards") == 4
    assert manifest.get("sharded_levels")
    # ISSUE 6 stamps: the run epoch and the shard->rank ownership map
    # (2 local devices per rank -> shards 0,1 on rank 0 and 2,3 on 1).
    assert manifest["run"]["epoch"] == 1
    assert manifest["run"]["num_processes"] == 2
    assert manifest["run"]["ranks"] == [0, 0, 1, 1]
    assert manifest["level_seals"]
    for seal in manifest["level_seals"].values():
        assert seal["epoch"] == 1 and seal["ranks"] == [0, 0, 1, 1]

    # Resume: a fresh two-process run against the same directory loads
    # shard-to-shard (epoch 2) and must answer identically.
    ranks2 = _launch(
        [_C3, "--devices", "4", "--checkpoint-dir", str(ck)], tmp_path
    )
    _assert_world_ok(ranks2)
    for r in ranks2:
        assert "value: TIE" in r.stdout and "remoteness: 9" in r.stdout
    manifest = json.loads((pathlib.Path(ck) / "manifest.json").read_text())
    assert manifest["run"]["epoch"] == 2
