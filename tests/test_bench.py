"""bench.py contract tests (subprocess): the driver consumes its stdout.

The benchmark must ALWAYS print exactly one JSON line on stdout with the
fields the driver records (metric/value/device/engine/fallback_cpu), on
the happy path and through the failure ladders (dense engine failure ->
classic demotion on the same platform). These run the real script on the
tiny 3x3 connect-3 board, CPU-pinned.
"""

import json
import os
import subprocess
import sys

import pytest

# Smoke tier: fast, compile-light, single-process-safe (see pyproject).
pytestmark = pytest.mark.smoke

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "bench.py")


def _run_bench(extra_env, timeout=600):
    env = dict(os.environ)
    # Isolate from the suite's 8-device faking: conftest put the device-
    # count flag into XLA_FLAGS, which the child would inherit; a real
    # bench invocation runs single-device.
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env.update(
        GAMESMAN_PLATFORM="cpu",
        BENCH_GAME="connect4:w=3,h=3,connect=3",
        BENCH_SYM="0",
        BENCH_LADDER="0",
        BENCH_REPEATS="1",
    )
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, _BENCH], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    return json.loads(lines[0]), proc.stderr


def test_bench_smoke_fused_contract():
    """BENCH_SMOKE=1 (ISSUE 14): the fast contract check that pins the
    new record fields so they can't rot — warmup excluded from the
    variance block but published raw, dispatch economy present, and the
    fused/unfused A/B with per-arm dispatches and byte-parity."""
    record, stderr = _run_bench({
        "BENCH_ENGINE": "classic",
        "BENCH_SMOKE": "1",
    })
    # Warmup contract: excluded from n/median/all_pps, raw rate kept.
    assert record["runs"]["n"] == 1
    assert len(record["runs"]["all_pps"]) == 1
    assert len(record["runs"]["warmup_pps"]) == 1
    # Dispatch economy fields (the ISSUE 14 claim surface).
    assert record["dispatches"]["total"] > 0
    assert record["dispatches"]["per_level"] > 0
    assert "overlap_secs" in record and "fused" in record
    # Fused A/B: parity proven every round, per-arm dispatch counts.
    ab = record["fused_ab"]
    assert ab["parity_ok"] is True
    assert ab["unfused"]["table_sha256"] == ab["fused"]["table_sha256"]
    assert ab["fused"]["dispatches_per_level"] \
        < ab["unfused"]["dispatches_per_level"]
    assert ab["speedup"] > 0
    # ISSUE 15 roofline fields: present, with a MEASURED (calibrated)
    # per-dispatch cost pricing a nonzero overhead fraction.
    rf = record["roofline"]
    assert set(rf) == {"operand_gbps", "pps_per_chip",
                       "dispatch_overhead_frac"}
    assert rf["pps_per_chip"] == record["value"]
    assert 0 < rf["dispatch_overhead_frac"] <= 1.0
    assert "dispatch cost:" in stderr  # the calibration ran
    # The XLA host-feature-mismatch spam is filtered from the forwarded
    # stderr (it dwarfed the run lines in BENCH_r05.json's tail).
    assert "host machine features" not in stderr
    assert "could lead to execution errors" not in stderr


def test_bench_hybrid_sym_subrun_keeps_engine():
    """ADVICE r5 leftover (pinned by ISSUE 10): BENCH_ENGINE=hybrid must
    NOT gate on game.sym — the secondary sym sub-run benches the SAME
    hybrid engine as the primary, and the sym sub-record says which
    engine actually ran so a silent demotion can never masquerade as a
    hybrid measurement."""
    record, stderr = _run_bench({
        "BENCH_ENGINE": "hybrid",
        "BENCH_SYM": "1",
    })
    assert record["engine"] == "hybrid", stderr[-1000:]
    assert "demoting to the classic engine" not in stderr
    sym = record.get("sym")
    assert sym is not None, "sym sub-run missing from the record"
    assert sym["engine"] == "hybrid", sym
    assert sym["positions"] > 0


@pytest.mark.slow
def test_bench_dense_happy_path():
    record, _ = _run_bench({"BENCH_ENGINE": "dense"})
    assert record["engine"] == "dense"
    assert record["positions"] == 694  # exact reachable count
    assert record["device"] == "cpu"
    assert record["fallback_cpu"] is False  # deliberate CPU pin, not a fallback
    assert record["value"] > 0
    # Variance block (VERDICT r4 weak #1): best is the headline, the
    # per-run spread is published alongside it.
    assert record["runs"]["n"] == 1
    assert record["runs"]["median_pps"] <= record["value"]
    assert len(record["runs"]["all_pps"]) == 1


@pytest.mark.slow
def test_bench_multiprocess_smoke_artifact(tmp_path):
    """BENCH_PROCESSES=2 (ISSUE 6): the bench additionally drives a REAL
    2-process sharded solve through tools/launch_multihost.py and emits
    a MULTICHIP-style artifact with per-rank level times, while stdout
    stays exactly one JSON line with a multiprocess summary."""
    out = tmp_path / "MULTICHIP_mp.json"
    record, stderr = _run_bench({
        "BENCH_ENGINE": "classic",
        "BENCH_PROCESSES": "2",
        "BENCH_MP_GAME": "connect4:w=3,h=3,connect=3",
        "BENCH_PROCESSES_OUT": str(out),
    })
    assert record["positions"] == 694  # the primary metric still ran
    mp = record["multiprocess"]
    artifact = json.loads(out.read_text())
    if not mp["ok"] and "Multiprocess computations" in artifact.get(
            "error", ""):
        pytest.skip("backend cannot run multiprocess collectives")
    assert mp["ok"] is True, artifact.get("error")
    assert mp["processes"] == 2 and mp["shards"] == 4
    assert mp["positions"] == 694
    assert artifact["rc_by_rank"] == [0, 0]
    # Per-rank level times: every level row carries BOTH ranks' forward
    # seconds (the point of the artifact — a perf trajectory per rank).
    assert artifact["levels"], artifact
    for row in artifact["levels"]:
        assert set(row["fwd_secs"]) == {"0", "1"} or \
            set(row["bwd_secs"]) == {"0", "1"}, row
    assert set(artifact["done_by_rank"]) == {"0", "1"}


@pytest.mark.slow
def test_bench_demotes_to_classic_when_dense_breaks():
    # A malformed dense-only knob breaks DenseSolver's constructor; the
    # bench must demote to the classic engine on the same platform and
    # still publish a valid record.
    record, stderr = _run_bench(
        {"BENCH_ENGINE": "dense", "GAMESMAN_DENSE_BLOCK": "not-a-number"}
    )
    assert record["engine"] == "classic"
    assert record["positions"] == 694
    assert "demoting to the classic engine" in stderr


@pytest.mark.slow
def test_bench_serve_slo_artifact(tmp_path):
    """BENCH_SERVE=1 (ISSUE 7): the bench additionally exports a DB,
    launches the supervised fleet, drives load-gen traffic through a
    mid-load worker SIGKILL, and gates on the latency SLO — stdout
    stays exactly one JSON line with a serve summary, the full record
    lands in BENCH_SERVE_OUT."""
    out = tmp_path / "BENCH_serve.json"
    record, _ = _run_bench({
        "BENCH_ENGINE": "classic",
        "BENCH_SERVE": "1",
        "BENCH_SERVE_GAME": "subtract:total=21,moves=1-2-3",
        "BENCH_SERVE_SECS": "4",
        "BENCH_SERVE_CONC": "4",
        "BENCH_SERVE_SLO_P99_MS": "2000",
        "BENCH_SERVE_OUT": str(out),
    })
    sv = record["serve"]
    artifact = json.loads(out.read_text())
    assert sv["ok"] is True, artifact.get("error")
    assert sv["workers"] == 2
    assert sv["slo_ok"] is True
    assert sv["mismatches"] == 0
    assert sv["dropped"] <= 4  # the in-flight budget of the kill
    assert sv["worker_restarts"] == 1
    assert sv["recovered_secs"] is not None
    assert artifact["spawn_mode"] == "fork"
    assert artifact["requests"] > 0
    assert artifact["p99_ms"] > 0
    # The chaos really happened and really healed inside the run.
    assert artifact["killed_worker"] in ("0", "1")


@pytest.mark.slow
def test_bench_campaign_artifact(tmp_path):
    """BENCH_CAMPAIGN=1 (ISSUE 12): the bench additionally drives a
    chaos campaign — injected kills at forward/backward/write-behind,
    auto-resumed to completion by tools/run_campaign.py — and gates on
    byte-parity vs an uninterrupted solve. Single-process tiny config
    here; the committed artifacts/CAMPAIGN_r12.json is the 2-process
    5x4 acceptance run of the same code path."""
    out = tmp_path / "BENCH_campaign.json"
    record, _ = _run_bench({
        "BENCH_ENGINE": "classic",
        "BENCH_CAMPAIGN": "1",
        "BENCH_CAMPAIGN_GAME": "connect4:w=3,h=3,connect=3",
        "BENCH_CAMPAIGN_PROCESSES": "1",
        "BENCH_CAMPAIGN_SHARDS": "2",
        "BENCH_CAMPAIGN_OUT": str(out),
    }, timeout=900)
    cb = record["campaign"]
    artifact = json.loads(out.read_text())
    assert cb["ok"] is True, artifact.get("error")
    assert cb["chaos_ok"] is True
    assert cb["parity_ok"] is True
    assert cb["attempts"] == 4
    assert cb["causes"] == ["killed"] * 3 + ["complete"]
    # The artifact carries the whole ledger: every attempt auditable.
    phases = [r.get("phase") for r in artifact["ledger"]]
    assert phases.count("campaign_attempt") == 4
    assert phases[-1] == "campaign_done"


@pytest.mark.slow
def test_bench_campaign_elastic_artifact(tmp_path):
    """BENCH_CAMPAIGN_ELASTIC=1 (ISSUE 13): a solve SIGKILLed at 4
    shards is resumed by a campaign at 2 shards (reshard adoption on
    the ledger), and an injected-oom campaign auto-escalates 2->4
    shards to completion — both byte-parity vs an uninterrupted solve.
    Tiny config here; the committed artifacts/CAMPAIGN_r13.json is the
    5x4 acceptance run of the same code path."""
    out = tmp_path / "BENCH_campaign_elastic.json"
    record, _ = _run_bench({
        "BENCH_ENGINE": "classic",
        "BENCH_CAMPAIGN_ELASTIC": "1",
        "BENCH_CAMPAIGN_ELASTIC_GAME": "connect4:w=3,h=3,connect=3",
        "BENCH_CAMPAIGN_ELASTIC_SHARDS": "2",
        "BENCH_CAMPAIGN_ELASTIC_SEAL_SHARDS": "4",
        "BENCH_CAMPAIGN_ELASTIC_OOM_SHARDS": "2",
        "BENCH_CAMPAIGN_ELASTIC_OUT": str(out),
    }, timeout=900)
    eb = record["campaign_elastic"]
    artifact = json.loads(out.read_text())
    assert eb["ok"] is True, json.dumps(artifact)[:2000]
    assert eb["reshard"]["sealed_shards"] == 4
    assert eb["reshard"]["attempt_shards"] == 2
    assert eb["reshard"]["parity_ok"] is True
    assert eb["oom"]["causes"][0] == "oom"
    assert eb["oom"]["causes"][-1] == "complete"
    assert eb["oom"]["escalations"][0]["from_shards"] == 2
    assert eb["oom"]["escalations"][0]["to_shards"] == 4
    assert eb["oom"]["parity_ok"] is True
    # Both scenario ledgers ride the artifact, auditable end to end.
    assert any(r.get("phase") == "campaign_reshard"
               for r in artifact["oom"]["ledger"])


@pytest.mark.slow
def test_bench_db_compress_artifact(tmp_path):
    """BENCH_DB_COMPRESS=1 (ISSUE 9): the bench additionally solves a
    board once, exports it v1 AND block-compressed v2, proves the two
    logically identical (full content, not a sample), gates the
    whole-DB ratio, and serves BOTH through real fleets under load-gen
    traffic gating the v2 p99 — stdout stays exactly one JSON line
    with a db_compress summary, the full A/B lands in
    BENCH_DB_COMPRESS_OUT."""
    out = tmp_path / "BENCH_db_compress.json"
    record, _ = _run_bench({
        "BENCH_ENGINE": "classic",
        "BENCH_DB_COMPRESS": "1",
        # ttt compresses well even at tiny scale; 1.5x keeps the gate
        # honest without demanding the 5x4 board's 15x in a smoke test.
        "BENCH_DB_GAME": "tictactoe",
        "BENCH_DB_MIN_RATIO": "1.5",
        "BENCH_DB_SECS": "3",
        "BENCH_DB_CONC": "4",
        "BENCH_DB_SLO_P99_MS": "2000",
        "BENCH_DB_COMPRESS_OUT": str(out),
    })
    dbc = record["db_compress"]
    artifact = json.loads(out.read_text())
    assert dbc["ok"] is True, artifact.get("error")
    assert dbc["full_equal"] is True
    assert dbc["ratio"] >= 1.5
    assert dbc["ratio_ok"] is True and dbc["slo_ok"] is True
    assert artifact["positions"] == 5478
    for arm in ("v1", "v2"):
        assert artifact[arm]["errors"] == 0
        assert artifact[arm]["mismatches"] == 0
        assert artifact[arm]["p99_ms"] > 0
    assert artifact["v2_bytes"] < artifact["v1_bytes"]
