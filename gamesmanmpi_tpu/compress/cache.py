"""BlockCache: bounded, thread-safe LRU over decoded hot blocks.

The serving half of decompress-on-probe (ROADMAP item 2): a compressed
DB answers a probe by decoding only the block the key lands in, and real
query traffic is heavily skewed — openings and common midgames hash to a
small set of hot blocks. Caching those decoded blocks makes the steady
state cost one searchsorted per probe (the v1 mmap experience) while the
cold tail pays one ~0.5 ms block decode.

Design:

* **Byte-budget LRU**, not entry-count: blocks are fixed position count
  but variable decoded size (last-block ragged, keys vs cells width), so
  the budget that matters for RSS is bytes.
* **Thread-safe**: the fleet's per-route batcher flush thread, the
  breaker's half-open re-probe thread, and direct DbReader users may
  probe concurrently. Lookup/insert hold the lock; *decoding never
  does* — two threads racing the same cold block both decode (counted
  as two misses) and the second insert wins, which is strictly cheaper
  than serializing every cold decode behind one lock.
* **Per-reader instances**: each DbReader (so each fleet route, and
  each forked worker after copy-on-write) has its own cache and its own
  metric series — the per-worker cache behavior is an observable, not
  an aggregate (tools/obs_report.py folds the per-worker streams).
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class BlockCache:
    """LRU of decoded block payloads, bounded by total decoded bytes."""

    def __init__(self, budget_bytes: int, *, registry=None, labels=None,
                 instruments=None):
        """labels: metric labels distinguishing THIS cache's series on a
        shared registry (DbReader passes ``db=<dir name>``). Without
        them, two caches in one process would share one registry child
        and the bytes gauge would be last-writer-wins — exactly the
        multi-route fleet worker shape.

        instruments: pre-built (hits, misses, evictions, bytes) registry
        children for subclasses that export under a DIFFERENT family
        name (store.TieredCache's ``gamesman_store_cache_*``) — metric
        names must stay literal at their creation site (GM403), so the
        name cannot be a constructor parameter here."""
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._map: OrderedDict = OrderedDict()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self._hits = 0  # guarded-by: _lock
        self._misses = 0  # guarded-by: _lock
        self._evictions = 0  # guarded-by: _lock
        self._m_hits = self._m_misses = self._m_evictions = None
        self._m_bytes = None
        if instruments is not None:
            (self._m_hits, self._m_misses, self._m_evictions,
             self._m_bytes) = instruments
        elif registry is not None:
            lbl = dict(labels or {})
            self._m_hits = registry.counter(
                "gamesman_db_cache_hits_total",
                "probes answered from an already-decoded hot block",
                **lbl,
            )
            self._m_misses = registry.counter(
                "gamesman_db_cache_misses_total",
                "probes that had to decode a cold block",
                **lbl,
            )
            self._m_evictions = registry.counter(
                "gamesman_db_cache_evictions_total",
                "decoded blocks evicted by the byte budget "
                "(GAMESMAN_DB_CACHE_MB)",
                **lbl,
            )
            self._m_bytes = registry.gauge(
                "gamesman_db_cache_bytes",
                "decoded bytes resident in the hot-block cache",
                **lbl,
            )

    def get(self, key):
        """The cached value for key (refreshing recency), or None."""
        with self._lock:
            entry = self._map.get(key)
            if entry is not None:
                self._map.move_to_end(key)
                self._hits += 1
            else:
                self._misses += 1
        # Metrics outside the lock: registry children take their own
        # lock, and nested unrelated locks are how deadlocks start.
        if entry is not None:
            if self._m_hits is not None:
                self._m_hits.inc()
            return entry[0]
        if self._m_misses is not None:
            self._m_misses.inc()
        return None

    def put(self, key, value, nbytes: int) -> None:
        """Insert a decoded block (value is opaque to the cache; nbytes
        is its decoded size for the budget). Oversized values are still
        admitted and evict everything else — refusing them would make
        the hottest block of a tiny-budget config permanently cold."""
        evicted = 0
        with self._lock:
            old = self._map.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._map[key] = (value, int(nbytes))
            self._bytes += int(nbytes)
            while self._bytes > self.budget_bytes and len(self._map) > 1:
                _, (_, dropped) = self._map.popitem(last=False)
                self._bytes -= dropped
                evicted += 1
            self._evictions += evicted
            now_bytes = self._bytes
        if evicted and self._m_evictions is not None:
            self._m_evictions.inc(evicted)
        if self._m_bytes is not None:
            self._m_bytes.set(now_bytes)

    def contains(self, key) -> bool:
        """Residency peek: no recency refresh, no hit/miss accounting —
        the store's hint() uses it so readahead probing never skews the
        cache's observed hit rate."""
        with self._lock:
            return key in self._map

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()
            self._bytes = 0
        if self._m_bytes is not None:
            self._m_bytes.set(0)

    def stats(self) -> dict:
        """Point-in-time counters (also exported as gamesman_db_cache_*
        registry series): hits/misses/evictions/bytes/blocks."""
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "bytes": self._bytes,
                "blocks": len(self._map),
            }
