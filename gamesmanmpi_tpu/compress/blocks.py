"""Block framing: arrays <-> independently-decodable compressed blocks.

The framing contract every compressed on-disk surface shares (DB format
v2 levels, ``GAMESMAN_CKPT_COMPRESS=blocks`` checkpoint/spill members):

* An array is split into **fixed position-count blocks** (the last block
  ragged). Fixed counts, not fixed bytes: block *b* always holds
  positions ``[b*P, (b+1)*P)``, so a reader maps a position index to a
  block with one division — no search through the index.
* Each block is encoded independently (codecs.encode_best — raw
  passthrough when compression loses), so a probe decodes only the
  blocks it touches and a torn tail corrupts only the blocks it covers.
* The **index travels separately from the data** (in the DB's
  checksummed manifest, or the npz's ``__blocks__`` member): per-block
  codec name, encoded byte length, and crc32. Offsets are derived by
  cumulative sum — an index whose lengths disagree with the file size
  is itself a detectable corruption.
* ``decode_block`` verifies the stored crc32 BEFORE handing bytes to a
  codec: a torn or bit-rotted block surfaces as BlockCorruptError (a
  ValueError — both the checkpoint quarantine tuple and DbFormatError
  speak it), never as a silently-wrong array.

Index dicts are plain JSON-serializable content (ints + short strings)
on purpose: they live inside manifests that existing machinery already
checksums and atomically replaces.
"""

from __future__ import annotations

import zlib

import numpy as np

from gamesmanmpi_tpu.compress.codecs import (
    BlockCorruptError,
    encode_best,
    get_codec,
)

#: Default positions per block. 64Ki positions is ~512 KiB of raw uint64
#: keys — big enough that DEFLATE reaches its asymptotic ratio, small
#: enough that a point probe decodes well under a millisecond and a
#: hot-block cache holds hundreds of blocks in a few tens of MB.
DEFAULT_BLOCK_POSITIONS = 65536


def split_blocks(n: int, block_positions: int):
    """Yield (start, stop) of each block of an n-element array."""
    if block_positions <= 0:
        raise ValueError(f"block_positions must be positive, "
                         f"got {block_positions}")
    for start in range(0, n, block_positions):
        yield start, min(start + block_positions, n)


def encode_array(arr: np.ndarray, block_positions: int,
                 candidates) -> tuple[dict, list]:
    """Encode one 1-D array into framed blocks. -> (index, [bytes]).

    The index is the JSON-serializable per-array record the caller
    embeds in its manifest: dtype, count, block_positions, and the
    parallel per-block lists (codec, encoded length, crc32).
    """
    arr = np.ascontiguousarray(arr)
    if arr.ndim != 1:
        raise ValueError("block framing is for 1-D arrays")
    codecs, lengths, crcs, blobs = [], [], [], []
    for start, stop in split_blocks(arr.shape[0], block_positions):
        name, blob = encode_best(arr[start:stop], candidates)
        codecs.append(name)
        lengths.append(len(blob))
        crcs.append(zlib.crc32(blob) & 0xFFFFFFFF)
        blobs.append(blob)
    index = {
        "dtype": arr.dtype.name,
        "count": int(arr.shape[0]),
        "block_positions": int(block_positions),
        "codecs": codecs,
        "lengths": lengths,
        "crc32": crcs,
    }
    return index, blobs


def index_offsets(index: dict) -> np.ndarray:
    """Byte offset of each block in the concatenated stream (derived,
    never stored: lengths are the single source of truth)."""
    lengths = np.asarray(index["lengths"], dtype=np.int64)
    out = np.zeros(lengths.shape[0] + 1, dtype=np.int64)
    np.cumsum(lengths, out=out[1:])
    return out


def num_blocks(index: dict) -> int:
    return len(index["lengths"])


def block_bounds(index: dict, b: int) -> tuple[int, int]:
    """(start position, stop position) of block b within the array."""
    bp = int(index["block_positions"])
    start = b * bp
    return start, min(start + bp, int(index["count"]))


def validate_index(index: dict, stream_bytes: int | None = None) -> None:
    """Structural sanity of one per-array index; raises BlockCorruptError.

    Catches the index-vs-data mismatches a reader would otherwise turn
    into out-of-range reads: parallel lists of unequal length, a block
    count that cannot cover ``count`` positions, lengths that do not sum
    to the stream size.
    """
    try:
        n = int(index["count"])
        bp = int(index["block_positions"])
        codecs = index["codecs"]
        lengths = index["lengths"]
        crcs = index["crc32"]
        np.dtype(index["dtype"])
    except (KeyError, TypeError, ValueError) as e:
        raise BlockCorruptError(f"malformed block index: {e}") from None
    if bp <= 0:
        raise BlockCorruptError(f"block index: block_positions {bp}")
    if not (len(codecs) == len(lengths) == len(crcs)):
        raise BlockCorruptError(
            f"block index: parallel lists disagree "
            f"({len(codecs)} codecs, {len(lengths)} lengths, "
            f"{len(crcs)} crcs)"
        )
    want_blocks = (n + bp - 1) // bp
    if len(lengths) != want_blocks:
        raise BlockCorruptError(
            f"block index: {len(lengths)} blocks cannot hold {n} "
            f"positions at {bp}/block (expected {want_blocks})"
        )
    if stream_bytes is not None and int(sum(lengths)) != int(stream_bytes):
        raise BlockCorruptError(
            f"block index: lengths sum to {int(sum(lengths))} bytes but "
            f"the stream holds {stream_bytes}"
        )


def decode_block(index: dict, b: int, blob: bytes) -> np.ndarray:
    """Decode block b's bytes, crc-verified first. -> array slice."""
    if not 0 <= b < num_blocks(index):
        raise BlockCorruptError(
            f"block {b} out of range (index holds {num_blocks(index)})"
        )
    want_crc = int(index["crc32"][b])
    if len(blob) != int(index["lengths"][b]):
        raise BlockCorruptError(
            f"block {b}: {len(blob)} bytes, index says "
            f"{int(index['lengths'][b])}"
        )
    got = zlib.crc32(blob) & 0xFFFFFFFF
    if got != want_crc:
        raise BlockCorruptError(
            f"block {b}: crc32 {got:#010x} != indexed {want_crc:#010x} "
            "— torn or bit-rotted block"
        )
    start, stop = block_bounds(index, b)
    out = get_codec(index["codecs"][b]).decode(
        blob, np.dtype(index["dtype"]), stop - start
    )
    if out.shape[0] != stop - start:
        raise BlockCorruptError(
            f"block {b}: decoded {out.shape[0]} positions, "
            f"expected {stop - start}"
        )
    return out


def decode_array(index: dict, stream: bytes) -> np.ndarray:
    """Decode a whole framed stream back into one array (checkpoint
    loads and integrity checks consume arrays whole; probes use
    decode_block through the reader's hot-block cache instead)."""
    validate_index(index, stream_bytes=len(stream))
    offs = index_offsets(index)
    parts = [
        decode_block(index, b, stream[offs[b]:offs[b + 1]])
        for b in range(num_blocks(index))
    ]
    if not parts:
        return np.zeros(0, dtype=np.dtype(index["dtype"]))
    return np.concatenate(parts)
