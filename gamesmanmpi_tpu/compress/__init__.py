"""compress: block-compressed storage shared by every on-disk surface.

The subsystem behind ROADMAP item 2 ("Compressed Game Solving",
PAPERS.md arXiv 2411.07273): a codec registry exploiting the solved-DB
payload shape (sorted keys, 2-bit value alphabet), block framing with a
separately-stored per-block index + crc32, and a thread-safe hot-block
LRU for decompress-on-probe serving. Consumers:

* ``db/`` format v2 — per-level keys/cells as framed block files, index
  in the checksummed manifest, DbReader decodes only probed blocks;
* ``utils/checkpoint.py`` — ``GAMESMAN_CKPT_COMPRESS=blocks`` frames
  every checkpoint/spill npz member behind the existing crc-seal and
  quarantine machinery (torn block -> BlockCorruptError, a
  TORN_NPZ_ERRORS ValueError);
* ``bench.py`` — BENCH_DB_COMPRESS gates ratio + probe-latency SLO.

Pure numpy + stdlib: no jax anywhere in this package (it runs on host
I/O paths and inside jax-free tools like tools/check_db.py).
"""

from gamesmanmpi_tpu.compress.blocks import (
    DEFAULT_BLOCK_POSITIONS,
    block_bounds,
    decode_array,
    decode_block,
    encode_array,
    index_offsets,
    num_blocks,
    validate_index,
)
from gamesmanmpi_tpu.compress.cache import BlockCache
from gamesmanmpi_tpu.compress.codecs import (
    CELL_CANDIDATES,
    CODECS,
    GENERIC_CANDIDATES,
    KEY_CANDIDATES,
    BlockCorruptError,
    encode_best,
    get_codec,
)

__all__ = [
    "BlockCache",
    "BlockCorruptError",
    "CELL_CANDIDATES",
    "CODECS",
    "DEFAULT_BLOCK_POSITIONS",
    "GENERIC_CANDIDATES",
    "KEY_CANDIDATES",
    "block_bounds",
    "decode_array",
    "decode_block",
    "encode_array",
    "encode_best",
    "get_codec",
    "index_offsets",
    "num_blocks",
    "validate_index",
]
