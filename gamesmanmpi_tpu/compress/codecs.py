"""Block codecs: typed 1-D numpy arrays <-> compressed bytes.

"Compressed Game Solving" (PAPERS.md, arXiv 2411.07273) observes that
solved-game databases are orders of magnitude more compressible than
generic data, because the payload is not generic: keys are *sorted*
packed bitboards (small, smooth deltas) and cells carry a *tiny value
alphabet* (2-bit WIN/LOSE/TIE/UNDECIDED) next to a remoteness that
rarely needs more than one byte. The codecs here exploit exactly that
structure, with DEFLATE as the entropy stage:

* ``raw``      — identity passthrough. Always wins ties: a block that
  does not compress must cost zero decode work and zero risk.
* ``zlib``     — plain DEFLATE of the array bytes; the generic backstop
  for data with no exploitable shape (edge indices, slot maps).
* ``keydelta`` — sorted-key transform: first key verbatim + deltas
  narrowed to the smallest unsigned width that holds the block's
  maximum, then DEFLATE. Sorted level keys shrink 5-50x because
  neighboring bitboards share almost all their bits.
* ``cellpack`` — packed-cell transform: the 2-bit values of four cells
  share one byte, remoteness is split into its own stream narrowed to
  min-width (u8 for every real game so far), both DEFLATE'd. This is
  the value+remoteness entropy coding of the ROADMAP item.

Every codec is **self-checking at the framing layer** (compress/blocks
stores a crc32 per encoded block) and **deterministic**: encode is pure,
decode(encode(a)) round-trips bit-exactly, and a codec that cannot
represent an input (keydelta on unsorted data) returns None instead of
guessing, so ``encode_best`` falls through to the next candidate.

No jax anywhere in this package: compression runs on the host I/O path
(DB export, checkpoint seal, decompress-on-probe serving) where pulling
in a backend would be pure startup cost.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np


class BlockCorruptError(ValueError):
    """An encoded block failed structural validation (bad header, crc
    mismatch at the framing layer, wrong decoded count). Subclasses
    ValueError so checkpoint loaders treat it as one more
    TORN_NPZ_ERRORS shape (quarantine-and-degrade), and DB readers can
    wrap it in DbFormatError (also a ValueError) for the serving
    breaker."""


def _writable_frombuffer(data: bytes, dtype) -> np.ndarray:
    # bytes -> writable array with ONE copy (np.frombuffer over immutable
    # bytes yields a read-only view; loaders hand these arrays to code
    # that sorts/slices in place).
    return np.frombuffer(bytearray(data), dtype=dtype)


def _min_unsigned_dtype(max_value: int) -> np.dtype:
    for dt in (np.uint8, np.uint16, np.uint32, np.uint64):
        if max_value <= np.iinfo(dt).max:
            return np.dtype(dt)
    raise BlockCorruptError(f"delta {max_value} exceeds uint64")


class RawCodec:
    """Identity: the passthrough every block can fall back to."""

    name = "raw"

    def encode(self, arr: np.ndarray):
        return arr.tobytes()

    def decode(self, blob: bytes, dtype, count: int) -> np.ndarray:
        dtype = np.dtype(dtype)
        if len(blob) != count * dtype.itemsize:
            raise BlockCorruptError(
                f"raw block: {len(blob)} bytes for {count} x {dtype}"
            )
        return _writable_frombuffer(blob, dtype)


class ZlibCodec:
    """DEFLATE over the array bytes — the shape-agnostic backstop."""

    name = "zlib"

    def __init__(self, level: int = 6):
        self.level = level

    def encode(self, arr: np.ndarray):
        return zlib.compress(arr.tobytes(), self.level)

    def decode(self, blob: bytes, dtype, count: int) -> np.ndarray:
        dtype = np.dtype(dtype)
        try:
            data = zlib.decompress(blob)
        except zlib.error as e:
            raise BlockCorruptError(f"zlib block: {e}") from None
        if len(data) != count * dtype.itemsize:
            raise BlockCorruptError(
                f"zlib block: decoded {len(data)} bytes for "
                f"{count} x {dtype}"
            )
        return _writable_frombuffer(data, dtype)


class KeyDeltaCodec:
    """Sorted unsigned keys: first key + min-width deltas + DEFLATE.

    Declines (returns None) for non-integer/unsorted/2-D inputs rather
    than producing an encoding whose decode could not reproduce them;
    strictly-ascending is the DB key invariant, but merely
    non-descending data (checkpoint cells sorted by key, say) encodes
    fine — only a *descending* pair is unrepresentable.
    """

    name = "keydelta"
    _HEADER = struct.Struct("<BQ")  # delta width byte, first key (u64)

    def encode(self, arr: np.ndarray):
        if arr.dtype.kind != "u" or arr.ndim != 1 or arr.shape[0] == 0:
            return None
        if arr.shape[0] > 1 and bool(np.any(arr[1:] < arr[:-1])):
            return None  # descending somewhere: not delta-codable
        # Unsigned subtraction is exact here because non-descending was
        # just established (np.diff on unsorted unsigned data would wrap,
        # not go negative — hence the explicit check above).
        deltas = arr[1:] - arr[:-1]
        width_dt = _min_unsigned_dtype(
            int(deltas.max()) if deltas.size else 0
        )
        payload = zlib.compress(deltas.astype(width_dt).tobytes(), 6)
        return self._HEADER.pack(width_dt.itemsize, int(arr[0])) + payload

    def decode(self, blob: bytes, dtype, count: int) -> np.ndarray:
        dtype = np.dtype(dtype)
        if count == 0:
            return np.zeros(0, dtype=dtype)
        if len(blob) < self._HEADER.size:
            raise BlockCorruptError("keydelta block: truncated header")
        width, first = self._HEADER.unpack_from(blob)
        if width not in (1, 2, 4, 8):
            raise BlockCorruptError(f"keydelta block: delta width {width}")
        try:
            data = zlib.decompress(blob[self._HEADER.size:])
        except zlib.error as e:
            raise BlockCorruptError(f"keydelta block: {e}") from None
        deltas = np.frombuffer(data, dtype=np.dtype(f"u{width}"))
        if deltas.shape[0] != count - 1:
            raise BlockCorruptError(
                f"keydelta block: {deltas.shape[0]} deltas for "
                f"{count} keys"
            )
        out = np.empty(count, dtype=np.uint64)
        out[0] = first
        np.cumsum(deltas, dtype=np.uint64, out=out[1:])
        out[1:] += np.uint64(first)
        return out.astype(dtype, copy=False)


class CellPackCodec:
    """Packed uint32 cells: 2-bit values four-to-a-byte + min-width
    remoteness stream, each DEFLATE'd (core/codec.py layout: value in
    the low 2 bits, remoteness in the high 30)."""

    name = "cellpack"
    _HEADER = struct.Struct("<BI")  # remoteness width byte, value bytes

    def encode(self, arr: np.ndarray):
        if arr.dtype != np.uint32 or arr.ndim != 1 or arr.shape[0] == 0:
            return None
        values = (arr & np.uint32(3)).astype(np.uint8)
        rem = arr >> np.uint32(2)
        pad = (-values.shape[0]) % 4
        if pad:
            values = np.concatenate([values, np.zeros(pad, np.uint8)])
        quads = values.reshape(-1, 4)
        vbytes = (
            quads[:, 0] | (quads[:, 1] << 2) | (quads[:, 2] << 4)
            | (quads[:, 3] << 6)
        ).tobytes()
        width_dt = _min_unsigned_dtype(int(rem.max()) if rem.size else 0)
        vblob = zlib.compress(vbytes, 6)
        rblob = zlib.compress(rem.astype(width_dt).tobytes(), 6)
        return (
            self._HEADER.pack(width_dt.itemsize, len(vblob)) + vblob + rblob
        )

    def decode(self, blob: bytes, dtype, count: int) -> np.ndarray:
        if np.dtype(dtype) != np.uint32:
            raise BlockCorruptError(
                f"cellpack block: cells must be uint32, not {dtype}"
            )
        if count == 0:
            return np.zeros(0, dtype=np.uint32)
        if len(blob) < self._HEADER.size:
            raise BlockCorruptError("cellpack block: truncated header")
        width, vlen = self._HEADER.unpack_from(blob)
        if width not in (1, 2, 4, 8):
            raise BlockCorruptError(
                f"cellpack block: remoteness width {width}"
            )
        body = blob[self._HEADER.size:]
        try:
            vbytes = zlib.decompress(body[:vlen])
            rbytes = zlib.decompress(body[vlen:])
        except zlib.error as e:
            raise BlockCorruptError(f"cellpack block: {e}") from None
        packed = np.frombuffer(vbytes, dtype=np.uint8)
        if packed.shape[0] * 4 < count:
            raise BlockCorruptError(
                f"cellpack block: {packed.shape[0] * 4} packed values "
                f"for {count} cells"
            )
        values = np.empty((packed.shape[0], 4), dtype=np.uint32)
        for j in range(4):
            values[:, j] = (packed >> (2 * j)) & 3
        values = values.reshape(-1)[:count]
        rem = np.frombuffer(rbytes, dtype=np.dtype(f"u{width}"))
        if rem.shape[0] != count:
            raise BlockCorruptError(
                f"cellpack block: {rem.shape[0]} remotenesses for "
                f"{count} cells"
            )
        return (values | (rem.astype(np.uint32) << np.uint32(2))).astype(
            np.uint32
        )


#: The codec registry: every name a block index may reference. Append-only
#: by design — a reader must be able to decode every codec any historical
#: writer recorded, forever (the "v1 stays readable" contract applied to
#: codecs).
CODECS = {
    c.name: c
    for c in (RawCodec(), ZlibCodec(), KeyDeltaCodec(), CellPackCodec())
}

#: Candidate orderings by payload shape: the writer tries these in order
#: and keeps the smallest (raw included, so compression can only win).
KEY_CANDIDATES = ("keydelta", "zlib")
CELL_CANDIDATES = ("cellpack", "zlib")
GENERIC_CANDIDATES = ("zlib",)


def get_codec(name: str):
    codec = CODECS.get(name)
    if codec is None:
        raise BlockCorruptError(
            f"unknown block codec {name!r} — written by a newer version?"
        )
    return codec


def encode_best(arr: np.ndarray, candidates) -> tuple[str, bytes]:
    """Encode one block with the smallest of ``candidates``, falling back
    to raw passthrough whenever compression loses (or every candidate
    declines). -> (codec name, encoded bytes).

    Raw competes by SIZE (arr.nbytes) without materializing bytes: at
    export scale the common case is a codec winning (15x on the 5x4
    board), and copying every raw block just to use it as a yardstick
    would memcpy the whole DB for nothing. tobytes() runs only when raw
    actually wins.
    """
    best_name, best = None, None
    best_len = arr.nbytes
    for name in candidates:
        blob = get_codec(name).encode(arr)
        if blob is not None and len(blob) < best_len:
            best_name, best, best_len = name, blob, len(blob)
    if best is None:
        return "raw", arr.tobytes()
    return best_name, best
